"""Workload-controller tests: one class per kind (reference analogue:
apis/training/v1alpha1/*_test.go defaults tables + per-controller suites
like controllers/tensorflow/tfjob_controller_test.go).

Pattern: drive the engine synchronously with the kind's controller, flip pod
phases via PodDriver, assert on generated env/configs — SURVEY.md §4's
"distributed topology simulated by constructing pod lists" trick.
"""

import json

import pytest

from kubedl_tpu.api import constants
from kubedl_tpu.api.types import (
    JobConditionType,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    SuccessPolicy,
)
from kubedl_tpu.core.objects import Container, PodPhase
from kubedl_tpu.core.store import ObjectStore
from kubedl_tpu.engine.job_controller import JobEngine
from kubedl_tpu.observability.metrics import JobMetrics, MetricsRegistry
from kubedl_tpu.workloads.elasticdljob import ElasticDLJob, ElasticDLJobController
from kubedl_tpu.workloads.marsjob import MarsJob, MarsJobController
from kubedl_tpu.workloads.mpijob import (
    HOSTFILE_NAME,
    INTEL_MPI,
    MPIJob,
    MPIJobController,
    RSH_AGENT_NAME,
)
from kubedl_tpu.workloads.pytorchjob import PyTorchJob, PyTorchJobController
from kubedl_tpu.workloads.registry import WORKLOAD_REGISTRY
from kubedl_tpu.workloads.tfjob import TFJob, TFJobController
from kubedl_tpu.workloads.xdljob import XDLJob, XDLJobController
from kubedl_tpu.workloads.xgboostjob import XGBoostJob, XGBoostJobController

from tests.helpers import PodDriver, env_of, pod_names


def make_engine(controller):
    store = ObjectStore()
    engine = JobEngine(
        store=store,
        controller=controller,
        gang_scheduler=None,
        metrics=JobMetrics(MetricsRegistry()),
    )
    return engine, store, PodDriver(store)


def add_replicas(job, rtype, n, **kw):
    spec = ReplicaSpec(replicas=n, restart_policy=kw.pop("restart_policy", RestartPolicy.ON_FAILURE))
    spec.template.spec.containers.append(Container(**kw))
    job.spec.replica_specs[rtype] = spec
    return spec


def reconcile(engine, job, times=1):
    for _ in range(times):
        engine.reconcile(job.metadata.namespace, job.metadata.name)


class TestRegistry:
    def test_all_reference_kinds_registered(self):
        # the 7 reference kinds (SURVEY.md §2.2) + the flagship TPUJob
        assert set(WORKLOAD_REGISTRY) >= {
            "TPUJob", "TFJob", "PyTorchJob", "XDLJob", "XGBoostJob",
            "MarsJob", "ElasticDLJob", "MPIJob",
        }


class TestTFJob:
    def make(self, ps=2, workers=2, chief=0):
        engine, store, driver = make_engine(TFJobController(local_addresses=True))
        job = TFJob()
        job.metadata.name = "tf1"
        add_replicas(job, ReplicaType.PS, ps)
        add_replicas(job, ReplicaType.WORKER, workers)
        if chief:
            add_replicas(job, ReplicaType.CHIEF, chief)
        store.create(job)
        return engine, store, driver, job

    def test_tf_config_cluster_and_task(self):
        engine, store, driver, job = self.make(ps=2, workers=2, chief=1)
        reconcile(engine, job)
        # DAG: workers wait for PS Running -> only PS + chief pods first
        driver.run_all(store)
        reconcile(engine, job)
        pod = store.get("Pod", "tf1-worker-1")
        cfg = json.loads(env_of(pod)["TF_CONFIG"])
        assert set(cfg["cluster"]) == {"ps", "worker", "chief"}
        assert len(cfg["cluster"]["ps"]) == 2
        assert cfg["task"] == {"type": "worker", "index": 1}
        assert cfg["environment"] == "cloud"
        # JAX bootstrap rides along for workers only
        env = env_of(pod)
        assert env[constants.ENV_NUM_PROCESSES] == "2"
        ps_env = env_of(store.get("Pod", "tf1-ps-0"))
        assert constants.ENV_NUM_PROCESSES not in ps_env

    def test_evaluator_excluded_from_cluster_spec(self):
        engine, store, driver, job = self.make(ps=1, workers=1)
        add_replicas(job, ReplicaType.EVALUATOR, 1)
        store.update(job)
        reconcile(engine, job)
        driver.run_all(store)
        reconcile(engine, job)
        ev = store.get("Pod", "tf1-evaluator-0")
        cfg = json.loads(env_of(ev)["TF_CONFIG"])
        assert "evaluator" not in cfg["cluster"]
        assert cfg["task"]["type"] == "evaluator"

    def test_dag_workers_wait_for_ps(self):
        engine, store, driver, job = self.make(ps=1, workers=2)
        reconcile(engine, job)
        assert pod_names(store) == ["tf1-ps-0"]
        driver.run("tf1-ps-0")
        reconcile(engine, job)
        assert "tf1-worker-0" in pod_names(store)

    def test_success_from_chief(self):
        engine, store, driver, job = self.make(ps=1, workers=2, chief=1)
        reconcile(engine, job)
        driver.run_all(store)
        reconcile(engine, job)
        driver.run_all(store)
        reconcile(engine, job)
        driver.succeed("tf1-chief-0")
        reconcile(engine, job)
        got = store.get("TFJob", "tf1")
        assert got.status.phase == JobConditionType.SUCCEEDED

    def test_success_worker0_when_masterless(self):
        engine, store, driver, job = self.make(ps=1, workers=2)
        reconcile(engine, job)
        driver.run_all(store)
        reconcile(engine, job)
        driver.run_all(store)
        driver.succeed("tf1-worker-0")
        reconcile(engine, job)
        assert store.get("TFJob", "tf1").status.phase == JobConditionType.SUCCEEDED

    def test_all_workers_policy(self):
        engine, store, driver, job = self.make(ps=1, workers=2)
        job.spec.success_policy = SuccessPolicy.ALL_WORKERS
        store.update(job)
        reconcile(engine, job)
        driver.run_all(store)
        reconcile(engine, job)
        driver.run_all(store)
        driver.succeed("tf1-worker-0")
        reconcile(engine, job)
        assert store.get("TFJob", "tf1").status.phase != JobConditionType.SUCCEEDED
        driver.succeed("tf1-worker-1")
        reconcile(engine, job)
        assert store.get("TFJob", "tf1").status.phase == JobConditionType.SUCCEEDED


class TestPyTorchJob:
    def make(self, workers=2, backend="xla"):
        engine, store, driver = make_engine(PyTorchJobController(local_addresses=True))
        job = PyTorchJob()
        job.metadata.name = "pt1"
        job.backend = backend
        add_replicas(job, ReplicaType.MASTER, 1)
        add_replicas(job, ReplicaType.WORKER, workers)
        store.create(job)
        return engine, store, driver, job

    def test_master_env(self):
        engine, store, driver, job = self.make()
        reconcile(engine, job)
        env = env_of(store.get("Pod", "pt1-master-0"))
        assert env["MASTER_ADDR"] == "localhost"
        assert env["RANK"] == "0"
        assert env["WORLD_SIZE"] == "3"
        assert env["PJRT_DEVICE"] == "TPU"

    def test_worker_rank_offset_and_addr(self):
        engine, store, driver, job = self.make()
        reconcile(engine, job)
        driver.run_all(store)
        reconcile(engine, job)
        env = env_of(store.get("Pod", "pt1-worker-1"))
        assert env["RANK"] == "2"  # offset +1 past the master
        assert env["MASTER_ADDR"] == "127.0.0.1"
        assert env["WORLD_SIZE"] == "3"

    def test_service_only_for_master(self):
        engine, store, driver, job = self.make()
        reconcile(engine, job)
        driver.run_all(store)
        reconcile(engine, job)
        svcs = [s.metadata.name for s in store.list("Service")]
        assert svcs == ["pt1-master-0"]

    def test_masterless_rendezvous_on_worker0(self):
        engine, store, driver = make_engine(PyTorchJobController(local_addresses=True))
        job = PyTorchJob()
        job.metadata.name = "pt2"
        add_replicas(job, ReplicaType.WORKER, 3)
        store.create(job)
        reconcile(engine, job)
        e0 = env_of(store.get("Pod", "pt2-worker-0"))
        e2 = env_of(store.get("Pod", "pt2-worker-2"))
        assert e0["MASTER_ADDR"] == "localhost" and e0["RANK"] == "0"
        assert e2["MASTER_ADDR"] == "127.0.0.1" and e2["RANK"] == "2"
        assert e0["MASTER_PORT"] == e2["MASTER_PORT"]  # one endpoint
        # worker-0 must be addressable: worker services exist when masterless
        svcs = sorted(s.metadata.name for s in store.list("Service"))
        assert "pt2-worker-0" in svcs

    def test_gloo_backend_skips_pjrt(self):
        engine, store, driver, job = self.make(backend="gloo")
        reconcile(engine, job)
        env = env_of(store.get("Pod", "pt1-master-0"))
        assert "PJRT_DEVICE" not in env


class TestXGBoostJob:
    def test_rabit_env(self):
        engine, store, driver = make_engine(XGBoostJobController(local_addresses=True))
        job = XGBoostJob()
        job.metadata.name = "xgb1"
        add_replicas(job, ReplicaType.MASTER, 1)
        add_replicas(job, ReplicaType.WORKER, 3)
        store.create(job)
        reconcile(engine, job)
        driver.run_all(store)
        reconcile(engine, job)
        menv = env_of(store.get("Pod", "xgb1-master-0"))
        wenv = env_of(store.get("Pod", "xgb1-worker-2"))
        assert menv["RANK"] == "0"
        assert wenv["RANK"] == "3"
        assert wenv["WORLD_SIZE"] == "4"
        assert wenv["PYTHONUNBUFFERED"] == "1"
        assert wenv["MASTER_ADDR"] == "127.0.0.1"


    def test_masterless_single_tracker_endpoint(self):
        engine, store, driver = make_engine(XGBoostJobController(local_addresses=True))
        job = XGBoostJob()
        job.metadata.name = "xgb2"
        add_replicas(job, ReplicaType.WORKER, 3)
        store.create(job)
        reconcile(engine, job)
        envs = [env_of(store.get("Pod", f"xgb2-worker-{i}")) for i in range(3)]
        assert len({e["MASTER_PORT"] for e in envs}) == 1
        assert [e["RANK"] for e in envs] == ["0", "1", "2"]


class TestXDLJob:
    def make(self, workers=4, **job_kw):
        engine, store, driver = make_engine(XDLJobController(local_addresses=True))
        job = XDLJob(**job_kw)
        job.metadata.name = "xdl1"
        add_replicas(job, ReplicaType.SCHEDULER, 1)
        add_replicas(job, ReplicaType.PS, 2)
        add_replicas(job, ReplicaType.WORKER, workers)
        store.create(job)
        return engine, store, driver, job

    def run_all_up(self, engine, store, driver, job):
        # scheduler -> ps -> workers, DAG-gated round by round
        for _ in range(3):
            reconcile(engine, job)
            driver.run_all(store)
        reconcile(engine, job)

    def test_cluster_spec_env(self):
        engine, store, driver, job = self.make()
        self.run_all_up(engine, store, driver, job)
        env = env_of(store.get("Pod", "xdl1-worker-0"))
        cluster = json.loads(env["XDL_CLUSTER_SPEC"])
        assert set(cluster) == {"scheduler", "ps", "worker"}
        assert len(cluster["worker"]) == 4
        assert env["XDL_TASK_NAME"] == "worker"
        assert env["XDL_TASK_INDEX"] == "0"

    def test_partial_success_num(self):
        engine, store, driver, job = self.make(workers=4, min_finish_worker_num=2)
        self.run_all_up(engine, store, driver, job)
        driver.succeed("xdl1-worker-0")
        reconcile(engine, job)
        assert store.get("XDLJob", "xdl1").status.phase != JobConditionType.SUCCEEDED
        driver.succeed("xdl1-worker-1")
        reconcile(engine, job)
        got = store.get("XDLJob", "xdl1")
        assert got.status.phase == JobConditionType.SUCCEEDED
        assert got.status.completion_time is not None

    def test_partial_success_percentage(self):
        engine, store, driver, job = self.make(
            workers=4, min_finish_worker_percentage=50.0
        )
        self.run_all_up(engine, store, driver, job)
        driver.succeed("xdl1-worker-0")
        driver.succeed("xdl1-worker-3")
        reconcile(engine, job)
        assert store.get("XDLJob", "xdl1").status.phase == JobConditionType.SUCCEEDED


class TestMarsJob:
    def make(self):
        engine, store, driver = make_engine(MarsJobController(local_addresses=True))
        job = MarsJob()
        job.metadata.name = "mars1"
        add_replicas(job, ReplicaType.SCHEDULER, 1)
        spec = add_replicas(job, ReplicaType.WORKER, 2)
        spec.template.spec.main_container().resources.update(
            {"cpu": 4.0, "memory": 8e9}
        )
        add_replicas(job, ReplicaType.WEBSERVICE, 1)
        job.memory_tuning.plasma_store_ratio = 0.3
        job.memory_tuning.spill_dirs = ["/spill"]
        store.create(job)
        return engine, store, driver, job

    def test_cluster_detail(self):
        engine, store, driver, job = self.make()
        reconcile(engine, job)
        driver.run_all(store)
        reconcile(engine, job)
        wenv = env_of(store.get("Pod", "mars1-worker-0"))
        detail = json.loads(wenv["MARS_CLUSTER_DETAIL"])
        # workers excluded from the endpoint list (auto-scalable)
        assert "worker" not in detail["cluster"]
        assert len(detail["cluster"]["scheduler"]) == 1
        assert detail["resources"]["cpu"] == 4.0
        assert detail["memory_tuning"]["plasma_store_ratio"] == 0.3
        assert detail["memory_tuning"]["spill_dirs"] == ["/spill"]
        senv = env_of(store.get("Pod", "mars1-scheduler-0"))
        sdetail = json.loads(senv["MARS_CLUSTER_DETAIL"])
        assert "resources" not in sdetail

    def test_web_addresses_published(self):
        engine, store, driver, job = self.make()
        job.web_host = "mars.example.com"
        store.update(job)
        reconcile(engine, job)
        driver.run_all(store)
        reconcile(engine, job)
        got = store.get("MarsJob", "mars1")
        assert any("mars.example.com" in a for a in got.web_service_addresses)
        assert any(a.startswith("http://127.0.0.1") for a in got.web_service_addresses)


class TestElasticDLJob:
    def test_master_only_no_services(self):
        engine, store, driver = make_engine(ElasticDLJobController(local_addresses=True))
        job = ElasticDLJob()
        job.metadata.name = "edl1"
        add_replicas(job, ReplicaType.MASTER, 1)
        add_replicas(job, ReplicaType.WORKER, 3)  # illegal: dropped by defaults
        store.create(job)
        reconcile(engine, job)
        assert pod_names(store) == ["edl1-master-0"]
        assert store.list("Service") == []
        env = env_of(store.get("Pod", "edl1-master-0"))
        assert env["ELASTICDL_MASTER_POD"] == "elasticdl-edl1-master"
        driver.run_all(store)
        reconcile(engine, job)
        driver.succeed("edl1-master-0")
        reconcile(engine, job)
        assert store.get("ElasticDLJob", "edl1").status.phase == JobConditionType.SUCCEEDED


class TestMPIJob:
    def make(self, workers=2, distribution="OpenMPI"):
        engine, store, driver = make_engine(MPIJobController(local_addresses=True))
        job = MPIJob()
        job.metadata.name = "mpi1"
        job.mpi_distribution = distribution
        add_replicas(job, ReplicaType.LAUNCHER, 1, command=["mpirun", "true"])
        add_replicas(job, ReplicaType.WORKER, workers)
        store.create(job)
        return engine, store, driver, job

    def test_workers_first_then_launcher(self):
        engine, store, driver, job = self.make()
        reconcile(engine, job)
        assert pod_names(store) == ["mpi1-worker-0", "mpi1-worker-1"]
        driver.run_all(store)
        reconcile(engine, job)
        assert "mpi1-launcher-0" in pod_names(store)
        # workers get headless services (hostfile DNS); the launcher none
        svcs = sorted(s.metadata.name for s in store.list("Service"))
        assert svcs == ["mpi1-worker-0", "mpi1-worker-1"]

    def test_hostfile_configmap(self):
        engine, store, driver, job = self.make()
        reconcile(engine, job)
        cm = store.get("ConfigMap", "mpi1-config")
        assert "slots=1" in cm.data[HOSTFILE_NAME]
        assert cm.data[HOSTFILE_NAME].count("\n") == 2
        assert cm.data[RSH_AGENT_NAME].startswith("#!/bin/sh")

    def test_worker_default_sleep(self):
        engine, store, driver, job = self.make()
        reconcile(engine, job)
        pod = store.get("Pod", "mpi1-worker-0")
        assert pod.spec.main_container().command == ["sleep", "365d"]

    def test_launcher_env_openmpi(self):
        engine, store, driver, job = self.make()
        reconcile(engine, job)
        driver.run_all(store)
        reconcile(engine, job)
        env = env_of(store.get("Pod", "mpi1-launcher-0"))
        assert env["OMPI_MCA_plm_rsh_agent"].endswith(RSH_AGENT_NAME)
        assert env["OMPI_MCA_orte_default_hostfile"].endswith(HOSTFILE_NAME)
        assert env[constants.ENV_NUM_PROCESSES] == "2"

    def test_launcher_env_intelmpi(self):
        engine, store, driver, job = self.make(distribution=INTEL_MPI)
        reconcile(engine, job)
        cm = store.get("ConfigMap", "mpi1-config")
        assert ":1" in cm.data[HOSTFILE_NAME]  # host:N syntax
        driver.run_all(store)
        reconcile(engine, job)
        env = env_of(store.get("Pod", "mpi1-launcher-0"))
        assert env["I_MPI_HYDRA_BOOTSTRAP"] == "rsh"

    def test_hostfile_refreshed_on_scale(self):
        engine, store, driver, job = self.make(workers=2)
        reconcile(engine, job)
        job = store.get("MPIJob", "mpi1")
        job.spec.replica_specs[ReplicaType.WORKER].replicas = 3
        store.update(job)
        reconcile(engine, job)
        cm = store.get("ConfigMap", "mpi1-config")
        assert cm.data[HOSTFILE_NAME].count("\n") == 3

    def test_launcher_success_finishes_job(self):
        engine, store, driver, job = self.make()
        reconcile(engine, job)
        driver.run_all(store)
        reconcile(engine, job)
        driver.run_all(store)
        reconcile(engine, job)
        driver.succeed("mpi1-launcher-0")
        reconcile(engine, job)
        assert store.get("MPIJob", "mpi1").status.phase == JobConditionType.SUCCEEDED


class TestAdmission:
    """Submit-time validation (the reference's validating-webhook layer)."""

    def _op(self, tmp_path):
        from kubedl_tpu.operator import Operator, OperatorOptions
        from kubedl_tpu.runtime.executor import FakeRuntime

        return Operator(
            OperatorOptions(local_addresses=True,
                            artifact_registry_root=str(tmp_path / "r")),
            runtime=FakeRuntime(),
        )

    def test_rejects_empty_replica_specs(self, tmp_path):
        from kubedl_tpu.operator import ValidationError
        from kubedl_tpu.workloads.tpujob import TPUJob

        op = self._op(tmp_path)
        job = TPUJob()
        job.metadata.name = "empty"
        with pytest.raises(ValidationError, match="at least one replica"):
            op.submit(job)

    def test_rejects_mixed_slice_types(self, tmp_path):
        from kubedl_tpu.api.topology import get_slice
        from kubedl_tpu.operator import ValidationError
        from kubedl_tpu.workloads.tpujob import TPUJob

        op = self._op(tmp_path)
        job = TPUJob()
        job.metadata.name = "mixed"
        for rtype, st in ((ReplicaType.WORKER, "v5e-8"),
                          (ReplicaType.EVALUATOR, "v5e-16")):
            spec = ReplicaSpec(replicas=1, topology=get_slice(st))
            spec.template.spec.containers.append(Container(command=["x"]))
            job.spec.replica_specs[rtype] = spec
        with pytest.raises(ValidationError, match="mixed slice types"):
            op.submit(job)

    def test_mpi_requires_single_launcher(self, tmp_path):
        from kubedl_tpu.operator import ValidationError
        from kubedl_tpu.workloads.mpijob import MPIJob

        op = self._op(tmp_path)
        job = MPIJob()
        job.metadata.name = "no-launcher"
        spec = ReplicaSpec(replicas=2)
        spec.template.spec.containers.append(Container(command=["x"]))
        job.spec.replica_specs[ReplicaType.WORKER] = spec
        with pytest.raises(ValidationError, match="Launcher"):
            op.submit(job)

    def test_pytorch_single_master(self, tmp_path):
        from kubedl_tpu.operator import ValidationError
        from kubedl_tpu.workloads.pytorchjob import PyTorchJob

        op = self._op(tmp_path)
        job = PyTorchJob()
        job.metadata.name = "two-masters"
        spec = ReplicaSpec(replicas=2)
        spec.template.spec.containers.append(Container(command=["x"]))
        job.spec.replica_specs[ReplicaType.MASTER] = spec
        with pytest.raises(ValidationError, match="one Master"):
            op.submit(job)

    def test_disabled_kind_rejected(self, tmp_path):
        from kubedl_tpu.operator import Operator, OperatorOptions, ValidationError
        from kubedl_tpu.runtime.executor import FakeRuntime
        from kubedl_tpu.workloads.marsjob import MarsJob

        op = Operator(
            OperatorOptions(workloads="TPUJob", local_addresses=True,
                            artifact_registry_root=str(tmp_path / "r")),
            runtime=FakeRuntime(),
        )
        job = MarsJob()
        job.metadata.name = "mars"
        with pytest.raises(ValidationError, match="not enabled"):
            op.submit(job)

    def test_valid_job_admitted_with_defaults_applied(self, tmp_path):
        from kubedl_tpu.workloads.tpujob import TPUJob

        op = self._op(tmp_path)
        job = TPUJob()
        job.metadata.name = "ok"
        spec = ReplicaSpec(replicas=0)  # defaulting bumps to 1
        spec.template.spec.containers.append(Container(command=["x"]))
        job.spec.replica_specs[ReplicaType.WORKER] = spec
        created = op.submit(job)
        assert created.spec.replica_specs[ReplicaType.WORKER].replicas == 1


class TestMarsIngress:
    """VERDICT r2 missing #4: the web UI routing OBJECT (reference creates
    a real Ingress, controllers/mars/ingress.go:37-166)."""

    def test_ingress_route_created_and_gcd(self):
        engine, store, driver = make_engine(MarsJobController(local_addresses=True))
        job = MarsJob()
        job.metadata.name = "mars2"
        job.web_host = "mars.example.com"
        add_replicas(job, ReplicaType.SCHEDULER, 1)
        add_replicas(job, ReplicaType.WEBSERVICE, 1)
        store.create(job)
        reconcile(engine, job)
        route = store.get("IngressRoute", "mars2-web")
        assert route.host == "mars.example.com"
        assert route.path == "/default/mars2"
        assert route.service == "mars2-webservice-0"
        assert route.port > 0
        # owner-ref'd to the job -> GC'd with it
        ref = route.metadata.controller_ref()
        assert ref is not None and ref.name == "mars2"
        # webHost change refreshes the route in place
        job2 = store.get("MarsJob", "mars2")
        job2.web_host = "other.example.com"
        store.update(job2)
        reconcile(engine, job2)
        assert store.get("IngressRoute", "mars2-web").host == "other.example.com"

    def test_no_route_without_web_host(self):
        engine, store, driver = make_engine(MarsJobController(local_addresses=True))
        job = MarsJob()
        job.metadata.name = "mars3"
        add_replicas(job, ReplicaType.SCHEDULER, 1)
        add_replicas(job, ReplicaType.WEBSERVICE, 1)
        store.create(job)
        reconcile(engine, job)
        assert store.try_get("IngressRoute", "mars3-web") is None


class TestMPILegacy:
    """VERDICT r2 missing #5: v1alpha1/v1alpha2 field spellings
    (reference: controllers/mpi/legacy.go:1-126)."""

    def _job(self, legacy):
        from kubedl_tpu.workloads.mpijob import MPIJob, MPILegacySpec

        job = MPIJob()
        job.metadata.name = "mpileg"
        job.legacy_spec = MPILegacySpec(**legacy)
        add_replicas(job, ReplicaType.LAUNCHER, 1, command=["true"])
        return job

    def test_processing_units_sized_workers(self):
        from kubedl_tpu.workloads.mpijob import MPIJobController

        ctrl = MPIJobController(local_addresses=True)
        job = self._job({"processing_units": 8, "processing_units_per_node": 4})
        spec = add_replicas(job, ReplicaType.WORKER, 0, command=["sleep", "1"])
        ctrl.apply_defaults(job)
        assert job.spec.replica_specs[ReplicaType.WORKER].replicas == 2
        assert job.slots_per_worker == 4

    def test_deprecated_gpus_spelling(self):
        from kubedl_tpu.workloads.mpijob import MPIJobController

        ctrl = MPIJobController(local_addresses=True)
        job = self._job({"gpus": 3, "gpus_per_node": 4})  # < per-node: 1 worker
        add_replicas(job, ReplicaType.WORKER, 0, command=["sleep", "1"])
        ctrl.apply_defaults(job)
        assert job.spec.replica_specs[ReplicaType.WORKER].replicas == 1
        assert job.slots_per_worker == 3

    def test_replicas_with_resource_type(self):
        from kubedl_tpu.workloads.mpijob import MPIJobController

        ctrl = MPIJobController(local_addresses=True)
        job = self._job({"replicas": 3, "processing_resource_type": "tpu"})
        spec = add_replicas(job, ReplicaType.WORKER, 0, command=["sleep", "1"])
        spec.template.spec.main_container().resources["tpu"] = 2
        ctrl.apply_defaults(job)
        assert job.spec.replica_specs[ReplicaType.WORKER].replicas == 3
        assert job.slots_per_worker == 2

    def test_explicit_fields_win_and_conflicts_raise(self):
        import pytest as _pytest

        from kubedl_tpu.workloads.mpijob import MPIJobController

        ctrl = MPIJobController(local_addresses=True)
        job = self._job({"processing_units": 8, "processing_units_per_node": 4})
        job.slots_per_worker = 7  # user-set wins
        add_replicas(job, ReplicaType.WORKER, 5, command=["sleep", "1"])
        ctrl.apply_defaults(job)
        assert job.spec.replica_specs[ReplicaType.WORKER].replicas == 5
        assert job.slots_per_worker == 7
        bad = self._job({"gpus": 4, "processing_units": 8})
        add_replicas(bad, ReplicaType.WORKER, 0, command=["sleep", "1"])
        with _pytest.raises(ValueError, match="both"):
            ctrl.apply_defaults(bad)
        indiv = self._job({"processing_units": 7, "processing_units_per_node": 4})
        add_replicas(indiv, ReplicaType.WORKER, 0, command=["sleep", "1"])
        with _pytest.raises(ValueError, match="multiple"):
            ctrl.apply_defaults(indiv)

    def test_legacy_clean_pod_policy(self):
        from kubedl_tpu.api.types import CleanPodPolicy
        from kubedl_tpu.workloads.mpijob import MPIJobController

        ctrl = MPIJobController(local_addresses=True)
        job = self._job({"replicas": 1, "clean_pod_policy": "None"})
        add_replicas(job, ReplicaType.WORKER, 0, command=["sleep", "1"])
        ctrl.apply_defaults(job)
        assert job.spec.run_policy.clean_pod_policy == CleanPodPolicy.NONE

    def test_codec_round_trips_legacy(self):
        from kubedl_tpu.api import codec

        job = self._job({"processing_units": 4, "processing_units_per_node": 2})
        data = codec.encode(job)
        back = codec.decode_object(data)
        assert back.legacy_spec.processing_units == 4


def test_mars_route_deleted_when_web_host_cleared():
    """Review r3: unpublishing (clearing webHost) must delete the route,
    not keep serving the old hostname until job deletion."""
    engine, store, driver = make_engine(MarsJobController(local_addresses=True))
    job = MarsJob()
    job.metadata.name = "mars4"
    job.web_host = "mars.example.com"
    add_replicas(job, ReplicaType.SCHEDULER, 1)
    add_replicas(job, ReplicaType.WEBSERVICE, 1)
    store.create(job)
    reconcile(engine, job)
    assert store.try_get("IngressRoute", "mars4-web") is not None
    job2 = store.get("MarsJob", "mars4")
    job2.web_host = ""
    store.update(job2)
    reconcile(engine, job2)
    assert store.try_get("IngressRoute", "mars4-web") is None

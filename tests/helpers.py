"""Shared test builders (reference analogue: pkg/test_util/v1/)."""

from __future__ import annotations

import time
from typing import Dict, Optional

from kubedl_tpu.api import constants
from kubedl_tpu.api.interface import JobObject
from kubedl_tpu.api.types import ReplicaSpec, ReplicaType, RestartPolicy
from kubedl_tpu.core.objects import Container, ContainerStatus, Pod, PodPhase
from kubedl_tpu.core.store import NotFound, ObjectStore
from kubedl_tpu.workloads.tpujob import TPUJob


def make_tpujob(
    name: str = "job1",
    workers: int = 2,
    command=None,
    entrypoint: str = "",
    restart_policy: RestartPolicy = RestartPolicy.ON_FAILURE_SLICE,
    topology=None,
) -> TPUJob:
    job = TPUJob()
    job.metadata.name = name
    spec = ReplicaSpec(replicas=workers, restart_policy=restart_policy, topology=topology)
    spec.template.spec.containers.append(
        Container(command=command or [], entrypoint=entrypoint)
    )
    job.spec.replica_specs[ReplicaType.WORKER] = spec
    return job


class PodDriver:
    """Drive pod phases by hand (FakeRuntime companion) — the reference's
    fake-client pattern where tests construct pod states directly."""

    def __init__(self, store: ObjectStore) -> None:
        self.store = store

    def _set(self, name: str, phase: PodPhase, exit_code: Optional[int] = None,
             reason: str = "", namespace: str = "default") -> None:
        def mutate(pod: Pod) -> None:  # type: ignore[type-arg]
            pod.status.phase = phase
            pod.status.reason = reason
            if phase == PodPhase.RUNNING and pod.status.start_time is None:
                pod.status.start_time = time.time()
            if exit_code is not None:
                pod.status.container_statuses = [ContainerStatus(exit_code=exit_code)]

        self.store.update_with_retry("Pod", name, namespace, mutate)

    def run(self, name: str, **kw) -> None:
        self._set(name, PodPhase.RUNNING, **kw)

    def succeed(self, name: str, **kw) -> None:
        self._set(name, PodPhase.SUCCEEDED, exit_code=0, **kw)

    def fail(self, name: str, exit_code: int = 1, **kw) -> None:
        self._set(name, PodPhase.FAILED, exit_code=exit_code, **kw)

    def evict(self, name: str, **kw) -> None:
        self._set(name, PodPhase.FAILED, exit_code=137, reason="Evicted", **kw)

    def run_all(self, store: ObjectStore, namespace: str = "default") -> None:
        for pod in store.list("Pod", namespace):
            if pod.status.phase == PodPhase.PENDING:  # type: ignore[attr-defined]
                self.run(pod.metadata.name, namespace=namespace)


def pod_names(store: ObjectStore, namespace: str = "default"):
    return sorted(p.metadata.name for p in store.list("Pod", namespace))


def env_of(pod: Pod) -> Dict[str, str]:
    return {e.name: e.value for e in pod.spec.main_container().env}

"""Chaos suite (tier-1): deterministic seeded fault injection across the
control plane + the unified retry/degradation policy (docs/robustness.md).

Invariants asserted here:
- same seed => identical fault trace (schedules are deterministic);
- a disarmed `chaos.check()` is unmeasurable overhead on hot paths;
- injected faults at every wired site degrade along the designed path
  (retry, requeue, eviction, gang restart, shed) — never a wedged job:
  anything submitted reaches a terminal phase and restart counts match
  the plan's injected fault count;
- a poison-pill job quarantines exactly once (condition + metric + event)
  instead of hot-looping the workqueue;
- serving under overload sheds boundedly (503 + counter) and stays live;
- a torn checkpoint save falls back to the previous good step;
- the README performance table stays derivable from the committed bench
  artifact, and the r5 `http:/` junk tree never reappears.
"""

import json
import os
import time
from pathlib import Path

import pytest

from kubedl_tpu import chaos
from kubedl_tpu.chaos import (
    FaultInjected,
    FaultPlan,
    FaultSpec,
    RetryBudgetExhausted,
    RetryPolicy,
)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _disarmed():
    """No plan leaks across tests — chaos is process-global state."""
    chaos.disarm()
    yield
    chaos.disarm()


# --------------------------------------------------------------------------
# FaultPlan schedules
# --------------------------------------------------------------------------


class TestFaultPlan:
    def test_nth_fails_exactly_the_nth_call(self):
        with FaultPlan(1, sites={"s": [FaultSpec.nth(3)]}) as plan:
            chaos.check("s")
            chaos.check("s")
            with pytest.raises(FaultInjected):
                chaos.check("s")
            chaos.check("s")
        assert plan.faults("s") == 1
        assert plan.calls("s") == 4

    def test_first_k_then_clean(self):
        with FaultPlan(1, sites={"s": [FaultSpec.first(2)]}) as plan:
            for _ in range(2):
                with pytest.raises(FaultInjected):
                    chaos.check("s")
            chaos.check("s")
        assert plan.faults("s") == 2

    def test_always_is_a_poison_pill(self):
        with FaultPlan(1, sites={"s": [FaultSpec.always()]}):
            for _ in range(5):
                with pytest.raises(FaultInjected):
                    chaos.check("s")

    def test_prob_fails_a_seeded_subset(self):
        with FaultPlan(42, sites={"s": [FaultSpec.prob(0.5, 40)]}) as plan:
            for _ in range(40):
                try:
                    chaos.check("s")
                except FaultInjected:
                    pass
        assert 0 < plan.faults("s") < 40

    def test_latency_spike_sleeps_instead_of_raising(self):
        naps = []
        plan = FaultPlan(1, sites={"s": [FaultSpec.latency(50.0, every=2)]},
                         sleep=naps.append)
        with plan:
            chaos.check("s")       # call 1: pass
            chaos.check("s")       # call 2: spike, no exception
            assert chaos.should_fail("s") is False  # call 3: pass
        assert naps == [0.05]

    def test_custom_exception_factory(self):
        class Boom(Exception):
            pass

        with FaultPlan(1, sites={"s": [FaultSpec.nth(1, exc=Boom)]}):
            with pytest.raises(Boom):
                chaos.check("s")

    def test_unknown_site_passes_and_is_counted(self):
        with FaultPlan(1, sites={"s": [FaultSpec.always()]}) as plan:
            chaos.check("other")
        assert plan.calls("other") == 1
        assert plan.faults("other") == 0

    def test_context_manager_disarms(self):
        plan = FaultPlan(1)
        with plan:
            assert chaos.active() is plan
        assert chaos.active() is None
        chaos.check("anything")  # disarmed: no-op


class TestDeterminism:
    SITES = {
        "a.site": [FaultSpec.prob(0.5, 30)],
        "b.site": [FaultSpec.prob(0.3, 40), FaultSpec.latency(1.0, every=7)],
    }

    def _drive(self, plan):
        with plan:
            for _ in range(40):
                for site in ("a.site", "b.site"):
                    try:
                        chaos.check(site)
                    except FaultInjected:
                        pass
        return plan.trace_tuples()

    def test_same_seed_identical_trace(self):
        naps = lambda _: None
        t1 = self._drive(FaultPlan(7, sites=self.SITES, sleep=naps))
        t2 = self._drive(FaultPlan(7, sites=self.SITES, sleep=naps))
        assert t1 == t2
        assert any(a == "fault" for _, _, a in t1)

    def test_different_seed_different_trace(self):
        naps = lambda _: None
        t1 = self._drive(FaultPlan(7, sites=self.SITES, sleep=naps))
        t2 = self._drive(FaultPlan(8, sites=self.SITES, sleep=naps))
        assert t1 != t2

    def test_per_site_rng_isolated(self):
        """Adding a site must not perturb another site's schedule — the
        RNG is derived from (seed, site), not shared."""
        base = FaultPlan(7, sites={"a.site": [FaultSpec.prob(0.5, 30)]})
        with base:
            for _ in range(30):
                try:
                    chaos.check("a.site")
                except FaultInjected:
                    pass
        grown = FaultPlan(7, sites={"a.site": [FaultSpec.prob(0.5, 30)],
                                    "z.site": [FaultSpec.prob(0.9, 30)]})
        with grown:
            for _ in range(30):
                try:
                    chaos.check("z.site")
                except FaultInjected:
                    pass
                try:
                    chaos.check("a.site")
                except FaultInjected:
                    pass
        a_of = lambda t: [x for x in t if x[0] == "a.site"]
        assert a_of(base.trace_tuples()) == a_of(grown.trace_tuples())

    def test_disarmed_check_overhead_unmeasurable(self):
        """The default-off fast path is one global load + None test; a
        generous absolute bound (5us/call — ~50x the expected cost) keeps
        this stable on slow CI while still catching an accidental lock,
        dict lookup, or allocation on the disarmed path."""
        n = 200_000
        check = chaos.check
        t0 = time.perf_counter()
        for _ in range(n):
            check("store.update")
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 5e-6, f"disarmed chaos.check costs {per_call * 1e9:.0f}ns/call"


# --------------------------------------------------------------------------
# RetryPolicy
# --------------------------------------------------------------------------


class TestRetryPolicy:
    def test_backoff_full_jitter_bounds(self):
        p = RetryPolicy(base_delay=0.1, max_delay=1.0)
        for attempt in range(8):
            cap = min(1.0, 0.1 * 2 ** attempt)
            for _ in range(20):
                d = p.backoff(attempt)
                assert 0.0 <= d <= cap

    def test_retries_then_succeeds(self):
        naps = []
        p = RetryPolicy(max_attempts=5, base_delay=0.01, sleep=naps.append)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ValueError("transient")
            return "ok"

        assert p.call(flaky, retry_on=(ValueError,)) == "ok"
        assert calls["n"] == 3
        assert p.retries == 2
        assert len(naps) <= 2  # zero-jitter draws skip the sleep

    def test_giveup_surfaces_immediately(self):
        p = RetryPolicy(max_attempts=5, sleep=lambda _: None)
        calls = {"n": 0}

        def permanent():
            calls["n"] += 1
            raise ValueError("permanent")

        with pytest.raises(ValueError):
            p.call(permanent, retry_on=(ValueError,), giveup=lambda e: True)
        assert calls["n"] == 1

    def test_exhausted_attempts_raise_last_error(self):
        p = RetryPolicy(max_attempts=3, sleep=lambda _: None)
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise ValueError(f"try {calls['n']}")

        with pytest.raises(ValueError, match="try 3"):
            p.call(always, retry_on=(ValueError,))
        assert calls["n"] == 3

    def test_unlisted_exception_not_retried(self):
        p = RetryPolicy(max_attempts=5, sleep=lambda _: None)
        calls = {"n": 0}

        def wrong():
            calls["n"] += 1
            raise KeyError("not transient")

        with pytest.raises(KeyError):
            p.call(wrong, retry_on=(ValueError,))
        assert calls["n"] == 1

    def test_budget_exhaustion_chains_last_error(self):
        # rng pinned to the cap so every retry spends a full base_delay
        p = RetryPolicy(max_attempts=50, base_delay=1.0, max_delay=1.0,
                        budget_s=2.5, rng=lambda a, b: b, sleep=lambda _: None)

        def always():
            raise ValueError("still down")

        with pytest.raises(RetryBudgetExhausted) as ei:
            p.call(always, retry_on=(ValueError,))
        assert isinstance(ei.value.__cause__, ValueError)
        assert p.budget_remaining() == 0.0


# --------------------------------------------------------------------------
# Wired sites: store, heartbeat, gang bind, client, remote blobs
# --------------------------------------------------------------------------


class TestStoreSite:
    def test_injected_create_fault_then_clean(self):
        from kubedl_tpu.core.store import ObjectStore

        from tests.helpers import make_tpujob

        store = ObjectStore()
        with FaultPlan(1, sites={"store.create": [FaultSpec.nth(1)]}) as plan:
            with pytest.raises(FaultInjected):
                store.create(make_tpujob("x"))
            store.create(make_tpujob("x"))
        assert plan.faults("store.create") == 1
        assert store.get("TPUJob", "x") is not None

    def test_update_with_retry_rides_policy_over_injected_conflicts(self):
        from kubedl_tpu.core.store import Conflict, ObjectStore

        from tests.helpers import make_tpujob

        store = ObjectStore()
        store.create(make_tpujob("x"))
        spec = FaultSpec.first(2, exc=lambda s: Conflict(f"injected at {s}"))
        with FaultPlan(1, sites={"store.update": [spec]}) as plan:
            got = store.update_with_retry(
                "TPUJob", "x", "default",
                lambda o: o.metadata.labels.update({"touched": "yes"}),
            )
        assert got.metadata.labels["touched"] == "yes"
        assert plan.faults("store.update") == 2

    def test_update_with_retry_gives_up_after_attempts(self):
        from kubedl_tpu.core.store import Conflict, ObjectStore

        from tests.helpers import make_tpujob

        store = ObjectStore()
        store.create(make_tpujob("x"))
        spec = FaultSpec.always(exc=lambda s: Conflict(s))
        with FaultPlan(1, sites={"store.update": [spec]}):
            with pytest.raises(Conflict):
                store.update_with_retry("TPUJob", "x", "default",
                                        lambda o: None, attempts=3)


class TestHeartbeatSite:
    def test_injected_heartbeat_loss_evicts_then_recovers(self):
        from kubedl_tpu.core.nodes import (
            EVICT_EXIT_CODE, NODE_NAMESPACE, NodeHeartbeater,
            NodeLifecycleController,
        )
        from kubedl_tpu.core.objects import Container, Pod, PodPhase
        from kubedl_tpu.core.store import ObjectStore

        store = ObjectStore()
        t = {"now": 1000.0}
        hb = NodeHeartbeater(store, ["nodeA"], clock=lambda: t["now"])
        ctrl = NodeLifecycleController(store, grace=10.0, clock=lambda: t["now"])
        hb.beat_once()
        p = Pod()
        p.metadata.name = "p1"
        p.spec.containers.append(Container())
        p.spec.node_name = "nodeA"
        p.status.phase = PodPhase.RUNNING
        store.create(p)
        ctrl.reconcile(NODE_NAMESPACE, "nodeA")  # observe the heartbeat

        with FaultPlan(5, sites={"node.heartbeat": [FaultSpec.first(2)]}) as plan:
            t["now"] = 1005.0
            hb.beat_once()  # skipped (injected miss 1)
            t["now"] = 1011.0
            hb.beat_once()  # skipped (injected miss 2) — now past grace
            ctrl.reconcile(NODE_NAMESPACE, "nodeA")
            node = store.get("Node", "nodeA", NODE_NAMESPACE)
            assert not node.ready
            got = store.get("Pod", "p1")
            assert got.status.phase == PodPhase.FAILED
            assert got.status.container_statuses[0].exit_code == EVICT_EXIT_CODE
            assert plan.faults("node.heartbeat") == 2
            hb.beat_once()  # plan spent: the kubelet comes back
            assert store.get("Node", "nodeA", NODE_NAMESPACE).ready


class TestGangBindSite:
    def test_injected_bind_rejection_queues_then_admits(self):
        from kubedl_tpu.api.types import JobConditionType

        from tests.helpers import make_tpujob
        from tests.test_engine import make_engine

        engine, store, _ = make_engine()
        job = make_tpujob("gangy", workers=1)
        store.create(job)
        with FaultPlan(3, sites={"gang.bind": [FaultSpec.first(2)]}) as plan:
            engine.reconcile("default", "gangy")
            assert store.list("Pod") == []
            assert (store.get("TPUJob", "gangy").status.phase
                    == JobConditionType.QUEUED)
            for _ in range(4):  # requeues re-admit until the plan is spent
                engine.reconcile("default", "gangy")
                if store.list("Pod"):
                    break
            assert store.list("Pod"), "bind never recovered after injected rejections"
            assert plan.faults("gang.bind") == 2


class TestClientTransportSite:
    def _client(self, once):
        from kubedl_tpu.client.http import KubeDLClient

        c = KubeDLClient("http://127.0.0.1:1")  # never actually dialed
        c._call_once = once
        return c

    def test_injected_transport_fault_is_retried(self):
        calls = []

        def once(method, path, body=None):
            calls.append(path)
            chaos.check("client.http")
            return {"ok": True}

        c = self._client(once)
        with FaultPlan(1, sites={"client.http": [FaultSpec.nth(1)]}):
            assert c._call("GET", "/x") == {"ok": True}
        assert len(calls) == 2

    def test_4xx_is_permanent_no_retry(self):
        from kubedl_tpu.client.base import ApiException

        calls = []

        def once(method, path, body=None):
            calls.append(path)
            raise ApiException(404, "nope")

        c = self._client(once)
        with pytest.raises(ApiException):
            c._call("GET", "/x")
        assert len(calls) == 1

    def test_5xx_retries_to_attempt_cap(self):
        from kubedl_tpu.client.base import ApiException

        calls = []

        def once(method, path, body=None):
            calls.append(path)
            raise ApiException(503, "overloaded")

        c = self._client(once)
        with pytest.raises(ApiException):
            c._call("GET", "/x")
        assert len(calls) == 4  # the transport policy's max_attempts


class TestRemoteBlobSite:
    def test_blob_fetch_retries_through_injected_faults(self, tmp_path):
        from kubedl_tpu.remote import RemoteStoreServer, get_blob, put_blob

        with RemoteStoreServer(str(tmp_path / "root")) as srv:
            put_blob(srv.base_url, "m/w.bin", b"weights")
            with FaultPlan(2, sites={"remote.request": [FaultSpec.first(2)]}) as plan:
                assert get_blob(srv.base_url, "m/w.bin") == b"weights"
            assert plan.faults("remote.request") == 2

    def test_blob_fetch_gives_up_on_poison(self, tmp_path):
        from kubedl_tpu.remote import RemoteStoreServer, get_blob

        with RemoteStoreServer(str(tmp_path / "root")) as srv:
            with FaultPlan(2, sites={"remote.request": [FaultSpec.always()]}):
                with pytest.raises(FaultInjected):
                    get_blob(srv.base_url, "m/w.bin")


# --------------------------------------------------------------------------
# Poison-pill quarantine
# --------------------------------------------------------------------------


class TestQuarantine:
    def test_poison_job_quarantines_exactly_once(self):
        from kubedl_tpu.api.types import JobConditionType

        from tests.helpers import make_tpujob
        from tests.test_engine import make_engine

        engine, store, metrics = make_engine()
        job = make_tpujob("poison", workers=1)
        store.create(job)
        engine.reconcile("default", "poison")  # healthy pass creates pods
        assert store.list("Pod")

        def bad(job):
            raise RuntimeError("poison pill")

        engine.reconcile_job = bad
        engine.quarantine_budget = 3
        # under budget: the exception propagates (workqueue requeues it)
        for _ in range(2):
            with pytest.raises(RuntimeError):
                engine.reconcile("default", "poison")
        # at budget: swallowed, parked — the workqueue forgets the key
        assert engine.reconcile("default", "poison") is None

        got = store.get("TPUJob", "poison")
        assert got.status.phase == JobConditionType.QUARANTINED
        cond = got.status.conditions[-1]
        assert cond.reason == "ReconcileBudgetExhausted"
        assert store.list("Pod") == []  # torn down, slices freed
        assert metrics.quarantined.value(kind="TPUJob") == 1.0
        assert any(e.reason == "Quarantined" for e in store.list("Event", None))
        assert "kubedl_tpu_jobs_quarantined" in metrics.registry.render()
        # parked means parked: further triggers no-op, the counter stays 1
        assert engine.reconcile("default", "poison") is None
        assert metrics.quarantined.value(kind="TPUJob") == 1.0

    def test_transient_failures_below_budget_never_quarantine(self):
        from tests.helpers import make_tpujob
        from tests.test_engine import make_engine

        engine, store, metrics = make_engine()
        job = make_tpujob("flaky", workers=1)
        store.create(job)
        engine.quarantine_budget = 3
        real = engine.reconcile_job
        state = {"n": 0}

        def sometimes(job):
            state["n"] += 1
            if state["n"] % 2 == 1:  # never 3 consecutive failures
                raise RuntimeError("transient")
            return real(job)

        engine.reconcile_job = sometimes
        for _ in range(6):
            try:
                engine.reconcile("default", "flaky")
            except RuntimeError:
                pass
        assert metrics.quarantined.value(kind="TPUJob") == 0.0
        assert store.list("Pod")  # the healthy passes did their work


# --------------------------------------------------------------------------
# Serving: load shedding + injected device fault recovery
# --------------------------------------------------------------------------


@pytest.fixture(scope="class")
def tiny_engine():
    from kubedl_tpu.serving.server import LlamaEngine

    eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                      max_queue_depth=2)
    yield eng
    eng.close()


class TestServingChaos:
    def test_load_shedding_bounded_and_observable(self, tiny_engine):
        import threading

        from kubedl_tpu.serving.server import EngineOverloaded

        eng = tiny_engine
        n = 12
        results = [None] * n
        barrier = threading.Barrier(n)

        def worker(i):
            barrier.wait()
            try:
                results[i] = eng.generate([i + 1], max_tokens=40)
            except EngineOverloaded as e:
                results[i] = ("shed", e.retry_after_s)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        completed = [r for r in results if isinstance(r, dict)]
        sheds = [r for r in results if isinstance(r, tuple)]
        assert all(r is not None for r in results)
        # conservation: every request either served or shed, nothing lost
        assert len(completed) + len(sheds) == n
        assert len(completed) >= 1  # shedding is bounded: the engine serves
        assert sheds, "burst of 12 against depth budget 2 never shed"
        assert all(retry >= 1.0 for _, retry in sheds)
        stats = eng.stats()
        assert stats["shed"] == len(sheds)
        assert stats["shed_recent"] == len(sheds)
        # the counter is on /metrics (predictor pods export this registry)
        rendered = eng.metrics.registry.render()
        assert "kubedl_tpu_serving_shed_requests" in rendered
        assert eng.metrics.shed_requests.value() == float(len(sheds))
        # still live after the storm
        again = eng.generate([7], max_tokens=3)
        assert len(again["token_ids"]) == 3

    def test_autoscaler_folds_shed_into_backlog(self):
        """A replica answering 503s is saturated even when its queue reads
        shallow — shed_recent must veto scale-down exactly like queued."""
        from kubedl_tpu.core.objects import PodPhase
        from kubedl_tpu.core.store import ObjectStore
        from kubedl_tpu.lineage.types import ModelVersion, ModelVersionPhase
        from kubedl_tpu.serving.controller import InferenceController
        from kubedl_tpu.serving.types import AutoScaleSpec, Inference, Predictor

        store = ObjectStore()
        mv = ModelVersion(model_name="m", phase=ModelVersionPhase.SUCCEEDED)
        mv.metadata.name = "m-v1"
        store.create(mv)
        load = {"qps": 35.0, "queued": 0, "shed_recent": 0}
        t = {"now": 0.0}
        ctrl = InferenceController(store, local_addresses=True,
                                   qps_probe=lambda pod: dict(load),
                                   clock=lambda: t["now"])
        inf = Inference()
        inf.metadata.name = "shedsvc"
        inf.predictors.append(Predictor(
            name="main", model_version="m-v1", replicas=1,
            autoscale=AutoScaleSpec(min_replicas=1, max_replicas=4,
                                    target_qps=10.0)))
        store.create(inf)

        def run_pods():
            for p in store.list("Pod"):
                if p.status.phase != PodPhase.RUNNING:
                    def mut(o):
                        o.status.phase = PodPhase.RUNNING
                    store.update_with_retry("Pod", p.metadata.name,
                                            "default", mut)

        ctrl.reconcile("default", "shedsvc")
        run_pods()
        ctrl.reconcile("default", "shedsvc")
        assert len(store.list("Pod")) == 4  # scale-up on load
        run_pods()
        # completion QPS collapses because requests are being SHED, not
        # served — the shed count must veto the scale-down
        load.update(qps=1.0, shed_recent=6)
        t["now"] += 120.0
        ctrl.reconcile("default", "shedsvc")
        assert len(store.list("Pod")) == 4
        # shedding stops -> scale-down proceeds
        load.update(shed_recent=0)
        t["now"] += 120.0
        ctrl.reconcile("default", "shedsvc")
        assert len(store.list("Pod")) == 1

    def test_injected_dispatch_fault_fails_fast_and_recovers(self, tiny_engine):
        eng = tiny_engine
        before = eng.metrics.scheduler_errors.value()
        with FaultPlan(4, sites={"serving.dispatch": [FaultSpec.nth(1)]}) as plan:
            hit = eng.generate([3, 1], max_tokens=6)
        assert plan.faults("serving.dispatch") == 1
        assert "error" in hit  # the in-flight request failed loudly...
        assert eng.metrics.scheduler_errors.value() == before + 1
        # ...and the engine rebuilt its donated cache and kept serving
        ok = eng.generate([3, 1], max_tokens=6)
        assert len(ok["token_ids"]) == 6


# --------------------------------------------------------------------------
# Torn checkpoint save
# --------------------------------------------------------------------------


class TestTornCheckpoint:
    def test_torn_save_falls_back_to_previous_good_step(self, tmp_path):
        import jax.numpy as jnp
        import numpy as np

        from kubedl_tpu.training.checkpoint import (
            latest_step, restore_checkpoint, save_checkpoint,
        )

        d = str(tmp_path / "ckpt")
        good = {"step": jnp.asarray(1), "w": jnp.arange(8.0)}
        save_checkpoint(d, good, 1)
        newer = {"step": jnp.asarray(2), "w": jnp.arange(8.0) * 2}
        with FaultPlan(9, sites={"checkpoint.torn": [FaultSpec.nth(1)]}):
            with pytest.raises(FaultInjected):
                save_checkpoint(d, newer, 2)  # dies after shards, before meta
        # the torn step-2 dir exists but is not the latest good save
        assert (tmp_path / "ckpt" / "step-00000002").is_dir()
        assert latest_step(d) == 1
        like = {"step": jnp.asarray(0), "w": jnp.zeros(8)}
        restored = restore_checkpoint(d, like)
        assert restored is not None
        assert int(restored["step"]) == 1
        np.testing.assert_allclose(np.asarray(restored["w"]), np.arange(8.0))


# --------------------------------------------------------------------------
# End-to-end: chaos plan through the full operator
# --------------------------------------------------------------------------


def _flaky_worker(env):
    """ThreadRuntime entrypoint that crashes retryably when the armed plan
    schedules a fault at the test-local ``worker.crash`` site."""
    from kubedl_tpu import chaos as _chaos

    if _chaos.should_fail("worker.crash"):
        raise SystemExit(137)  # retryable: gang restart
    return 0


class TestChaosE2E:
    def test_restart_count_matches_plan_and_job_terminates(self, tmp_path):
        from kubedl_tpu.api.types import JobConditionType
        from kubedl_tpu.operator import Operator, OperatorOptions
        from kubedl_tpu.runtime.executor import ThreadRuntime

        from tests.helpers import make_tpujob

        opts = OperatorOptions(
            local_addresses=True,
            artifact_registry_root=str(tmp_path / "reg"),
        )
        plan = FaultPlan(11, sites={"worker.crash": [FaultSpec.first(2)]})
        with plan, Operator(opts, runtime=ThreadRuntime()) as op:
            job = make_tpujob("chaosjob", workers=1,
                              entrypoint=f"{__name__}:_flaky_worker")
            op.submit(job)
            got = op.wait_for_phase(
                "TPUJob", "chaosjob",
                [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
                timeout=60,
            )
            # invariant: the job is terminal, not wedged mid-restart
            assert got.status.phase == JobConditionType.SUCCEEDED
            # invariant: observed restarts == the plan's injected crashes
            assert plan.faults("worker.crash") == 2
            assert got.status.restart_count == 2


# --------------------------------------------------------------------------
# Repo hygiene riders (r5 VERDICT satellites)
# --------------------------------------------------------------------------


class TestRepoHygiene:
    def test_no_http_junk_tree_in_repo(self):
        """r5 regression (commit 8a8bcf5): the remote e2e's unguarded final
        publish wrote a literal `http:/host/...` tree into the repo cwd and
        it got committed. The entry publish is now guarded
        (training/entry.py) — the tree must never exist again."""
        junk = [p.name for p in REPO.iterdir()
                if p.name.startswith("http:") or p.name.startswith("https:")]
        assert junk == [], f"committed URL-as-path junk tree resurfaced: {junk}"

    def test_remote_publish_guard_uploads_instead_of_mkdir(self, tmp_path,
                                                          monkeypatch):
        """The guard itself: train_main with a REMOTE model root must push
        the final checkpoint through the blob client — never save onto the
        URL as if it were a directory (which recreates the junk tree)."""
        from kubedl_tpu.remote import RemoteStoreServer, list_blobs
        from kubedl_tpu.training.entry import train_main

        monkeypatch.chdir(tmp_path)  # any junk tree would land here
        with RemoteStoreServer(str(tmp_path / "blob-root")) as srv:
            remote_root = f"{srv.base_url}/blobs/models/guard"
            monkeypatch.setenv("KUBEDL_MODEL_PATH", remote_root)
            monkeypatch.setenv("KUBEDL_TRAIN_CONFIG", json.dumps(
                {"model": "tiny", "steps": 2, "global_batch": 8,
                 "seq_len": 16, "ckpt_every": 1}
            ))
            assert train_main() == 0
            blobs = list_blobs(srv.base_url, "models/guard")
            assert any(b.endswith("latest") for b in blobs), blobs
            assert any("shards-p0" in b for b in blobs), blobs
        junk = [p for p in os.listdir(tmp_path) if p.startswith("http:")]
        assert junk == [], f"publish created URL-as-path dirs: {junk}"

    def test_readme_numbers_derivable_from_bench_artifact(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "check_readme_numbers",
            REPO / "scripts" / "check_readme_numbers.py",
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.check() == []


# --------------------------------------------------------------------------
# Injection-site registry (doc-drift guard)
# --------------------------------------------------------------------------


class TestSiteRegistry:
    """chaos.sites() is the canonical registry; the module docstring table
    and the check()/should_fail() literals in production code must both
    agree with it — a new site wired without a registry entry (or a stale
    doc row) fails here, not in a postmortem."""

    def test_docstring_table_matches_registry(self):
        import re

        import kubedl_tpu.chaos.plan as plan_mod

        block = plan_mod.__doc__.split(
            "Injection sites wired in this repo::", 1
        )[1]
        documented = set()
        for line in block.splitlines():
            s = line.strip()
            if not s:
                if documented:
                    break  # blank line after the rows ends the table
                continue
            first_col = re.split(r"\s{2,}", s)[0]
            for name in first_col.split(" / "):
                documented.add(name.strip())
        assert documented == set(chaos.sites()), (
            f"docstring table drifted from chaos.sites(): "
            f"missing={sorted(set(chaos.sites()) - documented)} "
            f"stale={sorted(documented - set(chaos.sites()))}"
        )

    def test_source_literals_match_registry(self):
        import re

        pat = re.compile(
            r"""chaos\.(?:check|should_fail)\(\s*["']([^"']+)["']"""
        )
        consulted = set()
        for p in (REPO / "kubedl_tpu").rglob("*.py"):
            consulted |= set(pat.findall(p.read_text()))
        registered = set(chaos.sites())
        assert consulted <= registered, (
            f"sites consulted in code but missing from chaos.sites(): "
            f"{sorted(consulted - registered)}"
        )
        assert registered <= consulted, (
            f"sites registered but consulted nowhere (dead registry rows): "
            f"{sorted(registered - consulted)}"
        )

    def test_sites_returns_a_copy(self):
        s = chaos.sites()
        s["bogus.site"] = "mutation"
        assert "bogus.site" not in chaos.sites()


class TestPlanFromConfig:
    """plan_from_config builds a FaultPlan from the JSON-shaped dict that
    rides KUBEDL_SERVE_CONFIG["chaos"] into subprocess replicas (the
    rollout drive arms the canary-dispatch latency fault this way)."""

    def test_builds_latency_plan(self):
        slept = []
        plan = chaos.plan_from_config(
            {"seed": 17, "sites": {"serving.canary_dispatch": [
                {"mode": "latency", "latency_ms": 250.0, "every": 1}]}},
            sleep=slept.append,
        )
        chaos.arm(plan)
        try:
            chaos.check("serving.canary_dispatch")
            chaos.check("serving.canary_dispatch")
        finally:
            chaos.disarm()
        assert slept == [0.25, 0.25]

    def test_modes_map_to_fault_specs(self):
        plan = chaos.plan_from_config({"sites": {
            "serving.dispatch": [{"mode": "nth", "n": 3}],
            "serving.kv_alloc": [{"mode": "first", "k": 2}],
            "node.heartbeat": [{"mode": "prob", "p": 0.5, "k": 4}],
            "store.update": [{"mode": "always"}],
        }})
        chaos.arm(plan)
        try:
            import pytest as _pt

            chaos.check("serving.dispatch")
            chaos.check("serving.dispatch")
            with _pt.raises(chaos.FaultInjected):
                chaos.check("serving.dispatch")
            with _pt.raises(chaos.FaultInjected):
                chaos.check("serving.kv_alloc")
            with _pt.raises(chaos.FaultInjected):
                chaos.check("store.update")
        finally:
            chaos.disarm()

    def test_rejects_unknown_site_and_mode(self):
        import pytest as _pt

        with _pt.raises(ValueError):
            chaos.plan_from_config({"sites": {"no.such.site": [
                {"mode": "always"}]}})
        with _pt.raises(ValueError):
            chaos.plan_from_config({"sites": {"serving.dispatch": [
                {"mode": "sideways"}]}})

"""Seed regression fixture (PR 6 env race, FIXED form): the entrypoint
routes through the sanctioned changed-vars guard (utils/envguard.py) —
steady-state restarts re-enter with an identical env and never touch
environ at all.
"""

from kubedl_tpu.utils.envguard import apply_env


def worker_main(env=None):
    apply_env(env)
    return 0

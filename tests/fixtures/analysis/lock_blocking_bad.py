"""Seed regression fixture (the PR 11 stats-harvest shape, BAD form):
blocking work — a device->host ``np.array`` harvest and a sleep — runs
lexically inside ``with self._cv:``, stalling every producer/consumer
parked on that condition for the duration.
"""

import threading
import time

import numpy as np


class Engine:
    def __init__(self):
        self._cv = threading.Condition()
        self._last_batch = None

    def tick(self):
        with self._cv:
            harvested = np.array(self._last_batch)
            time.sleep(0.01)
            self._cv.notify_all()
        return harvested

"""Fixed form: write/stage the whole set, then pay the durability
barrier once — same guarantee, O(batches) commits."""

import os


def append_all(f, records):
    for rec in records:
        f.write(rec)
    f.flush()
    os.fsync(f.fileno())  # one commit for the batch


def stage_all(wal, batch):
    ticket = None
    for op in batch:
        ticket = wal.append(op)
    wal.wait_durable(ticket)  # the last ticket covers every earlier one

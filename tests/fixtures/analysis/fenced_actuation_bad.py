"""Seed fixture: the unfenced-actuation shape PR 20's federation forbids
— a reconcile reserves slice capacity in pure memory and launches a pod
batch without ever consulting the shard's fencing token, so a SIGSTOP'd
owner resumed past its lease TTL replays both against a shard a live
member now owns."""


def admit_gang(scheduler, gang, owner):
    assigned = scheduler.inventory.try_reserve(
        gang.slice_type, gang.num_slices, owner
    )  # memory-only reservation, no fence consulted
    if not assigned:
        return False
    scheduler.store.update_with_retry(
        "PodGroup", gang.metadata.name, gang.metadata.namespace, lambda o: o
    )
    return True


def launch_pods(store, pods):
    return store.create_many(pods)  # externally visible, unfenced


def reap_pod(store, pod):
    store.try_delete("Pod", pod.metadata.name, pod.metadata.namespace)

"""Seed regression fixture (PR 8 mirror-borrow bug, FIXED form): the
``_upload_mirror`` pattern — ``jnp.asarray(arr) + 0`` — materializes an
XLA-owned copy so the donated cache can never alias the host mirror.
"""

import jax
import jax.numpy as jnp
import numpy as np


def _decode_step(cache, block_table):
    return cache


class Decoder:
    def __init__(self):
        self._bt_host = np.zeros((4, 4), dtype=np.int32)
        self._decode = jax.jit(_decode_step, donate_argnums=(0,))

    def _upload_mirror(self, arr):
        return jnp.asarray(arr) + 0

    def step(self, cache):
        bt = self._upload_mirror(self._bt_host)
        return self._decode(cache, bt)

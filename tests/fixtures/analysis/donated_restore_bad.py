"""Seed regression fixture (the PR 6 restore bug, BAD form): checkpoint
leaves are zero-copy borrowed from the aligned host read buffer
(np.frombuffer -> jnp.asarray) and then DONATED on the first train step —
donation frees XLA to recycle the mmap'd heap under the live weights.
Never imported; parsed by tests/test_analysis.py through analyze_file.
"""

import jax
import jax.numpy as jnp
import numpy as np


def _train_step(params, batch):
    return params


class Restorer:
    def __init__(self):
        self._step = jax.jit(_train_step, donate_argnums=(0,))

    def restore_and_step(self, path, batch):
        raw = open(path, "rb").read()
        leaves = np.frombuffer(raw, dtype=np.float32)
        params = jnp.asarray(leaves)
        return self._step(params, batch)

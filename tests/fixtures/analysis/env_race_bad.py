"""Seed regression fixture (the PR 6 env race, BAD form): a worker
entrypoint unconditionally rewrites ``os.environ`` on every gang-restart
re-entry. glibc setenv may realloc the environ block, racing native
getenv from XLA's persistent worker threads in the same process.
"""

import os


def worker_main(env=None):
    if env:
        for k, v in env.items():
            os.environ[k] = v
    return 0

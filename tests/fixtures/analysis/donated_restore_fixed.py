"""Seed regression fixture (PR 6 restore bug, FIXED form): the defensive
``+ 0`` forces an XLA-owned buffer before donation, so recycling the
donated input never touches the checkpoint read buffer.
"""

import jax
import jax.numpy as jnp
import numpy as np


def _train_step(params, batch):
    return params


class Restorer:
    def __init__(self):
        self._step = jax.jit(_train_step, donate_argnums=(0,))

    def restore_and_step(self, path, batch):
        raw = open(path, "rb").read()
        leaves = np.frombuffer(raw, dtype=np.float32)
        params = jnp.asarray(leaves) + 0
        return self._step(params, batch)

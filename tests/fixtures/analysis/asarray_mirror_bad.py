"""Seed regression fixture (the PR 8 mirror-borrow bug, BAD form):
``jnp.asarray`` of a persistent numpy host mirror (``self._bt_host``)
passed into a call whose donated cache lets XLA alias segment outputs
onto the borrowed mirror memory. Canonical fix lives in
serving/server.py ``_upload_mirror``.
"""

import jax
import jax.numpy as jnp
import numpy as np


def _decode_step(cache, block_table):
    return cache


class Decoder:
    def __init__(self):
        self._bt_host = np.zeros((4, 4), dtype=np.int32)
        self._decode = jax.jit(_decode_step, donate_argnums=(0,))

    def step(self, cache):
        bt = jnp.asarray(self._bt_host)
        return self._decode(cache, bt)

"""Fixed shape: every externally-visible actuation is gated by
assert_fenced_actuation earlier in the same function, so a deposed or
stale owner rejects the whole side effect (FencedOut) before any part
of it — including the memory-only inventory reservation — fires."""

from kubedl_tpu.federation.actuation import assert_fenced_actuation


def admit_gang(scheduler, gang, owner):
    assert_fenced_actuation(
        scheduler.store, gang.metadata.namespace, gang.metadata.name,
        action="gang bind",
    )
    assigned = scheduler.inventory.try_reserve(
        gang.slice_type, gang.num_slices, owner
    )
    if not assigned:
        return False
    scheduler.store.update_with_retry(
        "PodGroup", gang.metadata.name, gang.metadata.namespace, lambda o: o
    )
    return True


def launch_pods(store, job, pods):
    assert_fenced_actuation(
        store, job.metadata.namespace, job.metadata.name, action="pod launch"
    )
    return store.create_many(pods)


def reap_pod(store, root, pod):
    assert_fenced_actuation(
        store, pod.metadata.namespace, root, action="pod delete"
    )
    store.try_delete("Pod", pod.metadata.name, pod.metadata.namespace)

"""Seed fixture: the per-iteration durability barrier BENCH_r18 measured
at scale — one fsync per appended record (220k fsyncs for 220k appends),
and one wait_durable per staged ticket, each of which re-serializes the
whole batch behind a commit it could have shared."""

import os


def append_all(f, records):
    for rec in records:
        f.write(rec)
        f.flush()
        os.fsync(f.fileno())  # one commit per record


def stage_all(wal, batch):
    for op in batch:
        ticket = wal.append(op)
        wal.wait_durable(ticket)  # re-serializes the group commit

"""Seed regression fixture (PR 11 stats-harvest shape, FIXED form): the
``_spec_tick`` pattern — snapshot references under the lock, do the
blocking device harvest outside it, re-acquire to publish.
"""

import threading
import time

import numpy as np


class Engine:
    def __init__(self):
        self._cv = threading.Condition()
        self._last_batch = None
        self._published = None

    def tick(self):
        with self._cv:
            snapshot = self._last_batch
        harvested = np.array(snapshot)
        time.sleep(0.01)
        with self._cv:
            self._published = harvested
            self._cv.notify_all()
        return harvested

"""Rollout-controller tests (docs/serving.md "Model lifecycle"): the
weight ladder, the SLO-burn rollback gate on the canary's OWN partition,
the RolledBack condition's postmortem payload, and the re-promotion
fence — all on a fake clock, no sockets."""

import pytest

from kubedl_tpu.serving.rollout import (
    COMPLETE,
    PENDING,
    PROGRESSING,
    ROLLED_BACK,
    RolloutController,
    RolloutFenced,
)
from kubedl_tpu.serving.router import ServingRouter


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


#: One tight alert pair so tests can burn it with a handful of events:
#: objective 90%, page when both the 5s and 30s windows burn >= 2x.
SLO = {
    "objective": 0.9,
    "latency_objective_ms": None,
    "alerts": [{"severity": "page", "short_s": 5.0, "long_s": 30.0,
                "threshold": 2.0}],
}


def _rig(soak_s=10.0):
    clk = FakeClock()
    router = ServingRouter(hedge_enabled=False, clock=clk, slo=SLO)
    ctrl = RolloutController(router, canary_version="v2",
                             baseline_version="v1",
                             steps=(1, 10, 50, 100),
                             soak_s=soak_s, clock=clk)
    return clk, router, ctrl


def _burn(router, version, n=20, trace_id="t-bad"):
    """Feed the version's partition enough failures to fire both windows."""
    tr = router.version_tracker(version)
    for _ in range(n):
        tr.observe(ok=False, latency_ms=1.0, trace_id=trace_id)


class TestLadder:
    def test_clean_soak_walks_ladder_then_promotes(self):
        clk, router, ctrl = _rig(soak_s=10.0)
        ctrl.begin()
        assert ctrl.phase == PROGRESSING
        assert router.version_weights() == {"v1": 99, "v2": 1}
        assert ctrl.tick() == "soaking"  # soak not elapsed
        for expect in ({"v1": 90, "v2": 10}, {"v1": 50, "v2": 50},
                       {"v1": 0, "v2": 100}):
            clk.t += 10.0
            assert ctrl.tick() == "advanced"
            assert router.version_weights() == expect
        clk.t += 10.0
        assert ctrl.tick() == "promoted"
        assert ctrl.phase == COMPLETE
        assert router.version_weights() == {"v1": 0, "v2": 100}
        assert ctrl.tick() == "idle"  # terminal: no further action
        m = router.metrics
        assert m.rollout_events.value(event="advance") == 3.0
        assert m.rollout_events.value(event="promote") == 1.0

    def test_begin_is_idempotent_while_progressing(self):
        clk, router, ctrl = _rig()
        ctrl.begin()
        clk.t += 10.0
        ctrl.tick()
        ctrl.begin()  # no-op: must not reset the ladder to step 0
        assert router.version_weights() == {"v1": 90, "v2": 10}

    def test_step_validation(self):
        clk, router, _ = _rig()
        for bad in ((), (10, 5, 100), (50,), (0, 100), (1, 10, 110)):
            with pytest.raises(ValueError):
                RolloutController(router, "v2", "v1", steps=bad, clock=clk)
        with pytest.raises(ValueError):
            RolloutController(router, "v1", "v1", clock=clk)


class TestRollback:
    def test_canary_burn_rolls_back_in_one_flip(self):
        clk, router, ctrl = _rig(soak_s=10.0)
        ctrl.begin()
        _burn(router, "v2", trace_id="t-exemplar")
        assert ctrl.tick() == "rolled_back"
        assert ctrl.phase == ROLLED_BACK
        # ONE weight flip: baseline owns everything, canary fenced at 0
        assert router.version_weights() == {"v1": 100, "v2": 0}
        assert router.metrics.rollout_events.value(event="rollback") == 1.0
        assert router.metrics.version_burning.value(
            version="v2", severity="page") == 1.0
        assert ctrl.tick() == "idle"

    def test_rollback_fires_mid_soak_not_just_on_advance(self):
        clk, router, ctrl = _rig(soak_s=1000.0)
        ctrl.begin()
        _burn(router, "v2")
        # the soak timer has NOT elapsed — burn still wins immediately
        assert ctrl.tick() == "rolled_back"

    def test_rolled_back_condition_carries_postmortem_payload(self):
        clk, router, ctrl = _rig()
        ctrl.begin()
        _burn(router, "v2", trace_id="t-1234")
        ctrl.tick()
        cond = ctrl.conditions[-1]
        assert cond["type"] == "RolledBack" and cond["reason"] == "SLOBurn"
        assert cond["severity"] == "page"
        assert cond["short_s"] == 5.0 and cond["long_s"] == 30.0
        assert cond["short_burn"] >= 2.0 and cond["long_burn"] >= 2.0
        assert cond["trace_id"] == "t-1234"  # the exemplar: /v1/trace entry
        assert "t-1234" in cond["message"]

    def test_baseline_burn_does_not_roll_back(self):
        """The gate reads the canary's OWN partition: a baseline that is
        also unhealthy must not blame (or mask) the canary."""
        clk, router, ctrl = _rig(soak_s=10.0)
        ctrl.begin()
        clk.t += 10.0
        _burn(router, "v1")  # fresh burn, inside both windows at tick
        assert ctrl.tick() == "advanced"
        assert router.metrics.version_burning.value(
            version="v1", severity="page") == 1.0
        assert router.metrics.version_burning.value(
            version="v2", severity="page") == 0.0

    def test_burn_clears_with_time_window_rule(self):
        """Both windows must burn: once the short window ages out the
        bad events, the alert clears and the ladder advances again."""
        clk, router, ctrl = _rig(soak_s=10.0)
        ctrl.begin()
        tr = router.version_tracker("v2")
        for _ in range(20):
            tr.observe(ok=False, latency_ms=1.0)
        clk.t += 6.0  # past short_s: the 5s window is clean now
        for _ in range(5):
            tr.observe(ok=True, latency_ms=1.0)
        clk.t += 4.0  # soak elapsed; long window still dirty, short not
        assert ctrl.tick() == "advanced"


class TestFence:
    def test_rolled_back_version_is_fenced_until_cleared(self):
        clk, router, ctrl = _rig()
        ctrl.begin()
        _burn(router, "v2")
        ctrl.tick()
        assert "v2" in ctrl.fenced()
        with pytest.raises(RolloutFenced):
            ctrl.begin()
        assert ctrl.clear_fence() is True
        assert ctrl.clear_fence() is False  # idempotent
        assert ctrl.phase == PENDING
        assert router.metrics.rollout_events.value(
            event="fence_cleared") == 1.0
        ctrl.begin()  # manual clear re-opens promotion
        assert ctrl.phase == PROGRESSING
        assert router.version_weights() == {"v1": 99, "v2": 1}

    def test_status_surfaces_fence_and_conditions(self):
        clk, router, ctrl = _rig()
        ctrl.begin()
        _burn(router, "v2")
        ctrl.tick()
        st = ctrl.status()
        assert st["phase"] == ROLLED_BACK
        assert st["fenced"] == ["v2"]
        assert st["weight"] == 0
        assert any(c["type"] == "RolledBack" for c in st["conditions"])

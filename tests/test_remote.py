"""Network-remote storage (VERDICT r2 #8): the HTTP blob + persist store.

Every prior backend/provider was local-disk; these tests prove both
registries across a REAL network boundary (a localhost HTTP server):
unit coverage for blobs and the persist RPC, then the full e2e — train
-> staging -> MV build uploads blobs -> serving fetches blobs over HTTP
and serves the TRAINED weights, while the persist mirror writes job/pod/
event rows through the same server.
"""

import json
import os
import time

import pytest

from kubedl_tpu.persist.backends import Query
from kubedl_tpu.persist.dmo import EventInfo, JobInfo, ReplicaInfo
from kubedl_tpu.persist.http_backend import HTTPBackend
from kubedl_tpu.remote import (
    RemoteStoreServer,
    download_tree,
    get_blob,
    is_remote_root,
    list_blobs,
    put_blob,
    upload_tree,
)


@pytest.fixture()
def server(tmp_path):
    with RemoteStoreServer(str(tmp_path / "remote-root")) as srv:
        yield srv


class TestBlobs:
    def test_put_get_list_roundtrip(self, server):
        put_blob(server.base_url, "a/b.bin", b"hello")
        put_blob(server.base_url, "a/c.bin", b"world")
        put_blob(server.base_url, "z.bin", b"!")
        assert get_blob(server.base_url, "a/b.bin") == b"hello"
        assert list_blobs(server.base_url, "a") == ["a/b.bin", "a/c.bin"]
        assert len(list_blobs(server.base_url)) == 3

    def test_traversal_rejected(self, server):
        from kubedl_tpu.remote.client import RemoteError

        with pytest.raises(RemoteError):
            get_blob(server.base_url, "../../etc/passwd")

    def test_tree_roundtrip(self, server, tmp_path):
        src = tmp_path / "src"
        (src / "sub").mkdir(parents=True)
        (src / "x.txt").write_bytes(b"x")
        (src / "sub" / "y.txt").write_bytes(b"y")
        root = f"{server.base_url}/blobs/model/v1"
        assert is_remote_root(root)
        assert upload_tree(str(src), root) == 2
        dest = tmp_path / "dest"
        assert download_tree(root, str(dest)) == 2
        assert (dest / "sub" / "y.txt").read_bytes() == b"y"


class TestHTTPPersist:
    def test_job_rows_over_the_wire(self, server):
        b = HTTPBackend(server.base_url)
        b.initialize()
        b.save_job(JobInfo(uid="u1", name="j1", kind="TPUJob",
                           phase="Running", created_at=1.0))
        got = b.get_job("default", "j1", "TPUJob")
        assert got is not None and got.uid == "u1" and got.phase == "Running"
        b.save_job(JobInfo(uid="u1", name="j1", kind="TPUJob",
                           phase="Succeeded", created_at=1.0))
        rows = b.list_jobs(Query(kind="TPUJob"))
        assert [r.phase for r in rows] == ["Succeeded"]
        b.mark_job_deleted("default", "j1", "TPUJob")
        rows = b.list_jobs(Query(kind="TPUJob", include_deleted=True))
        assert rows and not rows[0].is_in_etcd

    def test_pods_and_events(self, server):
        b = HTTPBackend(server.base_url)
        b.save_pod(ReplicaInfo(uid="p1", name="j1-worker-0", job_uid="u1",
                               replica_type="Worker", phase="Running"))
        pods = b.list_pods("u1")
        assert [p.name for p in pods] == ["j1-worker-0"]
        b.save_event(EventInfo(name="e1", involved_kind="TPUJob",
                               involved_name="j1", reason="Created",
                               last_timestamp=2.0))
        evs = b.list_events("TPUJob", "j1")
        assert [e.reason for e in evs] == ["Created"]


class TestRemoteE2E:
    def test_train_build_serve_and_persist_through_http(self, tmp_path):
        """The VERDICT done-criterion: persist mirror + MV build + serving
        load round-trip through the network store."""
        import urllib.request

        from kubedl_tpu.api.types import (
            JobConditionType, ModelVersionSpecRef, ReplicaSpec, ReplicaType,
            RestartPolicy,
        )
        from kubedl_tpu.core.objects import Container, EnvVar
        from kubedl_tpu.lineage.storage import RemoteBlobProvider, register_storage_provider
        from kubedl_tpu.lineage.types import ModelVersionPhase
        from kubedl_tpu.operator import Operator, OperatorOptions
        from kubedl_tpu.runtime.executor import ThreadRuntime
        from kubedl_tpu.serving.types import Framework, Inference, Predictor
        from kubedl_tpu.workloads.tpujob import TPUJob

        with RemoteStoreServer(str(tmp_path / "remote-root")) as srv:
            # isolate this test's staging from other runs
            register_storage_provider(
                RemoteBlobProvider(staging_root=str(tmp_path / "staging"))
            )
            remote_root = f"{srv.base_url}/blobs/models/m1"
            opts = OperatorOptions(
                local_addresses=True,
                pod_log_dir=str(tmp_path / "logs"),
                artifact_registry_root=str(tmp_path / "reg"),
                meta_storage="http", event_storage="http",
                remote_storage_url=srv.base_url,
            )
            with Operator(opts, runtime=ThreadRuntime()) as op:
                job = TPUJob()
                job.metadata.name = "rtrain"
                spec = ReplicaSpec(
                    replicas=1, restart_policy=RestartPolicy.ON_FAILURE_SLICE
                )
                spec.template.spec.containers.append(Container(
                    entrypoint="kubedl_tpu.training.entry:train_main",
                    env=[EnvVar("KUBEDL_TRAIN_CONFIG", json.dumps(
                        {"model": "tiny", "steps": 4, "global_batch": 8,
                         "seq_len": 32}
                    ))],
                ))
                job.spec.replica_specs[ReplicaType.WORKER] = spec
                job.spec.model_version = ModelVersionSpecRef(
                    model_name="m1", storage_root=remote_root,
                    storage_provider="http",
                )
                op.submit(job)
                got = op.wait_for_phase(
                    "TPUJob", "rtrain",
                    [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
                    timeout=120,
                )
                assert got.status.phase == JobConditionType.SUCCEEDED

                # MV builds; artifact_dir publishes staging -> remote blobs
                deadline = time.time() + 60
                mv = None
                while time.time() < deadline:
                    mvs = op.store.list("ModelVersion", "default")
                    if mvs and mvs[0].phase in (
                        ModelVersionPhase.SUCCEEDED, ModelVersionPhase.FAILED
                    ):
                        mv = mvs[0]
                        break
                    time.sleep(0.2)
                assert mv is not None and mv.phase == ModelVersionPhase.SUCCEEDED, (
                    mv and mv.message
                )
                blobs = list_blobs(srv.base_url, "models/m1")
                assert any("shards-p0" in b for b in blobs), blobs
                assert any(b.endswith("latest") for b in blobs), blobs

                # serving fetches the blobs over HTTP and serves trained
                # weights (compare against a direct local engine)
                port = 18095
                pred = Predictor(name="main", model_version=mv.metadata.name)
                pred.template.spec.main_container().set_env(
                    "KUBEDL_SERVE_CONFIG",
                    json.dumps({"port": port, "preset": "tiny"}),
                )
                inf = Inference(framework=Framework.JAX, predictors=[pred])
                inf.metadata.name = "rserve"
                op.store.create(inf)

                result = None
                deadline = time.time() + 90
                while time.time() < deadline and result is None:
                    try:
                        req = urllib.request.Request(
                            f"http://127.0.0.1:{port}/v1/generate",
                            data=json.dumps({"prompt_ids": [3, 7],
                                             "max_tokens": 5}).encode(),
                            headers={"Content-Type": "application/json"},
                        )
                        with urllib.request.urlopen(req, timeout=5) as resp:
                            result = json.loads(resp.read())
                    except Exception:
                        time.sleep(0.5)
                assert result is not None, "remote-backed server never answered"

                from kubedl_tpu.serving.server import LlamaEngine

                local_dir = tmp_path / "local-copy"
                download_tree(remote_root, str(local_dir))
                eng = LlamaEngine(preset="tiny", ckpt_dir=str(local_dir))
                try:
                    want = eng.generate([3, 7], max_tokens=5)["token_ids"]
                finally:
                    eng.close()
                assert result["token_ids"] == want

                # persist mirror wrote THROUGH the network store
                rows = srv.backend.list_jobs(Query(kind="TPUJob"))
                assert [r.name for r in rows] == ["rtrain"]
                assert rows[0].phase == "Succeeded"
                pods = srv.backend.list_pods(rows[0].uid)
                assert pods and pods[0].name == "rtrain-worker-0"
                evs = srv.backend.list_events("TPUJob", "rtrain")
                assert any(e.reason for e in evs)

        # r5 regression (commit 8a8bcf5): this exact flow once wrote a
        # literal `http:/host/...` tree into the process cwd because the
        # final publish treated the remote model root as a directory. The
        # entry publish is now guarded (training/entry.py is_remote_root);
        # assert the junk tree can never come back.
        junk = [p for p in os.listdir(".")
                if p.startswith("http:") or p.startswith("https:")]
        assert junk == [], f"remote e2e recreated URL-as-path dirs in cwd: {junk}"


class TestBlobEdgeCases:
    """Review r3 findings: prefix boundaries, in-flight temp files,
    unencoded keys."""

    def test_prefix_matches_on_path_boundary(self, server):
        put_blob(server.base_url, "models/m1/w.bin", b"one")
        put_blob(server.base_url, "models/m10/w.bin", b"ten")
        assert list_blobs(server.base_url, "models/m1") == ["models/m1/w.bin"]
        assert list_blobs(server.base_url, "models/m10") == ["models/m10/w.bin"]

    def test_inflight_tmp_uploads_invisible(self, server, tmp_path):
        put_blob(server.base_url, "m/a.bin", b"done")
        # simulate a crashed/in-progress PUT's temp file on the server
        (server.root / "m" / "b.bin.tmp-upload").write_bytes(b"partial")
        assert list_blobs(server.base_url, "m") == ["m/a.bin"]
        # and the reserved suffix can't be uploaded or fetched directly
        from kubedl_tpu.remote.client import RemoteError

        with pytest.raises(RemoteError):
            put_blob(server.base_url, "m/x.tmp-upload", b"no")
        with pytest.raises(RemoteError):
            get_blob(server.base_url, "m/b.bin.tmp-upload")

    def test_keys_with_spaces_and_specials(self, server):
        put_blob(server.base_url, "team a/m#1/w&x.bin", b"odd")
        assert list_blobs(server.base_url, "team a") == ["team a/m#1/w&x.bin"]
        assert get_blob(server.base_url, "team a/m#1/w&x.bin") == b"odd"

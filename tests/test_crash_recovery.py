"""Control-plane crash recovery (docs/robustness.md "Crash recovery"):
WAL-backed ObjectStore, cold-start rehydration, pod adoption, gang
re-reservation, and the observability that rides along.

The acceptance spine is the kill-recover e2e: N jobs running under a
WAL-backed operator, hard-kill mid-reconcile, restart on the same WAL dir,
and the new incarnation adopts every running pod (zero duplicate launches),
re-reserves the identical gang slice assignments, and finishes the job that
was caught mid-gang-create."""

import os
import shutil
import sys
import time

import numpy as np
import pytest

from kubedl_tpu import chaos
from kubedl_tpu.chaos import FaultInjected, FaultPlan, FaultSpec
from kubedl_tpu.core.objects import Pod, PodGroup, PodPhase, new_uid
from kubedl_tpu.core.store import Conflict, ObjectStore
from kubedl_tpu.core.wal import WalCorruption, WriteAheadLog

from tests.helpers import make_tpujob


@pytest.fixture(autouse=True)
def _disarmed():
    chaos.disarm()
    yield
    chaos.disarm()


def _pod(name: str, phase: PodPhase = PodPhase.PENDING) -> Pod:
    p = Pod()
    p.metadata.name = name
    p.status.phase = phase
    return p


# ---------------------------------------------------------------------------
# WAL unit behavior
# ---------------------------------------------------------------------------


class TestWalStore:
    def test_round_trip_rehydration(self, tmp_path):
        wal = str(tmp_path / "wal")
        s1 = ObjectStore(wal_dir=wal)
        p1 = s1.create(_pod("p1"))
        s1.create(_pod("p2"))
        g = PodGroup(min_member=2, slice_type="v5e-8",
                     assigned_slices=["s1"], phase="Running")
        g.metadata.name = "gang1"
        s1.create(g)
        # mutate + delete must replay too
        p1.status.phase = PodPhase.RUNNING
        s1.update(p1)
        s1.delete("Pod", "p2", "default")
        rv = s1.revision
        s1.close()

        s2 = ObjectStore(wal_dir=wal)
        assert s2.rehydrated and s2.replayed_records > 0
        assert s2.revision == rv
        got = s2.get("Pod", "p1")
        assert got.status.phase == PodPhase.RUNNING
        assert got.metadata.uid == p1.metadata.uid
        assert s2.try_get("Pod", "p2") is None  # delete survived replay
        gg = s2.get("PodGroup", "gang1")
        assert gg.phase == "Running" and gg.assigned_slices == ["s1"]
        # optimistic concurrency still works against replayed objects
        got.status.phase = PodPhase.SUCCEEDED
        s2.update(got)
        stale = s1.get("Pod", "p1")  # from the dead incarnation's memory
        stale.status.reason = "stale"
        with pytest.raises(Conflict):
            s2.update(stale)

    def test_fresh_dir_is_not_rehydrated(self, tmp_path):
        s = ObjectStore(wal_dir=str(tmp_path / "wal"))
        assert not s.rehydrated and s.replayed_records == 0

    def test_uid_floor_prevents_collisions(self, tmp_path):
        wal = str(tmp_path / "wal")
        s1 = ObjectStore(wal_dir=wal)
        p = s1.create(_pod("p1"))
        s1.close()
        s2 = ObjectStore(wal_dir=wal)
        adopted_uid = s2.get("Pod", "p1").metadata.uid
        assert adopted_uid == p.metadata.uid
        # a fresh object minted AFTER rehydration must not reuse an
        # adopted uid (adoption matches pods by (name, uid))
        fresh = s2.create(_pod("p-new"))
        assert fresh.metadata.uid != adopted_uid

    def test_torn_append_applies_nothing(self, tmp_path):
        """A crash mid-append (torn record) must leave memory and the
        caller's object untouched, and replay must truncate the torn tail
        instead of refusing to start."""
        wal = str(tmp_path / "wal")
        s1 = ObjectStore(wal_dir=wal)
        s1.create(_pod("good"))
        with FaultPlan(1, sites={"store.wal_append": [FaultSpec.nth(1)]}):
            torn = _pod("torn")
            with pytest.raises(FaultInjected):
                s1.create(torn)
        assert s1.try_get("Pod", "torn") is None  # not applied to memory
        assert torn.metadata.resource_version == 0  # caller untouched
        # the WAL is now crash-only: further writes refuse instead of
        # appending after a known-torn tail
        with pytest.raises(WalCorruption):
            s1.create(_pod("after"))

        s2 = ObjectStore(wal_dir=wal)
        assert s2.try_get("Pod", "good") is not None
        assert s2.try_get("Pod", "torn") is None
        # the truncated log accepts appends again
        s2.create(_pod("after"))
        s2.close()
        s3 = ObjectStore(wal_dir=wal)
        assert {p.metadata.name for p in s3.list("Pod")} == {"good", "after"}

    def test_corrupted_record_rejected(self, tmp_path):
        wal = str(tmp_path / "wal")
        s1 = ObjectStore(wal_dir=wal)
        s1.create(_pod("p1"))
        s1.close()
        log_file = tmp_path / "wal" / "wal.log"
        raw = bytearray(log_file.read_bytes())
        raw[12] ^= 0xFF  # flip a payload byte, lengths intact
        log_file.write_bytes(bytes(raw))
        with pytest.raises(WalCorruption):
            ObjectStore(wal_dir=wal)

    def test_snapshot_bounds_replay(self, tmp_path):
        """Compaction: with snapshot_every=10, 100 writes must leave a
        snapshot + a short tail, not a 100-record log — replay cost is
        O(live objects + tail), not O(history)."""
        wal = str(tmp_path / "wal")
        s1 = ObjectStore(wal_dir=wal, wal_snapshot_every=10)
        p = s1.create(_pod("hot"))
        for i in range(100):
            p.status.reason = f"tick-{i}"
            s1.update(p)
        s1.close()
        snap = WriteAheadLog(wal)
        snap_rev, snap_objs, records = snap.recover()
        snap.close()
        assert snap_rev > 0 and len(snap_objs) == 1  # one live object
        assert len(records) <= 10  # tail only
        s2 = ObjectStore(wal_dir=wal)
        assert s2.get("Pod", "hot").status.reason == "tick-99"
        assert s2.revision == 101

    def test_explicit_compact(self, tmp_path):
        wal = str(tmp_path / "wal")
        s1 = ObjectStore(wal_dir=wal)
        for i in range(5):
            s1.create(_pod(f"p{i}"))
        s1.compact()
        assert os.path.getsize(tmp_path / "wal" / "wal.log") == 0
        s1.close()
        s2 = ObjectStore(wal_dir=wal)
        assert len(s2.list("Pod")) == 5

    def test_fsync_policy_knob(self, tmp_path):
        with pytest.raises(ValueError):
            ObjectStore(wal_dir=str(tmp_path / "w1"), wal_fsync="sometimes")
        for policy in ("always", "batch", "off"):
            d = str(tmp_path / f"wal-{policy}")
            s = ObjectStore(wal_dir=d, wal_fsync=policy)
            s.create(_pod("p1"))
            fsyncs = s.wal_fsyncs
            s.close()
            if policy == "always":
                assert fsyncs >= 1
            else:
                assert fsyncs == 0
            assert ObjectStore(wal_dir=d).try_get("Pod", "p1") is not None

    def test_fsync_fault_injected(self, tmp_path):
        s = ObjectStore(wal_dir=str(tmp_path / "wal"), wal_fsync="always")
        with FaultPlan(1, sites={"store.wal_fsync": [FaultSpec.nth(1)]}):
            with pytest.raises(FaultInjected):
                s.create(_pod("p1"))

    def test_group_policy_round_trips(self, tmp_path):
        d = str(tmp_path / "wal-group")
        s = ObjectStore(wal_dir=d, wal_fsync="group", wal_group_window=0.001)
        s.create(_pod("p1"))
        s.close()
        assert ObjectStore(wal_dir=d).try_get("Pod", "p1") is not None

    def test_wal_off_store_has_zero_overhead_path(self):
        s = ObjectStore()
        assert s._wal is None and s.wal_appends == 0
        t0 = time.perf_counter()
        pods = [s.create(_pod(f"p{i}")) for i in range(1500)]
        for p in pods:
            p.status.phase = PodPhase.RUNNING
            s.update(p)
        elapsed = time.perf_counter() - t0
        # generous guard (scheduler_microbench owns the tight budget):
        # 3000 ops of pure-memory store work must stay fast
        assert elapsed < 5.0, f"WAL-off store slowed down: {elapsed:.2f}s"


class TestGroupCommit:
    """WAL group commit (``fsync="group"``): fsync-before-ack durability,
    O(batches) fsyncs instead of O(appends), per-batch fsync floor, and
    the crash/chaos contract — acknowledged records always replay,
    unacknowledged ones may be lost, never the reverse."""

    def test_batch_amortizes_fsyncs(self, tmp_path):
        s = ObjectStore(wal_dir=str(tmp_path / "wal"), wal_fsync="group",
                        wal_group_window=0.005)
        s.create_many([_pod(f"p{i}") for i in range(32)])
        # one staged burst, one (maybe two) covering fsyncs — never 32
        assert s.wal_appends == 32
        assert s.wal_fsyncs < 32 and s.wal_batches >= 1
        assert s.wal_batch_records == 32
        s.close()
        s2 = ObjectStore(wal_dir=str(tmp_path / "wal"))
        assert len(s2.list("Pod")) == 32

    def test_acked_records_survive_crash_without_close(self, tmp_path):
        """fsync-before-ack: once create() returned, the record is exactly
        as durable as under fsync="always" — a hard crash (no close(), no
        final fsync) must replay it."""
        wal = str(tmp_path / "wal")
        s1 = ObjectStore(wal_dir=wal, wal_fsync="group",
                         wal_group_window=0.001)
        s1.create(_pod("acked"))  # returned => batched fsync covered it
        # simulate the hard crash: drop the store without close()
        s2 = ObjectStore(wal_dir=wal)
        assert s2.try_get("Pod", "acked") is not None

    def test_failed_group_commit_poisons_log(self, tmp_path):
        """A failed batch fsync is the crash seam: the waiting writer gets
        WalCorruption (its write is UNacknowledged), the log goes
        crash-only, and replay still holds every earlier acked record."""
        wal = str(tmp_path / "wal")
        s1 = ObjectStore(wal_dir=wal, wal_fsync="group",
                         wal_group_window=0.001)
        s1.create(_pod("acked"))
        with FaultPlan(1, sites={"store.wal_group_commit":
                                 [FaultSpec.always()]}):
            with pytest.raises(WalCorruption):
                s1.create(_pod("unacked"))
        # crash-only from here: later writes refuse loudly
        with pytest.raises(WalCorruption):
            s1.create(_pod("after"))
        s2 = ObjectStore(wal_dir=wal)
        # the contract is one-sided: acked always replays; the unacked
        # record's bytes were staged so it MAY replay — never assert on it
        assert s2.try_get("Pod", "acked") is not None
        assert s2.try_get("Pod", "after") is None

    def test_fsync_floor_applies_per_batch(self, tmp_path):
        """The commit floor (modeling etcd-class disks) is paid once per
        batched fsync, not once per record — the whole point of group
        commit. 16 records at a 30ms floor must cost ~1 floor, nowhere
        near 16."""
        floor = 0.03
        s = ObjectStore(wal_dir=str(tmp_path / "wal"), wal_fsync="group",
                        wal_group_window=0.0, wal_fsync_floor=floor)
        t0 = time.perf_counter()
        s.create_many([_pod(f"p{i}") for i in range(16)])
        elapsed = time.perf_counter() - t0
        assert elapsed >= floor  # the ack really waited for a commit
        assert elapsed < 16 * floor / 2  # and not one commit per record
        assert s.wal_fsyncs <= 4
        s.close()

    def test_concurrent_writers_share_one_commit_window(self, tmp_path):
        """N threads creating concurrently must overlap their ack waits:
        total fsyncs stays O(batches) and every write is durable."""
        s = ObjectStore(wal_dir=str(tmp_path / "wal"), wal_fsync="group",
                        wal_group_window=0.01)
        errs = []

        def writer(base):
            try:
                for i in range(10):
                    s.create(_pod(f"w{base}-{i}"))
            except Exception as e:  # pragma: no cover - surfaced below
                errs.append(e)

        import threading
        threads = [threading.Thread(target=writer, args=(b,))
                   for b in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert s.wal_appends == 80
        assert s.wal_fsyncs < 80  # batches shared across writers
        s.close()
        s2 = ObjectStore(wal_dir=str(tmp_path / "wal"))
        assert len(s2.list("Pod")) == 80


class TestWatchGapRobustness:
    def test_since_revision_replays_missed_changes(self, tmp_path):
        s = ObjectStore()
        s.create(_pod("old"))
        rev = s.revision
        s.create(_pod("new1"))
        p = s.create(_pod("new2"))
        p.status.phase = PodPhase.RUNNING
        s.update(p)
        seen = []
        s.watch(lambda e, obj, old: seen.append((e, obj.metadata.name)),
                kinds=["Pod"], since_revision=rev)
        # everything changed after `rev` is synthesized as ADDED, in
        # revision order, exactly once per object
        assert seen == [("ADDED", "new1"), ("ADDED", "new2")]
        assert s.watch_gaps == 0

    def test_deletion_gap_is_flagged(self):
        s = ObjectStore()
        s.create(_pod("p1"))
        rev = s.revision
        s.create(_pod("p2"))
        s.delete("Pod", "p2", "default")
        seen = []
        s.watch(lambda e, obj, old: seen.append(e), kinds=["Pod"],
                since_revision=rev)
        # the DELETED event is unreplayable from live state: the gap is
        # counted instead of silently dropped
        assert s.watch_gaps == 1

    def test_current_revision_replays_nothing(self):
        s = ObjectStore()
        s.create(_pod("p1"))
        seen = []
        s.watch(lambda e, obj, old: seen.append(e), kinds=["Pod"],
                since_revision=s.revision)
        assert seen == [] and s.watch_gaps == 0


# ---------------------------------------------------------------------------
# expectations observability (satellite c)
# ---------------------------------------------------------------------------


class TestExpectationsExpiry:
    def test_collect_expired_pops_only_expired_unfulfilled(self, monkeypatch):
        from kubedl_tpu.engine import expectations as exmod

        exps = exmod.ControllerExpectations()
        exps.expect_creations("default/a/worker/pods", 2)
        exps.expect_creations("default/a2/worker/pods", 2)  # prefix-bounded
        exps.expect_creations("default/b/worker/pods", 1)
        exps.creation_observed("default/b/worker/pods")  # fulfilled
        monkeypatch.setattr(exmod, "EXPECTATION_TIMEOUT", 0.0)
        time.sleep(0.01)
        assert exps.collect_expired("default/a") == ["default/a/worker/pods"]
        assert exps.collect_expired("default/a") == []  # popped
        assert exps.collect_expired("default/b") == []  # fulfilled != lost

    def test_reconcile_past_expired_expectations_counts(self, tmp_path,
                                                        monkeypatch):
        from kubedl_tpu.engine import expectations as exmod
        from kubedl_tpu.engine.expectations import expectation_key
        from kubedl_tpu.operator import Operator, OperatorOptions
        from kubedl_tpu.runtime.executor import ThreadRuntime

        opts = OperatorOptions(
            local_addresses=True,
            artifact_registry_root=str(tmp_path / "reg"),
        )
        with Operator(opts, runtime=ThreadRuntime()) as op:
            engine = op.engines["TPUJob"]
            job = make_tpujob("expjob", workers=1,
                              entrypoint="tests.test_crash_recovery:_noop")
            op.submit(job)
            from kubedl_tpu.api.types import JobConditionType

            op.wait_for_phase(
                "TPUJob", "expjob",
                [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
                timeout=30,
            )
            # a dead incarnation's expectation that timed out: the next
            # reconcile proceeds but says so
            key = expectation_key("default/expjob", "worker", "pods")
            engine.expectations.expect_creations(key, 3)
            monkeypatch.setattr(exmod, "EXPECTATION_TIMEOUT", 0.0)
            time.sleep(0.01)
            engine.reconcile("default", "expjob")
            assert op.metrics.expectations_expired.value(kind="TPUJob") == 1.0
            assert "kubedl_tpu_expectations_expired" in op.render_metrics()


def _noop(env):
    return 0


# ---------------------------------------------------------------------------
# checkpoint fallback across an operator kill mid-save (satellite d)
# ---------------------------------------------------------------------------


class TestCheckpointCrashFallback:
    def test_torn_save_falls_back_and_gc(self, tmp_path):
        from kubedl_tpu.training.checkpoint import (
            restore_checkpoint, save_checkpoint,
        )

        state = {"w": np.arange(16, dtype=np.float32),
                 "step": np.asarray(0, dtype=np.int64)}
        ckpt = str(tmp_path / "ckpt")
        save_checkpoint(ckpt, state, step=1, process_index=0)
        # simulated SIGKILL between shard write and manifest: step-2 dir
        # holds shards but no meta.json
        state2 = {"w": np.arange(16, dtype=np.float32) * 2,
                  "step": np.asarray(2, dtype=np.int64)}
        with FaultPlan(1, sites={"checkpoint.torn": [FaultSpec.nth(1)]}):
            with pytest.raises(FaultInjected):
                save_checkpoint(ckpt, state2, step=2, process_index=0)
        assert (tmp_path / "ckpt" / "step-00000002").exists()

        got = restore_checkpoint(ckpt, state2, gc_torn=True)
        assert got is not None
        assert int(got["step"]) == 0  # step-1 payload (saved step field)
        np.testing.assert_array_equal(got["w"], state["w"])
        # the torn newer dir was garbage-collected by the fallback
        assert not (tmp_path / "ckpt" / "step-00000002").exists()
        assert (tmp_path / "ckpt" / "step-00000001").exists()

    def test_gc_off_keeps_torn_dir(self, tmp_path):
        from kubedl_tpu.training.checkpoint import (
            restore_checkpoint, save_checkpoint,
        )

        state = {"w": np.ones(4, dtype=np.float32)}
        ckpt = str(tmp_path / "ckpt")
        save_checkpoint(ckpt, state, step=1, process_index=0)
        with FaultPlan(1, sites={"checkpoint.torn": [FaultSpec.nth(1)]}):
            with pytest.raises(FaultInjected):
                save_checkpoint(ckpt, state, step=2, process_index=0)
        assert restore_checkpoint(ckpt, state) is not None
        assert (tmp_path / "ckpt" / "step-00000002").exists()


# ---------------------------------------------------------------------------
# the acceptance spine: kill-recover e2e
# ---------------------------------------------------------------------------


def _fresh_inventory():
    from kubedl_tpu.gang.slice_scheduler import SliceInventory

    inv = SliceInventory()
    for s in ("s1", "s2", "s3"):
        inv.add_slice(s, "v5e-8")
    return inv


def _hard_kill(op) -> None:
    """Simulated SIGKILL inside one process: no graceful teardown, the
    pods keep running, the kubelet forgets its handles, the WAL detaches.
    (The cross-process variant with a REAL SIGKILL lives in
    scripts/verify-drives/drive_crash_recovery.py.)"""
    op.manager.stop()
    op.node_heartbeater.stop()
    op.kubelet._running.clear()
    op.kubelet._running_uid.clear()
    op.store.close()


def _running_pods(store):
    return {
        f"{p.metadata.namespace}/{p.metadata.name}": p.metadata.uid
        for p in store.list("Pod")
        if p.status.phase == PodPhase.RUNNING
    }


class TestKillRecoverE2E:
    def test_restart_adopts_everything(self, tmp_path):
        from kubedl_tpu.api.topology import get_slice
        from kubedl_tpu.api.types import JobConditionType
        from kubedl_tpu.operator import Operator, OperatorOptions
        from kubedl_tpu.runtime.executor import SubprocessRuntime

        wal = str(tmp_path / "wal")
        sleep_cmd = [sys.executable, "-c", "import time; time.sleep(60)"]
        topo = get_slice("v5e-8")
        opts = OperatorOptions(
            local_addresses=True, wal_dir=wal,
            pod_log_dir=str(tmp_path / "logs"),
            artifact_registry_root=str(tmp_path / "reg"),
        )
        op1 = Operator(opts, runtime=SubprocessRuntime(str(tmp_path / "logs")),
                       inventory=_fresh_inventory())
        op2 = None
        try:
            op1.start()
            for name in ("job1", "job2"):
                op1.submit(make_tpujob(name, workers=2, command=sleep_cmd,
                                       topology=topo))
                op1.wait_for_phase("TPUJob", name, JobConditionType.RUNNING,
                                   timeout=30)
            assert op1.manager.wait(
                lambda: len(_running_pods(op1.store)) == 4, timeout=20)
            before = _running_pods(op1.store)
            assert op1.kubelet.launch_count == 4
            pre_gangs = {g.metadata.name: list(g.assigned_slices)
                         for g in op1.store.list("PodGroup")}

            # job3 dies mid-gang-create: PodGroup admitted (Running,
            # slices assigned, durably in the WAL) but zero pods yet
            op1.manager.stop()
            job3 = make_tpujob("job3", workers=2, command=sleep_cmd,
                               topology=topo)
            op1.submit(job3)
            gang3 = op1.gang.create_gang(job3)
            assert op1.gang.try_admit(gang3)
            pre_gangs["job3-gang"] = list(
                op1.store.get("PodGroup", "job3-gang").assigned_slices)
            _hard_kill(op1)

            # restart on the same WAL dir: fresh store, fresh kubelet,
            # fresh (empty) inventory — everything must come back
            op2 = Operator(opts,
                           runtime=SubprocessRuntime(str(tmp_path / "logs")),
                           inventory=_fresh_inventory())
            assert op2.store.rehydrated
            op2.start()
            op2.wait_for_phase("TPUJob", "job3", JobConditionType.RUNNING,
                               timeout=30)
            assert op2.manager.wait(
                lambda: len(_running_pods(op2.store)) == 6, timeout=20)
            after = _running_pods(op2.store)

            # every pre-kill pod adopted in place: same name, SAME uid
            for key, uid in before.items():
                assert after[key] == uid, f"{key} was re-created, not adopted"
            assert op2.kubelet.adopted_count == 4
            # zero duplicate creates: only job3's two pods launched
            assert op2.kubelet.launch_count == 2
            # identical gang slice assignments, re-reserved in the fresh
            # inventory under the same owners
            post_gangs = {g.metadata.name: list(g.assigned_slices)
                          for g in op2.store.list("PodGroup")}
            assert post_gangs == pre_gangs
            for g in op2.store.list("PodGroup"):
                owner = f"{g.metadata.namespace}/{g.metadata.name}"
                assert sorted(op2.inventory.owned_slices(owner)) == sorted(
                    g.assigned_slices)
            # same phases as before the kill
            for name in ("job1", "job2", "job3"):
                assert (op2.store.get("TPUJob", name).status.phase
                        == JobConditionType.RUNNING)
            # recovery observability
            assert op2.store.replayed_records > 0
            rendered = op2.render_metrics()
            assert "kubedl_tpu_pods_adopted 4.0" in rendered
            assert "kubedl_tpu_wal_replayed_records" in rendered
            assert "kubedl_tpu_recovery_duration_seconds" in rendered
        finally:
            if op2 is not None:
                op2.stop()
            try:
                op1.stop()
            except Exception:
                pass

    def test_lost_pod_fails_retryably(self, tmp_path):
        """A pod whose process died WITH the operator (or whose pid
        annotation is gone) must fail with a retryable exit, not hang as
        a RUNNING ghost."""
        from kubedl_tpu.api.types import JobConditionType
        from kubedl_tpu.operator import Operator, OperatorOptions
        from kubedl_tpu.runtime.executor import (
            PID_ANNOTATION, SubprocessRuntime,
        )

        wal = str(tmp_path / "wal")
        opts = OperatorOptions(
            local_addresses=True, wal_dir=wal,
            artifact_registry_root=str(tmp_path / "reg"),
        )
        sleep_cmd = [sys.executable, "-c", "import time; time.sleep(60)"]
        op1 = Operator(opts, runtime=SubprocessRuntime())
        op2 = None
        try:
            op1.start()
            op1.submit(make_tpujob("ghost", workers=1, command=sleep_cmd))
            op1.wait_for_phase("TPUJob", "ghost", JobConditionType.RUNNING,
                               timeout=30)
            assert op1.manager.wait(
                lambda: len(_running_pods(op1.store)) == 1, timeout=20)
            # operator dies first, THEN the pod's process dies with the
            # host — the restarted operator finds a stale pid annotation
            # (the WAL still says RUNNING: the dead incarnation's reaper
            # can no longer write through its detached WAL)
            [(key, _)] = _running_pods(op1.store).items()
            pod = op1.store.get("Pod", key.split("/", 1)[1])
            pid = int(pod.metadata.annotations[PID_ANNOTATION])
            _hard_kill(op1)
            os.kill(pid, 9)
            time.sleep(0.3)

            op2 = Operator(opts, runtime=SubprocessRuntime())
            op2.start()
            # the ghost is detected, failed retryably (exit 137), and the
            # job restarts it — back to RUNNING with a NEW pod
            def recovered():
                pods = _running_pods(op2.store)
                return len(pods) == 1 and op2.kubelet.launch_count >= 1

            assert op2.manager.wait(recovered, timeout=30)
            # detected as lost (not adopted), failed retryably, relaunched
            assert op2.kubelet.adopted_count == 0
            assert op2.kubelet.launch_count >= 1
        finally:
            if op2 is not None:
                op2.stop()
            try:
                op1.stop()
            except Exception:
                pass

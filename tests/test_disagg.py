"""Disaggregated prefill/decode serving (docs/serving.md "Disaggregated
serving"): the KVHandoff wire format, the per-tenant weighted-fair QoS
arbiter, tier-1 bit-exactness of prefill-on-A/decode-on-B against the
colocated engine (both attention kernels), KV-block conservation across
the handoff window under injected transfer failures, and the router's
role partition + colocated fallback when the decode pool dies."""

import threading
import time
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

from kubedl_tpu import chaos
from kubedl_tpu.chaos import FaultPlan, FaultSpec
from kubedl_tpu.serving.disagg import (
    DisaggCoordinator,
    HandoffError,
    KVHandoff,
    QoSClassSpec,
    QoSShed,
    WeightedFairQueue,
    qos_from_config,
)


@pytest.fixture(autouse=True)
def _disarmed():
    chaos.disarm()
    yield
    chaos.disarm()


# ---------------------------------------------------------------------------
# wire format


class TestKVHandoffWire:
    def _make(self, **over):
        kw = dict(
            model="tiny", prompt_ids=[1, 2, 3], first_token=42, pos=3,
            block_size=8,
            k=np.arange(2 * 1 * 8 * 2 * 4, dtype=np.float32).reshape(
                2, 1, 8, 2, 4),
            v=-np.arange(2 * 1 * 8 * 2 * 4, dtype=np.float32).reshape(
                2, 1, 8, 2, 4),
            max_tokens=7, temperature=0.5, request_id="rid-1",
            cache_prefix=True, ttft_ms=12.5,
        )
        kw.update(over)
        return KVHandoff(**kw)

    def test_roundtrip_preserves_everything(self):
        h = self._make()
        g = KVHandoff.from_bytes(h.to_bytes())
        assert g.model == "tiny"
        assert g.prompt_ids == [1, 2, 3]
        assert g.first_token == 42
        assert g.pos == 3
        assert g.block_size == 8
        assert g.max_tokens == 7
        assert g.temperature == 0.5
        assert g.request_id == "rid-1"
        assert g.cache_prefix is True
        assert g.ttft_ms == 12.5
        assert g.k.dtype == np.float32
        np.testing.assert_array_equal(g.k, h.k)
        np.testing.assert_array_equal(g.v, h.v)

    def test_bad_magic_rejected(self):
        with pytest.raises(ValueError, match="magic"):
            KVHandoff.from_bytes(b"nope" + b"\x00" * 64)

    def test_truncated_rejected(self):
        data = self._make().to_bytes()
        with pytest.raises(ValueError, match="truncated"):
            KVHandoff.from_bytes(data[:-8])

    def test_nbytes_counts_both_payloads(self):
        h = self._make()
        assert h.nbytes == h.k.nbytes + h.v.nbytes


# ---------------------------------------------------------------------------
# QoS arbiter


class TestWeightedFairQueue:
    def _wfq(self, capacity=1, max_queue=4):
        return WeightedFairQueue(
            classes={"gold": QoSClassSpec(weight=3, priority=0),
                     "bronze": QoSClassSpec(weight=1, priority=2)},
            capacity=capacity, max_queue=max_queue,
        )

    def test_fast_path_grant_and_release(self):
        q = self._wfq(capacity=2)
        assert q.acquire("gold", timeout_s=0.1) == "gold"
        assert q.acquire("bronze", timeout_s=0.1) == "bronze"
        q.release("gold")
        q.release("bronze")
        assert q.admits == {"gold": 1, "bronze": 1}
        assert q.queue_depths() == {"gold": 0, "bronze": 0}

    def test_unknown_class_maps_to_default_worst(self):
        q = self._wfq()
        # default is the WORST-priority class: unknown tenants never
        # outrank a configured one
        assert q.default_class == "bronze"
        assert q.acquire("no-such-class", timeout_s=0.1) == "bronze"
        q.release("bronze")

    def test_resolve_tenant_map_then_literal_then_default(self):
        q = self._wfq()
        tenants = {"acme": "gold"}
        assert q.resolve("acme", tenants) == "gold"
        assert q.resolve("gold", tenants) == "gold"
        assert q.resolve("stranger", tenants) == "bronze"
        assert q.resolve(None, tenants) == "bronze"

    def _spin_waiters(self, q, cls, n, grants, sheds):
        def go():
            try:
                got = q.acquire(cls, timeout_s=5.0)
                grants.append(got)
                q.release(got)
            except QoSShed as e:
                sheds.append(e.qos_class)

        ts = [threading.Thread(target=go, daemon=True) for _ in range(n)]
        for t in ts:
            t.start()
        return ts

    def test_smooth_wrr_is_proportional(self):
        """Weights 3:1 under sustained contention -> gold gets ~3x the
        grants of bronze within any window."""
        q = self._wfq(capacity=1, max_queue=64)
        hold = q.acquire("gold", timeout_s=0.1)  # saturate the slot
        grants: list = []
        sheds: list = []
        order: list = []

        done = threading.Event()

        def worker(cls):
            while not done.is_set():
                try:
                    got = q.acquire(cls, timeout_s=2.0)
                except QoSShed:
                    continue
                order.append(got)
                q.release(got)
                if len(order) >= 40:
                    done.set()

        ts = [threading.Thread(target=worker, args=(c,), daemon=True)
              for c in ("gold", "bronze") for _ in range(4)]
        for t in ts:
            t.start()
        q.release(hold)
        done.wait(timeout=20)
        assert done.is_set(), "arbiter stalled"
        for t in ts:
            t.join(timeout=5)
        window = order[:40]
        g = window.count("gold")
        b = window.count("bronze")
        # smooth WRR: 3:1 +- scheduling noise (both classes always ready)
        assert g + b == 40
        assert g >= 2 * b, (g, b)

    def test_overflow_sheds_lowest_priority_queued_waiter(self):
        q = self._wfq(capacity=1, max_queue=1)
        hold = q.acquire("gold", timeout_s=0.1)
        grants: list = []
        sheds: list = []
        self._spin_waiters(q, "bronze", 1, grants, sheds)
        time.sleep(0.1)  # bronze is queued, queue now full
        # a gold arrival overflows the queue: the queued BRONZE waiter is
        # the victim, gold takes its place
        self._spin_waiters(q, "gold", 1, grants, sheds)
        time.sleep(0.1)
        assert sheds == ["bronze"]
        q.release(hold)
        time.sleep(0.2)
        assert grants == ["gold"]
        assert q.sheds["gold"] == 0

    def test_overflow_arrival_absorbs_shed_when_worst(self):
        q = self._wfq(capacity=1, max_queue=1)
        hold = q.acquire("gold", timeout_s=0.1)
        grants: list = []
        sheds: list = []
        self._spin_waiters(q, "gold", 1, grants, sheds)
        time.sleep(0.1)
        # a bronze arrival cannot evict the queued gold: it shed ITSELF
        with pytest.raises(QoSShed) as ei:
            q.acquire("bronze", timeout_s=0.1)
        assert ei.value.qos_class == "bronze"
        q.release(hold)
        time.sleep(0.2)
        assert grants == ["gold"] and sheds == []

    def test_queue_deadline_expiry_counts_as_shed(self):
        q = self._wfq(capacity=1)
        hold = q.acquire("gold", timeout_s=0.1)
        with pytest.raises(QoSShed, match="deadline"):
            q.acquire("bronze", timeout_s=0.05)
        assert q.sheds["bronze"] == 1
        q.release(hold)

    def test_qos_from_config(self):
        q = qos_from_config({
            "classes": {"gold": {"weight": 8, "priority": 0},
                        "bronze": {"weight": 1, "priority": 2}},
            "default_class": "bronze", "capacity": 3, "max_queue": 7,
        })
        assert q.capacity == 3 and q.max_queue == 7
        assert q.classes["gold"].weight == 8
        assert q.default_class == "bronze"
        assert qos_from_config(None) is None
        assert qos_from_config({}) is None


# ---------------------------------------------------------------------------
# engine-level bit-exactness (tier-1 oracle)


def _engines(kernel="gather", with_ref=True, **kw):
    from kubedl_tpu.serving.server import LlamaEngine

    base = dict(preset="tiny", max_batch=4, max_seq=64, kv_block_size=8,
                kv_attention=kernel)
    base.update(kw)
    ref = LlamaEngine(**base) if with_ref else None
    pre = LlamaEngine(role="prefill", **base)
    dec = LlamaEngine(role="decode", **base)
    return ref, pre, dec


@pytest.fixture(scope="class")
def gather_fleet():
    """One shared gather fleet for the bit-exactness class — engine
    builds dominate this module's runtime, and row/slot reuse across
    requests is itself part of the surface under test."""
    ref, pre, dec = _engines("gather")
    co = DisaggCoordinator(pre, dec, serialize=True)
    yield ref, pre, dec, co
    for e in (ref, pre, dec):
        e.close()


RAGGED_PROMPTS = [
    [1, 2, 3, 4, 5],            # partial tail block (5 < 8)
    [7, 8, 9],                  # short
    list(range(2, 18)),         # two full blocks exactly
    [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11],  # full + partial tail
]


class TestDisaggBitExact:
    def test_greedy_bit_identical_gather(self, gather_fleet):
        """The tentpole acceptance oracle: prefill on A, decode on B,
        greedy output token-for-token identical to the colocated engine —
        for ragged batches and partial tail blocks. The handoff
        roundtrips through the wire format."""
        ref, pre, dec, co = gather_fleet
        for p in RAGGED_PROMPTS:
            want = ref.generate(list(p), max_tokens=8, temperature=0.0,
                                timeout_s=120)
            got = co.generate(list(p), max_tokens=8, temperature=0.0,
                              timeout_s=120)
            assert "error" not in got, got
            assert got["token_ids"] == want["token_ids"], (
                p, want["token_ids"], got["token_ids"])
            assert got["prompt_len"] == len(p)

    def test_greedy_bit_identical_blocked(self):
        """Same oracle under the blocked paged-attention kernel."""
        ref, pre, dec = _engines("blocked")
        co = DisaggCoordinator(pre, dec, serialize=True)
        try:
            for p in RAGGED_PROMPTS:
                want = ref.generate(list(p), max_tokens=8, temperature=0.0,
                                    timeout_s=120)
                got = co.generate(list(p), max_tokens=8, temperature=0.0,
                                  timeout_s=120)
                assert "error" not in got, got
                assert got["token_ids"] == want["token_ids"], (
                    p, want["token_ids"], got["token_ids"])
        finally:
            for e in (ref, pre, dec):
                e.close()

    def test_prefix_grafted_rows_bit_identical(self, gather_fleet):
        """Adopted rows join the decode replica's prefix cache; a repeat
        of the same prompt grafts shared full blocks on adopt — output
        must not change."""
        ref, pre, dec, co = gather_fleet
        p = list(range(3, 19))  # two full blocks: graftable
        want = ref.generate(list(p), max_tokens=6, temperature=0.0,
                            timeout_s=120)
        first = co.generate(list(p), max_tokens=6, temperature=0.0,
                            timeout_s=120, cache_prefix=True)
        again = co.generate(list(p), max_tokens=6, temperature=0.0,
                            timeout_s=120, cache_prefix=True)
        assert first["token_ids"] == want["token_ids"]
        assert again["token_ids"] == want["token_ids"]
        # the repeat actually grafted on the decode side
        assert again["cached_prefix_len"] > 0 or (
            dec.stats()["prefix_cache"] is None)

    def test_sampled_decode_per_seed_determinism(self):
        """temperature>0 regression: two fresh disagg fleets produce the
        SAME sampled stream (the engines' RNG is seeded, the handoff must
        not add nondeterminism)."""
        outs = []
        for _ in range(2):
            _, pre, dec = _engines(with_ref=False)
            co = DisaggCoordinator(pre, dec)
            try:
                outs.append([
                    co.generate([5, 6, 7, 8], max_tokens=6, temperature=0.8,
                                timeout_s=120)["token_ids"],
                    co.generate([9, 3, 1], max_tokens=6, temperature=0.8,
                                timeout_s=120)["token_ids"],
                ])
            finally:
                pre.close()
                dec.close()
        assert outs[0] == outs[1]

    def test_adopt_rejects_geometry_mismatch(self, gather_fleet):
        ref, pre, dec, co = gather_fleet
        h = pre.prefill_handoff([1, 2, 3], max_tokens=4, timeout_s=120)
        bad = KVHandoff(
            model=h.model, prompt_ids=h.prompt_ids,
            first_token=h.first_token, pos=h.pos,
            block_size=h.block_size + 1, k=h.k, v=h.v,
            max_tokens=h.max_tokens,
        )
        with pytest.raises(ValueError, match="block"):
            dec.adopt_handoff(bad, timeout_s=30)
        # the good one still adopts cleanly afterwards
        r = dec.adopt_handoff(h, timeout_s=120)
        assert "token_ids" in r


# ---------------------------------------------------------------------------
# conservation across the transfer window (chaos satellite)


class TestHandoffConservation:
    def test_no_leaks_no_double_frees_across_100_handoffs(self):
        """>=100 handoffs with seeded mid-flight transfer failures at
        ``serving.kv_handoff`` (both the export and adopt legs consult
        it): every block returns to the free list on BOTH engines, and no
        double-free ever raises (the allocator turns one into ValueError,
        which would surface as an engine scheduler error)."""
        from kubedl_tpu.serving.server import LlamaEngine

        pre = LlamaEngine(preset="tiny", max_batch=4, max_seq=64,
                          kv_block_size=8, role="prefill",
                          handoff_ttl_s=0.5)
        dec = LlamaEngine(preset="tiny", max_batch=4, max_seq=64,
                          kv_block_size=8, role="decode")
        co = DisaggCoordinator(pre, dec)
        pre_total = pre.stats()["kv_blocks"]["total"]
        dec_total = dec.stats()["kv_blocks"]["total"]
        ok = failed = 0
        try:
            with FaultPlan(seed=7, sites={
                "serving.kv_handoff": [FaultSpec.prob(0.25, 400)],
            }):
                for n in range(100):
                    try:
                        r = co.generate([1 + n % 50, 2, 3 + n % 7],
                                        max_tokens=1, temperature=0.0,
                                        timeout_s=120)
                    except HandoffError:
                        failed += 1  # export leg died mid-flight
                        continue
                    if r.get("handoff_failed"):
                        failed += 1  # adopt leg died mid-flight
                        continue
                    assert "token_ids" in r, r
                    ok += 1
            assert ok > 0 and failed > 0, (ok, failed)

            # parked handoffs drain (TTL GC on the prefill engine); then
            # every block is back on both free lists — conservation
            deadline = time.time() + 20
            while time.time() < deadline:
                ps = pre.stats()
                ds = dec.stats()
                if (ps["kv_blocks"]["free"] == pre_total
                        and ps["handoffs_parked"] == 0
                        and ds["kv_blocks"]["free"] == dec_total):
                    break
                time.sleep(0.1)
            assert ps["kv_blocks"]["free"] == pre_total, ps["kv_blocks"]
            assert ps["handoffs_parked"] == 0
            assert ds["kv_blocks"]["free"] == dec_total, ds["kv_blocks"]
            # a double-free raises in the scheduler: recovery would count
            assert pre.metrics.scheduler_errors.value() == 0
            assert dec.metrics.scheduler_errors.value() == 0
        finally:
            pre.close()
            dec.close()


# ---------------------------------------------------------------------------
# router: role partition, disagg dispatch, colocated fallback, QoS 503


def _serve(engine, name="tiny"):
    from kubedl_tpu.serving.server import make_handler

    srv = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(engine, name))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class TestRouterDisagg:
    def test_sync_from_store_partitions_by_model_and_role(self):
        """Pods carry their Predictor role as a label (serving
        controller) and their model preset in the serve config; the
        router's sync partitions its pools accordingly and dedupes
        duplicate (host, port) endpoints."""
        from kubedl_tpu.core.objects import Pod, PodPhase
        from kubedl_tpu.core.store import ObjectStore
        from kubedl_tpu.serving.controller import (
            LABEL_INFERENCE, LABEL_PREDICTOR, LABEL_ROLE,
        )
        from kubedl_tpu.serving.router import ServingRouter

        store = ObjectStore()

        def pod(name, role, ip, port=8080):
            p = Pod()
            p.metadata.name = name
            p.metadata.labels = {
                LABEL_INFERENCE: "inf", LABEL_PREDICTOR: "main",
            }
            if role:
                p.metadata.labels[LABEL_ROLE] = role
            p.spec.main_container().set_env(
                "KUBEDL_SERVE_CONFIG",
                '{"port": %d, "preset": "tiny"}' % port)
            p.status.phase = PodPhase.RUNNING
            p.status.pod_ip = ip
            store.create(p)

        pod("pre-0", "prefill", "10.0.0.1")
        pod("dec-0", "decode", "10.0.0.2")
        pod("dec-1", "decode", "10.0.0.3")
        pod("col-0", "", "10.0.0.4")
        pod("dup-0", "decode", "10.0.0.2")  # same endpoint as dec-0

        r = ServingRouter()
        n = r.sync_from_store(store, "inf")
        assert n == 4  # dup deduped
        st = r.stats()
        assert st["pools"] == {"prefill": 1, "decode": 2, "colocated": 1}
        assert st["replicas"]["pre-0"]["role"] == "prefill"
        assert st["replicas"]["pre-0"]["model"] == "tiny"
        assert st["replicas"]["col-0"]["role"] == "colocated"
        assert "dup-0" not in st["replicas"]

    def test_disagg_dispatch_and_decode_outage_fallback(self):
        """With both pools up, requests run as two legs and greedy output
        is bit-identical to a direct engine call. When the DECODE pool
        dies, the same request degrades to the role-blind colocated path
        (the prefill engine still serves /v1/generate) — NOT a fleet-wide
        503, and zero requests are lost."""
        from kubedl_tpu.serving.server import LlamaEngine
        from kubedl_tpu.serving.router import ServingRouter

        ref = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_block_size=8)
        pre = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_block_size=8, role="prefill")
        dec = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_block_size=8, role="decode")
        s_pre = s_dec = None
        try:
            prompt = [3, 1, 4, 1, 5]
            want = ref.generate(list(prompt), max_tokens=6,
                                temperature=0.0)["token_ids"]
            s_pre, s_dec = _serve(pre), _serve(dec)
            r = ServingRouter(
                [{"name": "pre-0", "host": "127.0.0.1",
                  "port": s_pre.server_port, "role": "prefill"},
                 {"name": "dec-0", "host": "127.0.0.1",
                  "port": s_dec.server_port, "role": "decode"}],
                hedge_enabled=False,
            )
            body = {"prompt_ids": list(prompt), "max_tokens": 6,
                    "temperature": 0.0}
            code, payload, _ = r.handle_generate(dict(body), 30_000)
            assert code == 200
            assert payload["token_ids"] == want
            assert r.metrics.disagg_requests.value() == 1

            # decode pool dies: the adopt leg fails, the request falls
            # back to the colocated path on the prefill engine
            s_dec.shutdown()
            s_dec.server_close()
            s_dec = None
            code, payload, _ = r.handle_generate(dict(body), 30_000)
            assert code == 200, payload
            assert payload["token_ids"] == want
            assert r.metrics.disagg_fallbacks.value() >= 1
        finally:
            for s in (s_pre, s_dec):
                if s is not None:
                    s.shutdown()
                    s.server_close()
            for e in (ref, pre, dec):
                e.close()

    def test_qos_shed_is_distinguishable_503(self):
        """A saturated arbiter sheds the worst class with a 503 whose
        reason (qos_shed) and class are machine-readable — composing
        with, not masking, the engines' own shed reasons."""
        from kubedl_tpu.serving.router import ServingRouter

        r = ServingRouter(qos={
            "classes": {"gold": {"weight": 8, "priority": 0},
                        "bronze": {"weight": 1, "priority": 2}},
            "tenants": {"acme": "gold"},
            "capacity": 1, "max_queue": 1,
        })
        hold = r.qos.acquire("gold", timeout_s=0.1)  # saturate
        q: list = []
        t = threading.Thread(
            target=lambda: q.append(r.handle_generate(
                {"prompt_ids": [1]}, 5_000, tenant="acme")),
            daemon=True)
        t.start()  # gold: queued, fills max_queue
        time.sleep(0.2)
        code, payload, hdrs = r.handle_generate(
            {"prompt_ids": [1]}, 1_000, tenant="unknown-tenant")
        assert code == 503
        assert payload["reason"] == "qos_shed"
        assert payload["qos_class"] == "bronze"
        assert payload["shed"] is True
        assert "Retry-After" in hdrs
        assert r.metrics.qos_sheds.value(qos_class="bronze") == 1
        # the queued gold request was NOT disturbed; release the slot and
        # it proceeds to (no replica -> 503 no_replica, but admitted)
        r.qos.release(hold)
        t.join(timeout=10)
        assert q and q[0][1].get("reason") == "no_replica"
        assert r.metrics.qos_sheds.value(qos_class="gold") == 0

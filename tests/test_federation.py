"""Multi-operator federation: deterministic placement + staggered
succession, WAL-tail read replicas, fenced actuation under the nastiest
SIGSTOP-past-TTL schedule, partition demotion, real-subprocess lease
takeover timing, and the Operator.stop() ordering pin."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from kubedl_tpu import chaos
from kubedl_tpu.chaos import FaultPlan, FaultSpec
from kubedl_tpu.core.manager import ControllerManager, owner_mapper
from kubedl_tpu.core.objects import OwnerRef, Pod
from kubedl_tpu.core.store import ObjectStore
from kubedl_tpu.core.wal import WriteAheadLog
from kubedl_tpu.federation import (
    FederationMember,
    ShardWalTail,
    actuation_root,
    assert_fenced_actuation,
    campaign_delay,
    duplicate_creates,
    plan_assignment,
    rank_of,
    successors,
)
from kubedl_tpu.shards import (
    FencedOut,
    FileLeaseStore,
    ShardedObjectStore,
    acquire_shard_lease,
)
from kubedl_tpu.workloads.tpujob import TPUJob

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MEMBERS = ["op-a", "op-b", "op-c"]


def _job(name, namespace="default"):
    job = TPUJob()
    job.metadata.name = name
    job.metadata.namespace = namespace
    return job


def _pod(name, owner=None, namespace="default"):
    pod = Pod()
    pod.metadata.name = name
    pod.metadata.namespace = namespace
    if owner is not None:
        pod.metadata.owner_refs.append(OwnerRef(
            kind=owner.kind, name=owner.metadata.name,
            uid=owner.metadata.uid, controller=True,
        ))
    return pod


def _wait(pred, timeout, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class TestRebalance:
    def test_succession_is_total_deterministic_and_identical(self):
        for shard in range(16):
            order = successors(shard, MEMBERS)
            assert sorted(order) == sorted(MEMBERS)
            # every member computes the identical order from the list
            assert order == successors(shard, list(MEMBERS))
            assert [rank_of(shard, m, MEMBERS) for m in order] == [0, 1, 2]

    def test_plan_covers_every_shard_exactly_once(self):
        plan = plan_assignment(8, MEMBERS)
        owned = sorted(i for shards in plan.values() for i in shards)
        assert owned == list(range(8))

    def test_orphans_spread_across_survivors(self):
        """Per-shard independent ranking: a dead member's shards must not
        all dogpile one successor (checked over enough shards that a
        constant-successor bug cannot hide)."""
        members = [f"m{i}" for i in range(4)]
        heirs = {
            successors(shard, members)[1]
            for shard in range(32)
            if successors(shard, members)[0] == members[0]
        }
        assert len(heirs) > 1, heirs

    def test_campaign_delay_staggers_by_rank(self):
        ttl = 2.0
        for shard in range(8):
            delays = sorted(
                campaign_delay(shard, m, MEMBERS, ttl) for m in MEMBERS
            )
            # planned owner campaigns immediately; each later rank holds
            # back one more stagger step, all strictly below 2 TTLs
            assert delays[0] == 0.0
            assert delays == [0.0, ttl * 0.5, ttl * 1.0]


class TestShardWalTail:
    def test_incremental_refresh_serves_owner_writes(self, tmp_path):
        owner = ObjectStore(wal_dir=str(tmp_path), wal_snapshot_every=10**6)
        tail = ShardWalTail(str(tmp_path))
        job = _job("t1")
        owner.create(job)
        events = tail.refresh()
        assert [e[0] for e in events] == ["ADDED"]
        assert tail.try_get("TPUJob", "t1") is not None
        cursor = tail._cursor
        owner.create(_pod("t1-p0", owner=job))
        tail.refresh()
        # incremental: the cursor advanced instead of re-reading from 0
        assert tail._cursor > cursor
        assert {o.metadata.name for o in tail.list("Pod")} == {"t1-p0"}
        owner.delete("Pod", "t1-p0", "default")
        events = tail.refresh()
        assert [e[0] for e in events] == ["DELETED"]
        assert tail.list("Pod") == []
        owner.close()

    def test_torn_tail_tolerated_without_truncation(self, tmp_path):
        owner = ObjectStore(wal_dir=str(tmp_path), wal_snapshot_every=10**6)
        owner.create(_job("t1"))
        tail = ShardWalTail(str(tmp_path))
        tail.refresh()
        # simulate the owner mid-append: a record header promising more
        # payload bytes than exist yet
        log_path = os.path.join(str(tmp_path), "wal.log")
        size = os.path.getsize(log_path)
        with open(log_path, "ab") as fh:
            fh.write(b"\xff\x00\x00\x00\x12\x34\x56\x78half")
        assert tail.refresh() == []  # scan stops at the torn record
        assert tail.try_get("TPUJob", "t1") is not None
        # read-only contract: the tail never truncated the owner's log
        assert os.path.getsize(log_path) > size
        owner.close()

    def test_compaction_triggers_rebuild_from_snapshot(self, tmp_path):
        owner = ObjectStore(wal_dir=str(tmp_path), wal_snapshot_every=4)
        tail = ShardWalTail(str(tmp_path))
        for i in range(3):
            owner.create(_job(f"t{i}"))
        tail.refresh()
        assert tail.object_count() == 3
        # crossing snapshot_every compacts: snapshot written, log
        # truncated -> the tail sees the segment shrink below its cursor
        # and rebuilds, converging on the same objects
        for i in range(3, 8):
            owner.create(_job(f"t{i}"))
        tail.refresh()
        assert {o.metadata.name for o in tail.list("TPUJob")} == {
            f"t{i}" for i in range(8)
        }
        owner.close()

    def test_facade_serves_unowned_shards_from_tails(self, tmp_path):
        """Cross-shard visibility: a member that owns NOTHING still
        answers get/list for every shard by tailing the owners' WAL
        segments — and still cannot actuate."""
        lease_dir = str(tmp_path / "leases")
        wal_dir = str(tmp_path / "wal")
        owner = ShardedObjectStore(
            shards=4, wal_dir=wal_dir,
            lease_backend=FileLeaseStore(lease_dir), identity="owner",
            lease_ttl=5.0, own=list(range(4)),
        )
        names = [f"vis-{i}" for i in range(12)]
        for n in names:
            owner.create(_job(n))
        reader = ShardedObjectStore(
            shards=4, wal_dir=wal_dir,
            lease_backend=FileLeaseStore(lease_dir), identity="reader",
            lease_ttl=5.0, own=[], standby=[],
        )
        reader.enable_tail_reads()
        reader.refresh_tails()
        assert {
            o.metadata.name for o in reader.list("TPUJob", None)
        } == set(names)
        assert reader.get("TPUJob", names[0]).metadata.name == names[0]
        with pytest.raises(FencedOut):
            reader.create(_job("vis-write"))
        with pytest.raises(FencedOut):
            assert_fenced_actuation(reader, "default", names[0],
                                    action="pod launch")
        reader.close()
        owner.close()


class TestDuplicateCreatesAudit:
    def _append(self, wal, rev, op, name, uid):
        wal.append(rev, op, "Pod", "default", name, obj={
            "kind": "Pod",
            "metadata": {"name": name, "namespace": "default", "uid": uid},
        } if op == "PUT" else None)

    def test_recreate_after_durable_delete_is_not_a_duplicate(self, tmp_path):
        seg = tmp_path / "shard-0"
        seg.mkdir()
        wal = WriteAheadLog(str(seg))
        wal.recover()
        self._append(wal, 1, "PUT", "p0", "uid-1")
        self._append(wal, 2, "PUT", "p0", "uid-1")  # status update: same uid
        self._append(wal, 3, "DELETE", "p0", "")
        self._append(wal, 4, "PUT", "p0", "uid-2")  # fresh generation
        wal.close()
        assert duplicate_creates(str(tmp_path), 1) == []

    def test_second_create_of_live_name_is_flagged(self, tmp_path):
        seg = tmp_path / "shard-0"
        seg.mkdir()
        wal = WriteAheadLog(str(seg))
        wal.recover()
        self._append(wal, 1, "PUT", "p0", "uid-1")
        self._append(wal, 2, "PUT", "p0", "uid-2")  # live name, new uid
        wal.close()
        assert duplicate_creates(str(tmp_path), 1) == ["p0"]


class TestFencedTakeoverSchedule:
    def test_sigstop_past_ttl_old_owner_observes_but_never_acts(
        self, tmp_path
    ):
        """The nastiest schedule (also drilled cross-process by
        scripts/verify-drives/drive_federation.py): the owner stalls past
        its lease TTL without renewing (the in-process equivalent of
        SIGSTOP), a standby takes its shards over and launches pods, the
        old owner resumes — every queued actuation must be rejected with
        FencedOut, its reads must keep working, and the WAL audit must
        show zero duplicate pod launches."""
        ttl = 0.5
        lease_dir = str(tmp_path / "leases")
        wal_dir = str(tmp_path / "wal")
        old = ShardedObjectStore(
            shards=2, wal_dir=wal_dir,
            lease_backend=FileLeaseStore(lease_dir), identity="old",
            lease_ttl=ttl, own=[0, 1],
        )
        job = _job("g1")
        old.create(job)
        old.create_many([_pod(f"g1-p{k}", owner=job) for k in range(3)])
        # the owner stalls: no campaigns running, so nothing renews and
        # both leases expire on the shared root
        time.sleep(ttl * 1.3)
        new = ShardedObjectStore(
            shards=2, wal_dir=wal_dir,
            lease_backend=FileLeaseStore(lease_dir), identity="new",
            lease_ttl=ttl, own=[], standby=[0, 1],
        )
        try:
            new.start_campaigns()
            assert _wait(lambda: new.owned_shards() == [0, 1], ttl * 8)
            # rehydrate-then-adopt: the standby sees the old owner's world
            assert new.get("TPUJob", "g1") is not None
            assert len(new.list("Pod", "default")) == 3
            # ...and acts on it: launch the rest of the gang
            new.create_many([_pod(f"g1-p{k}", owner=job) for k in (3, 4)])

            # the old owner resumes. It may observe...
            assert old.get("TPUJob", "g1") is not None
            # ...but every externally-visible actuation it had queued is
            # rejected: the fencing gate first,
            for action in ("pod launch", "gang bind", "slice reservation",
                           "pod delete"):
                with pytest.raises(FencedOut):
                    assert_fenced_actuation(old, "default", "g1",
                                            action=action)
            # and the store write paths behind it
            with pytest.raises(FencedOut):
                old.create_many([_pod("g1-p9", owner=job)])
            with pytest.raises(FencedOut):
                old.try_delete("Pod", "g1-p0", "default")
            # fencing is sticky: still fenced after the first rejection
            with pytest.raises(FencedOut):
                old.create(_job("g2"))
        finally:
            new.close()
            old.close()
        # ground truth: nothing was ever launched twice
        assert duplicate_creates(wal_dir, 2) == []

    def test_actuation_root_follows_controller_ref(self):
        job = _job("g1")
        pod = _pod("g1-p0", owner=job)
        assert actuation_root(pod) == "g1"
        assert actuation_root(job) == "g1"


class TestPartitionDemotion:
    def test_lost_lease_root_demotes_before_ttl(self, tmp_path):
        """federation.lease_io: a member that cannot reach the lease root
        demotes to read-only in < demotion_deadline + one beat — strictly
        before its leases can have been re-acquired elsewhere — and keeps
        serving reads from its mounted shards."""
        ttl = 1.5
        store = ShardedObjectStore(
            shards=2, wal_dir=str(tmp_path / "wal"),
            lease_backend=FileLeaseStore(str(tmp_path / "leases")),
            identity="op-a", lease_ttl=ttl, own=[0, 1],
        )
        store.create(_job("d1"))
        member = FederationMember(
            store, store._lease_backend, "op-a", ["op-a"],
            lease_ttl=ttl, heartbeat_interval=0.05,
            demotion_deadline=0.3,
        )
        chaos.arm(FaultPlan(seed=20, sites={
            "federation.lease_io": [FaultSpec.always()],
        }))
        try:
            t0 = time.monotonic()
            member.start()
            assert _wait(lambda: member.read_only, ttl * 2)
            demoted_after = time.monotonic() - t0
            assert demoted_after < ttl, demoted_after
            assert member.heartbeat_misses > 0
            assert member.demotions == 1
            # demoted: observes (mounted shards still answer reads)...
            assert store.get("TPUJob", "d1") is not None
            # ...but can never act again
            with pytest.raises(FencedOut):
                store.create(_job("d2"))
        finally:
            member.stop()
            chaos.disarm()
            store.close()

    def test_wedged_heartbeat_site_counts_misses(self, tmp_path):
        store = ShardedObjectStore(
            shards=1, wal_dir=str(tmp_path / "wal"),
            lease_backend=FileLeaseStore(str(tmp_path / "leases")),
            identity="op-a", lease_ttl=2.0, own=[0],
        )
        member = FederationMember(
            store, store._lease_backend, "op-a", ["op-a"], lease_ttl=2.0,
            heartbeat_interval=0.05, demotion_deadline=0.5,
        )
        chaos.arm(FaultPlan(seed=20, sites={
            "federation.heartbeat": [FaultSpec.nth(1)],
        }))
        try:
            member._heartbeat_once()  # beat 1: wedged publisher
            member._heartbeat_once()  # beat 2: healthy
            assert member.heartbeat_misses == 1
            assert member.heartbeats == 1
            assert not member.read_only
        finally:
            chaos.disarm()
            store.close()

    def test_presence_and_live_members(self, tmp_path):
        store = ShardedObjectStore(
            shards=1, wal_dir=str(tmp_path / "wal"),
            lease_backend=FileLeaseStore(str(tmp_path / "leases")),
            identity="op-a", lease_ttl=2.0, own=[0],
        )
        member = FederationMember(
            store, store._lease_backend, "op-a", MEMBERS, lease_ttl=2.0,
        )
        member._heartbeat_once()
        assert member.live_members() == ["op-a"]
        store.close()


class TestManagerShardWorkers:
    def test_takeover_mount_spawns_worker_pool(self, tmp_path):
        """A federated standby starts with worker pools only for owned
        shards; a takeover AFTER start() must spawn the new shard's pool
        via the store's on_shard_mounted hook — otherwise adopted keys
        sit in a queue nothing drains."""
        lease_dir = str(tmp_path / "leases")
        wal_dir = str(tmp_path / "wal")
        seeded = ShardedObjectStore(
            shards=2, wal_dir=wal_dir,
            lease_backend=FileLeaseStore(lease_dir), identity="seed",
            lease_ttl=0.5, own=[0, 1],
        )
        for i in range(8):
            seeded.create(_job(f"tk-{i}"))
        seeded.stop_campaigns()  # crash-style: leases expire, WAL stays
        seeded.close()
        time.sleep(0.7)

        standby = ShardedObjectStore(
            shards=2, wal_dir=wal_dir,
            lease_backend=FileLeaseStore(lease_dir), identity="standby",
            lease_ttl=0.5, own=[], standby=[0, 1],
        )
        manager = ControllerManager(store=standby)
        done = set()
        lock = threading.Lock()

        def reconcile(namespace, name):
            with lock:
                done.add(name)
            return None

        manager.register(
            "tk", reconcile, watch_kinds=["TPUJob"],
            mapper=owner_mapper("TPUJob"), workers=1, resync_on_start=True,
        )
        reg = manager._registrations[0]
        manager.start()
        assert reg.worker_shards == set()  # nothing owned yet
        standby.start_campaigns()
        try:
            assert _wait(lambda: standby.owned_shards() == [0, 1], 5.0)
            # the takeover mounts fired the hook: pools exist and the
            # rehydrated jobs' ADDED events were reconciled
            assert _wait(lambda: reg.worker_shards == {0, 1}, 2.0)
            assert _wait(
                lambda: done == {f"tk-{i}" for i in range(8)}, 5.0
            ), done
        finally:
            manager.stop()
            standby.close()


@pytest.mark.slow
class TestFileLeaseTakeoverTiming:
    """Satellite: FileLeaseStore takeover timing across REAL processes —
    the cross-process twin of test_leader.py::TestFailoverTiming."""

    TTL = 1.5

    HOLDER = textwrap.dedent("""
        import os, sys, time
        from kubedl_tpu.shards.fencing import (
            SHARD_LEASE_NAMESPACE, FileLeaseStore, ShardElector,
            shard_lease_name,
        )
        root, ttl = sys.argv[1], float(sys.argv[2])
        backend = FileLeaseStore(os.path.join(root, "leases"))
        el = ShardElector(
            backend, identity="child", name=shard_lease_name(0),
            namespace=SHARD_LEASE_NAMESPACE, ttl=ttl,
        )
        el.start()
        while not el.is_leader:
            time.sleep(0.01)
        open(os.path.join(root, "acquired"), "w").write("ok")
        while not os.path.exists(os.path.join(root, "stop")):
            time.sleep(0.01)
        el.stop()  # clean: releases the lease
    """)

    def _spawn(self, script, *args):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        return subprocess.Popen(
            [sys.executable, "-c", script, *args],
            env=env, cwd=REPO_ROOT,
        )

    def _acquire_delay(self, root, timeout):
        backend = FileLeaseStore(os.path.join(root, "leases"))
        t0 = time.monotonic()
        deadline = t0 + timeout
        while time.monotonic() < deadline:
            if acquire_shard_lease(backend, 0, "parent", ttl=self.TTL) is not None:
                return time.monotonic() - t0
            time.sleep(0.02)
        pytest.fail(f"parent could not take over within {timeout}s")

    def test_clean_release_hands_over_within_a_renew_interval(self, tmp_path):
        child = self._spawn(self.HOLDER, str(tmp_path), str(self.TTL))
        try:
            assert _wait(
                lambda: os.path.exists(str(tmp_path / "acquired")), 20.0
            )
            open(str(tmp_path / "stop"), "w").write("x")
            delay = self._acquire_delay(str(tmp_path), self.TTL * 4)
            assert delay < self.TTL * 0.6, delay
            assert child.wait(timeout=10) == 0
        finally:
            if child.poll() is None:
                child.kill()

    def test_sigkilled_holder_waits_out_the_ttl(self, tmp_path):
        child = self._spawn(self.HOLDER, str(tmp_path), str(self.TTL))
        try:
            assert _wait(
                lambda: os.path.exists(str(tmp_path / "acquired")), 20.0
            )
            child.kill()  # SIGKILL: no release — the lease must EXPIRE
            child.wait()
            delay = self._acquire_delay(str(tmp_path), self.TTL * 4)
            assert delay > self.TTL * 0.55, delay
        finally:
            if child.poll() is None:
                child.kill()

    STOPPED = textwrap.dedent("""
        import os, sys, time
        from kubedl_tpu.core.wal import WriteAheadLog
        from kubedl_tpu.shards.fencing import (
            SHARD_LEASE_NAMESPACE, FencedOut, FencedWal, FileLeaseStore,
            ShardElector, ShardFence, shard_lease_name,
        )
        root, ttl = sys.argv[1], float(sys.argv[2])
        backend = FileLeaseStore(os.path.join(root, "leases"))
        el = ShardElector(
            backend, identity="child", name=shard_lease_name(0),
            namespace=SHARD_LEASE_NAMESPACE, ttl=ttl,
        )
        el.start()
        while not el.is_leader:
            time.sleep(0.01)
        fence = ShardFence(
            backend, 0, "child", el.fence_token, verify_interval=0.0,
        )
        raw = WriteAheadLog(os.path.join(root, "wal"))
        os.makedirs(raw.dir, exist_ok=True)
        raw.recover()
        wal = FencedWal(raw, fence)
        wal.append(1, "PUT", "Pod", "default", "p0",
                   obj={"kind": "Pod", "metadata": {"name": "p0"}})
        open(os.path.join(root, "acquired"), "w").write("ok")
        # parent SIGSTOPs us here, waits out the TTL, takes the lease,
        # then SIGCONTs and drops the go file
        while not os.path.exists(os.path.join(root, "go")):
            time.sleep(0.01)
        try:
            wal.append(2, "PUT", "Pod", "default", "p1",
                       obj={"kind": "Pod", "metadata": {"name": "p1"}})
        except FencedOut:
            open(os.path.join(root, "fenced"), "w").write("ok")
            sys.exit(0)
        sys.exit(3)  # durable append went through with a stale token
    """)

    def test_resumed_sigstopped_holder_is_fenced_on_next_append(
        self, tmp_path
    ):
        child = self._spawn(self.STOPPED, str(tmp_path), str(self.TTL))
        try:
            assert _wait(
                lambda: os.path.exists(str(tmp_path / "acquired")), 20.0
            )
            os.kill(child.pid, signal.SIGSTOP)  # freeze renewals mid-hold
            self._acquire_delay(str(tmp_path), self.TTL * 4)
            os.kill(child.pid, signal.SIGCONT)
            open(str(tmp_path / "go"), "w").write("x")
            assert child.wait(timeout=20) == 0
            assert os.path.exists(str(tmp_path / "fenced"))
        finally:
            if child.poll() is None:
                os.kill(child.pid, signal.SIGCONT)
                child.kill()


class TestStopOrdering:
    def test_stop_during_commit_window_loses_no_acked_record(self, tmp_path):
        """The Operator.stop() ordering pin (named in operator.py): the
        federation member and shard campaigns stop first, then workers,
        and the WAL closes LAST — so a stop() racing an in-flight
        group-commit window surfaces no append-after-close and every
        record acked before stop() was called is durable."""
        from kubedl_tpu.operator import Operator, OperatorOptions

        opts = OperatorOptions(
            local_addresses=True,
            pod_log_dir=str(tmp_path / "logs"),
            artifact_registry_root=str(tmp_path / "registry"),
            control_plane_shards=2,
            wal_dir=str(tmp_path / "wal"),
            wal_fsync="group",
            wal_group_window_ms=25.0,
            wal_snapshot_every=10**6,
            shard_lease_dir=str(tmp_path / "leases"),
            shard_lease_ttl=2.0,
            federation=True,
            federation_peers=["solo"],
            leader_identity="solo",
        )
        op = Operator(opts)
        op.start()
        assert op.federation is not None
        assert _wait(lambda: op.store.owned_shards() == [0, 1], 10.0)

        acked = []
        failure = []
        quit_evt = threading.Event()

        def writer():
            i = 0
            while not quit_evt.is_set():
                job = _job(f"sw-{i:04d}")
                try:
                    op.store.create(job)  # returns only once durable
                except FencedOut:
                    return  # acceptable: fenced after demotion/close
                except Exception as exc:  # noqa: BLE001 — the pin
                    failure.append(exc)
                    return
                acked.append(job.metadata.name)
                i += 1

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        assert _wait(lambda: len(acked) >= 5, 10.0)
        # stop mid-commit-window: records are staged and unacked RIGHT NOW
        acked_before_stop = list(acked)
        op.stop()
        quit_evt.set()
        t.join(timeout=10)
        assert not failure, failure

        rehydrated = ShardedObjectStore(
            shards=2, wal_dir=str(tmp_path / "wal"),
        )
        names = {o.metadata.name for o in rehydrated.list("TPUJob", None)}
        missing = set(acked_before_stop) - names
        assert not missing, f"acked records lost across stop(): {missing}"
        rehydrated.close()

    def test_federation_member_stops_before_store_closes(self, tmp_path):
        """Order probe: by the time the store closes, the federation
        loops and campaign electors must already be down — a takeover
        firing into a closing process is the bug class this pins."""
        from kubedl_tpu.operator import Operator, OperatorOptions

        opts = OperatorOptions(
            local_addresses=True,
            pod_log_dir=str(tmp_path / "logs"),
            artifact_registry_root=str(tmp_path / "registry"),
            control_plane_shards=2,
            wal_dir=str(tmp_path / "wal"),
            shard_lease_dir=str(tmp_path / "leases"),
            shard_lease_ttl=2.0,
            federation=True,
            federation_peers=["solo"],
            leader_identity="solo",
        )
        op = Operator(opts)
        op.start()
        assert _wait(lambda: op.store.owned_shards() == [0, 1], 10.0)
        order = []
        member_stop = op.federation.stop
        store_close = op.store.close

        def spying_member_stop():
            order.append("member")
            member_stop()

        def spying_store_close():
            order.append("close")
            assert not op.store._electors, (
                "campaign electors still running at store close"
            )
            store_close()

        op.federation.stop = spying_member_stop
        op.store.close = spying_store_close
        op.stop()
        assert order == ["member", "close"]

"""Host-side tests for the prefix KV cache (kubedl_tpu.serving.prefix_cache).

Pure data-structure behavior: trie matching, LRU + byte budget, refcount
pinning, the observation trie's shared-prefix candidates. Payloads are
numpy arrays (the cache only reads ``.nbytes``) — no device work.
"""

import numpy as np

from kubedl_tpu.serving.prefix_cache import PrefixCache


def _kv(n_bytes: int = 1024):
    half = max(1, n_bytes // 8 // 2)
    return np.zeros((half,), np.float64), np.zeros((half,), np.float64)


def _insert(pc, tokens, n_bytes: int = 1024):
    k, v = _kv(n_bytes)
    return pc.insert(tokens, k, v, len(tokens))


class TestMatch:
    def test_longest_stored_prefix_wins(self):
        pc = PrefixCache(1 << 20, min_len=1)
        _insert(pc, [1, 2])
        _insert(pc, [1, 2, 3, 4])
        entry, n = pc.match([1, 2, 3, 4, 9, 9])
        assert n == 4 and entry.tokens == (1, 2, 3, 4)
        pc.unpin(entry)

    def test_match_must_leave_a_suffix_token(self):
        # the engine needs >= 1 uncached token for last-token logits: a
        # full-prompt entry is unusable for that exact prompt
        pc = PrefixCache(1 << 20, min_len=1)
        _insert(pc, [1, 2, 3])
        entry, n = pc.match([1, 2, 3])
        assert entry is None and n == 0
        entry, n = pc.match([1, 2, 3, 4])
        assert n == 3
        pc.unpin(entry)

    def test_miss_on_divergent_prompt(self):
        pc = PrefixCache(1 << 20, min_len=1)
        _insert(pc, [1, 2, 3])
        assert pc.match([7, 8, 9, 10]) == (None, 0)
        assert pc.stats()["misses"] == 1

    def test_match_pins_and_caller_unpins(self):
        pc = PrefixCache(1 << 20, min_len=1)
        _insert(pc, [1, 2])
        entry, _ = pc.match([1, 2, 3])
        assert entry.refs == 1
        pc.match([1, 2, 4])
        assert entry.refs == 2
        pc.unpin(entry)
        pc.unpin(entry)
        assert entry.refs == 0


class TestEviction:
    def test_lru_order(self):
        pc = PrefixCache(3 * 1024, min_len=1)
        _insert(pc, [1], 1024)
        _insert(pc, [2], 1024)
        _insert(pc, [3], 1024)
        # touch [1]: oldest unused is now [2]
        e, _ = pc.match([1, 99])
        pc.unpin(e)
        _insert(pc, [4], 1024)  # evicts [2]
        assert pc.match([2, 99]) == (None, 0)
        e, _ = pc.match([1, 99])
        assert e is not None
        pc.unpin(e)
        assert pc.stats()["evictions"] == 1

    def test_pinned_entries_never_evicted(self):
        pc = PrefixCache(2 * 1024, min_len=1)
        _insert(pc, [1], 1024)
        _insert(pc, [2], 1024)
        pinned, _ = pc.match([1, 99])  # pin the LRU candidate
        assert _insert(pc, [3], 2048) is False  # would need BOTH evicted
        assert pc.match([1, 99])[0] is not None  # survived
        st = pc.stats()
        assert st["insert_rejects"] == 1 and st["pinned"] == 1

    def test_oversized_entry_rejected(self):
        pc = PrefixCache(1024, min_len=1)
        assert _insert(pc, [1], 4096) is False
        assert len(pc) == 0 and pc.stats()["insert_rejects"] == 1

    def test_byte_accounting_across_evictions(self):
        pc = PrefixCache(4 * 1024, min_len=1)
        for t in range(8):
            _insert(pc, [t], 1024)
        st = pc.stats()
        assert st["bytes"] <= pc.budget_bytes
        assert st["entries"] == 4 and st["evictions"] == 4

    def test_duplicate_insert_refreshes_not_duplicates(self):
        pc = PrefixCache(1 << 20, min_len=1)
        assert _insert(pc, [1, 2]) is True
        assert _insert(pc, [1, 2]) is False  # dedup: LRU refresh only
        st = pc.stats()
        assert st["entries"] == 1 and st["inserts"] == 1

    def test_eviction_prunes_trie(self):
        pc = PrefixCache(1 << 20, min_len=1)
        _insert(pc, [1, 2, 3])
        _insert(pc, [1, 9])
        pc._remove_locked(pc._entries[(1, 2, 3)])
        # sibling branch intact, removed branch gone
        assert pc.match([1, 2, 3, 4]) == (None, 0)
        e, n = pc.match([1, 9, 5])
        assert n == 2
        pc.unpin(e)


class TestObservation:
    def test_shared_prefix_becomes_candidate_after_min_seen(self):
        pc = PrefixCache(1 << 20, min_len=4, min_seen=2)
        sys_prompt = [5, 6, 7, 8, 9, 10]
        a = sys_prompt + [100, 101]
        b = sys_prompt + [200, 201]
        pc.observe(a)
        assert pc.insert_candidate(a) == 0  # seen once: nothing shared yet
        pc.observe(b)
        # the LCP of the two requests — exactly the system prompt
        assert pc.insert_candidate(b) == len(sys_prompt)

    def test_min_len_floor(self):
        pc = PrefixCache(1 << 20, min_len=8, min_seen=2)
        short = [1, 2, 3]
        pc.observe(short)
        pc.observe(short)
        assert pc.insert_candidate(short) == 0  # shared but too short

    def test_tagged_request_skips_observation(self):
        pc = PrefixCache(1 << 20, min_len=4, min_seen=2)
        p = [1, 2, 3, 4, 5]
        assert pc.insert_candidate(p, tagged=True) == len(p)
        assert pc.insert_candidate([1, 2], tagged=True) == 0  # < min_len

    def test_observation_node_bound_respected(self):
        pc = PrefixCache(1 << 20, min_len=1, min_seen=1, max_obs_nodes=10)
        for t in range(50):
            pc.observe([t, t + 1000])
        assert pc._obs_nodes <= 10


class TestAccounting:
    def test_tokens_saved_counter(self):
        pc = PrefixCache(1 << 20)
        pc.add_tokens_saved(12)
        pc.add_tokens_saved(0)
        pc.add_tokens_saved(-3)  # dropped grafts never subtract
        assert pc.stats()["tokens_saved"] == 12

    def test_hit_rate(self):
        pc = PrefixCache(1 << 20, min_len=1)
        _insert(pc, [1, 2])
        e, _ = pc.match([1, 2, 3])
        pc.unpin(e)
        pc.match([9, 9, 9])
        st = pc.stats()
        assert st["hits"] == 1 and st["misses"] == 1
        assert st["hit_rate"] == 0.5

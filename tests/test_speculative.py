"""Speculative decoding tests: draft models, the accept rule, stats,
and the engine-level draft-k/verify-1 exactness gate (speculative output
must be BIT-IDENTICAL to plain greedy decode — speculation may only
change how many sequential forwards it takes)."""

import pytest

from kubedl_tpu.serving.speculative import (
    NgramDraft,
    RepeatDraft,
    ScriptedDraft,
    SpecStats,
    accept_length,
    make_draft,
)


class TestAcceptRule:
    def test_full_agreement(self):
        assert accept_length([1, 2, 3], [1, 2, 3]) == 3

    def test_no_agreement(self):
        assert accept_length([1, 2, 3], [9, 2, 3]) == 0

    def test_longest_prefix_only(self):
        # agreement after a mismatch never counts: position 2 diverges
        assert accept_length([1, 2, 3, 4], [1, 2, 9, 4]) == 2

    def test_empty(self):
        assert accept_length([], []) == 0


class TestDrafts:
    def test_repeat_draft(self):
        d = RepeatDraft()
        assert d.propose([5, 9, 13], 3) == [13, 13, 13]
        assert d.propose([], 2) == []  # empty context: nothing to repeat

    def test_ngram_draft_prompt_lookup(self):
        # context ends with [7, 8]; the same bigram appeared earlier
        # followed by [9, 10] -> those are the proposal
        ctx = [1, 7, 8, 9, 10, 2, 7, 8]
        d = NgramDraft(max_ngram=2)
        assert d.propose(ctx, 2) == [9, 10]

    def test_ngram_draft_falls_back_to_repeat(self):
        d = NgramDraft()
        out = d.propose([1, 2, 3], 3)
        assert out == [3, 3, 3]  # no earlier match: repeat tail

    def test_ngram_prefers_longest_match(self):
        # trigram [5,6,7] matched (followed by 1); bigram [6,7] also
        # appears (followed by 2) — the longer n-gram wins
        ctx = [5, 6, 7, 1, 0, 6, 7, 2, 0, 5, 6, 7]
        d = NgramDraft(max_ngram=3)
        assert d.propose(ctx, 1) == [1]

    def test_scripted_draft(self):
        d = ScriptedDraft([[1, 2], [3, 4]])
        assert d.propose([0], 2) == [1, 2]
        assert d.propose([0], 2) == [3, 4]
        # script exhausted: repeat fallback
        assert d.propose([9], 2) == [9, 9]

    def test_make_draft(self):
        assert isinstance(make_draft("ngram"), NgramDraft)
        assert isinstance(make_draft("repeat"), RepeatDraft)
        with pytest.raises(ValueError):
            make_draft("oracle")


class TestSpecStats:
    def test_accounting(self):
        st = SpecStats()
        st.record(proposed=4, accepted=2, emitted=3)
        st.record(proposed=4, accepted=4, emitted=5)
        snap = st.snapshot()
        assert snap["proposed"] == 8
        assert snap["accepted"] == 6
        assert snap["verifies"] == 2
        assert snap["emitted"] == 8
        assert snap["acceptance_rate"] == 0.75
        assert snap["tokens_per_verify"] == 4.0
        assert snap["accept_len_mean"] == 3.0

    def test_empty_snapshot(self):
        snap = SpecStats().snapshot()
        assert snap["verifies"] == 0
        assert snap["acceptance_rate"] == 0.0


def _oracle(eng, prompt, n):
    import jax
    import jax.numpy as jnp

    from kubedl_tpu.models import llama

    cfg = eng.cfg
    decode = jax.jit(lambda p, c, t: llama.decode_step(p, c, t, cfg))
    cache = llama.init_cache(cfg, 1, eng.max_seq)
    logits = None
    for tok in prompt:
        logits, cache = decode(eng.params, cache,
                               jnp.full((1, 1), int(tok), jnp.int32))
    out = []
    for _ in range(n):
        nxt = int(logits[0].argmax())
        out.append(nxt)
        logits, cache = decode(eng.params, cache,
                               jnp.full((1, 1), nxt, jnp.int32))
    return out


class TestSpeculativeEngine:
    def test_spec_bit_identical_to_plain_greedy(self):
        """THE speculative exactness gate: spec_k > 0 changes latency,
        never tokens — outputs match the plain contiguous engine and the
        single-sequence oracle bit-for-bit."""
        from kubedl_tpu.serving.server import LlamaEngine

        prompts = [[5, 9, 13], [1, 2, 3, 4, 5, 6, 7, 8, 9], [7]]
        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", spec_k=4)
        try:
            for p in prompts:
                got = eng.generate(p, max_tokens=10)
                assert got["token_ids"] == _oracle(eng, p, 10), p
            snap = eng.stats()["speculative"]
            assert snap["verifies"] > 0
            # the first token of each request comes from prefill; the
            # remaining 9 per request are spec-emitted
            assert snap["emitted"] == 27
        finally:
            eng.close()

    def test_spec_requires_paged(self):
        from kubedl_tpu.serving.server import LlamaEngine

        with pytest.raises(ValueError):
            LlamaEngine(preset="tiny", kv_layout="contiguous", spec_k=4)

    def test_acceptance_length_distribution_scripted(self):
        """Seeded acceptance distribution: feed the verifier drafts that
        ARE the target's own greedy continuations (computed by the
        oracle) — every draft must be accepted, so each verify emits
        k+1 tokens and the accept-length stats pin to k."""
        from kubedl_tpu.serving.server import LlamaEngine

        k = 3
        eng = LlamaEngine(preset="tiny", max_batch=1, max_seq=64,
                          kv_layout="paged", spec_k=k,
                          prefix_cache_mb=0)
        try:
            prompt = [5, 9, 13]
            want = _oracle(eng, prompt, 12)
            # each fully-accepted verify emits k+1 tokens (k accepted +
            # 1 bonus), so verify j starts from want[j*(k+1)] and must
            # be fed the k true continuations after it; the first
            # proposal starts after the prefill token (want[0])
            script = [want[1 + j * (k + 1): 1 + j * (k + 1) + k]
                      for j in range((len(want) - 2) // (k + 1) + 1)]
            eng._draft = ScriptedDraft(script)
            got = eng.generate(prompt, max_tokens=12)
            assert got["token_ids"] == want
            snap = eng.stats()["speculative"]
            # perfect drafts: every verify accepted all k proposals
            assert snap["acceptance_rate"] == 1.0
            assert snap["accept_len_p50"] == k
            assert snap["accept_len_mean"] == k
        finally:
            eng.close()

    def test_wrong_drafts_all_rejected_still_exact(self):
        """Adversarial draft (always proposes an unlikely token): zero
        acceptance, pure verify-1 decode — output still exact, and the
        rejected-suffix blocks are freed (pool drains to empty)."""
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=1, max_seq=64,
                          kv_layout="paged", spec_k=4, prefix_cache_mb=0)
        try:
            prompt = [5, 9, 13]
            want = _oracle(eng, prompt, 8)
            eng._draft = ScriptedDraft([])  # exhausted: repeats tail
            # repeats of the previous token are near-never the greedy
            # pick for this model after the first few steps; accept rate
            # just has to be < 1 for the rollback path to be exercised
            got = eng.generate(prompt, max_tokens=8)
            assert got["token_ids"] == want
            snap = eng.stats()["speculative"]
            assert snap["acceptance_rate"] < 1.0
            st = eng.stats()["kv_blocks"]
            assert st["used"] == 0  # rejected-suffix blocks came home
        finally:
            eng.close()

    def test_non_greedy_falls_back_to_segment_path(self):
        """temperature > 0 rows cannot be verified greedily: the tick
        falls through to the plain segment path (no verify recorded)."""
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=1, max_seq=64,
                          kv_layout="paged", spec_k=4)
        try:
            out = eng.generate([5, 9, 13], max_tokens=6, temperature=0.9)
            assert len(out["token_ids"]) == 6
            assert eng.stats()["speculative"]["verifies"] == 0
        finally:
            eng.close()

    def test_spec_metrics_exported(self):
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=1, max_seq=64,
                          kv_layout="paged", spec_k=4)
        try:
            eng.generate([5, 9, 13], max_tokens=6)
            body = eng.metrics.registry.render()
            for fam in ("kubedl_tpu_serving_spec_tokens_proposed",
                        "kubedl_tpu_serving_spec_tokens_accepted",
                        "kubedl_tpu_serving_spec_acceptance_rate"):
                assert fam in body, fam
        finally:
            eng.close()

    def test_mixed_batch_greedy_exactness(self):
        """Two concurrent greedy requests share verify ticks; both still
        match their oracles exactly."""
        import threading

        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", spec_k=4)
        try:
            prompts = [[5, 9, 13], [1, 2, 3]]
            want = [_oracle(eng, p, 8) for p in prompts]
            results = [None] * 2

            def worker(i):
                results[i] = eng.generate(prompts[i], max_tokens=8)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert [r["token_ids"] for r in results] == want
        finally:
            eng.close()


class TestDraftTree:
    """Host-side trie unit tests: insert/dedup/cap, the fixed-shape
    array layout, and the greedy walk."""

    def _tree(self):
        from kubedl_tpu.serving.speculative import build_tree

        # chains sharing the 7 -> 3 prefix + one divergent chain
        return build_tree(42, [[7, 3, 8], [7, 3, 2], [9, 1]], k=3, m_max=16)

    def test_insert_dedups_shared_prefixes(self):
        tr = self._tree()
        # root + {7, 3, 8, 2, 9, 1}: the 7->3 prefix is stored once
        assert tr.size == 7
        assert tr.tokens[0] == 42 and tr.depth[0] == 0
        n7 = tr.children[0][7]
        n3 = tr.children[n7][3]
        assert sorted(tr.children[n3]) == [2, 8]
        assert tr.depth[n3] == 2

    def test_cap_drops_excess_suffix_only(self):
        from kubedl_tpu.serving.speculative import build_tree

        tr = build_tree(42, [[7, 3, 8], [9, 1, 2]], k=3, m_max=5)
        # candidate 0 fits whole (4 nodes); candidate 1 gets one node
        assert tr.size == 5
        assert 9 in tr.children[0]
        n9 = tr.children[0][9]
        assert tr.children[n9] == {}  # 1, 2 dropped by the cap

    def test_k_truncates_chains(self):
        from kubedl_tpu.serving.speculative import build_tree

        tr = build_tree(42, [[7, 3, 8, 5, 6]], k=2, m_max=16)
        assert tr.size == 3  # root + 7 + 3

    def test_arrays_layout_and_pad_nodes(self):
        import numpy as np

        tr = self._tree()
        toks, dep, mask = tr.arrays(10)
        assert toks.shape == (10,) and mask.shape == (10, 10)
        assert list(toks[:2]) == [42, 7]
        # ancestor mask: leaf 8 sees root -> 7 -> 3 -> itself, nothing else
        n8 = tr.children[tr.children[tr.children[0][7]][3]][8]
        assert mask[n8].sum() == 4
        assert mask[n8, 0] and mask[n8, n8]
        # pad nodes: depth-1 root children repeating the root token,
        # masked to themselves + root only
        for m in range(tr.size, 10):
            assert toks[m] == 42 and dep[m] == 1
            assert mask[m].sum() == 2 and mask[m, 0] and mask[m, m]
        # no live node attends a pad node
        assert not mask[:tr.size, tr.size:].any()
        with pytest.raises(ValueError):
            tr.arrays(tr.size - 1)

    def test_walk_follows_greedy_chain(self):
        tr = self._tree()
        ids = [0] * tr.size
        n7 = tr.children[0][7]
        n3 = tr.children[n7][3]
        ids[0] = 7       # root's continuation matches child 7
        ids[n7] = 3      # then 3
        ids[n3] = 2      # then the 2 branch (not 8)
        assert tr.walk(ids) == [7, 3, 2]
        ids[n3] = 5      # no child matches: path stops at depth 2
        assert tr.walk(ids) == [7, 3]
        ids[0] = 1       # no root child matches at all
        assert tr.walk(ids) == []


class TestTreeSpeculativeEngine:
    def test_tree_spec_bit_identical_to_plain_greedy(self):
        """THE tree exactness gate: spec_tree=True changes how drafts
        are scored, never the emitted tokens — outputs match the oracle
        and the flat multi-candidate engine bit-for-bit."""
        from kubedl_tpu.serving.server import LlamaEngine

        prompts = [[5, 9, 13], [1, 2, 3, 4, 5, 6, 7, 8, 9], [7]]
        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", spec_k=4, spec_candidates=3,
                          spec_tree=True)
        try:
            assert eng._verify_tree is not None
            for p in prompts:
                got = eng.generate(p, max_tokens=10)
                assert got["token_ids"] == _oracle(eng, p, 10), p
            snap = eng.stats()["speculative"]
            assert snap["verifies"] > 0
            assert snap["candidates_scored"] > 0
        finally:
            eng.close()

    def test_tree_needs_candidates(self):
        """spec_tree quietly degrades to flat verify when there is
        nothing to branch on (one candidate) or no speculation at all."""
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", spec_k=4, spec_tree=True)
        try:
            assert eng.spec_tree is False
            assert eng._verify_tree is None
        finally:
            eng.close()


class TestZooDraft:
    def test_from_zoo_and_engine_exactness(self):
        """A trained-architecture draft from MODEL_ZOO drives the engine
        and stays bit-exact (acceptance may be poor at random init; the
        accept rule keeps the output the target's own)."""
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", spec_k=3,
                          spec_draft="zoo:tiny")
        try:
            assert eng._draft.name == "zoo:tiny"
            p = [5, 9, 13]
            assert eng.generate(p, max_tokens=8)["token_ids"] == \
                _oracle(eng, p, 8)
        finally:
            eng.close()

    def test_save_load_roundtrip(self, tmp_path):
        import jax
        import numpy as np

        from kubedl_tpu.models import llama
        from kubedl_tpu.serving.speculative import ModelDraft

        cfg = llama.preset("tiny")
        d = ModelDraft.from_zoo("tiny", cfg, seed=3, max_context=64)
        path = str(tmp_path / "draft.npz")
        d.save(path)
        d2 = ModelDraft.from_zoo("tiny", cfg, seed=9, ckpt_path=path,
                                 max_context=64)
        for a, b in zip(jax.tree_util.tree_leaves(d.params),
                        jax.tree_util.tree_leaves(d2.params)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_distill_reduces_loss(self):
        """A few hard-label distillation steps against the target's own
        rollouts must drive the draft's loss down — the training loop
        that turns a zoo architecture into a useful draft."""
        import jax

        from kubedl_tpu.models import llama
        from kubedl_tpu.serving.speculative import (
            ModelDraft,
            distill_draft,
        )

        cfg = llama.preset("tiny")
        target = llama.llama_init(jax.random.PRNGKey(0), cfg)
        d = ModelDraft.from_zoo("tiny", cfg, max_context=64)
        losses = distill_draft(d, target, cfg, [[5, 9, 13], [1, 2, 3]],
                               gen_len=4, steps=3)
        assert len(losses) == 3
        assert losses[-1] < losses[0]

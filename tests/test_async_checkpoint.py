"""Asynchronous replicated checkpointing (training/checkpoint.py).

Invariants asserted here:
- the snapshot/write split produces byte-identical checkpoints to the
  legacy synchronous save (same format, same restore);
- AsyncCheckpointer holds at most ONE write in flight (backpressure) and
  wait_for_pending() is a real durability barrier that also re-raises
  background-write failures — a save the caller believes happened must
  not silently not-exist;
- completed saves mirror to a peer blob root with the `latest` marker
  uploaded LAST, and restore_from_best pulls from the peer when the
  local shard dir is gone (ISSUE 6 acceptance) — preferring local when
  it exists;
- trainer.fit wires it all up: one save per interval boundary, the
  redundant final save skipped when the last interval already wrote that
  exact step, the pending write joined before fit returns.
"""

import shutil
import threading
import time

import jax
import numpy as np
import pytest

from kubedl_tpu import chaos
from kubedl_tpu.api.topology import MeshSpec
from kubedl_tpu.chaos import FaultInjected, FaultPlan, FaultSpec
from kubedl_tpu.models import llama
from kubedl_tpu.parallel.mesh import build_mesh
from kubedl_tpu.training import checkpoint as ck
from kubedl_tpu.training.checkpoint import (
    AsyncCheckpointer,
    latest_step,
    restore_checkpoint,
    restore_from_best,
    save_checkpoint,
    snapshot_state,
    write_snapshot,
)
from kubedl_tpu.training.data import SyntheticTokens
from kubedl_tpu.training.trainer import TrainConfig, Trainer

CFG = llama.TINY


@pytest.fixture(autouse=True)
def _disarmed():
    chaos.disarm()
    yield
    chaos.disarm()


@pytest.fixture(scope="module")
def trained():
    """(trainer, state) after a short fit — module-scoped: the fit is the
    expensive part and every test here only reads the state."""
    mesh = build_mesh(MeshSpec({"data": 2}), jax.devices()[:2])
    cfg = TrainConfig(model=CFG, global_batch=4, seq_len=16, steps=2)
    trainer = Trainer(cfg, mesh)
    data = SyntheticTokens(4, 16, CFG.vocab_size)
    state, _ = trainer.fit(iter(data))
    return trainer, state


def _assert_same_params(restored, state):
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored["params"]["embed"])),
        np.asarray(jax.device_get(state["params"]["embed"])),
    )


class TestSnapshotWriteSplit:
    def test_split_save_restores_identically_to_sync(self, trained, tmp_path):
        trainer, state = trained
        sync_dir, split_dir = tmp_path / "sync", tmp_path / "split"
        save_checkpoint(str(sync_dir), state, 2)
        shards, manifest = snapshot_state(state)
        write_snapshot(str(split_dir), shards, manifest, 2, 0, 1)
        for d in (sync_dir, split_dir):
            restored = restore_checkpoint(str(d), trainer.init_state())
            assert int(jax.device_get(restored["step"])) == 2
            _assert_same_params(restored, state)
        # same files, same names — one on-disk format, not two
        assert sorted(p.name for p in (sync_dir / "step-00000002").iterdir()) \
            == sorted(p.name for p in (split_dir / "step-00000002").iterdir())

    def test_restored_leaves_never_alias_host_buffers(
        self, trained, tmp_path, monkeypatch
    ):
        """Restore must hand back XLA-OWNED buffers: when an assembled
        host array happens to be sufficiently aligned,
        make_array_from_callback zero-copies on CPU and the restored
        jax.Array aliases numpy-owned memory. The first train step then
        DONATES that leaf, and XLA recycles a buffer numpy also manages
        — heap corruption, or silently scrambled weights, on a per-leaf
        coin flip. Record every host pointer the shard store hands out
        and assert no device shard ended up on one of them."""
        trainer, state = trained
        save_checkpoint(str(tmp_path), state, 2)
        host_ptrs = set()
        real_region = ck._ShardStore.region

        def spy_region(self, key, shape, dtype, index):
            out = real_region(self, key, shape, dtype, index)
            base = out
            while base.base is not None:
                base = base.base
            host_ptrs.add(base.__array_interface__["data"][0])
            host_ptrs.add(out.__array_interface__["data"][0])
            return out

        monkeypatch.setattr(ck._ShardStore, "region", spy_region)
        restored = restore_checkpoint(str(tmp_path), trainer.init_state())
        assert host_ptrs  # the spy actually saw the reads
        for leaf in jax.tree_util.tree_leaves(restored):
            if not isinstance(leaf, jax.Array):
                continue
            for s in leaf.addressable_shards:
                assert s.data.unsafe_buffer_pointer() not in host_ptrs

    def test_snapshot_is_immutable_host_copy(self, trained, tmp_path):
        """The snapshot must be detached from devices: the train step
        DONATES the state, so on CPU (where device_get is zero-copy) a
        view-based snapshot would alias buffers the NEXT step overwrites
        — the deferred write would persist the wrong step's values. Run
        a real donating step between snapshot and write to prove the
        captured values survive buffer recycling."""
        trainer, _ = trained
        state = trainer.init_state()  # private state: donation-safe here
        shards, manifest = snapshot_state(state)
        before = {k: v.copy() for k, v in shards.items()}
        batch = trainer.shard_batch(
            next(iter(SyntheticTokens(4, 16, CFG.vocab_size))))
        state, _ = trainer.train_step(state, batch)  # recycles old buffers
        jax.block_until_ready(state["step"])
        write_snapshot(str(tmp_path), shards, manifest, 2, 0, 1)
        import numpy as _np

        with _np.load(str(tmp_path / "step-00000002" / "shards-p0.npz")) as z:
            for k, v in before.items():
                _np.testing.assert_array_equal(z[k], v)


class TestAsyncCheckpointer:
    def test_save_then_barrier_is_restorable(self, trained, tmp_path):
        trainer, state = trained
        with AsyncCheckpointer(str(tmp_path)) as acp:
            acp.save(state, 2)
            assert acp.last_saved_step == 2
        # __exit__ == wait_for_pending: latest marker durable now
        assert latest_step(str(tmp_path)) == 2
        restored = restore_checkpoint(str(tmp_path), trainer.init_state())
        _assert_same_params(restored, state)
        assert acp.saves == 1

    def test_at_most_one_write_in_flight(self, trained, tmp_path, monkeypatch):
        """Backpressure: save() must JOIN the previous write before
        enqueueing — snapshots are host RAM; a queue would OOM."""
        _, state = trained
        gauge = {"cur": 0, "max": 0}
        lock = threading.Lock()
        real = ck.write_snapshot

        def slow_write(*a, **kw):
            with lock:
                gauge["cur"] += 1
                gauge["max"] = max(gauge["max"], gauge["cur"])
            time.sleep(0.05)
            try:
                return real(*a, **kw)
            finally:
                with lock:
                    gauge["cur"] -= 1

        monkeypatch.setattr(ck, "write_snapshot", slow_write)
        acp = AsyncCheckpointer(str(tmp_path))
        for step in (1, 2, 3):
            acp.save(state, step)
        acp.wait_for_pending()
        assert gauge["max"] == 1
        assert acp.saves == 3
        # the blocking shows up as caller stall — the bench's metric
        assert acp.stall_seconds >= 0.05

    def test_background_failure_reraises_at_barrier(self, trained, tmp_path):
        """A torn write on the writer thread (checkpoint.torn chaos site)
        must surface at the next barrier, not vanish."""
        _, state = trained
        acp = AsyncCheckpointer(str(tmp_path))
        with FaultPlan(3, sites={"checkpoint.torn": [FaultSpec.nth(1)]}):
            acp.save(state, 2)
            with pytest.raises(FaultInjected):
                acp.wait_for_pending()
        # the error is consumed: the checkpointer stays usable and the
        # NEXT save lands durably (retry semantics, not poisoned-forever)
        acp.save(state, 4)
        acp.wait_for_pending()
        assert latest_step(str(tmp_path)) == 4


class TestPeerReplication:
    def test_push_and_restore_from_peer_after_local_loss(self, trained, tmp_path):
        """ISSUE 6 acceptance: delete the local shard dir, restore
        succeeds from the peer replica."""
        from kubedl_tpu.remote import RemoteStoreServer, list_blobs

        trainer, state = trained
        local = tmp_path / "ck"
        with RemoteStoreServer(str(tmp_path / "peer-root")) as srv:
            peer = f"{srv.base_url}/blobs/replicas/w0"
            with AsyncCheckpointer(str(local), peer_url=peer) as acp:
                acp.save(state, 2)
            assert acp.peer_pushes == 1
            blobs = list_blobs(srv.base_url, "replicas/w0")
            assert any(b.endswith("latest") for b in blobs), blobs
            assert any("step-00000002/shards-p0" in b for b in blobs), blobs
            assert any("step-00000002/meta.json" in b for b in blobs), blobs
            # local disk lost wholesale (node replacement)
            shutil.rmtree(local)
            restored = restore_from_best(
                str(local), trainer.init_state(), sources=[peer]
            )
            assert restored is not None
            assert int(jax.device_get(restored["step"])) == 2
            _assert_same_params(restored, state)

    def test_restore_prefers_local_when_present(self, trained, tmp_path):
        """Preference order local -> peer: an intact local dir restores
        without touching the (unreachable) peer at all."""
        trainer, state = trained
        local = tmp_path / "ck"
        save_checkpoint(str(local), state, 2)
        restored = restore_from_best(
            str(local), trainer.init_state(),
            sources=["http://127.0.0.1:1/blobs/nope"],  # would error if hit
        )
        assert restored is not None
        assert int(jax.device_get(restored["step"])) == 2

    def test_dead_peer_degrades_not_fails(self, trained, tmp_path):
        """Replication is best-effort: an unreachable peer must not fail
        the save (durability degrades; training never does)."""
        _, state = trained
        acp = AsyncCheckpointer(
            str(tmp_path / "ck"), peer_url="http://127.0.0.1:1/blobs/nope"
        )
        acp.save(state, 2)
        acp.wait_for_pending()  # must NOT raise
        assert acp.peer_pushes == 0
        assert latest_step(str(tmp_path / "ck")) == 2  # local landed


class TestTrainerIntegration:
    def test_fit_async_saves_each_interval_and_skips_final_dup(self, tmp_path):
        """ckpt_every=2, steps=4: interval saves at 2 and 4; the final
        save is SKIPPED because step 4 is already on disk (the duplicate
        double-save the sync path used to pay)."""
        mesh = build_mesh(MeshSpec({"data": 2}), jax.devices()[:2])
        cfg = TrainConfig(model=CFG, global_batch=4, seq_len=16, steps=4,
                          ckpt_every=2)
        trainer = Trainer(cfg, mesh)
        data = SyntheticTokens(4, 16, CFG.vocab_size)
        state, summary = trainer.fit(iter(data), ckpt_dir=str(tmp_path))
        assert summary["ckpt_async"] is True
        assert summary["ckpt_saves"] == 2  # steps 2 and 4 — NOT 3
        assert summary["ckpt_stall_s"] >= 0.0
        steps_on_disk = sorted(
            p.name for p in tmp_path.iterdir() if p.name.startswith("step-")
        )
        assert steps_on_disk == ["step-00000002", "step-00000004"]
        # durable by the time fit returned (the wait_for_pending barrier)
        restored = restore_checkpoint(str(tmp_path), trainer.init_state())
        assert int(jax.device_get(restored["step"])) == 4

    def test_fit_sync_fallback_still_writes(self, tmp_path):
        mesh = build_mesh(MeshSpec({"data": 2}), jax.devices()[:2])
        cfg = TrainConfig(model=CFG, global_batch=4, seq_len=16, steps=3,
                          ckpt_every=2, ckpt_async=False)
        trainer = Trainer(cfg, mesh)
        data = SyntheticTokens(4, 16, CFG.vocab_size)
        _, summary = trainer.fit(iter(data), ckpt_dir=str(tmp_path))
        assert summary["ckpt_async"] is False
        assert "ckpt_saves" not in summary
        assert latest_step(str(tmp_path)) == 3  # final save still lands

"""Static analyzer + lock witness (tier-1 gate for docs/static-analysis.md).

Three contracts:
- the seed-regression fixtures (tests/fixtures/analysis/) reproduce the
  repo's historical bug shapes and each BAD form is caught by its rule
  while the FIXED form passes — the rules can never silently stop
  understanding the bugs they were built from;
- the repo itself is clean: ``python -m kubedl_tpu.analysis`` exits 0
  against this checkout with the committed baseline (run in-process here
  the same way check_readme_numbers.py is gated);
- the lock witness finds an ABBA ordering cycle, stays quiet on
  consistent ordering, and its disarmed path costs nothing (chaos-style
  budget). The full-suite zero-cycle gate lives in conftest.py and runs
  when KUBEDL_LOCKWITNESS=1.
"""

import json
import os
import shutil
import threading
import time
from pathlib import Path

import pytest

from kubedl_tpu.analysis import lockwitness
from kubedl_tpu.analysis.engine import (
    analyze_file,
    apply_baseline,
    load_baseline,
    run,
    write_baseline,
)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "analysis"

#: true when THIS suite run is the witnessed one (conftest armed it) —
#: the arming/overhead assertions below only make sense disarmed
_WITNESSED_RUN = os.environ.get(lockwitness.ENV_VAR, "") == "1"


def _rules(path: Path):
    return [f.rule for f in analyze_file(path)]


# --------------------------------------------------------------------------
# Seed-regression fixtures
# --------------------------------------------------------------------------


class TestSeedRegressions:
    CASES = [
        ("donated_restore", "KTL001"),  # PR 6: frombuffer -> donated step
        ("asarray_mirror", "KTL001"),   # PR 8: self._bt_host borrow
        ("env_race", "KTL003"),         # PR 6: environ rewrite on re-entry
        ("lock_blocking", "KTL002"),    # PR 11: harvest under the cv
        ("fsync_loop", "KTL010"),
        ("fenced_actuation", "KTL011"),       # PR 19: fsync-per-append at scale
    ]

    @pytest.mark.parametrize("name,rule", CASES)
    def test_bad_form_caught(self, name, rule):
        found = _rules(FIXTURES / f"{name}_bad.py")
        assert rule in found, f"{name}_bad.py: expected {rule}, got {found}"

    @pytest.mark.parametrize("name,rule", CASES)
    def test_fixed_form_passes(self, name, rule):
        found = _rules(FIXTURES / f"{name}_fixed.py")
        assert rule not in found, (
            f"{name}_fixed.py: {rule} still fires on the fixed form: {found}"
        )

    @pytest.mark.parametrize("name,rule", CASES)
    def test_cli_nonzero_on_seeded_tree(self, name, rule, tmp_path, capsys):
        """The CLI exits non-zero on a tree seeded with each bad fixture,
        and the expected rule is among the findings."""
        pkg = tmp_path / "kubedl_tpu"
        pkg.mkdir()
        shutil.copy(FIXTURES / f"{name}_bad.py", pkg / "seeded.py")
        rc = run(["--root", str(tmp_path), "--no-baseline", "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert any(
            f["rule"] == rule and f["path"].endswith("seeded.py")
            for f in out["findings"]
        ), out["findings"]

    def test_inline_pragma_suppresses(self, tmp_path):
        src = FIXTURES / "env_race_bad.py"
        suppressed = tmp_path / "pragma.py"
        suppressed.write_text(
            src.read_text().replace(
                "os.environ[k] = v",
                "os.environ[k] = v  # ktl: disable=KTL003 -- fixture",
            )
        )
        assert "KTL003" in _rules(src)
        assert "KTL003" not in _rules(suppressed)

    def test_baseline_roundtrip(self, tmp_path):
        """Accepted findings stop failing; anything new still does."""
        findings = analyze_file(FIXTURES / "env_race_bad.py")
        assert findings
        bl_path = tmp_path / "baseline.json"
        write_baseline(findings, bl_path)
        new, stale = apply_baseline(findings, load_baseline(bl_path))
        assert new == [] and stale == []
        extra = analyze_file(FIXTURES / "lock_blocking_bad.py")
        new, _ = apply_baseline(findings + extra, load_baseline(bl_path))
        assert new == extra


# --------------------------------------------------------------------------
# The repo itself is clean (the tier-1 gate)
# --------------------------------------------------------------------------


class TestRepoClean:
    def test_analyzer_exits_zero_on_repo(self, capsys):
        """`python -m kubedl_tpu.analysis` against this checkout with the
        committed baseline: zero new findings, zero stale entries."""
        rc = run(["--root", str(REPO)])
        out = capsys.readouterr().out
        assert rc == 0, f"static analysis regressed:\n{out}"
        assert "stale baseline" not in out, out


# --------------------------------------------------------------------------
# Lock witness
# --------------------------------------------------------------------------


class TestLockWitness:
    def test_abba_cycle_detected(self):
        """Two threads taking the same pair of lock classes in opposite
        orders — the classic ABBA potential deadlock — must close a cycle
        even though this run never actually deadlocks."""
        w = lockwitness.Witness()
        lock_a = w.Lock()
        lock_b = w.Lock()  # separate line: a distinct lock class

        def path_ab():
            with lock_a:
                with lock_b:
                    pass

        def path_ba():
            with lock_b:
                with lock_a:
                    pass

        for target in (path_ab, path_ba):
            t = threading.Thread(target=target)
            t.start()
            t.join()
        cycles = w.cycles()
        assert len(cycles) == 1
        assert set(cycles[0].sites) == {lock_a.site, lock_b.site}

    def test_consistent_order_no_cycle(self):
        w = lockwitness.Witness()
        lock_a = w.Lock()
        lock_b = w.Lock()
        for _ in range(2):
            with lock_a:
                with lock_b:
                    pass
        assert w.cycles() == []
        assert (lock_a.site, lock_b.site) in w.edges

    def test_condition_protocol_compat(self):
        """A witnessed Condition must survive the wait/notify protocol
        (_release_save/_acquire_restore) with depth bookkeeping intact."""
        w = lockwitness.Witness()
        cv = w.Condition()
        done = []

        def waiter():
            with cv:
                while not done:
                    cv.wait(timeout=1.0)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cv:
            done.append(True)
            cv.notify_all()
        t.join(timeout=5.0)
        assert not t.is_alive()
        assert w.cycles() == []

    def test_blocking_call_under_lock_flagged(self):
        """Armed witness flags time.sleep while a witnessed lock is held
        (runtime twin of static KTL002) — report-only by default."""
        if _WITNESSED_RUN:
            pytest.skip("global witness already armed; private-arm test")
        w = lockwitness.install(force=True)
        try:
            lock = threading.Lock()  # patched: witnessed, created here
            with lock:
                time.sleep(0.001)
            flagged = [
                b for b in w.blocking_findings()
                if "test_analysis.py" in b.caller
            ]
            assert flagged and lock.site in flagged[0].held
            assert lockwitness.check() == []  # report-only class
        finally:
            lockwitness.uninstall()

    @pytest.mark.skipif(_WITNESSED_RUN, reason="armed run: overhead expected")
    def test_install_is_noop_when_unarmed(self):
        assert os.environ.get(lockwitness.ENV_VAR, "") != "1"
        before = threading.Lock
        assert lockwitness.install() is None
        assert threading.Lock is before
        assert not lockwitness.armed()
        assert lockwitness.check() == []

    @pytest.mark.skipif(_WITNESSED_RUN, reason="armed run: overhead expected")
    def test_disarmed_overhead_unmeasurable(self):
        """Disarmed, the factory route is one global load + None test over
        a bare threading.Lock — same budget style as the chaos layer's
        disarmed-check test (generous absolute bound for slow CI)."""
        n = 200_000
        lock = lockwitness.Lock()
        assert type(lock) is type(threading.Lock())  # bare primitive
        acquire, release = lock.acquire, lock.release
        t0 = time.perf_counter()
        for _ in range(n):
            acquire()
            release()
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 5e-6, (
            f"disarmed witnessed lock costs {per_call * 1e9:.0f}ns/cycle"
        )

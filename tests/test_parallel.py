"""Sequence/pipeline/expert parallelism tests on the virtual 8-device CPU
mesh (conftest sets xla_force_host_platform_device_count=8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubedl_tpu.api.topology import MeshSpec
from kubedl_tpu.models import llama, moe
from kubedl_tpu.parallel import ring as ringlib
from kubedl_tpu.parallel.mesh import build_mesh
from kubedl_tpu.parallel.pipeline import make_pipeline


def _qkv(key, B=2, S=64, H=4, KV=2, hd=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, hd), dtype)
    k = jax.random.normal(kk, (B, S, KV, hd), dtype)
    v = jax.random.normal(kv, (B, S, KV, hd), dtype)
    return q, k, v


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_matches_dense_oracle(self, causal, impl):
        mesh = build_mesh(MeshSpec({"sp": 8}))
        if impl == "ulysses":  # ulysses needs H and KV divisible by axis
            q, k, v = _qkv(jax.random.PRNGKey(0), H=8, KV=8)
        else:
            q, k, v = _qkv(jax.random.PRNGKey(0))
        want = llama.attention(q, k, v, causal=causal)
        attn = ringlib.make_context_attention(mesh, impl=impl, causal=causal)
        assert attn is not None
        got = jax.jit(attn)(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_mixed_data_and_sp_axes(self):
        mesh = build_mesh(MeshSpec({"data": 2, "sp": 4}))
        q, k, v = _qkv(jax.random.PRNGKey(1))
        want = llama.attention(q, k, v, causal=True)
        attn = ringlib.make_context_attention(mesh)
        got = jax.jit(attn)(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_no_sp_axis_returns_none(self):
        mesh = build_mesh(MeshSpec({"data": 8}))
        assert ringlib.make_context_attention(mesh) is None

    def test_gradients_match_dense(self):
        mesh = build_mesh(MeshSpec({"sp": 8}))
        q, k, v = _qkv(jax.random.PRNGKey(2))
        attn = ringlib.make_context_attention(mesh)

        def loss_ring(q):
            return attn(q, k, v).sum()

        def loss_dense(q):
            return llama.attention(q, k, v, causal=True).sum()

        g_ring = jax.jit(jax.grad(loss_ring))(q)
        g_dense = jax.jit(jax.grad(loss_dense))(q)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                                   atol=1e-4, rtol=1e-4)

    def test_llama_forward_with_ring_attention(self):
        """End-to-end: tiny llama forward with sequence-sharded tokens
        matches the dense forward."""
        mesh = build_mesh(MeshSpec({"data": 2, "sp": 4}))
        cfg = llama.TINY
        params = llama.llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0,
                                    cfg.vocab_size)
        want = llama.llama_forward(params, tokens, cfg)
        attn = ringlib.make_context_attention(mesh)
        with mesh:
            got = jax.jit(
                lambda p, t: llama.llama_forward(p, t, cfg, attn)
            )(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4, rtol=2e-4)


class TestPipeline:
    def test_matches_sequential(self):
        n_stages, M, mb, D = 4, 8, 2, 16
        mesh = build_mesh(MeshSpec({"pipe": 4, "data": 2}))
        key = jax.random.PRNGKey(0)
        w = jax.random.normal(key, (n_stages, D, D)) / np.sqrt(D)

        def stage_fn(wj, x):  # wj [1, D, D]: this stage's slice
            return jnp.tanh(x @ wj[0]), jnp.zeros((), jnp.float32)

        x = jax.random.normal(jax.random.PRNGKey(1), (M, mb, D))
        run = make_pipeline(mesh, stage_fn, pipe_axis="pipe")
        got, _aux = jax.jit(run)(w, x)

        want = x
        for j in range(n_stages):
            want = jnp.tanh(want @ w[j])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)

    def test_differentiable(self):
        mesh = build_mesh(MeshSpec({"pipe": 8}))
        D, M, mb = 8, 16, 2
        w = jax.random.normal(jax.random.PRNGKey(0), (8, D, D)) / np.sqrt(D)
        x = jax.random.normal(jax.random.PRNGKey(1), (M, mb, D))

        def stage_fn(wj, x):
            return jnp.tanh(x @ wj[0]), jnp.zeros((), jnp.float32)

        run = make_pipeline(mesh, stage_fn)

        def loss_pp(w):
            return run(w, x)[0].sum()

        def loss_seq(w):
            y = x
            for j in range(8):
                y = jnp.tanh(y @ w[j])
            return y.sum()

        g_pp = jax.jit(jax.grad(loss_pp))(w)
        g_seq = jax.jit(jax.grad(loss_seq))(w)
        np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_seq),
                                   atol=1e-5, rtol=1e-5)


class TestMoE:
    def test_dispatch_matches_expert_loop(self):
        """Dense one-hot dispatch == per-token expert loop (no drops)."""
        cfg = moe.MoEConfig(
            vocab_size=64, dim=16, n_layers=1, n_heads=2, n_kv_heads=2,
            n_experts=4, ffn_dim=32, capacity_factor=4.0,  # no capacity drops
            dtype=jnp.float32, remat=False,
        )
        key = jax.random.PRNGKey(0)
        B, S = 2, 8
        x = jax.random.normal(key, (B, S, cfg.dim))
        router = jax.random.normal(jax.random.PRNGKey(1), (cfg.dim, cfg.n_experts))
        w_in = jax.random.normal(jax.random.PRNGKey(2),
                                 (cfg.n_experts, cfg.dim, cfg.ffn_dim)) * 0.1
        w_out = jax.random.normal(jax.random.PRNGKey(3),
                                  (cfg.n_experts, cfg.ffn_dim, cfg.dim)) * 0.1
        got, aux = moe.moe_ffn(x, router, w_in, w_out, cfg)

        xt = x.reshape(-1, cfg.dim)
        logits = xt @ router
        probs = jax.nn.softmax(logits, axis=-1)
        choice = jnp.argmax(probs, axis=-1)
        want = []
        for t in range(xt.shape[0]):
            e = int(choice[t])
            h = jax.nn.silu(xt[t] @ w_in[e])
            want.append((h @ w_out[e]) * probs[t, e])
        want = jnp.stack(want).reshape(B, S, cfg.dim)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)
        assert float(aux) > 0.0

    def test_capacity_drops_fall_back_to_residual(self):
        cfg = moe.MoEConfig(
            vocab_size=64, dim=8, n_layers=1, n_heads=2, n_kv_heads=2,
            n_experts=2, ffn_dim=16, capacity_factor=0.25,  # tiny capacity
            dtype=jnp.float32, remat=False,
        )
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, cfg.dim))
        router = jnp.zeros((cfg.dim, cfg.n_experts))  # all tokens -> expert 0
        w_in = jnp.ones((cfg.n_experts, cfg.dim, cfg.ffn_dim))
        w_out = jnp.ones((cfg.n_experts, cfg.ffn_dim, cfg.dim))
        out, _ = moe.moe_ffn(x, router, w_in, w_out, cfg)
        # capacity = 0.25*16/2 = 2: only 2 tokens routed, rest contribute 0
        nonzero_tokens = int(
            (jnp.abs(out.reshape(-1, cfg.dim)).sum(-1) > 1e-6).sum()
        )
        assert nonzero_tokens == 2

    def test_expert_parallel_train_step(self):
        """Full MoE loss+grad jitted over a data x expert mesh."""
        mesh = build_mesh(MeshSpec({"data": 2, "expert": 4}))
        cfg = moe.TINY_MOE
        params = moe.moe_init(jax.random.PRNGKey(0), cfg)
        pspecs = moe.param_pspecs(cfg)
        # prune axes absent from this mesh (no fsdp/tensor here)
        names = set(mesh.axis_names)

        def prune(s):
            return P(*(a if (a in names) else None
                       for a in (tuple(s) if len(s) else (None,))))

        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, prune(s)), pspecs,
            is_leaf=lambda x: isinstance(x, P),
        )
        params = jax.tree_util.tree_map(jax.device_put, params, shardings)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                    cfg.vocab_size)
        with mesh:
            loss, grads = jax.jit(
                jax.value_and_grad(lambda p: moe.moe_loss(p, tokens, cfg))
            )(params)
        assert np.isfinite(float(loss))
        g = jax.tree_util.tree_leaves(grads)
        assert all(np.isfinite(np.asarray(x)).all() for x in g)


class TestTrainerContextParallel:
    def test_trainer_with_sp_axis(self):
        from kubedl_tpu.training.data import SyntheticTokens
        from kubedl_tpu.training.trainer import TrainConfig, Trainer

        mesh = build_mesh(MeshSpec({"data": 2, "sp": 4}))
        cfg = TrainConfig(model=llama.TINY, global_batch=4, seq_len=64, steps=2)
        trainer = Trainer(cfg, mesh)
        data = iter(SyntheticTokens(cfg.global_batch, cfg.seq_len,
                                    llama.TINY.vocab_size))
        state, summary = trainer.fit(data, steps=2)
        assert np.isfinite(summary["final_loss"])


class TestPipeComposition:
    """VERDICT r2 #5: pipe x tensor (and MoE x pipe x expert) compose —
    the stage body issues megatron/expert collectives inside shard_map."""

    def _llama_cfg(self):
        return llama.LlamaConfig(
            vocab_size=128, dim=32, n_layers=4, n_heads=4, n_kv_heads=2,
            ffn_dim=64, max_seq=64, dtype=jnp.float32, remat=False,
        )

    def test_pipe_x_tensor_matches_unpipelined_loss(self):
        from kubedl_tpu.training.data import SyntheticTokens
        from kubedl_tpu.training.trainer import TrainConfig, Trainer

        model = self._llama_cfg()
        M = 4
        cfg = TrainConfig(model=model, global_batch=8, seq_len=16, steps=1,
                          microbatches=M, attn_impl="dense")
        mesh_pp = build_mesh(MeshSpec({"data": 2, "pipe": 2, "tensor": 2}))
        t_pp = Trainer(cfg, mesh_pp)
        mesh_1 = build_mesh(MeshSpec({"data": 8}))
        t_1 = Trainer(cfg, mesh_1)
        data = SyntheticTokens(cfg.global_batch, cfg.seq_len, model.vocab_size)
        batch = next(iter(data))
        # same PRNG -> same params; pipelined+tensor loss must equal plain
        s_pp = t_pp.init_state()
        s_1 = t_1.init_state()
        _, m_pp = t_pp.train_step(s_pp, t_pp.shard_batch(batch))
        _, m_1 = t_1.train_step(s_1, t_1.shard_batch(batch))
        import numpy as np

        np.testing.assert_allclose(
            float(jax.device_get(m_pp["loss"])),
            float(jax.device_get(m_1["loss"])),
            rtol=1e-4, atol=1e-4,
        )

    def test_pipe_x_tensor_trains(self):
        from kubedl_tpu.training.data import SyntheticTokens
        from kubedl_tpu.training.trainer import TrainConfig, Trainer

        model = self._llama_cfg()
        cfg = TrainConfig(model=model, global_batch=8, seq_len=16, steps=12,
                          microbatches=4, learning_rate=3e-3, warmup_steps=2,
                          attn_impl="dense")
        mesh = build_mesh(MeshSpec({"data": 2, "pipe": 2, "tensor": 2}))
        trainer = Trainer(cfg, mesh)
        import itertools

        batch = next(iter(
            SyntheticTokens(cfg.global_batch, cfg.seq_len, model.vocab_size)
        ))
        _, s = trainer.fit(itertools.repeat(batch))  # memorize one batch
        assert s["final_loss"] < s["first_loss"], s

    def test_moe_pipe_x_expert_trains(self):
        from kubedl_tpu.training.data import SyntheticTokens
        from kubedl_tpu.training.trainer import TrainConfig, Trainer

        mcfg = moe.MoEConfig(
            vocab_size=128, dim=32, n_layers=4, n_heads=2, n_kv_heads=2,
            n_experts=4, ffn_dim=64, dtype=jnp.float32, remat=False,
            capacity_factor=4.0,
        )
        cfg = TrainConfig(model=mcfg, global_batch=8, seq_len=16, steps=12,
                          microbatches=4, learning_rate=3e-3, warmup_steps=2,
                          attn_impl="dense")
        mesh = build_mesh(MeshSpec({"data": 2, "pipe": 2, "expert": 2}))
        trainer = Trainer(cfg, mesh)
        import itertools

        batch = next(iter(
            SyntheticTokens(cfg.global_batch, cfg.seq_len, mcfg.vocab_size)
        ))
        _, s = trainer.fit(itertools.repeat(batch))  # memorize one batch
        assert s["final_loss"] < s["first_loss"], s

    def test_moe_pipe_nll_matches_unpipelined(self):
        """With aux weight 0 and no capacity drops, the pipelined MoE loss
        must equal the plain pjit MoE loss exactly (same routing)."""
        import dataclasses

        from kubedl_tpu.training.data import SyntheticTokens
        from kubedl_tpu.training.trainer import TrainConfig, Trainer

        mcfg = moe.MoEConfig(
            vocab_size=128, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
            n_experts=4, ffn_dim=64, dtype=jnp.float32, remat=False,
            capacity_factor=8.0, aux_loss_weight=0.0,
        )
        cfg = TrainConfig(model=mcfg, global_batch=4, seq_len=16, steps=1,
                          microbatches=2, attn_impl="dense")
        t_pp = Trainer(cfg, build_mesh(MeshSpec({"data": 2, "pipe": 2, "expert": 2})))
        t_1 = Trainer(cfg, build_mesh(MeshSpec({"data": 4, "expert": 2})))
        data = SyntheticTokens(cfg.global_batch, cfg.seq_len, mcfg.vocab_size)
        batch = next(iter(data))
        _, m_pp = t_pp.train_step(t_pp.init_state(), t_pp.shard_batch(batch))
        _, m_1 = t_1.train_step(t_1.init_state(), t_1.shard_batch(batch))
        import numpy as np

        np.testing.assert_allclose(
            float(jax.device_get(m_pp["loss"])),
            float(jax.device_get(m_1["loss"])),
            rtol=1e-4, atol=1e-4,
        )

    def test_pipe_x_sp_still_rejected(self):
        from kubedl_tpu.training.trainer import TrainConfig, Trainer

        cfg = TrainConfig(model=self._llama_cfg(), global_batch=8, seq_len=16)
        mesh = build_mesh(MeshSpec({"pipe": 2, "sp": 2, "data": 2}))
        with pytest.raises(ValueError, match="sp"):
            Trainer(cfg, mesh)

    def test_indivisible_tensor_rejected(self):
        import dataclasses

        from kubedl_tpu.training.trainer import TrainConfig, Trainer

        model = dataclasses.replace(self._llama_cfg(), n_kv_heads=3, n_heads=6)
        cfg = TrainConfig(model=model, global_batch=8, seq_len=16)
        mesh = build_mesh(MeshSpec({"pipe": 2, "tensor": 2, "data": 2}))
        with pytest.raises(ValueError, match="divisible"):
            Trainer(cfg, mesh)

"""Regression tests for control-plane bugs found in review."""

import time

from kubedl_tpu.api import constants
from kubedl_tpu.api.topology import get_slice, peak_flops_for_device_kind
from kubedl_tpu.api.types import (
    JobConditionType,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
)
from kubedl_tpu.core.objects import Container, PodPhase
from kubedl_tpu.engine.expectations import ControllerExpectations, expectation_key
from kubedl_tpu.gang.slice_scheduler import SliceInventory
from kubedl_tpu.runtime.executor import ThreadRuntime

from tests.helpers import PodDriver, env_of, make_tpujob, pod_names
from tests.test_engine import make_engine, submit_and_reconcile


def test_evaluator_success_does_not_complete_job():
    """DEFAULT policy: only WORKER index-0 finishing succeeds a masterless
    job; a fast evaluator must not kill running workers."""
    engine, store, _ = make_engine()
    driver = PodDriver(store)
    job = make_tpujob(workers=2)
    ev = ReplicaSpec(replicas=1, restart_policy=RestartPolicy.NEVER)
    ev.template.spec.containers.append(Container())
    job.spec.replica_specs[ReplicaType.EVALUATOR] = ev
    submit_and_reconcile(engine, store, job)
    driver.run("job1-worker-0")
    driver.run("job1-worker-1")
    driver.succeed("job1-evaluator-0")
    engine.reconcile("default", "job1")
    got = store.get("TPUJob", "job1")
    assert got.status.phase != JobConditionType.SUCCEEDED
    assert "job1-worker-0" in pod_names(store)  # workers untouched
    driver.succeed("job1-worker-0")
    engine.reconcile("default", "job1")
    assert store.get("TPUJob", "job1").status.phase == JobConditionType.SUCCEEDED


def test_expectation_prefix_is_slash_bounded():
    exps = ControllerExpectations()
    exps.expect_creations(expectation_key("default/train2", "Worker", "pods"), 3)
    assert exps.all_satisfied("default/train")  # train != train2
    assert not exps.all_satisfied("default/train2")
    exps.delete_job_expectations("default/train2")
    assert exps.all_satisfied("default/train2")


def test_thread_runtime_systemexit_string_is_failure():
    import sys

    handle = ThreadRuntime.spawn(lambda env: sys.exit("fatal: bad config"), {})
    assert handle.wait() == 1


def test_thread_runtime_exit_codes():
    import sys

    assert ThreadRuntime.spawn(lambda env: None, {}).wait() == 0
    assert ThreadRuntime.spawn(lambda env: 3, {}).wait() == 3
    assert ThreadRuntime.spawn(lambda env: sys.exit(9), {}).wait() == 9
    assert ThreadRuntime.spawn(lambda env: sys.exit(None), {}).wait() == 0


def test_multislice_defaults_demand_and_env():
    """num_slices=2 on v5e-8: 4 workers over 2 slices, consistent
    MEGASCALE env, both slices reserved."""
    inventory = SliceInventory()
    inventory.add_slice("s1", "v5e-8")
    inventory.add_slice("s2", "v5e-8")
    engine, store, _ = make_engine(inventory=inventory)
    job = make_tpujob("ms", workers=1, topology=get_slice("v5e-8"))
    job.num_slices = 2
    submit_and_reconcile(engine, store, job)
    names = pod_names(store)
    assert len(names) == 4  # 2 slices x 2 hosts
    # slice assignment spans both slices
    slices = {store.get("Pod", n).spec.slice_assignment for n in names}
    assert slices == {"s1", "s2"}
    # MEGASCALE env consistent with physical binding
    for n in names:
        pod = store.get("Pod", n)
        env = env_of(pod)
        assert env[constants.ENV_MEGASCALE_NUM_SLICES] == "2"
        expected_slice = {"s1": "0", "s2": "1"}[pod.spec.slice_assignment]
        assert env[constants.ENV_MEGASCALE_SLICE_ID] == expected_slice, n
    assert inventory.describe() == {"s1": "default/ms-gang", "s2": "default/ms-gang"}


def test_evaluator_not_bound_to_slice_hosts():
    """Topology-less evaluator must not double-book slice hosts."""
    inventory = SliceInventory()
    inventory.add_slice("s1", "v5e-8")
    engine, store, _ = make_engine(inventory=inventory)
    driver = PodDriver(store)
    job = make_tpujob("j", workers=2, topology=get_slice("v5e-8"))
    from kubedl_tpu.api.types import DAGCondition, ReplicaPhase

    ev = ReplicaSpec(replicas=1, restart_policy=RestartPolicy.NEVER)
    ev.template.spec.containers.append(Container())
    job.spec.replica_specs[ReplicaType.EVALUATOR] = ev
    submit_and_reconcile(engine, store, job)
    worker_nodes = {
        store.get("Pod", n).spec.node_name
        for n in pod_names(store)
        if "worker" in n
    }
    ev_pod = store.get("Pod", "j-evaluator-0")
    assert ev_pod.spec.node_name == ""  # unconstrained, not a slice host
    assert worker_nodes == {"s1-host-0", "s1-host-1"}


def test_peak_flops_lookup_from_catalog():
    assert peak_flops_for_device_kind("TPU v5 lite") == 197e12
    assert peak_flops_for_device_kind("TPU v4") == 275e12
    assert peak_flops_for_device_kind("TPU v6 lite") == 918e12
    assert peak_flops_for_device_kind("Intel Xeon") == 0.0


def test_kubelet_configmap_resync_does_not_deadlock():
    """Reconciling a RUNNING pod that mounts a ConfigMap volume must
    re-materialize without re-entering the kubelet lock (deadlock found in
    review: reconcile held self._lock while _materialize_config_volumes
    acquired it again)."""
    import threading

    from kubedl_tpu.core.objects import ConfigMap, Pod, Volume
    from kubedl_tpu.core.store import ObjectStore
    from kubedl_tpu.runtime.executor import Kubelet, _PlaceholderHandle

    store = ObjectStore()
    cm = ConfigMap(data={"hostfile": "127.0.0.1 slots=1\n"})
    cm.metadata.name = "job-config"
    store.create(cm)
    pod = Pod()
    pod.metadata.name = "p1"
    import tempfile

    mount = tempfile.mkdtemp()
    pod.spec.volumes.append(Volume(name="cfg", config_map="job-config",
                                   mount_path=mount))
    pod.status.phase = PodPhase.RUNNING
    created = store.create(pod)

    kubelet = Kubelet(store, ThreadRuntime())
    with kubelet._lock:
        pass  # sanity: lock is free
    kubelet._running["default/p1"] = _PlaceholderHandle()

    done = threading.Event()

    def run():
        kubelet.reconcile("default", "p1")
        done.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert done.wait(5.0), "kubelet reconcile deadlocked"
    import os

    assert os.path.exists(os.path.join(mount, "hostfile"))


def test_cron_long_outage_fires_fresh_run_once():
    """After an outage far past the missed-run warning threshold, exactly
    ONE run fires and it carries the MOST RECENT slot time (review bug:
    capped accounting returned the oldest slot, launching stale runs)."""
    from datetime import datetime

    from kubedl_tpu.core.store import ObjectStore
    from kubedl_tpu.cron.controller import CronController
    from kubedl_tpu.cron.types import Cron
    from tests.test_cron import FakeClock, make_template, ts

    store = ObjectStore()
    clock = FakeClock(ts(2026, 1, 1, 10, 0))
    ctrl = CronController(store, ["TPUJob"], clock=clock)
    cron = Cron(schedule="* * * * *", template=make_template())
    cron.metadata.name = "mn"
    cron.metadata.creation_timestamp = clock.t
    store.create(cron)
    clock.t = ts(2026, 1, 3, 10, 0)  # 2 days of missed minutes
    ctrl.reconcile("default", "mn")
    jobs = store.list("TPUJob")
    assert len(jobs) == 1
    got = store.get("Cron", "mn")
    assert got.last_schedule_time == ts(2026, 1, 3, 10, 0)  # freshest slot
    # immediate re-reconcile must NOT fire another stale run
    ctrl.reconcile("default", "mn")
    assert len(store.list("TPUJob")) == 1

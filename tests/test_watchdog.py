"""Progress watchdog (kubedl_tpu/watchdog/): hang / straggler /
silent-death classification from per-step beacons, and the restart path
it drives.

Invariants asserted here:
- beacons ride the heartbeat channel onto Node objects and survive the
  codec (announce_progress AND the file source);
- classification is observation-based (clock-skew safe), startup grace
  covers compilation, and a replaced pod (new uid) gets a fresh window;
- hang and silent death fail the pod RETRYABLY (exit 137) and stamp a
  HangDetected condition; stragglers get an event + metric, no restart;
- watchdog restarts consume the SAME backoff_limit budget crash restarts
  do, and the boundary is exact (== limit continues, limit+1 fails);
- e2e: a chaos-injected hang (no pod exit) triggers HangDetected + a
  gang restart that resumes from the latest checkpoint (ISSUE 6
  acceptance).
"""

import time

import pytest

from kubedl_tpu import chaos
from kubedl_tpu.api import constants
from kubedl_tpu.api.types import JobConditionType, ReplicaType, RestartPolicy
from kubedl_tpu.chaos import FaultPlan, FaultSpec
from kubedl_tpu.core.nodes import NODE_NAMESPACE, NodeHeartbeater
from kubedl_tpu.core.objects import Container, Pod, PodPhase
from kubedl_tpu.core.store import ObjectStore
from kubedl_tpu.observability.metrics import JobMetrics, MetricsRegistry
from kubedl_tpu.watchdog import (
    ProgressBeacon,
    WatchdogConfig,
    WatchdogController,
    beacon_path,
    read_beacon,
)

from tests.helpers import make_tpujob


@pytest.fixture(autouse=True)
def _disarmed():
    chaos.disarm()
    yield
    chaos.disarm()


def make_pod(store, name, node="hostX", job="job1", phase=PodPhase.RUNNING,
             namespace="default"):
    p = Pod()
    p.metadata.name = name
    p.metadata.namespace = namespace
    p.metadata.labels = {
        constants.LABEL_JOB_NAME: job,
        constants.LABEL_JOB_KIND: "TPUJob",
    }
    p.spec.containers.append(Container())
    p.spec.node_name = node
    p.status.phase = phase
    store.create(p)
    return store.get("Pod", name, namespace)


# --------------------------------------------------------------------------
# Beacon primitives
# --------------------------------------------------------------------------


class TestBeacon:
    def test_write_read_roundtrip(self, tmp_path):
        path = beacon_path(str(tmp_path), "default", "p0")
        b = ProgressBeacon(path, clock=lambda: 42.0)
        b.step(7, tokens=1024.0)
        b.write_once()
        got = read_beacon(path)
        assert got == {"step": 7.0, "tokens": 1024.0, "ts": 42.0}

    def test_read_missing_or_malformed_is_none(self, tmp_path):
        assert read_beacon(str(tmp_path / "nope.json")) is None
        bad = tmp_path / "bad.json"
        bad.write_text("{half a json")
        assert read_beacon(str(bad)) is None
        bad.write_text('{"no_step": 1}')
        assert read_beacon(str(bad)) is None

    def test_writer_thread_stamps_fresh_ts_while_step_frozen(self, tmp_path):
        """The hang signature: a wedged step loop never calls .step() again
        but the side thread keeps refreshing ts."""
        path = str(tmp_path / "b.json")
        with ProgressBeacon(path, interval=0.05) as b:
            b.step(3)
            time.sleep(0.2)
            first = read_beacon(path)
            time.sleep(0.2)
            second = read_beacon(path)
        assert first["step"] == second["step"] == 3.0
        assert second["ts"] > first["ts"]
        assert b.writes >= 3

    def test_file_source_scans_only_this_nodes_live_pods(self, tmp_path):
        from kubedl_tpu.watchdog import FileBeaconSource

        store = ObjectStore()
        make_pod(store, "p0", node="hostX")
        make_pod(store, "p1", node="hostY")
        make_pod(store, "p2", node="hostX", phase=PodPhase.SUCCEEDED)
        for name in ("p0", "p1", "p2"):
            b = ProgressBeacon(beacon_path(str(tmp_path), "default", name))
            b.step(5)
            b.write_once()
        src = FileBeaconSource(str(tmp_path), store)
        got = src("hostX")
        assert set(got) == {"default/p0"}  # not hostY's, not the terminal
        assert got["default/p0"]["step"] == 5.0


class TestHeartbeatChannel:
    def test_announce_progress_rides_beat_onto_node(self):
        store = ObjectStore()
        hb = NodeHeartbeater(store, ["hostX"], clock=lambda: 100.0)
        hb.announce_progress("hostX", "default/p0", step=4, tokens=64.0)
        hb.beat_once()
        node = store.get("Node", "hostX", NODE_NAMESPACE)
        assert node.beacons["default/p0"]["step"] == 4.0
        assert node.beacons["default/p0"]["ts"] == 100.0

    def test_beat_replaces_the_mapping(self):
        """A pod that left the node drops off the Node object on the next
        beat — no stale beacon lingers to confuse the watchdog."""
        store = ObjectStore()
        hb = NodeHeartbeater(store, ["hostX"])
        hb.announce_progress("hostX", "default/p0", step=1)
        hb.beat_once()
        hb.clear_progress("hostX", "default/p0")
        hb.beat_once()
        assert store.get("Node", "hostX", NODE_NAMESPACE).beacons == {}

    def test_beacons_survive_the_codec(self):
        from kubedl_tpu.api.codec import decode_object, encode
        from kubedl_tpu.core.objects import Node

        n = Node(beacons={"ns/p": {"step": 2.0, "tokens": 3.0, "ts": 9.0}})
        n.metadata.name = "hostX"
        assert decode_object(encode(n)).beacons == n.beacons

    def test_chaos_freeze_leaves_node_map_untouched(self):
        """watchdog.beacon: the kubelet's beacon publish wedges while its
        heartbeat stays healthy — the Node keeps the OLD beacons (frozen),
        which is exactly the silent-death signature downstream."""
        store = ObjectStore()
        t = {"now": 100.0}
        hb = NodeHeartbeater(store, ["hostX"], clock=lambda: t["now"])
        hb.announce_progress("hostX", "default/p0", step=1)
        hb.beat_once()
        with FaultPlan(1, sites={"watchdog.beacon": [FaultSpec.always()]}):
            t["now"] = 105.0
            hb.announce_progress("hostX", "default/p0", step=9)
            hb.beat_once()
        node = store.get("Node", "hostX", NODE_NAMESPACE)
        assert node.last_heartbeat == 105.0  # heartbeat itself healthy
        assert node.beacons["default/p0"]["step"] == 1.0  # frozen


# --------------------------------------------------------------------------
# Classification (fake clock, manual store)
# --------------------------------------------------------------------------


def _rig(grace=50.0, min_budget=5.0, mult=3.0, ratio=0.25):
    store = ObjectStore()
    t = {"now": 1000.0}
    clock = lambda: t["now"]
    hb = NodeHeartbeater(store, ["hostX"], clock=clock)
    metrics = JobMetrics(MetricsRegistry())
    wd = WatchdogController(
        store, metrics=metrics, clock=clock,
        config=WatchdogConfig(
            multiplier=mult, min_budget_seconds=min_budget,
            startup_grace_seconds=grace, straggler_ratio=ratio,
        ),
    )
    return store, t, hb, wd, metrics


def _tick(t, hb, wd, pod_key="default/p0", step=None, dt=1.0):
    """Advance the fake clock, beat a fresh beacon, reconcile."""
    t["now"] += dt
    if step is not None:
        hb.announce_progress("hostX", pod_key, step=step, ts=t["now"])
    hb.beat_once()
    wd.reconcile(NODE_NAMESPACE, "hostX")


class TestClassification:
    def test_hang_fires_after_ewma_budget(self):
        store, t, hb, wd, metrics = _rig()
        store.create(make_tpujob("job1", workers=1))
        make_pod(store, "p0")
        _tick(t, hb, wd, step=1)
        for s in range(2, 7):  # steady 1s steps: ewma ~= 1
            _tick(t, hb, wd, step=s)
        # freeze the step, keep ts fresh (the step loop wedged, the beacon
        # thread did not): budget = max(5, 3*~1) = 5s
        for _ in range(4):
            _tick(t, hb, wd, step=6)  # 4s frozen: under budget
        assert store.get("Pod", "p0").status.phase == PodPhase.RUNNING
        for _ in range(3):
            _tick(t, hb, wd, step=6)  # 7s frozen: past budget
        pod = store.get("Pod", "p0")
        assert pod.status.phase == PodPhase.FAILED
        assert pod.status.reason == "HangDetected"
        assert pod.status.container_statuses[0].exit_code == 137
        assert wd.fired["hang"] == 1
        assert metrics.watchdog_restarts.value(reason="hang") == 1
        job = store.get("TPUJob", "job1")
        assert job.status.phase == JobConditionType.HANG_DETECTED
        assert any(e.reason == "HangDetected"
                   for e in store.list("Event", None))
        assert wd.tracked() == 0  # track dropped with the pod

    def test_silent_death_fires_when_beacons_stop(self):
        store, t, hb, wd, _ = _rig()
        store.create(make_tpujob("job1", workers=1))
        make_pod(store, "p0")
        _tick(t, hb, wd, step=1)
        for s in range(2, 6):  # beacon ewma ~= 1 -> silent budget = 5
            _tick(t, hb, wd, step=s)
        # beacons stop ENTIRELY (ts frozen too): the Node map keeps the
        # last value; only the requeue timer re-evaluates
        for _ in range(7):
            t["now"] += 1.0
            wd.reconcile(NODE_NAMESPACE, "hostX")
        pod = store.get("Pod", "p0")
        assert pod.status.phase == PodPhase.FAILED
        assert wd.fired["silent_death"] == 1
        assert store.get("TPUJob", "job1").status.phase == (
            JobConditionType.HANG_DETECTED
        )

    def test_startup_grace_covers_compilation(self):
        """No step has EVER advanced: the budget is startup_grace (compile/
        restore time is unknowable), not min_budget."""
        store, t, hb, wd, _ = _rig(grace=50.0, min_budget=5.0)
        store.create(make_tpujob("job1", workers=1))
        make_pod(store, "p0")
        _tick(t, hb, wd, step=0)
        for _ in range(40):  # 40s of fresh beacons, step pinned at 0
            _tick(t, hb, wd, step=0)
        assert store.get("Pod", "p0").status.phase == PodPhase.RUNNING
        for _ in range(12):  # past the 50s grace
            _tick(t, hb, wd, step=0)
        assert store.get("Pod", "p0").status.phase == PodPhase.FAILED
        assert wd.fired["hang"] == 1

    def test_replacement_pod_gets_fresh_window(self):
        """Same name, new uid (gang restart): the track resets — the new
        incarnation must not inherit the dead one's stale clocks."""
        store, t, hb, wd, _ = _rig(grace=50.0)
        store.create(make_tpujob("job1", workers=1))
        make_pod(store, "p0")
        _tick(t, hb, wd, step=1)
        for s in range(2, 7):
            _tick(t, hb, wd, step=s)
        store.delete("Pod", "p0")
        make_pod(store, "p0")  # fresh uid, restarting from scratch
        for _ in range(20):  # 20s at step 0: under the fresh 50s grace
            _tick(t, hb, wd, step=0)
        assert store.get("Pod", "p0").status.phase == PodPhase.RUNNING
        assert wd.fired == {"hang": 0, "silent_death": 0}

    def test_pending_pod_never_fires(self):
        store, t, hb, wd, _ = _rig(grace=5.0, min_budget=2.0)
        store.create(make_tpujob("job1", workers=1))
        make_pod(store, "p0", phase=PodPhase.PENDING)
        _tick(t, hb, wd, step=0)
        for _ in range(20):
            _tick(t, hb, wd, step=0)
        assert store.get("Pod", "p0").status.phase == PodPhase.PENDING

    def test_straggler_flagged_not_restarted_and_recovers(self):
        store, t, hb, wd, metrics = _rig()
        store.create(make_tpujob("job1", workers=2))
        make_pod(store, "p0")
        make_pod(store, "p1")
        sa, sb = 0, 0
        for _ in range(12):  # A: 10 steps/s, B: 1 step/s -> B < 0.25*median
            sa += 10
            sb += 1
            t["now"] += 1.0
            hb.announce_progress("hostX", "default/p0", step=sa, ts=t["now"])
            hb.announce_progress("hostX", "default/p1", step=sb, ts=t["now"])
            hb.beat_once()
            wd.reconcile(NODE_NAMESPACE, "hostX")
        assert store.get("Pod", "p1").status.phase == PodPhase.RUNNING
        assert wd.fired == {"hang": 0, "silent_death": 0}
        assert metrics.watchdog_stragglers.value() == 1  # gauge: 1 current
        assert any(e.reason == "Straggler" for e in store.list("Event", None))
        # the once-per-track job event is the durable audit record (the
        # PS tier keys straggler decay off it)
        job_events = [
            e for e in store.list("Event", None)
            if e.reason == "StragglerDetected"
        ]
        assert len(job_events) == 1
        assert job_events[0].involved_kind == "TPUJob"
        assert job_events[0].involved_name == "job1"
        assert "p1" in job_events[0].message
        # B recovers: the flag clears (so a later relapse re-counts) and
        # the gauge drops back to zero with it
        for _ in range(25):
            sa += 10
            sb += 10
            t["now"] += 1.0
            hb.announce_progress("hostX", "default/p0", step=sa, ts=t["now"])
            hb.announce_progress("hostX", "default/p1", step=sb, ts=t["now"])
            hb.beat_once()
            wd.reconcile(NODE_NAMESPACE, "hostX")
        assert all(not tr.straggler for tr in wd._tracks.values())
        assert metrics.watchdog_stragglers.value() == 0.0
        # recovery does not re-fire the job event
        assert sum(
            1 for e in store.list("Event", None)
            if e.reason == "StragglerDetected"
        ) == 1


class TestGoodputBreakdown:
    """The goodput() blind spot: one ratio can't say WHERE the time went.
    stats() must attribute non-productive seconds to checkpoint / restart
    / re-admission buckets (console /api/v1/data/goodput)."""

    def test_stats_attributes_checkpoint_restart_readmission(self):
        store, t, hb, wd, _ = _rig()
        store.create(make_tpujob("job1", workers=1))
        make_pod(store, "p0")
        _tick(t, hb, wd, step=1)  # track created
        for s in range(2, 8):
            _tick(t, hb, wd, step=s)  # steady 1 s/step -> EWMA ~1 s
        base = wd.stats()["default/job1"]
        assert base["checkpoint_seconds"] == 0.0
        assert base["restart_seconds"] == 0.0

        # one 6 s step on a LIVE replica: the excess over the step-time
        # EWMA is checkpoint/recompile stall
        _tick(t, hb, wd, step=8, dt=6.0)
        got = wd.stats()["default/job1"]
        assert 3.0 < got["checkpoint_seconds"] <= 5.5
        assert got["restart_seconds"] == 0.0

        # same-name replacement (gang restart): the beacon gap between
        # the dead incarnation and its replacement is restart loss
        store.delete("Pod", "p0")
        make_pod(store, "p0")
        _tick(t, hb, wd, step=2, dt=10.0)  # fresh uid detected here
        got = wd.stats()["default/job1"]
        assert got["restart_seconds"] == pytest.approx(10.0)

        # the replacement's FIRST advance: restore + warm-join excess
        # over the predecessor's pace is re-admission loss
        assert got["readmission_seconds"] == 0.0
        _tick(t, hb, wd, step=3, dt=5.0)
        got = wd.stats()["default/job1"]
        assert 2.0 < got["readmission_seconds"] <= 5.0

        # report shape: every bucket present, goodput a sane ratio
        for k in (
            "productive_seconds", "lost_seconds", "unattributed_seconds",
            "checkpoint_seconds", "restart_seconds", "readmission_seconds",
            "goodput", "replicas", "stragglers", "kind",
        ):
            assert k in got
        assert 0.0 < got["goodput"] <= 1.0
        assert got["unattributed_seconds"] >= 0.0


# --------------------------------------------------------------------------
# Restart budget integration (satellite: backoff boundary)
# --------------------------------------------------------------------------


from tests.test_engine import make_engine, submit_and_reconcile  # noqa: E402
from tests.helpers import PodDriver, pod_names  # noqa: E402


class TestBackoffBudget:
    def test_restart_count_at_limit_continues(self):
        """Boundary: _check_limits uses `>` — restart_count == backoff_limit
        must still rebuild the gang."""
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=1)
        job.spec.run_policy.backoff_limit = 1
        submit_and_reconcile(engine, store, job)
        driver.fail("job1-worker-0", exit_code=137)
        engine.reconcile("default", "job1")  # slice restart
        engine.reconcile("default", "job1")  # recreate
        got = store.get("TPUJob", "job1")
        assert got.status.restart_count == 1  # == limit
        assert got.status.phase != JobConditionType.FAILED
        assert pod_names(store) == ["job1-worker-0"]

    def test_restart_count_past_limit_fails(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=1)
        job.spec.run_policy.backoff_limit = 1
        submit_and_reconcile(engine, store, job)
        for _ in range(2):
            driver.fail("job1-worker-0", exit_code=137)
            engine.reconcile("default", "job1")
            engine.reconcile("default", "job1")
        got = store.get("TPUJob", "job1")
        assert got.status.restart_count == 2  # == limit + 1
        assert got.status.phase == JobConditionType.FAILED
        assert got.status.conditions[-1].reason == "BackoffLimitExceeded"

    def test_watchdog_restart_counts_against_backoff_budget(self):
        """A watchdog-failed pod takes the SAME gang-restart path a crash
        does: restart_count increments, and with backoff_limit=0 the very
        first watchdog fire exhausts the budget."""
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        t = {"now": 1000.0}
        hb = NodeHeartbeater(store, ["hostX"], clock=lambda: t["now"])
        wd = WatchdogController(
            store, clock=lambda: t["now"],
            config=WatchdogConfig(multiplier=3.0, min_budget_seconds=5.0,
                                  startup_grace_seconds=50.0),
        )
        job = make_tpujob(workers=1)
        job.spec.run_policy.backoff_limit = 0
        submit_and_reconcile(engine, store, job)
        driver.run("job1-worker-0")
        store.update_with_retry(  # pin to the beaconing host
            "Pod", "job1-worker-0", "default",
            lambda p: setattr(p.spec, "node_name", "hostX"),
        )
        s = 0
        for _ in range(6):
            s += 1
            _tick(t, hb, wd, pod_key="default/job1-worker-0", step=s)
        for _ in range(7):  # wedge past budget -> watchdog fails the pod
            _tick(t, hb, wd, pod_key="default/job1-worker-0", step=s)
        assert store.get("Pod", "job1-worker-0").status.phase == PodPhase.FAILED
        engine.reconcile("default", "job1")  # gang restart: count += 1
        got = store.get("TPUJob", "job1")
        assert got.status.restart_count == 1
        engine.reconcile("default", "job1")
        # 1 > backoff_limit 0: the watchdog restart consumed the budget
        assert store.get("TPUJob", "job1").status.phase == (
            JobConditionType.FAILED
        )


# --------------------------------------------------------------------------
# E2e: injected hang -> HangDetected -> gang restart resumes from checkpoint
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_injected_hang_gang_restarts_and_resumes(tmp_path):
    """ISSUE 6 acceptance: a deterministic chaos-injected hang (the pod
    never exits) is classified by the watchdog, the job gains a
    HangDetected condition, and the gang restart resumes from the latest
    checkpoint instead of step 0."""
    import json

    from kubedl_tpu.core.objects import EnvVar
    from kubedl_tpu.operator import Operator, OperatorOptions
    from kubedl_tpu.runtime.executor import ThreadRuntime
    from kubedl_tpu.training import entry as entry_mod

    opts = OperatorOptions(
        local_addresses=True,
        artifact_registry_root=str(tmp_path / "reg"),
        node_grace_seconds=3.0,          # heartbeat (and beacon publish) every 1s
        heartbeat_nodes=["hostX"],
        beacon_dir=str(tmp_path / "beacons"),
        watchdog_multiplier=3.0,
        watchdog_min_budget_seconds=1.0,
        # generous: compile time must never fire the watchdog; the hang
        # budget comes from the observed step EWMA (~0.7s latency spec)
        watchdog_startup_grace_seconds=300.0,
    )
    cfg = {"model": "tiny", "steps": 6, "global_batch": 8, "seq_len": 32,
           "ckpt_every": 2}
    # call 3 (= step 3 of attempt 1) wedges the step loop WITHOUT exiting;
    # every other call pays a 700ms latency so beacons observe real step
    # spacing before the wedge (the EWMA the hang budget derives from)
    plan = FaultPlan(7, sites={"trainer.step_stall": [
        FaultSpec.nth(3), FaultSpec.latency(700.0, every=1),
    ]})
    with plan, Operator(opts, runtime=ThreadRuntime()) as op:
        job = make_tpujob(
            "hangjob", workers=1,
            entrypoint="kubedl_tpu.training.entry:train_main",
        )
        spec = job.spec.replica_specs[ReplicaType.WORKER]
        spec.template.spec.node_name = "hostX"
        main = spec.template.spec.containers[0]
        main.env.append(EnvVar("KUBEDL_TRAIN_CONFIG", json.dumps(cfg)))
        main.env.append(EnvVar(constants.ENV_CKPT_DIR, str(tmp_path / "ck")))
        op.submit(job)
        got = op.wait_for_phase(
            "TPUJob", "hangjob",
            [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
            timeout=180,
        )
        assert got.status.phase == JobConditionType.SUCCEEDED
        assert got.status.restart_count >= 1
        assert any(c.type == JobConditionType.HANG_DETECTED
                   for c in got.status.conditions), got.status.conditions
        assert any(e.reason == "HangDetected"
                   for e in op.store.list("Event", None))
        assert op.metrics.watchdog_restarts.value(reason="hang") >= 1
    # the retried attempt resumed from the step-2 checkpoint, not step 0
    assert entry_mod.LAST_SUMMARY is not None
    assert entry_mod.LAST_SUMMARY["start_step"] >= 2
    assert plan.faults("trainer.step_stall") == 1

"""Parameter-service aggregation tier (kubedl_tpu/ps/): bounded
staleness, atomic membership departure, lease-fenced shard failover with
WAL replay, and the seeded chaos cases KTL008 cross-references by
site literal (`ps.push`, `ps.pull`, `ps.shard_failover`).
"""

import numpy as np
import pytest

from kubedl_tpu import chaos
from kubedl_tpu.chaos import FaultInjected, FaultPlan, FaultSpec
from kubedl_tpu.core.store import ObjectStore
from kubedl_tpu.observability.metrics import PSMetrics
from kubedl_tpu.ps import (
    MemberEvicted,
    PSConfig,
    ParameterService,
    PushRejected,
    shard_for,
)
from kubedl_tpu.ps.shards import FencedOut, ShardState, partition
from kubedl_tpu.ps.server import PSClient, PSServer, PSUnavailable


def make_ps(num_shards=2, max_staleness=2, decay=0.5, wal_root="",
            lease_ttl=5.0, clock=None, params=None, **kw):
    cfg = PSConfig(
        num_shards=num_shards, max_staleness=max_staleness, decay=decay,
        wal_root=wal_root, lease_ttl=lease_ttl, **kw,
    )
    if params is None:
        params = {"w.a": np.zeros(4, np.float32), "w.b": np.zeros(3, np.float32),
                  "w.c": np.zeros(2, np.float32)}
    return ParameterService(
        params, cfg, store=ObjectStore(), metrics=PSMetrics(),
        clock=clock or __import__("time").time,
    )


DELTA = {"w.a": np.full(4, 1.0, np.float32),
         "w.b": np.full(3, 1.0, np.float32),
         "w.c": np.full(2, 1.0, np.float32)}


# --------------------------------------------------------------------------
# Hash partitioning
# --------------------------------------------------------------------------


class TestSharding:
    def test_shard_for_is_deterministic_and_in_range(self):
        for n in (1, 2, 3, 8):
            for name in ("w.a", "layers.0.q", "emb", ""):
                s = shard_for(name, n)
                assert s == shard_for(name, n)
                assert 0 <= s < n

    def test_partition_covers_every_name_once(self):
        names = [f"p{i}" for i in range(50)]
        parts = partition(names, 4)
        assert sorted(n for p in parts for n in p) == sorted(names)

    def test_service_routes_each_param_to_its_shard(self):
        svc = make_ps(num_shards=3)
        for sh in svc.shards:
            for name in sh.params:
                assert shard_for(name, 3) == sh.shard_id


# --------------------------------------------------------------------------
# Bounded staleness + decay weighting
# --------------------------------------------------------------------------


class TestStaleness:
    def test_fresh_push_full_weight(self):
        svc = make_ps()
        _, v = svc.register("w0")
        res = svc.push("w0", 1, DELTA, versions=v)
        assert res.outcome == "fresh" and res.weight == 1.0
        assert res.staleness == 0
        snap = svc.snapshot()
        np.testing.assert_allclose(snap["w.a"], np.full(4, 1.0))

    def test_stale_push_decay_weighted(self):
        svc = make_ps(max_staleness=4, decay=0.5)
        _, v0 = svc.register("slow")
        svc.register("fast")
        # fast advances the head twice; slow's anchor is now 2 behind
        _, vf = svc.pull("fast")
        vf = svc.push("fast", 1, DELTA, versions=vf).versions
        svc.push("fast", 2, DELTA, versions=vf)
        res = svc.push("slow", 1, DELTA, versions=v0)
        assert res.outcome == "decayed"
        assert res.staleness == 2
        assert res.weight == pytest.approx(0.25)  # 0.5 ** 2
        # the decayed delta landed at quarter weight on top of the 2 fulls
        np.testing.assert_allclose(svc.snapshot()["w.a"], np.full(4, 2.25))

    def test_push_beyond_bound_rejected_whole_then_repull_succeeds(self):
        svc = make_ps(max_staleness=1)
        _, v0 = svc.register("slow")
        svc.register("fast")
        _, vf = svc.pull("fast")
        for step in range(3):  # head moves 3 past slow's anchor
            vf = svc.push("fast", step, DELTA, versions=vf).versions
        before = svc.snapshot()
        with pytest.raises(PushRejected) as ei:
            svc.push("slow", 1, DELTA, versions=v0)
        # nothing applied — the reject is all-or-nothing across shards
        after = svc.snapshot()
        for k in before:
            np.testing.assert_array_equal(before[k], after[k])
        assert ei.value.versions == svc.versions()
        # the protocol: re-pull, then push at staleness 0
        _, v1 = svc.pull("slow")
        assert svc.push("slow", 2, DELTA, versions=v1).outcome == "fresh"
        assert svc.metrics.ps_pushes.value(outcome="rejected") == 1

    def test_straggler_gets_extra_decay(self):
        svc = make_ps(straggler_decay=0.5)
        _, v = svc.register("w0")
        svc.mark_straggler("w0", True)
        res = svc.push("w0", 1, DELTA, versions=v)
        assert res.outcome == "decayed" and res.weight == pytest.approx(0.5)
        svc.mark_straggler("w0", False)
        res = svc.push("w0", 2, DELTA, versions=res.versions)
        assert res.weight == pytest.approx(1.0)


# --------------------------------------------------------------------------
# Event-driven membership: commit vs discard, late join
# --------------------------------------------------------------------------


class TestMembership:
    def test_preemption_notice_commits_staged_inflight(self):
        svc = make_ps()
        svc.register("victim")
        svc.stage_push("victim", DELTA, weight=1.0)
        before = svc.versions()
        svc.handle_preemption_notice("victim")
        assert "victim" not in svc.members()
        assert sum(svc.versions()) > sum(before)  # contribution committed
        np.testing.assert_allclose(svc.snapshot()["w.a"], np.full(4, 1.0))
        assert svc.metrics.ps_evictions.value(reason="preemption") == 1

    def test_silent_death_discards_staged_inflight(self):
        svc = make_ps()
        svc.register("zombie")
        svc.stage_push("zombie", DELTA, weight=1.0)
        svc.evict_silent_death("zombie")
        assert "zombie" not in svc.members()
        np.testing.assert_allclose(svc.snapshot()["w.a"], np.zeros(4))
        assert svc.metrics.ps_evictions.value(reason="silent_death") == 1
        # an evicted member's ops bounce until it re-registers
        with pytest.raises(MemberEvicted):
            svc.push("zombie", 5, DELTA)
        with pytest.raises(MemberEvicted):
            svc.pull("zombie")

    def test_late_joiner_warm_starts_from_aggregate(self):
        svc = make_ps()
        _, v = svc.register("w0")
        svc.push("w0", 1, DELTA, versions=v)
        snap, versions = svc.register("late")
        np.testing.assert_allclose(snap["w.a"], np.full(4, 1.0))
        assert versions == svc.versions()
        assert svc.metrics.ps_members.value() == 2.0

    def test_watchdog_fire_evicts_via_listener(self):
        class FakeWatchdog:
            def __init__(self):
                self.listeners = []

        wd = FakeWatchdog()
        svc = make_ps()
        svc.register("w0")
        svc.stage_push("w0", DELTA)
        svc.bind_watchdog(wd, worker_for_pod=lambda pod: pod.replace("p", "w"))
        for fn in wd.listeners:
            fn("p0", "silent_death")
        assert "w0" not in svc.members()
        np.testing.assert_allclose(svc.snapshot()["w.a"], np.zeros(4))


# --------------------------------------------------------------------------
# Shard failover: lease fencing + WAL replay
# --------------------------------------------------------------------------


class TestFailover:
    def test_failover_replays_wal_and_bumps_fence(self, tmp_path):
        t = {"now": 1000.0}
        clock = lambda: t["now"]
        svc = make_ps(wal_root=str(tmp_path), lease_ttl=2.0, clock=clock)
        _, v = svc.register("w0")
        v = svc.push("w0", 1, DELTA, versions=v).versions
        v = svc.push("w0", 2, DELTA, versions=v).versions
        want = {k: a.copy() for k, a in svc.snapshot().items()}
        old_fences = [sh.fence for sh in svc.shards]
        svc.fail_shard(0)
        t["now"] += 10.0  # fake-clock past the dead owner's lease
        svc.recover_shard(0)
        got = svc.snapshot()
        for k in want:  # replayed to the exact pre-crash state
            np.testing.assert_allclose(got[k], want[k])
        assert svc.versions() == v
        assert svc.shards[0].fence > old_fences[0]
        assert svc.metrics.ps_shard_failovers.value() == 1
        # survivors keep pushing through the new owner
        assert svc.push("w0", 3, DELTA, versions=v).outcome == "fresh"

    def test_deposed_owner_write_is_fenced_out(self, tmp_path):
        t = {"now": 0.0}
        store = ObjectStore()
        sh = ShardState(0, store, wal_dir=str(tmp_path), lease_ttl=2.0,
                        clock=lambda: t["now"])
        sh.open("owner-a")
        sh.init_params({"w.a": np.zeros(2, np.float32)})
        stale_token = sh.fence
        sh.kill()
        t["now"] += 10.0
        sh.open("owner-b")  # lease steal bumps transitions
        assert sh.fence > stale_token
        with pytest.raises(FencedOut):
            sh.apply("w0", 1.0, {"w.a": np.ones(2, np.float32)},
                     fence=stale_token)

    def test_dead_shard_without_autorecover_raises(self):
        from kubedl_tpu.ps import ShardUnavailable

        svc = make_ps(auto_recover=False)
        svc.register("w0")
        svc.fail_shard(0)
        with pytest.raises(ShardUnavailable):
            svc.pull("w0")


# --------------------------------------------------------------------------
# Seeded chaos (KTL008: one case per ps.* site, armed by literal)
# --------------------------------------------------------------------------


class TestChaos:
    def test_ps_push_drop_is_all_or_nothing(self):
        svc = make_ps()
        _, v = svc.register("w0")
        with FaultPlan(7, sites={"ps.push": [FaultSpec.nth(1)]}):
            with pytest.raises(FaultInjected):
                svc.push("w0", 1, DELTA, versions=v)
            np.testing.assert_allclose(svc.snapshot()["w.a"], np.zeros(4))
            # the retry (call #2) lands in full
            res = svc.push("w0", 1, DELTA, versions=v)
        assert res.outcome == "fresh"
        np.testing.assert_allclose(svc.snapshot()["w.a"], np.full(4, 1.0))

    def test_ps_pull_drop_then_retry(self):
        svc = make_ps()
        svc.register("w0")
        with FaultPlan(7, sites={"ps.pull": [FaultSpec.nth(1)]}):
            with pytest.raises(FaultInjected):
                svc.pull("w0")
            params, versions = svc.pull("w0")
        assert versions == svc.versions()

    def test_ps_shard_failover_mid_run_keeps_survivors_going(self, tmp_path):
        t = {"now": 0.0}
        svc = make_ps(wal_root=str(tmp_path), lease_ttl=0.5,
                      clock=lambda: t["now"])
        _, v = svc.register("w0")
        v = svc.push("w0", 1, DELTA, versions=v).versions
        with FaultPlan(3, sites={"ps.shard_failover": [FaultSpec.nth(1)]}):
            t["now"] += 5.0  # next op kills a shard AND can steal its lease
            res = svc.push("w0", 2, DELTA, versions=v)
        assert res.outcome == "fresh"  # failover was transparent
        assert svc.stats()["failovers"] == 1
        np.testing.assert_allclose(svc.snapshot()["w.a"], np.full(4, 2.0))

    def test_seeded_chaos_trace_is_deterministic(self):
        def drive(plan):
            svc = make_ps()
            _, v = svc.register("w0")
            for step in range(12):
                try:
                    v = svc.push("w0", step, DELTA, versions=v).versions
                except FaultInjected:
                    pass
                try:
                    _, v = svc.pull("w0")
                except FaultInjected:
                    pass
            with plan:
                pass  # ensure same arm/disarm shape
            return plan.trace_tuples()

        sites = {"ps.push": [FaultSpec.prob(0.4, 20)],
                 "ps.pull": [FaultSpec.prob(0.2, 20)]}
        with FaultPlan(11, sites=sites) as p1:
            t1 = drive(p1)
        with FaultPlan(11, sites=sites) as p2:
            t2 = drive(p2)
        assert t1 == t2
        with FaultPlan(12, sites=sites) as p3:
            t3 = drive(p3)
        assert t1 != t3


# --------------------------------------------------------------------------
# HTTP front + client (protocol = exception mapping)
# --------------------------------------------------------------------------


class TestServer:
    def test_register_push_pull_roundtrip(self):
        svc = make_ps()
        with PSServer(svc) as srv:
            c = PSClient(srv.addr)
            snap, v = c.register("w0")
            assert set(snap) == {"w.a", "w.b", "w.c"}
            res = c.push("w0", 1, DELTA, versions=v)
            assert res.outcome == "fresh"
            pulled, v2 = c.pull("w0")
            np.testing.assert_allclose(pulled["w.a"], np.full(4, 1.0))
            assert v2 == res.versions
            assert c.stats()["members"] == ["w0"]

    def test_409_maps_to_push_rejected_with_versions(self):
        svc = make_ps(max_staleness=0)
        with PSServer(svc) as srv:
            c = PSClient(srv.addr)
            _, v0 = c.register("slow")
            _, vf = c.register("fast")
            c.push("fast", 1, DELTA, versions=vf)
            with pytest.raises(PushRejected) as ei:
                c.push("slow", 1, DELTA, versions=v0)
            assert ei.value.versions == svc.versions()

    def test_410_maps_to_member_evicted(self):
        svc = make_ps()
        with PSServer(svc) as srv:
            c = PSClient(srv.addr)
            c.register("w0")
            svc.evict_silent_death("w0")
            with pytest.raises(MemberEvicted):
                c.push("w0", 1, DELTA)

    def test_injected_fault_maps_to_503_ps_unavailable(self):
        svc = make_ps()
        with PSServer(svc) as srv:
            c = PSClient(srv.addr)
            _, v = c.register("w0")
            with FaultPlan(7, sites={"ps.push": [FaultSpec.nth(1)]}):
                with pytest.raises(PSUnavailable):
                    c.push("w0", 1, DELTA, versions=v)
            assert c.push("w0", 1, DELTA, versions=v).outcome == "fresh"

    def test_dead_server_maps_to_ps_unavailable(self):
        svc = make_ps()
        srv = PSServer(svc).start()
        addr = srv.addr
        srv.stop()
        with pytest.raises(PSUnavailable):
            PSClient(addr, timeout=0.5).register("w0")

    def test_admin_fail_and_recover_shard(self, tmp_path):
        t = {"now": 0.0}
        svc = make_ps(wal_root=str(tmp_path), lease_ttl=0.2,
                      clock=lambda: t["now"], auto_recover=False)
        with PSServer(svc) as srv:
            c = PSClient(srv.addr)
            _, v = c.register("w0")
            c.push("w0", 1, DELTA, versions=v)
            c._post("/ps/admin", {"op": "fail_shard", "shard": 0})
            with pytest.raises(PSUnavailable):
                c.pull("w0")
            t["now"] += 5.0
            out = c._post("/ps/admin", {"op": "recover_shard", "shard": 0})
            assert out["fence"] >= 1
            pulled, _ = c.pull("w0")
            np.testing.assert_allclose(pulled["w.a"], np.full(4, 1.0))


# --------------------------------------------------------------------------
# fit_ps: the training arm end to end (tiny model, CPU mesh)
# --------------------------------------------------------------------------


class TestFitPS:
    def _trainer(self, steps=4):
        import jax

        from kubedl_tpu.api.topology import MeshSpec
        from kubedl_tpu.models import llama
        from kubedl_tpu.parallel.mesh import build_mesh
        from kubedl_tpu.training.trainer import TrainConfig, Trainer

        mesh = build_mesh(MeshSpec({"data": 2}), jax.devices()[:2])
        cfg = TrainConfig(model=llama.TINY, global_batch=4, seq_len=16,
                          steps=steps, seed=0)
        return Trainer(cfg, mesh)

    def _data(self):
        from kubedl_tpu.models import llama
        from kubedl_tpu.training.data import SyntheticTokens

        return iter(SyntheticTokens(4, 16, llama.TINY.vocab_size, seed=1))

    def test_fit_ps_trains_and_pushes(self):
        t = self._trainer(steps=4)
        st = t.init_state()
        svc = make_ps(params=t._host_params(st["params"]))
        st, s = t.fit_ps(self._data(), svc, "w0", state=st, steps=4,
                         push_every=2)
        assert s["steps"] == 4
        assert s["ps_pushes"] == 2
        assert s["ps_rejected"] == 0
        assert np.isfinite(s["final_loss"])
        # the PS aggregate tracked the worker: every non-empty shard
        # ticked once per push
        pushes_per_shard = [
            2 if any(shard_for(k, 2) == sid for k in svc.snapshot()) else 0
            for sid in range(2)
        ]
        assert svc.versions() == pushes_per_shard

    def test_fit_ps_survives_push_drops(self):
        t = self._trainer(steps=4)
        st = t.init_state()
        svc = make_ps(params=t._host_params(st["params"]))
        with FaultPlan(5, sites={"ps.push": [FaultSpec.nth(1)]}):
            st, s = t.fit_ps(self._data(), svc, "w0", state=st, steps=4,
                             push_every=1)
        assert s["ps_dropped"] == 1
        assert s["ps_pushes"] == 3  # the other intervals landed
        assert np.isfinite(s["final_loss"])

    def test_fit_ps_rejected_push_repulls_aggregate(self):
        t = self._trainer(steps=2)
        st = t.init_state()
        svc = make_ps(params=t._host_params(st["params"]), max_staleness=0)
        # another member races the head ahead so the worker's push is stale
        _, v = svc.register("rival")
        rival_delta = {k: np.full_like(a, 0.01)
                       for k, a in svc.snapshot().items()}

        class RacingPS:
            """Duck-typed wrapper: the rival advances the head right
            before every worker push, forcing staleness > 0."""

            def register(self, w):
                return svc.register(w)

            def pull(self, w):
                return svc.pull(w)

            def push(self, w, step, deltas, versions=None):
                nonlocal v
                v = svc.push("rival", step, rival_delta, versions=v).versions
                return svc.push(w, step, deltas, versions=versions)

            def deregister(self, *a, **k):
                return svc.deregister(*a, **k)

        st, s = t.fit_ps(self._data(), RacingPS(), "w0", state=st, steps=2,
                         push_every=1)
        assert s["ps_rejected"] >= 1
        assert s["ps_repulls"] >= 1
        assert np.isfinite(s["final_loss"])


# --------------------------------------------------------------------------
# Durability detail: recovery keeps survivors' init semantics
# --------------------------------------------------------------------------


class TestWalDetail:
    def test_recovered_shard_skips_reinit(self, tmp_path):
        t = {"now": 0.0}
        store = ObjectStore()
        sh = ShardState(0, store, wal_dir=str(tmp_path), lease_ttl=1.0,
                        clock=lambda: t["now"])
        sh.open("a")
        sh.init_params({"w.a": np.zeros(2, np.float32)})
        sh.apply("w0", 1.0, {"w.a": np.ones(2, np.float32)}, fence=sh.fence)
        sh.kill()
        t["now"] += 5.0
        sh.open("b")
        # init after recovery must NOT reset the replayed state
        sh.init_params({"w.a": np.zeros(2, np.float32)})
        assert sh.version == 1
        np.testing.assert_allclose(sh.params["w.a"], np.ones(2))

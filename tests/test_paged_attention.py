"""Blocked paged-attention kernel + model-draft speculation tests.

Four layers, mirroring the subsystem's split:

- Kernel-level: the lax chunked scan and the pallas kernel (interpret
  mode) against a dense masked-softmax reference over the gathered view,
  across ragged rows, partial tail blocks, and trash-block rows — the
  garbage-contributes-exact-0.0 contract.
- Model-level: blocked vs gather through `paged_decode_step_batched` /
  `paged_verify` — logits fp-close, greedy argmax identical, and the
  read-only `paged_verify_multi` scoring pass agrees with the write-path
  verify (candidate 0 IS the verify).
- Engine-level: greedy token streams bit-identical between a gather and
  a blocked engine over ragged prompts AND prefix-grafted rows; the
  multi-candidate model-draft engine emits the same oracle stream while
  accepting at least as many draft tokens as single-candidate.
- Regression: `paged_decode_segment` at temperature > 0 is keyed off the
  gumbel chain alone, so the SAMPLED stream is deterministic per seed and
  identical across kernels (a kernel that perturbed the sampling path
  would break per-seed reproducibility silently).
"""

import math

import numpy as np
import pytest


def _dense_reference(q, k_pool, v_pool, bt, starts, max_s):
    """Gather + masked dense softmax — the oracle the kernels chase."""
    B, S, H, hd = q.shape
    BS, KV = k_pool.shape[1], k_pool.shape[2]
    group = H // KV
    kf = k_pool[bt].reshape(B, max_s, KV, hd)
    vf = v_pool[bt].reshape(B, max_s, KV, hd)
    posq = np.minimum(starts[:, None] + np.arange(S)[None, :], max_s - 1)
    qg = q.reshape(B, S, KV, group, hd).astype(np.float64)
    scores = np.einsum("bskgh,btkh->bkgst", qg, kf.astype(np.float64))
    scores /= math.sqrt(hd)
    mask = np.arange(max_s)[None, None, :] <= posq[:, :, None]  # [B,S,T]
    scores = np.where(mask[:, None, None], scores, -1e30)
    scores -= scores.max(axis=-1, keepdims=True)
    p = np.exp(scores)
    p /= p.sum(axis=-1, keepdims=True)
    out = np.einsum("bkgst,btkh->bskgh", p, vf.astype(np.float64))
    return out.reshape(B, S, H, hd)


def _random_pool(seed, B, MB, BS, KV, hd, trash_garbage=True):
    rng = np.random.RandomState(seed)
    NB = 1 + B * MB
    kp = rng.randn(NB, BS, KV, hd).astype(np.float32)
    vp = rng.randn(NB, BS, KV, hd).astype(np.float32)
    if trash_garbage:
        # poison the trash block with huge values: any leak through the
        # mask would blow the comparison instead of hiding in noise
        kp[0] = 37.0
        vp[0] = -29.0
    bt = np.arange(1, 1 + B * MB, dtype=np.int32).reshape(B, MB)
    return kp, vp, bt


class TestKernelParity:
    """lax + pallas-interpret vs the dense oracle."""

    B, MB, BS, KV, H, hd = 4, 4, 16, 2, 4, 16

    def _case(self, starts, S=1, seed=0, trash_rows=()):
        import jax.numpy as jnp

        from kubedl_tpu.models import paged_attention as pa

        kp, vp, bt = _random_pool(seed, self.B, self.MB, self.BS,
                                  self.KV, self.hd)
        for r in trash_rows:
            bt[r, :] = 0  # a freshly-admitted row: all entries trash
        max_s = self.MB * self.BS
        rng = np.random.RandomState(seed + 1)
        q = rng.randn(self.B, S, self.H, self.hd).astype(np.float32)
        starts = np.asarray(starts, np.int32)
        ref = _dense_reference(q, kp, vp, bt, starts, max_s)
        outs = {}
        for kern in ("lax", "pallas"):
            outs[kern] = np.asarray(pa.paged_attention(
                jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(bt), jnp.asarray(starts), kernel=kern,
            ))
        for kern, got in outs.items():
            d = np.abs(got.astype(np.float64) - ref).max()
            assert d < 1e-5, f"{kern} maxdiff {d}"
        return outs

    def test_ragged_rows_decode(self):
        # positions spread across the table, including block boundaries
        self._case([0, 15, 16, 47])

    def test_partial_tail_block(self):
        # every row's position lands mid-block (partial tail occupancy)
        self._case([3, 19, 35, 60])

    def test_trash_block_rows(self):
        """A fresh row whose table is still all trash entries: position 0
        sees only its own slot-0 key through the <= posq mask; poisoned
        trash values beyond it must contribute exactly nothing."""
        outs = self._case([0, 0, 22, 63], trash_rows=(0, 1))
        assert np.isfinite(outs["lax"]).all()
        assert np.isfinite(outs["pallas"]).all()

    def test_suffix_queries(self):
        # verify-shaped: S=8 queries per row walking forward from starts
        self._case([0, 5, 17, 40], S=8)

    def test_self_contained_mode_matches_concat_oracle(self):
        """Read-only mode: pool history (t < starts) + fresh causal
        suffix must equal dense attention over [history ++ suffix] —
        including a starts=0 row with NO pool history at all (the
        fully-masked-chunk case the -1e29 clamp exists for)."""
        import jax.numpy as jnp

        from kubedl_tpu.models import paged_attention as pa

        B, MB, BS, KV, H, hd, S = 3, 4, 16, 2, 4, 16, 4
        kp, vp, bt = _random_pool(3, B, MB, BS, KV, hd)
        max_s = MB * BS
        rng = np.random.RandomState(5)
        q = rng.randn(B, S, H, hd).astype(np.float32)
        sk = rng.randn(B, S, KV, hd).astype(np.float32)
        sv = rng.randn(B, S, KV, hd).astype(np.float32)
        starts = np.array([0, 7, 33], np.int32)
        got = np.asarray(pa.paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(starts),
            self_k=jnp.asarray(sk), self_v=jnp.asarray(sv), kernel="lax",
        ))
        assert np.isfinite(got).all()
        # oracle: dense over the gathered history + suffix, per row
        kf = kp[bt].reshape(B, max_s, KV, hd)
        vf = vp[bt].reshape(B, max_s, KV, hd)
        group = H // KV
        for b in range(B):
            n = int(starts[b])
            kcat = np.concatenate([kf[b, :n], sk[b]], axis=0)
            vcat = np.concatenate([vf[b, :n], sv[b]], axis=0)
            qg = q[b].reshape(S, KV, group, hd).astype(np.float64)
            sc = np.einsum("skgh,tkh->kgst", qg, kcat.astype(np.float64))
            sc /= math.sqrt(hd)
            causal = np.arange(n + S)[None, :] <= (n + np.arange(S))[:, None]
            sc = np.where(causal[None, None], sc, -1e30)
            sc -= sc.max(axis=-1, keepdims=True)
            p = np.exp(sc)
            p /= p.sum(axis=-1, keepdims=True)
            ref = np.einsum("kgst,tkh->skgh", p, vcat.astype(np.float64))
            d = np.abs(got[b].astype(np.float64)
                       - ref.reshape(S, H, hd)).max()
            assert d < 1e-5, f"row {b} maxdiff {d}"

    def test_blocks_per_chunk(self):
        from kubedl_tpu.models.paged_attention import blocks_per_chunk

        assert blocks_per_chunk(32, 16, 256) == 16
        assert blocks_per_chunk(4, 16, 256) == 4
        assert blocks_per_chunk(5, 16, 64) == 1  # 5 has no divisor <= 4
        assert blocks_per_chunk(1, 512, 256) == 1  # never below 1

    def test_unknown_kernel_rejected(self):
        import jax.numpy as jnp

        from kubedl_tpu.models import paged_attention as pa

        kp, vp, bt = _random_pool(0, 1, 2, 16, 2, 16)
        q = jnp.zeros((1, 1, 4, 16), jnp.float32)
        with pytest.raises(ValueError):
            pa.paged_attention(q, jnp.asarray(kp), jnp.asarray(vp),
                               jnp.asarray(bt), jnp.zeros((1,), jnp.int32),
                               kernel="dense")


class TestModelParity:
    """Blocked vs gather through the llama paged twins."""

    def _setup(self, preset="tiny", batch=2, max_seq=64, block_size=16):
        import jax
        import jax.numpy as jnp

        from kubedl_tpu.models import llama

        cfg = llama.preset(preset)
        params = llama.llama_init(jax.random.PRNGKey(0), cfg)
        nb = 1 + batch * (max_seq // block_size)
        cache = llama.init_paged_cache(cfg, batch, max_seq, nb, block_size)
        mb = max_seq // block_size
        bt = jnp.arange(1, 1 + batch * mb, dtype=jnp.int32).reshape(batch, mb)
        cache["bt"] = bt
        return llama, cfg, params, cache

    def _prefilled(self):
        import jax.numpy as jnp

        llama, cfg, params, cache = self._setup()
        toks = jnp.asarray(np.array([[5, 9, 13, 0], [1, 2, 0, 0]], np.int32))
        lens = jnp.asarray(np.array([3, 2], np.int32))
        logits, cache = llama.paged_prefill_batched(
            params, cache, toks, lens, cfg
        )
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
        return llama, cfg, params, cache, nxt

    def test_decode_chain_greedy_identical_logits_close(self):
        import jax
        import jax.numpy as jnp

        llama, cfg, params, cache, nxt = self._prefilled()
        temps = jnp.zeros((2,), jnp.float32)
        key = jax.random.PRNGKey(1)
        streams = {}
        for kern in ("gather", "blocked"):
            t, _, _, _ = llama.paged_decode_segment(
                params, dict(cache), nxt, temps, key, cfg, n_steps=8,
                greedy=True, kv_attention=kern,
            )
            streams[kern] = np.asarray(t)
        assert np.array_equal(streams["gather"], streams["blocked"])
        # single-step logits: fp-close (online softmax reorders the sum)
        lg, _ = llama.paged_decode_step_batched(
            params, dict(cache), nxt, cfg, kv_attention="gather"
        )
        lb, _ = llama.paged_decode_step_batched(
            params, dict(cache), nxt, cfg, kv_attention="blocked"
        )
        d = float(jnp.max(jnp.abs(lg - lb)))
        assert d < 1e-4, d
        assert np.array_equal(np.asarray(jnp.argmax(lg, -1)),
                              np.asarray(jnp.argmax(lb, -1)))

    def test_verify_ids_identical_across_kernels(self):
        import jax.numpy as jnp

        llama, cfg, params, cache, nxt = self._prefilled()
        toks = np.zeros((2, 4), np.int32)
        toks[:, 0] = np.asarray(nxt)[:, 0]
        toks[:, 1:] = [[7, 7, 7], [9, 9, 9]]
        lens = jnp.asarray(np.array([4, 4], np.int32))
        starts = cache["pos"]
        ids = {}
        for kern in ("gather", "blocked"):
            got, _ = llama.paged_verify(
                params, dict(cache), jnp.asarray(toks), lens, starts, cfg,
                kv_attention=kern,
            )
            ids[kern] = np.asarray(got)
        assert np.array_equal(ids["gather"], ids["blocked"])

    def test_verify_multi_candidate0_equals_write_path(self):
        """The read-only scoring pass on candidate 0 must produce the
        SAME ids the standard write-path verify emits — that equivalence
        is what lets the engine rank candidates without writing and still
        stay bit-exact on the winner."""
        import jax.numpy as jnp

        llama, cfg, params, cache, nxt = self._prefilled()
        N, S = 2, 4
        cands = np.zeros((2, N, S), np.int32)
        cands[:, :, 0] = np.asarray(nxt)
        cands[0, 0, 1:] = [7, 7, 7]
        cands[0, 1, 1:] = [3, 5, 8]
        cands[1, 0, 1:] = [9, 9, 9]
        cands[1, 1, 1:] = [2, 4, 6]
        lens = jnp.asarray(np.array([S, S], np.int32))
        starts = cache["pos"]
        for kern in ("gather", "blocked"):
            multi = np.asarray(llama.paged_verify_multi(
                params, dict(cache), jnp.asarray(cands), lens, starts, cfg,
                kv_attention=kern,
            ))
            write, _ = llama.paged_verify(
                params, dict(cache), jnp.asarray(cands[:, 0]), lens,
                starts, cfg, kv_attention=kern,
            )
            assert np.array_equal(multi[:, 0], np.asarray(write)), kern

    def test_pallas_interpret_through_decode_step(self):
        """Force DEFAULT_KERNEL=pallas (interpret on CPU) through the
        full model stack: same greedy argmax as the lax path."""
        import jax.numpy as jnp

        from kubedl_tpu.models import paged_attention as pa

        llama, cfg, params, cache, nxt = self._prefilled()
        lg, _ = llama.paged_decode_step_batched(
            params, dict(cache), nxt, cfg, kv_attention="blocked"
        )
        old = pa.DEFAULT_KERNEL
        pa.DEFAULT_KERNEL = "pallas"
        try:
            before = pa.TRACE_COUNT["pallas"]
            lp, _ = llama.paged_decode_step_batched(
                params, dict(cache), nxt, cfg, kv_attention="blocked"
            )
            assert pa.TRACE_COUNT["pallas"] > before
        finally:
            pa.DEFAULT_KERNEL = old
        d = float(jnp.max(jnp.abs(lg - lp)))
        assert d < 1e-4, d
        assert np.array_equal(np.asarray(jnp.argmax(lg, -1)),
                              np.asarray(jnp.argmax(lp, -1)))

    def test_tiny_deep_early_exit_slice_matches_target_at_init(self):
        """The tiny-deep preset zero-inits residual outputs (wo/w_down)
        for layers >= 2, so its 2-layer early-exit slice is bit-identical
        to the 4-layer target at init — the honest CPU proxy for a
        trained draft/target pair that the model-draft bench relies on."""
        import dataclasses

        import jax
        import jax.numpy as jnp

        from kubedl_tpu.models import llama

        cfg = llama.preset("tiny-deep")
        assert cfg.n_layers == 4 and cfg.zero_init_deep_from == 2
        params = llama.llama_init(jax.random.PRNGKey(0), cfg)
        sliced = dict(params)
        sliced["layers"] = jax.tree_util.tree_map(
            lambda a: a[:2], params["layers"]
        )
        cfg2 = dataclasses.replace(cfg, n_layers=2)
        toks = jnp.asarray(np.array([[5, 9, 13, 2]], np.int32))
        full = llama.llama_forward(params, toks, cfg)
        part = llama.llama_forward(sliced, toks, cfg2)
        assert np.array_equal(np.asarray(full), np.asarray(part))


class TestFusedKVWrite:
    """The fused KV-write path: attention + current-step pool write in
    one dispatch must be BIT-identical to the legacy scatter-then-attend
    pair — output and pools — on both kernels. Anything weaker would let
    the fused fast path drift from the semantics every other paged test
    pins down."""

    B, MB, BS, KV, H, hd = 4, 4, 16, 2, 4, 16

    def _case(self, starts, seed=0):
        import jax.numpy as jnp

        from kubedl_tpu.models import paged_attention as pa

        kp, vp, bt = _random_pool(seed, self.B, self.MB, self.BS,
                                  self.KV, self.hd)
        rng = np.random.RandomState(seed + 1)
        q = rng.randn(self.B, 1, self.H, self.hd).astype(np.float32)
        nk = rng.randn(self.B, self.KV, self.hd).astype(np.float32)
        nv = rng.randn(self.B, self.KV, self.hd).astype(np.float32)
        starts = np.asarray(starts, np.int32)
        # reference: external scatter first, then the plain read path
        kp2, vp2 = kp.copy(), vp.copy()
        for b in range(self.B):
            blk = bt[b, starts[b] // self.BS]
            off = starts[b] % self.BS
            kp2[blk, off] = nk[b]
            vp2[blk, off] = nv[b]
        for kern in ("lax", "pallas"):
            ref = np.asarray(pa.paged_attention(
                jnp.asarray(q), jnp.asarray(kp2), jnp.asarray(vp2),
                jnp.asarray(bt), jnp.asarray(starts), kernel=kern,
            ))
            before = pa.TRACE_COUNT["fused"]
            out, kpo, vpo = pa.paged_attention(
                jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(bt), jnp.asarray(starts), kernel=kern,
                new_k=jnp.asarray(nk), new_v=jnp.asarray(nv),
            )
            assert pa.TRACE_COUNT["fused"] == before + 1
            assert np.array_equal(np.asarray(kpo), kp2), kern
            assert np.array_equal(np.asarray(vpo), vp2), kern
            assert np.array_equal(np.asarray(out), ref), kern

    def test_ragged_rows_block_boundaries(self):
        # positions spread across the table, including block boundaries
        # (write lands in slot 0 of a block and slot BS-1)
        self._case([0, 15, 16, 47])

    def test_partial_tail_blocks(self):
        self._case([3, 19, 35, 60], seed=7)

    def test_fused_requires_single_query(self):
        import jax.numpy as jnp

        from kubedl_tpu.models import paged_attention as pa

        kp, vp, bt = _random_pool(0, 1, 2, 16, 2, 16)
        q = jnp.zeros((1, 2, 4, 16), jnp.float32)  # S=2: no fused form
        with pytest.raises(ValueError):
            pa.paged_attention(
                q, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt),
                jnp.zeros((1,), jnp.int32),
                new_k=jnp.zeros((1, 2, 16), jnp.float32),
                new_v=jnp.zeros((1, 2, 16), jnp.float32),
            )

    def test_decode_step_uses_fused_write(self):
        """`paged_decode_step_batched` on the blocked path must go
        through the fused write — the whole point is retiring the
        separate scatter dispatch per decode step — and still match the
        gather path's greedy argmax."""
        import jax
        import jax.numpy as jnp

        from kubedl_tpu.models import llama
        from kubedl_tpu.models import paged_attention as pa

        cfg = llama.preset("tiny")
        params = llama.llama_init(jax.random.PRNGKey(0), cfg)
        cache = llama.init_paged_cache(cfg, 2, 64, 9, 16)
        cache["bt"] = jnp.arange(1, 9, dtype=jnp.int32).reshape(2, 4)
        toks = jnp.asarray(np.array([[5, 9, 13, 0], [1, 2, 0, 0]], np.int32))
        lens = jnp.asarray(np.array([3, 2], np.int32))
        logits, cache = llama.paged_prefill_batched(
            params, cache, toks, lens, cfg
        )
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
        before = pa.TRACE_COUNT["fused"]
        lb, cb = llama.paged_decode_step_batched(
            params, dict(cache), nxt, cfg, kv_attention="blocked"
        )
        assert pa.TRACE_COUNT["fused"] > before
        lg, cg = llama.paged_decode_step_batched(
            params, dict(cache), nxt, cfg, kv_attention="gather"
        )
        assert np.array_equal(np.asarray(jnp.argmax(lb, -1)),
                              np.asarray(jnp.argmax(lg, -1)))
        # both paths committed the same K/V into the same pool slots
        for f in ("k", "v"):
            d = float(jnp.max(jnp.abs(cb[f] - cg[f])))
            assert d < 1e-5, (f, d)


class TestTreeVerify:
    """`paged_verify_tree` vs the flat multi-candidate scorer, plus the
    `paged_verify_multi` edges the tree path leans on. The pinned
    equivalence is tree == multi (both self-contained read-only
    forwards, so bit-exact agreement is a hard contract); the write-path
    cross-check is at the id level, which is what the engine consumes."""

    def _prefilled(self, batch=2):
        import jax
        import jax.numpy as jnp

        from kubedl_tpu.models import llama

        cfg = llama.preset("tiny")
        params = llama.llama_init(jax.random.PRNGKey(0), cfg)
        nb = 1 + batch * 4
        cache = llama.init_paged_cache(cfg, batch, 64, nb, 16)
        cache["bt"] = jnp.arange(1, nb, dtype=jnp.int32).reshape(batch, 4)
        toks = np.zeros((batch, 4), np.int32)
        toks[0, :3] = [5, 9, 13]
        toks[1, :2] = [1, 2]
        lens = jnp.asarray(np.array([3, 2] + [1] * (batch - 2), np.int32))
        logits, cache = llama.paged_prefill_batched(
            params, cache, jnp.asarray(toks), lens, cfg
        )
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return llama, cfg, params, cache, np.asarray(nxt)

    @staticmethod
    def _tree_inputs(trees, starts, m_max):
        from kubedl_tpu.serving.speculative import DraftTree  # noqa: F401

        B = len(trees)
        toks = np.zeros((B, m_max), np.int32)
        pos = np.zeros((B, m_max), np.int32)
        mask = np.zeros((B, m_max, m_max), bool)
        lens = np.zeros((B,), np.int32)
        for b, tr in enumerate(trees):
            t, d, m = tr.arrays(m_max)
            toks[b], mask[b] = t, m
            pos[b] = starts[b] + d
            lens[b] = tr.size
        return toks, pos, mask, lens

    @pytest.mark.parametrize("kern", ["gather", "blocked"])
    def test_chain_trie_equals_multi(self, kern):
        """A trie that IS a single chain must reproduce the flat
        multi-verify scorer bit-exactly, node by node."""
        import jax.numpy as jnp

        from kubedl_tpu.serving.speculative import build_tree

        llama, cfg, params, cache, nxt = self._prefilled()
        chains = [[7, 7, 7], [9, 2, 4]]
        starts = np.asarray(cache["pos"])
        trees = [build_tree(int(nxt[b]), [chains[b]], k=3, m_max=4)
                 for b in range(2)]
        toks, pos, mask, lens = self._tree_inputs(trees, starts, 4)
        tree_ids = np.asarray(llama.paged_verify_tree(
            params, dict(cache), jnp.asarray(toks), jnp.asarray(pos),
            jnp.asarray(mask), jnp.asarray(lens), jnp.asarray(starts),
            cfg, kv_attention=kern,
        ))
        cands = np.stack([
            np.concatenate([[int(nxt[b])], chains[b]]) for b in range(2)
        ]).astype(np.int32)[:, None]  # [B, 1, 4]
        multi = np.asarray(llama.paged_verify_multi(
            params, dict(cache), jnp.asarray(cands),
            jnp.asarray(np.full((2,), 4, np.int32)), jnp.asarray(starts),
            cfg, kv_attention=kern,
        ))
        assert np.array_equal(tree_ids, multi[:, 0])

    @pytest.mark.parametrize("kern", ["gather", "blocked"])
    def test_branching_trie_leaf_paths_equal_per_chain_multi(self, kern):
        """Chains sharing a prefix share trie nodes; every root->leaf
        path's ids must still equal the flat per-chain verify of that
        same path — sibling branches are invisible under the ancestor
        mask."""
        import jax.numpy as jnp

        from kubedl_tpu.serving.speculative import build_tree

        llama, cfg, params, cache, nxt = self._prefilled()
        # candidates share first token 7: trie is 1 root + 5 nodes
        chains = [[7, 3, 8], [7, 3, 2], [7, 5]]
        starts = np.asarray(cache["pos"])
        tr = build_tree(int(nxt[0]), chains, k=3, m_max=8)
        assert tr.size == 6  # root + {7, 3, 8, 2, 5}
        trees = [tr, build_tree(int(nxt[1]), [[9]], k=3, m_max=8)]
        toks, pos, mask, lens = self._tree_inputs(trees, starts, 8)
        tree_ids = np.asarray(llama.paged_verify_tree(
            params, dict(cache), jnp.asarray(toks), jnp.asarray(pos),
            jnp.asarray(mask), jnp.asarray(lens), jnp.asarray(starts),
            cfg, kv_attention=kern,
        ))
        # flat comparison: all 3 chains of row 0 as padded candidates
        S = 4
        cands = np.zeros((2, 3, S), np.int32)
        for n, c in enumerate(chains):
            cands[0, n, 0] = int(nxt[0])
            cands[0, n, 1:1 + len(c)] = c
        cands[1, :, 0] = int(nxt[1])
        cands[1, :, 1] = 9
        multi = np.asarray(llama.paged_verify_multi(
            params, dict(cache), jnp.asarray(cands),
            jnp.asarray(np.array([S, 2], np.int32)), jnp.asarray(starts),
            cfg, kv_attention=kern,
        ))
        # walk each chain through the trie, node ids must match the
        # flat candidate's ids position-for-position
        def node_path(tree, chain):
            cur, out = 0, [0]
            for t in chain:
                cur = tree.children[cur][int(t)]
                out.append(cur)
            return out

        for n, c in enumerate(chains):
            for j, node in enumerate(node_path(tr, c)):
                assert tree_ids[0, node] == multi[0, n, j], (n, j)
        assert tree_ids[1, 0] == multi[1, 0, 0]
        assert tree_ids[1, 1] == multi[1, 0, 1]

    def test_multi_ragged_row_lengths(self):
        """Rows verifying different suffix lengths in one batch: each
        row's live prefix must match its own single-row verify — padding
        on the short row cannot bleed into the long one."""
        import jax.numpy as jnp

        llama, cfg, params, cache, nxt = self._prefilled()
        starts = np.asarray(cache["pos"])
        cands = np.zeros((2, 2, 4), np.int32)
        cands[:, :, 0] = nxt[:, None]
        cands[0, 0, 1:] = [7, 7, 7]
        cands[0, 1, 1:] = [3, 5, 8]
        cands[1, 0, 1] = 9  # row 1 verifies only 2 live positions
        cands[1, 1, 1] = 2
        lens = np.array([4, 2], np.int32)
        multi = np.asarray(llama.paged_verify_multi(
            params, dict(cache), jnp.asarray(cands), jnp.asarray(lens),
            jnp.asarray(starts), cfg,
        ))
        for b in range(2):
            solo_cache = {
                "k": cache["k"], "v": cache["v"],
                "pos": cache["pos"][b:b + 1], "bt": cache["bt"][b:b + 1],
            }
            solo = np.asarray(llama.paged_verify_multi(
                params, solo_cache, jnp.asarray(cands[b:b + 1]),
                jnp.asarray(lens[b:b + 1]), jnp.asarray(starts[b:b + 1]),
                cfg,
            ))
            L = int(lens[b])
            assert np.array_equal(multi[b, :, :L], solo[0, :, :L]), b

    def test_multi_duplicate_prefix_candidates(self):
        """Two candidates agreeing on their first j tokens must score
        identical ids at those positions (the determinism build_tree's
        node sharing silently assumes)."""
        import jax.numpy as jnp

        llama, cfg, params, cache, nxt = self._prefilled()
        starts = np.asarray(cache["pos"])
        cands = np.zeros((2, 3, 4), np.int32)
        cands[:, :, 0] = nxt[:, None]
        cands[0, 0, 1:] = [7, 3, 8]
        cands[0, 1, 1:] = [7, 3, 2]  # shares 2-token prefix with cand 0
        cands[0, 2, 1:] = [7, 3, 8]  # full duplicate of cand 0
        cands[1, 0, 1:] = [9, 9, 9]
        cands[1, 1, 1:] = [9, 9, 9]
        cands[1, 2, 1:] = [2, 4, 6]
        lens = np.full((2,), 4, np.int32)
        multi = np.asarray(llama.paged_verify_multi(
            params, dict(cache), jnp.asarray(cands), jnp.asarray(lens),
            jnp.asarray(starts), cfg,
        ))
        assert np.array_equal(multi[0, 0, :3], multi[0, 1, :3])
        assert np.array_equal(multi[0, 0], multi[0, 2])
        assert np.array_equal(multi[1, 0], multi[1, 1])

    @pytest.mark.parametrize("kern", ["gather", "blocked"])
    def test_multi_n1_degenerates_to_verify(self, kern):
        """N=1 multi-verify must emit the same greedy ids as the
        write-path `paged_verify` — the degenerate case where ranking
        buys nothing and the engine behaves as plain speculation."""
        import jax.numpy as jnp

        llama, cfg, params, cache, nxt = self._prefilled()
        starts = np.asarray(cache["pos"])
        cands = np.zeros((2, 1, 4), np.int32)
        cands[:, 0, 0] = nxt
        cands[0, 0, 1:] = [7, 7, 7]
        cands[1, 0, 1:] = [9, 9, 9]
        lens = np.full((2,), 4, np.int32)
        multi = np.asarray(llama.paged_verify_multi(
            params, dict(cache), jnp.asarray(cands), jnp.asarray(lens),
            jnp.asarray(starts), cfg, kv_attention=kern,
        ))
        write, _ = llama.paged_verify(
            params, dict(cache), jnp.asarray(cands[:, 0]),
            jnp.asarray(lens), jnp.asarray(starts), cfg,
            kv_attention=kern,
        )
        assert np.array_equal(multi[:, 0], np.asarray(write)), kern


class TestEngineParity:
    """Greedy token streams must be identical between kernels through the
    full engine — ragged prompts, trash rows (fresh admissions), and
    prefix-grafted rows."""

    PROMPTS = [[5, 9, 13], [7, 3, 3, 11, 2], [1], [2, 4, 6, 8, 10, 12, 14]]

    def _run(self, **kw):
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", kv_block_size=4, kv_blocks=40,
                          **kw)
        try:
            return [eng.generate(p, max_tokens=10)["token_ids"]
                    for p in self.PROMPTS]
        finally:
            eng.close()

    def test_greedy_streams_identical(self):
        assert self._run() == self._run(kv_attention="blocked")

    def test_prefix_grafted_rows_identical(self):
        """Shared-prefix traffic: later requests decode from a grafted
        block table (shared history blocks + COW tail) — the blocked
        kernel must walk that table to the same tokens."""
        from kubedl_tpu.serving.server import LlamaEngine

        shared = list(range(3, 19))
        prompts = [shared + [100 + j] for j in range(4)]

        def arm(kern):
            eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                              kv_layout="paged", kv_block_size=4,
                              kv_blocks=60, prefix_min_len=4,
                              kv_attention=kern)
            try:
                outs = [eng.generate(p, max_tokens=8)["token_ids"]
                        for p in prompts]
                hits = eng.stats()["prefix_cache"]["hits"]
                return outs, hits
            finally:
                eng.close()

        g_outs, _ = arm("gather")
        b_outs, b_hits = arm("blocked")
        assert g_outs == b_outs
        assert b_hits > 0  # the blocked arm really decoded grafted rows

    def test_invalid_kernel_rejected(self):
        from kubedl_tpu.serving.server import LlamaEngine

        with pytest.raises(ValueError):
            LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                        kv_layout="paged", kv_attention="dense")


class TestModelDraftSpeculation:
    """ModelDraft + multi-candidate verification through the engine."""

    def _run(self, **kw):
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny-deep", max_batch=2, max_seq=64,
                          kv_layout="paged", kv_block_size=4, kv_blocks=40,
                          **kw)
        try:
            outs = [eng.generate(p, max_tokens=10)["token_ids"]
                    for p in ([5, 9, 13], [7, 3, 3, 11, 2])]
            return outs, eng.stats()["speculative"] if eng.spec_k else None
        finally:
            eng.close()

    def test_model_draft_exact_and_accepting(self):
        oracle, _ = self._run()
        outs, sp = self._run(spec_k=3, spec_draft="model",
                             spec_draft_layers=2, kv_attention="blocked")
        assert outs == oracle
        assert sp["draft_kind"] == "model"
        # tiny-deep's 2-layer slice IS the target at init: near-total
        # acceptance is the expected signal, not a lucky roll
        assert sp["acceptance_rate"] > 0.5, sp
        assert sp["draft_ms_total"] > 0

    def test_multi_candidate_accepts_at_least_single(self):
        oracle, _ = self._run()
        m_outs, m_sp = self._run(spec_k=3, spec_draft="model",
                                 spec_draft_layers=2, spec_candidates=2)
        s_outs, s_sp = self._run(spec_k=3, spec_draft="model",
                                 spec_draft_layers=2, spec_candidates=1)
        assert m_outs == oracle and s_outs == oracle
        assert m_sp["accepted"] >= s_sp["accepted"], (m_sp, s_sp)
        assert m_sp["candidates_scored"] > 0
        assert s_sp["candidates_scored"] == 0

    def test_model_draft_propose_candidates_contract(self):
        """Candidate 0 must be the plain greedy proposal — the invariant
        the multi>=single guarantee rests on."""
        import jax

        from kubedl_tpu.models import llama
        from kubedl_tpu.serving.speculative import ModelDraft

        cfg = llama.preset("tiny-deep")
        params = llama.llama_init(jax.random.PRNGKey(0), cfg)
        draft = ModelDraft.from_target(params, cfg, n_layers=2,
                                       max_context=64)
        ctx = [5, 9, 13, 2, 7]
        plain = draft.propose(ctx, 3)
        cands = draft.propose_candidates(ctx, 3, 2)
        assert cands[0] == plain
        assert len(cands) == 2 and cands[1] != cands[0]
        # batch path consistent with the single path
        assert draft.propose_batch([ctx, ctx[:3]], 3)[0] == plain


class TestSampledDeterminismRegression:
    """Temperature > 0: `paged_decode_segment`'s gumbel chain is keyed
    off the PRNG key alone, so a given seed must reproduce the same
    sampled stream on repeat runs AND across attention kernels (fp-close
    logits never flip a gumbel argmax at tiny scale in practice — and a
    kernel that DID perturb sampling would break per-seed repro, which is
    exactly what this pins)."""

    def _sample(self, seed, kern):
        import jax
        import jax.numpy as jnp

        from kubedl_tpu.models import llama

        cfg = llama.preset("tiny")
        params = llama.llama_init(jax.random.PRNGKey(0), cfg)
        batch, max_seq, bs = 2, 64, 16
        nb = 1 + batch * (max_seq // bs)
        cache = llama.init_paged_cache(cfg, batch, max_seq, nb, bs)
        mb = max_seq // bs
        cache["bt"] = jnp.arange(1, 1 + batch * mb,
                                 dtype=jnp.int32).reshape(batch, mb)
        toks = jnp.asarray(np.array([[5, 9, 13, 0], [1, 2, 0, 0]], np.int32))
        lens = jnp.asarray(np.array([3, 2], np.int32))
        logits, cache = llama.paged_prefill_batched(
            params, cache, toks, lens, cfg
        )
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
        temps = jnp.full((batch,), 0.8, jnp.float32)
        t, _, _, _ = llama.paged_decode_segment(
            params, cache, nxt, temps, jax.random.PRNGKey(seed), cfg,
            n_steps=12, greedy=False, kv_attention=kern,
        )
        return np.asarray(t)

    def test_sampled_stream_deterministic_per_seed_across_kernels(self):
        for seed in (1, 7):
            a = self._sample(seed, "gather")
            b = self._sample(seed, "gather")
            c = self._sample(seed, "blocked")
            assert np.array_equal(a, b), f"seed {seed} not reproducible"
            assert np.array_equal(a, c), f"seed {seed} differs by kernel"
        # different seeds actually differ (the test has teeth)
        assert not np.array_equal(self._sample(1, "gather"),
                                  self._sample(7, "gather"))


class TestBlockedHostBudget:
    def test_blocked_attention_within_budget(self):
        """Tier-1 gate on the blocked path's HOST cost: scheduler ticks
        with kv_attention="blocked" fit the same envelope as gather, and
        one compiled-kernel dispatch at a trivial shape stays far from
        per-tick scale (a jit-cache miss per call would blow this)."""
        from scripts.scheduler_microbench import (
            BLOCKED_BUDGET_MS,
            run_blocked_attention_microbench,
        )

        out = run_blocked_attention_microbench(
            requests=8, max_tokens=16, max_batch=4, iters=50
        )
        assert out["tokens"] == 8 * 16
        assert out["blocks_leaked"] == 0, out
        assert out["tick_ms_p50"] <= BLOCKED_BUDGET_MS, out
        assert out["kernel_dispatch_ms"] <= BLOCKED_BUDGET_MS, out
        assert out["within_budget"], out

"""Pallas kernel tests (interpret mode on CPU; same code path runs compiled
on TPU). The dense oracle llama.attention is the numerics reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubedl_tpu.models import llama
from kubedl_tpu.ops import flash_attention


def _qkv(key, B=2, S=128, H=4, KV=2, hd=32, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (B, S, H, hd), dtype),
        jax.random.normal(kk, (B, S, KV, hd), dtype),
        jax.random.normal(kv, (B, S, KV, hd), dtype),
    )


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(0))
        want = llama.attention(q, k, v, causal=causal)
        got = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_gqa_grouping(self):
        q, k, v = _qkv(jax.random.PRNGKey(1), H=8, KV=2)
        want = llama.attention(q, k, v, causal=True)
        got = flash_attention(q, k, v, block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_single_block(self):
        q, k, v = _qkv(jax.random.PRNGKey(2), S=64)
        want = llama.attention(q, k, v, causal=True)
        got = flash_attention(q, k, v)  # blocks larger than S -> one block
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize(
        "H,KV", [(4, 2), (8, 2), (8, 1)],  # group 2, 4, and MQA (group=H)
        ids=["group2", "group4", "mqa"],
    )
    def test_gradients_match_dense(self, causal, H, KV):
        """The fused backward accumulates dk/dv across the whole GQA group
        in kernel scratch (init on the group's first head, write-out on
        its last) — exercised at group sizes beyond the bench model's 2."""
        q, k, v = _qkv(jax.random.PRNGKey(3), S=64, H=H, KV=KV, hd=16)

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
            return (o * o).sum()

        def loss_dense(q, k, v):
            o = llama.attention(q, k, v, causal=causal)
            return (o * o).sum()

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_llama_forward_with_flash(self):
        cfg = llama.TINY
        params = llama.llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0,
                                    cfg.vocab_size)
        want = llama.llama_forward(params, tokens, cfg)
        got = llama.llama_forward(params, tokens, cfg, attn_fn=flash_attention)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4, rtol=2e-4)

    def test_mask_falls_back_to_dense(self):
        q, k, v = _qkv(jax.random.PRNGKey(4), S=32)
        mask = jnp.ones((1, 1, 1, 32, 32), bool)
        got = flash_attention(q, k, v, causal=False, mask=mask)
        want = llama.attention(q, k, v, causal=False, mask=mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("H,KV", [(4, 2), (8, 1)], ids=["gqa", "mqa"])
    def test_fused_rope_matches_explicit_rope(self, H, KV):
        """rope_cos/rope_sin fuse the rotary into the kernel: q/k go in
        PRE-rope and the output must match apply_rope + kernel (and the
        dense oracle), forward and gradients — including the inverse
        rotation that makes the backward emit pre-rope gradients."""
        S, hd = 128, 32
        q, k, v = _qkv(jax.random.PRNGKey(6), S=S, H=H, KV=KV, hd=hd)
        cos, sin = llama.rope_table(hd, 10000.0, S)

        def loss_fused(q, k, v):
            o = flash_attention(q, k, v, block_q=32, block_k=32,
                                rope_cos=cos, rope_sin=sin)
            return (o * o).sum()

        def loss_explicit(q, k, v):
            o = flash_attention(
                llama.apply_rope(q, cos, sin), llama.apply_rope(k, cos, sin),
                v, block_q=32, block_k=32,
            )
            return (o * o).sum()

        np.testing.assert_allclose(
            np.asarray(loss_fused(q, k, v)), np.asarray(loss_explicit(q, k, v)),
            rtol=1e-5,
        )
        g1 = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_explicit, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_fused_rope_split_backward_path(self, monkeypatch):
        """The split two-kernel backward must apply the same in-kernel
        rotation + inverse-rotation as the fused path."""
        from kubedl_tpu.ops import flash_attention_module as fa

        S, hd = 64, 16
        q, k, v = _qkv(jax.random.PRNGKey(7), S=S, H=4, KV=2, hd=hd)
        cos, sin = llama.rope_table(hd, 10000.0, S)

        def loss(q, k, v):
            o = flash_attention(q, k, v, block_q=16, block_k=16,
                                rope_cos=cos, rope_sin=sin)
            return (o * o).sum()

        g_fused = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        monkeypatch.setattr(fa, "_FUSED_BWD_SCRATCH_BYTES", 0)
        g_split = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_fused, g_split):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_untileable_shape_falls_back_to_oracle(self):
        # S=48 with 32-blocks has no legal tiling; the wrapper degrades to
        # the dense oracle instead of raising (r2: graceful fit_block path)
        import numpy as np

        from kubedl_tpu.models.llama import attention

        q, k, v = _qkv(jax.random.PRNGKey(5), S=48)
        got = flash_attention(q, k, v, block_q=32, block_k=32)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(attention(q, k, v)),
            rtol=2e-4, atol=2e-4,
        )


class TestBlockFitting:
    def test_fused_bwd_odd_long_seq_gradients(self):
        """Regression: the fused backward's long-S k-tile shrink must
        RE-FIT, not clamp — at S=5376/hd=16 the scratch threshold is
        crossed and fit_block picks 896; a min(bk,512) clamp stopped
        dividing S and silently dropped the tail k-blocks (NaN dk/dv,
        dq off by 1e-2)."""
        from kubedl_tpu.ops import flash_attention_module as fam

        S, hd = 5376, 16
        # shrink the thresholds so the tiny test shape crosses them the
        # way S=5376/hd=64 does in production (Sk*hd*8 = 672KB here)
        old_small, old_cap = (
            fam._FUSED_BWD_SMALL_TILE_BYTES, fam._FUSED_BWD_SCRATCH_BYTES,
        )
        fam._FUSED_BWD_SMALL_TILE_BYTES = 256 << 10
        fam._FUSED_BWD_SCRATCH_BYTES = 1 << 20
        try:
            q, k, v = _qkv(jax.random.PRNGKey(5), B=1, S=S, H=2, KV=1, hd=hd)

            def loss_flash(q, k, v):
                o = flash_attention(q, k, v, causal=True)
                return (o * o).sum()

            def loss_dense(q, k, v):
                o = llama.attention(q, k, v, causal=True)
                return (o * o).sum()

            g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
            g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
            for a, b in zip(g1, g2):
                assert np.isfinite(np.asarray(a)).all()
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           atol=2e-3, rtol=2e-3)
        finally:
            fam._FUSED_BWD_SMALL_TILE_BYTES = old_small
            fam._FUSED_BWD_SCRATCH_BYTES = old_cap

    def test_fit_block(self):
        from kubedl_tpu.ops.flash_attention import fit_block, supports

        assert fit_block(2048, 1024) == 1024
        assert fit_block(1536, 1024) == 768   # largest 128-multiple divisor
        assert fit_block(1280, 1024) == 640
        assert fit_block(64, 1024) == 64      # whole seq in one block
        assert fit_block(100, 1024) == 100    # whole seq fits one block
        assert fit_block(100, 64) == 0        # >64, no 128-multiple divisor
        assert supports(1536) and supports(2048) and supports(32)
        assert not supports(1000000007)       # prime > block

    def test_odd_seq_len_uses_flash_not_dense(self):
        """seq 1536 (divisible by 512, not 1024) must still run the fused
        kernel (regression: r2 review — default-block bump silently
        narrowed support)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from kubedl_tpu.models.llama import attention
        from kubedl_tpu.ops.flash_attention import flash_attention

        B, S, H, KV, hd = 1, 256, 2, 1, 16  # 256 % 128 == 0, < 1024
        q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, hd))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, KV, hd))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, KV, hd))
        got = flash_attention(q, k, v, block_q=1024, block_k=1024)
        want = attention(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


class TestRematKernelCounts:
    """Regression guard for the round-4 remat fix: with "flash_rope" the
    backward scan must NOT re-run the forward attention kernel (nor its
    input chain). The jaxpr-level signature: exactly TWO pallas_calls in
    the grad (fwd kernel + fused bwd kernel). Under "dots" the residuals
    aren't saveable so remat re-runs the forward — a third pallas_call.
    If defvjp(optimize_remat=True) ever returns, or the checkpoint_name
    tags drift, the flash_rope count jumps to 3 and this fails."""

    def _grad_pallas_count(self, policy: str) -> int:
        import dataclasses

        from kubedl_tpu.models import llama
        from kubedl_tpu.ops import flash_attention_module as fa

        cfg = dataclasses.replace(
            llama.TINY, remat=True, remat_policy=policy, dtype=jnp.float32
        )
        params = jax.eval_shape(
            lambda: llama.llama_init(jax.random.PRNGKey(0), cfg)
        )
        toks = jax.ShapeDtypeStruct((2, 64), jnp.int32)

        def attn(q, k, v, causal=True, mask=None):
            return fa.flash_attention(
                q, k, v, causal=causal, mask=mask, interpret=True
            )

        loss = lambda p, b: llama.llama_loss(p, b, cfg, attn)
        jaxpr = str(jax.make_jaxpr(jax.grad(loss))(params, toks))
        return jaxpr.count("pallas_call")

    def test_flash_rope_never_reruns_forward_kernel(self):
        assert self._grad_pallas_count("flash_rope") == 2

    def test_dots_documents_the_rerun(self):
        # not a bug — "dots" cannot name custom-call outputs; this pins
        # the contrast so the flash_rope assertion above stays meaningful
        assert self._grad_pallas_count("dots") == 3

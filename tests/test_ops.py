"""Pallas kernel tests (interpret mode on CPU; same code path runs compiled
on TPU). The dense oracle llama.attention is the numerics reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubedl_tpu.models import llama
from kubedl_tpu.ops import flash_attention


def _qkv(key, B=2, S=128, H=4, KV=2, hd=32, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (B, S, H, hd), dtype),
        jax.random.normal(kk, (B, S, KV, hd), dtype),
        jax.random.normal(kv, (B, S, KV, hd), dtype),
    )


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(0))
        want = llama.attention(q, k, v, causal=causal)
        got = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_gqa_grouping(self):
        q, k, v = _qkv(jax.random.PRNGKey(1), H=8, KV=2)
        want = llama.attention(q, k, v, causal=True)
        got = flash_attention(q, k, v, block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_single_block(self):
        q, k, v = _qkv(jax.random.PRNGKey(2), S=64)
        want = llama.attention(q, k, v, causal=True)
        got = flash_attention(q, k, v)  # blocks larger than S -> one block
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_gradients_match_dense(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(3), S=64, hd=16)

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
            return (o * o).sum()

        def loss_dense(q, k, v):
            o = llama.attention(q, k, v, causal=causal)
            return (o * o).sum()

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_llama_forward_with_flash(self):
        cfg = llama.TINY
        params = llama.llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0,
                                    cfg.vocab_size)
        want = llama.llama_forward(params, tokens, cfg)
        got = llama.llama_forward(params, tokens, cfg, attn_fn=flash_attention)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4, rtol=2e-4)

    def test_mask_falls_back_to_dense(self):
        q, k, v = _qkv(jax.random.PRNGKey(4), S=32)
        mask = jnp.ones((1, 1, 1, 32, 32), bool)
        got = flash_attention(q, k, v, causal=False, mask=mask)
        want = llama.attention(q, k, v, causal=False, mask=mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_indivisible_block_raises(self):
        q, k, v = _qkv(jax.random.PRNGKey(5), S=48)
        with pytest.raises(ValueError, match="divide"):
            flash_attention(q, k, v, block_q=32, block_k=32)

"""Console REST API tests (reference analogue: console/backend handler
tests — job list/submit/stop, logs, overview, sources, auth)."""

import json
import urllib.request
import urllib.error

import pytest
import yaml

from kubedl_tpu.api import codec
from kubedl_tpu.api.types import JobConditionType, ReplicaSpec, ReplicaType
from kubedl_tpu.console import ConsoleServer, PersistReadBackend, SessionAuth
from kubedl_tpu.operator import Operator, OperatorOptions
from kubedl_tpu.runtime.executor import SubprocessRuntime

from tests.helpers import make_tpujob


@pytest.fixture()
def console(tmp_path):
    opts = OperatorOptions(
        local_addresses=True,
        pod_log_dir=str(tmp_path / "logs"),
        artifact_registry_root=str(tmp_path / "registry"),
        meta_storage="sqlite",
        event_storage="sqlite",
        storage_db_path=str(tmp_path / "meta.db"),
    )
    op = Operator(opts, runtime=SubprocessRuntime(str(tmp_path / "logs")))
    srv = ConsoleServer(op)
    op.start()
    srv.start()
    try:
        yield op, srv
    finally:
        srv.stop()
        op.stop()


def call(srv, method, path, body=None, token="", raw=False):
    host, port = srv.address
    req = urllib.request.Request(
        f"http://{host}:{port}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"}
        | ({"Authorization": f"Bearer {token}"} if token else {}),
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            data = resp.read()
            return resp.status, data if raw else json.loads(data)
    except urllib.error.HTTPError as e:
        data = e.read()
        return e.code, data if raw else json.loads(data)


def submit_and_wait(op, srv, name, phase=JobConditionType.SUCCEEDED, workers=2):
    job = make_tpujob(name, workers=workers, command=["python", "-c", "pass"])
    status, resp = call(srv, "POST", "/api/v1/job/submit", codec.encode(job))
    assert status == 200, resp
    op.wait_for_phase("TPUJob", name, [phase], timeout=30)


def test_job_submit_list_detail_yaml(console):
    op, srv = console
    submit_and_wait(op, srv, "c1")

    status, resp = call(srv, "GET", "/api/v1/job/list?kind=TPUJob")
    assert status == 200
    rows = resp["data"]["jobInfos"]
    assert [r["name"] for r in rows] == ["c1"]
    assert rows[0]["phase"] == "Succeeded"

    status, resp = call(srv, "GET", "/api/v1/job/detail/default/c1?kind=TPUJob")
    assert status == 200
    detail = resp["data"]
    # worker-0 success finishes the job; CleanPodPolicy.RUNNING may reap the
    # other still-running worker, so 1..2 pods remain.
    assert 1 <= len(detail["replicas"]) <= 2
    assert {r["replica_type"] for r in detail["replicas"]} == {"Worker"}
    assert any(e["reason"] == "JobSucceeded" for e in detail["events"])

    status, resp = call(srv, "GET", "/api/v1/job/yaml/default/c1?kind=TPUJob")
    assert status == 200
    doc = yaml.safe_load(resp["data"]["yaml"])
    assert doc["kind"] == "TPUJob"
    decoded = codec.decode_object(doc)
    assert decoded.spec.replica_specs[ReplicaType.WORKER].replicas == 2


def test_job_submit_via_yaml_body(console):
    op, srv = console
    job = make_tpujob("c-yaml", workers=1, command=["python", "-c", "pass"])
    body = {"yaml": yaml.safe_dump(codec.encode(job))}
    status, resp = call(srv, "POST", "/api/v1/job/submit", body)
    assert status == 200, resp
    op.wait_for_phase("TPUJob", "c-yaml", [JobConditionType.SUCCEEDED], timeout=30)


def test_job_submit_rejects_bad_kind(console):
    _, srv = console
    status, resp = call(srv, "POST", "/api/v1/job/submit", {"kind": "Nope"})
    assert status == 400
    status, resp = call(srv, "POST", "/api/v1/job/submit", {"no": "kind"})
    assert status == 400


def test_job_submit_rejects_bad_name(console):
    _, srv = console
    job = codec.encode(make_tpujob("ok"))
    job["metadata"]["name"] = "<img src=x onerror=alert(1)>"
    status, resp = call(srv, "POST", "/api/v1/job/submit", job)
    assert status == 400 and "invalid job name" in resp["data"]


def test_malformed_params_get_400_not_dropped_socket(console):
    _, srv = console
    status, resp = call(srv, "GET", "/api/v1/job/list?page_size=abc")
    assert status == 400
    status, resp = call(srv, "GET", "/api/v1/job/list?start_time=xyz")
    assert status == 400
    status, resp = call(srv, "POST", "/api/v1/job/submit", {"yaml": ":\n:"})
    assert status == 400


def test_pagination_total_is_true_count(console):
    op, srv = console
    for i in range(5):
        job = make_tpujob(f"pg-{i}", workers=1, command=["python", "-c", "pass"])
        call(srv, "POST", "/api/v1/job/submit", codec.encode(job))
    for i in range(5):
        op.wait_for_phase(
            "TPUJob", f"pg-{i}", [JobConditionType.SUCCEEDED], timeout=30
        )
    status, resp = call(srv, "GET", "/api/v1/job/list?page_size=2&page_num=1")
    assert resp["data"]["total"] == 5
    assert len(resp["data"]["jobInfos"]) == 2
    status, resp = call(srv, "GET", "/api/v1/job/list?page_size=2&page_num=3")
    assert len(resp["data"]["jobInfos"]) == 1
    # page_num below 1 clamps rather than returning an empty page
    status, resp = call(srv, "GET", "/api/v1/job/list?page_size=2&page_num=0")
    assert status == 200 and len(resp["data"]["jobInfos"]) == 2


def test_job_stop_and_delete(console):
    op, srv = console
    job = make_tpujob(
        "c-stop", workers=1, command=["python", "-c", "import time; time.sleep(300)"]
    )
    call(srv, "POST", "/api/v1/job/submit", codec.encode(job))
    op.wait_for_phase("TPUJob", "c-stop", [JobConditionType.RUNNING], timeout=30)

    status, _ = call(srv, "POST", "/api/v1/job/stop/default/c-stop?kind=TPUJob")
    assert status == 200
    got = op.wait_for_phase("TPUJob", "c-stop", [JobConditionType.FAILED], timeout=30)
    assert got.status.condition(JobConditionType.FAILED).reason == "JobStopped"

    status, _ = call(srv, "DELETE", "/api/v1/job/delete/default/c-stop?kind=TPUJob")
    assert status == 200
    status, _ = call(srv, "GET", "/api/v1/job/detail/default/c-stop?kind=TPUJob")
    assert status == 404


def test_statistics_running_and_overview(console):
    op, srv = console
    submit_and_wait(op, srv, "c-stat")

    status, resp = call(srv, "GET", "/api/v1/job/statistics")
    assert status == 200
    stats = resp["data"]
    assert stats["totalJobCount"] == 1
    assert stats["statistics"]["Succeeded"] == 1
    assert stats["histogram"]["TPUJob"] == 1

    status, resp = call(srv, "GET", "/api/v1/job/running-jobs")
    assert resp["data"]["jobInfos"] == []

    status, resp = call(srv, "GET", "/api/v1/data/overview")
    overview = resp["data"]
    assert overview["jobTotal"] == 1
    assert "TPUJob" in overview["workloadKinds"]


def test_pod_logs_and_events(console):
    op, srv = console
    job = make_tpujob(
        "c-log", workers=1, command=["python", "-c", "print('hello-from-pod')"]
    )
    call(srv, "POST", "/api/v1/job/submit", codec.encode(job))
    op.wait_for_phase("TPUJob", "c-log", [JobConditionType.SUCCEEDED], timeout=30)

    status, resp = call(srv, "GET", "/api/v1/pod/list/default/c-log")
    pod_name = resp["data"]["replicas"][0]["name"]

    status, resp = call(srv, "GET", f"/api/v1/log/logs/default/{pod_name}")
    assert status == 200
    assert any("hello-from-pod" in line for line in resp["data"]["logs"])

    status, resp = call(srv, "GET", "/api/v1/event/events/default/TPUJob/c-log")
    assert status == 200
    assert any(e["reason"] == "JobSucceeded" for e in resp["data"]["events"])


def test_job_routes_reject_non_workload_kind(console):
    _, srv = console
    # the job API must never reach non-job kinds through ?kind=
    status, resp = call(
        srv, "DELETE",
        "/api/v1/job/delete/kubedl-system/kubedl-console-datasources?kind=ConfigMap",
    )
    assert status == 400
    status, resp = call(srv, "POST", "/api/v1/job/stop/default/x?kind=Pod")
    assert status == 400


def test_codesource_named_datasource_does_not_cross_route(console):
    _, srv = console
    call(srv, "POST", "/api/v1/codesource", {"name": "datasource", "git": "g"})
    status, resp = call(srv, "GET", "/api/v1/datasource")
    assert resp["data"] == {}
    status, resp = call(srv, "GET", "/api/v1/codesource")
    assert list(resp["data"]) == ["datasource"]
    call(srv, "DELETE", "/api/v1/codesource/datasource")
    status, resp = call(srv, "GET", "/api/v1/codesource")
    assert resp["data"] == {}


def test_job_list_strips_payload(console):
    op, srv = console
    submit_and_wait(op, srv, "c-payload")
    _, resp = call(srv, "GET", "/api/v1/job/list")
    assert "payload" not in resp["data"]["jobInfos"][0]
    _, resp = call(srv, "GET", "/api/v1/job/json/default/c-payload")
    assert resp["data"]["kind"] == "TPUJob"  # detail still serves the object


def test_source_crud(console):
    _, srv = console
    body = {"name": "imagenet", "type": "nfs", "path": "/mnt/data"}
    status, resp = call(srv, "POST", "/api/v1/datasource", body)
    assert status == 200

    status, resp = call(srv, "GET", "/api/v1/datasource")
    assert resp["data"]["imagenet"]["path"] == "/mnt/data"

    status, _ = call(srv, "DELETE", "/api/v1/datasource/imagenet")
    assert status == 200
    status, resp = call(srv, "GET", "/api/v1/datasource")
    assert resp["data"] == {}

    # codesource is an independent namespace
    call(srv, "POST", "/api/v1/codesource", {"name": "repo", "git": "https://x"})
    status, resp = call(srv, "GET", "/api/v1/codesource")
    assert list(resp["data"]) == ["repo"]


def test_persist_read_backend_survives_store_delete(console):
    op, srv = console
    submit_and_wait(op, srv, "c-persist")
    assert op.manager.wait(
        lambda: (row := op.object_backend.get_job("default", "c-persist")) is not None
        and row.phase == "Succeeded"
        and len(op.object_backend.list_pods(row.uid)) == 2
    )
    # replace reader with the persist mirror, then delete from live store
    srv.reader = PersistReadBackend(op.object_backend, op.event_backend)
    op.store.delete("TPUJob", "c-persist", "default")

    status, resp = call(srv, "GET", "/api/v1/job/list?kind=TPUJob&name=c-persist")
    assert status == 200
    rows = resp["data"]["jobInfos"]
    assert rows and rows[0]["phase"] == "Succeeded"
    status, resp = call(srv, "GET", "/api/v1/job/detail/default/c-persist")
    assert status == 200
    assert len(resp["data"]["replicas"]) == 2


def test_auth_wall(tmp_path):
    op = Operator(OperatorOptions(local_addresses=True))
    srv = ConsoleServer(op, auth=SessionAuth({"admin": "s3cret"}))
    srv.start()
    try:
        status, _ = call(srv, "GET", "/api/v1/job/list")
        assert status == 401

        status, resp = call(
            srv, "POST", "/api/v1/login",
            {"username": "admin", "password": "wrong"},
        )
        assert status == 401

        status, resp = call(
            srv, "POST", "/api/v1/login",
            {"username": "admin", "password": "s3cret"},
        )
        assert status == 200
        token = resp["data"]["token"]

        status, resp = call(srv, "GET", "/api/v1/current-user", token=token)
        assert resp["data"]["username"] == "admin"
        status, _ = call(srv, "GET", "/api/v1/job/list", token=token)
        assert status == 200

        # logout via bearer header revokes the session
        status, _ = call(srv, "POST", "/api/v1/logout", token=token)
        assert status == 200
        status, _ = call(srv, "GET", "/api/v1/job/list", token=token)
        assert status == 401

        # unauthenticated metrics/health/index stay open
        status, _ = call(srv, "GET", "/healthz")
        assert status == 200
        status, body = call(srv, "GET", "/", raw=True)
        assert status == 200 and b"KubeDL-TPU" in body
    finally:
        srv.stop()
        op.stop()


def test_tensorboard_routes(console):
    op, srv = console
    job = make_tpujob(
        "c-tb", workers=1, command=["python", "-c", "import time; time.sleep(120)"]
    )
    call(srv, "POST", "/api/v1/job/submit", codec.encode(job))
    op.wait_for_phase("TPUJob", "c-tb", [JobConditionType.RUNNING], timeout=30)

    status, resp = call(srv, "GET", "/api/v1/tensorboard/status/default/c-tb")
    assert resp["data"]["configured"] is False

    status, _ = call(
        srv, "POST", "/api/v1/tensorboard/apply/default/c-tb",
        {"log_dir": "/tmp/tb-logs"},
    )
    assert status == 200
    status, resp = call(srv, "GET", "/api/v1/tensorboard/status/default/c-tb")
    assert resp["data"]["configured"] is True

    status, _ = call(srv, "DELETE", "/api/v1/tensorboard/default/c-tb")
    assert status == 200
    status, resp = call(srv, "GET", "/api/v1/tensorboard/status/default/c-tb")
    assert resp["data"]["configured"] is False
    call(srv, "POST", "/api/v1/job/stop/default/c-tb?kind=TPUJob")


def test_submit_strips_caller_status(console):
    """YAML copied from the console's own /job/yaml view embeds status; a
    re-submit must create a FRESH job, not one born terminal (ADVICE r1:
    reference strips this via the CRD status subresource on create)."""
    op, srv = console
    job = make_tpujob("c-strip", workers=1, command=["python", "-c", "pass"])
    body = codec.encode(job)
    body["status"] = {
        "conditions": [
            {"type": "Succeeded", "status": True, "reason": "JobSucceeded",
             "message": "forged", "last_transition_time": 0.0}
        ],
    }
    body.setdefault("metadata", {})["uid"] = "uid-forged"
    status, resp = call(srv, "POST", "/api/v1/job/submit", body)
    assert status == 200, resp
    stored = op.store.get("TPUJob", "c-strip", "default")
    assert stored.metadata.uid != "uid-forged"
    # the job actually runs (a forged-terminal job would never be reconciled)
    op.wait_for_phase("TPUJob", "c-strip", [JobConditionType.SUCCEEDED], timeout=30)


def test_list_and_statistics_reject_non_workload_kind(console):
    """ADVICE r1: list/statistics/running-jobs must 400 on kinds that are
    not enabled workloads instead of 500ing on non-job objects."""
    _, srv = console
    for path in (
        "/api/v1/job/list?kind=Pod",
        "/api/v1/job/statistics?kind=ConfigMap",
        "/api/v1/job/running-jobs?kind=Service",
    ):
        status, resp = call(srv, "GET", path)
        assert status == 400, (path, resp)


def test_statistics_ignore_pagination(console):
    """ADVICE r1: aggregate counts must cover the full filtered set even
    when the client passes page_size/page_num."""
    op, srv = console
    for i in range(3):
        submit_and_wait(op, srv, f"c-stat{i}", workers=1)
    status, resp = call(
        srv, "GET", "/api/v1/job/statistics?page_size=1&page_num=1"
    )
    assert status == 200
    assert resp["data"]["totalJobCount"] == 3


def test_model_list_and_cluster_slices_endpoints(console):
    op, srv = console
    # model list: empty then populated via lineage
    status, resp = call(srv, "GET", "/api/v1/model/list")
    assert status == 200 and resp["data"]["models"] == []
    status, resp = call(srv, "GET", "/api/v1/cluster/slices")
    assert status == 200 and resp["data"]["slices"] == []

    from kubedl_tpu.lineage.types import Model, ModelVersion, ModelVersionPhase

    m = Model()
    m.metadata.name = "m1"
    op.store.create(m)
    mv = ModelVersion(model_name="m1", image="repo:v1",
                      phase=ModelVersionPhase.SUCCEEDED,
                      parent_version="m1-v0",
                      checkpoint_fingerprint="sha256:abc123")
    mv.metadata.name = "m1-v1"
    op.store.create(mv)
    status, resp = call(srv, "GET", "/api/v1/model/list")
    models = resp["data"]["models"]
    assert [x["name"] for x in models] == ["m1"]
    assert models[0]["versions"][0]["image"] == "repo:v1"
    assert models[0]["versions"][0]["phase"] == "Succeeded"
    # rollout provenance rides the console view (PR 17 lineage fields)
    assert models[0]["versions"][0]["parent_version"] == "m1-v0"
    assert (models[0]["versions"][0]["checkpoint_fingerprint"]
            == "sha256:abc123")


def test_frontend_spa_served(console):
    _, srv = console
    status, body = call(srv, "GET", "/", raw=True)
    assert status == 200
    html = body.decode()
    for frag in ("#/jobs", "#/models", "#/submit", "#/sources", "#/charts"):
        assert frag in html, frag
    # routes moved into the static app bundle with the round-3 split
    status, js = call(srv, "GET", "/static/app.js", raw=True)
    assert status == 200
    text = js.decode()
    for frag in ("cluster/slices", "model/list", "data/charts"):
        assert frag in text, frag


def test_static_assets_and_index(console):
    """Round-3 console split: the SPA is served from real static files
    (index + app.js + style.css), no longer one embedded string."""
    op, srv = console
    status, body = call(srv, "GET", "/", raw=True)
    assert status == 200
    html = body.decode()
    assert '<script src="/static/app.js">' in html
    assert '<link rel="stylesheet" href="/static/style.css">' in html
    status, js = call(srv, "GET", "/static/app.js", raw=True)
    assert status == 200
    text = js.decode()
    assert "VIEWS.charts" in text and "VIEWS.overview" in text
    status, css = call(srv, "GET", "/static/style.css", raw=True)
    assert status == 200 and b".tile" in css
    # traversal-safe
    status, _ = call(srv, "GET", "/static/..%2Ffrontend.py", raw=True)
    assert status == 404
    status, _ = call(srv, "GET", "/static/nope.js", raw=True)
    assert status == 404


def test_charts_endpoint_serves_metric_snapshots(console):
    op, srv = console
    submit_and_wait(op, srv, "chart1")
    status, resp = call(srv, "GET", "/api/v1/data/charts")
    assert status == 200
    d = resp["data"]
    first = d["launch_delay"]["first_pod"]
    assert first and first[0]["labels"].get("kind") == "TPUJob"
    assert first[0]["total"] >= 1
    assert len(first[0]["buckets"]) == len(first[0]["counts"])
    assert sum(first[0]["counts"]) >= 1  # the launch landed in a bucket
    created = {r["labels"].get("kind"): r["value"] for r in d["counters"]["created"]}
    assert created.get("TPUJob", 0) >= 1
    succ = {r["labels"].get("kind"): r["value"] for r in d["counters"]["successful"]}
    assert succ.get("TPUJob", 0) >= 1
    gauges = d["gauges"]
    assert any(r["labels"].get("kind") == "TPUJob" for r in gauges["running"])
    assert d["serving"] == []  # no inference objects in this fixture


def test_cluster_nodes_endpoint(console):
    op, srv = console
    from kubedl_tpu.core.nodes import NodeHeartbeater

    hb = NodeHeartbeater(op.store, ["hostZ"])
    hb.beat_once()
    status, resp = call(srv, "GET", "/api/v1/cluster/nodes")
    assert status == 200
    nodes = resp["data"]["nodes"]
    assert [n["name"] for n in nodes] == ["hostZ"]
    assert nodes[0]["ready"] is True and nodes[0]["pods"] == 0


def test_storage_list_endpoint(console):
    """PVC-list parity (reference routers/api/job.go:29-43): the submit
    form's storage surfaces — providers + configured/known roots."""
    op, srv = console
    status, resp = call(srv, "GET", "/api/v1/storage/list")
    assert status == 200
    data = resp["data"]
    names = {p["name"] for p in data["providers"]}
    # the reference's NFS/EFS/local union ported over, plus remote-blob
    assert {"shared", "nfs", "efs", "local", "http"} <= names
    shared_flags = {p["name"]: p["shared"] for p in data["providers"]}
    assert shared_flags["local"] is False and shared_flags["shared"] is True
    roots = {r["source"]: r for r in data["roots"]}
    assert "operator artifact registry" in roots


def test_proxy_header_auth_provider(tmp_path):
    """Pluggable auth (reference console/backend/pkg/auth oauth package):
    an authenticating reverse proxy asserts identity via headers; the
    shared-secret header stops direct spoofing."""
    import urllib.error
    import urllib.request

    from kubedl_tpu.console.auth import ProxyHeaderProvider, SessionAuth

    op = Operator(OperatorOptions(local_addresses=True))
    srv = ConsoleServer(op, auth=SessionAuth(
        users={"admin": "pw"},
        providers=[ProxyHeaderProvider(shared_secret="proxy-secret")],
    ))
    srv.start()
    try:
        host, port = srv.address

        def get(path, headers):
            req = urllib.request.Request(
                f"http://{host}:{port}{path}", headers=headers
            )
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        # no identity at all -> wall
        status, _ = get("/api/v1/current-user", {})
        assert status == 401
        # spoofed user header WITHOUT the proxy secret -> wall
        status, _ = get(
            "/api/v1/current-user", {"X-Auth-Request-User": "mallory"}
        )
        assert status == 401
        # proxy-asserted identity (header + shared secret) -> through,
        # with the asserted username
        status, resp = get("/api/v1/current-user", {
            "X-Auth-Request-User": "alice@corp",
            "X-Auth-Request-Secret": "proxy-secret",
        })
        assert status == 200
        assert resp["data"]["username"] == "alice@corp"
        # password login still works beside the proxy path
        status, resp = call(
            srv, "POST", "/api/v1/login",
            {"username": "admin", "password": "pw"},
        )
        assert status == 200
    finally:
        srv.stop()
        op.stop()

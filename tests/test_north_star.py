"""North-star validation at REAL shapes (VERDICT r2 #3 / BASELINE target 4):
Llama-3-8B, seq 8192, on a 32-virtual-device mesh at dp x fsdp x tp.

Runs in a subprocess because the test session pins 8 virtual devices; the
north star wants 32. The validator AOT-lowers the production train step
(sharding propagation runs at real shapes) and asserts per-chip residency
fits v5e HBM; an over-budget sharding must raise.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO_ROOT = str(Path(__file__).resolve().parents[1])

SCRIPT = """
import os, json, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=32"
sys.path.insert(0, {root!r})
import __graft_entry__ as g
rep = g.validate_north_star(32)
assert rep["lowered"], rep
assert rep["per_chip_gb"]["total"] <= rep["hbm_budget_gb"], rep
try:
    g.validate_north_star(32, mesh_axes={{"data": 32, "fsdp": 1, "tensor": 1}})
    raise SystemExit("over-budget sharding did not raise")
except RuntimeError:
    pass
print("NS_REPORT " + json.dumps(rep))
""".format(root=REPO_ROOT)


def test_llama3_8b_aot_on_v5e32(tmp_path):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-c", SCRIPT], env=env, capture_output=True,
        text=True, timeout=570,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    line = [l for l in out.stdout.splitlines() if l.startswith("NS_REPORT ")]
    assert line, out.stdout[-500:]
    rep = json.loads(line[-1][len("NS_REPORT "):])
    assert rep["model"] == "llama3-8b" and rep["n_devices"] == 32
    assert rep["n_params"] > 8e9
    assert rep["seq_len"] == 8192
    # the intended sharding leaves real headroom on a 16GB chip
    assert rep["per_chip_gb"]["total"] < 12.0, rep

"""Tracing tests (TPU addition per SURVEY.md §5 — no reference analogue).

Grown with the distributed-tracing work (docs/observability.md): trace
identity + header propagation, router→engine context hops against stub
replicas, hedge/retry/fallback span tagging, the flight-recorder response
shape, SLO burn-rate math under a fake clock, and exemplar rendering."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubedl_tpu.observability.slo import (
    DEFAULT_ALERTS,
    BurnAlert,
    SLOTracker,
    alerts_from_config,
)
from kubedl_tpu.observability.tracing import (
    TRACE_HEADER,
    TRACER,
    TraceContext,
    Tracer,
    build_span_tree,
    new_span_id,
    new_trace_id,
    parse_trace_header,
    span_to_dict,
    trace_for_job,
)

from tests.helpers import make_tpujob
from tests.test_engine import make_engine, submit_and_reconcile


@pytest.fixture(autouse=True)
def _clean_tracer():
    """Every test starts with an empty, armed ring and leaves it so."""
    TRACER.clear()
    TRACER.enabled = True
    yield
    TRACER.clear()
    TRACER.enabled = True


class TestTracer:
    def test_span_records_duration_and_attrs(self):
        t = Tracer()
        with t.span("work", key="v") as attrs:
            attrs["late"] = 1
        (s,) = t.spans("work")
        assert s.duration >= 0
        assert s.attrs == {"key": "v", "late": 1}

    def test_ring_capacity(self):
        t = Tracer(capacity=8)
        for i in range(20):
            with t.span(f"s{i}"):
                pass
        assert len(t.spans()) == 8
        assert t.spans()[0].name == "s12"

    def test_summary_and_chrome_export(self):
        t = Tracer()
        for _ in range(3):
            with t.span("phase"):
                pass
        agg = t.summary()["phase"]
        assert agg["count"] == 3 and agg["total_s"] >= 0
        trace = json.loads(t.chrome_trace())
        assert len(trace["traceEvents"]) == 3
        assert trace["traceEvents"][0]["ph"] == "X"

    def test_disabled_is_free(self):
        t = Tracer()
        t.enabled = False
        with t.span("skipped"):
            pass
        assert t.spans() == []

    def test_thread_names_become_tids(self):
        t = Tracer()

        def work():
            with t.span("x"):
                pass

        th = threading.Thread(target=work, name="worker-th")
        th.start()
        th.join()
        with t.span("x"):
            pass
        trace = json.loads(t.chrome_trace())
        assert len({e["tid"] for e in trace["traceEvents"]}) == 2


class TestTraceIdentity:
    def test_two_tracers_share_the_epoch_timebase(self):
        """Spans recorded by INDEPENDENT tracers (different processes in
        production) must land on one wall-clock timeline — the whole
        premise of scripts/tracemerge.py."""
        t1, t2 = Tracer(), Tracer()
        wall0 = time.time()
        with t1.span("a"):
            pass
        with t2.span("b"):
            pass
        wall1 = time.time()
        (a,), (b,) = t1.spans("a"), t2.spans("b")
        assert wall0 - 1.0 <= a.ts <= wall1 + 1.0
        assert wall0 - 1.0 <= b.ts <= wall1 + 1.0
        assert abs(a.ts - b.ts) < 1.0  # same timebase, not per-process zero

    def test_header_round_trip(self):
        ctx = TraceContext(new_trace_id(), new_span_id())
        back = parse_trace_header(ctx.to_header())
        assert back is not None
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id
        assert back.sampled

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "00-short-short-01",
        "00-" + "g" * 32 + "-" + "0" * 16 + "-01",  # non-hex
    ])
    def test_malformed_header_parses_to_none(self, bad):
        assert parse_trace_header(bad) is None

    def test_trace_for_job_is_deterministic(self):
        a, b = trace_for_job("uid-1"), trace_for_job("uid-1")
        assert a.trace_id == b.trace_id and a.span_id == b.span_id
        assert trace_for_job("uid-2").trace_id != a.trace_id

    def test_record_parents_under_context(self):
        t = Tracer()
        ctx = TraceContext(new_trace_id(), new_span_id())
        sid = t.record("child", duration=0.1, trace=ctx)
        (s,) = t.spans("child")
        assert s.span_id == sid
        assert s.trace_id == ctx.trace_id
        assert s.parent_id == ctx.span_id
        # explicit parent_id and span_id win over the context defaults
        forced = new_span_id()
        t.record("forced", trace=ctx, parent_id="p" * 16, span_id=forced)
        (f,) = t.spans("forced")
        assert f.span_id == forced and f.parent_id == "p" * 16

    def test_record_wall_ts_pins_the_epoch_timestamp(self):
        t = Tracer()
        t.record("pinned", duration=2.0, wall_ts=1000.0)
        (s,) = t.spans("pinned")
        assert s.ts == 1000.0

    def test_build_span_tree_roots_and_order(self):
        root_id, kid_id = new_span_id(), new_span_id()
        spans = [
            {"name": "kid", "span_id": kid_id, "parent_id": root_id,
             "ts": 2.0},
            {"name": "root", "span_id": root_id, "parent_id": "", "ts": 1.0},
            # self-parented (job.submit idiom) and orphaned spans are roots
            {"name": "selfp", "span_id": "s" * 16, "parent_id": "s" * 16,
             "ts": 0.5},
            {"name": "orphan", "span_id": new_span_id(),
             "parent_id": "missing-parent00", "ts": 3.0},
        ]
        tree = build_span_tree(spans)
        assert [n["name"] for n in tree] == ["selfp", "root", "orphan"]
        assert [c["name"] for c in tree[1]["children"]] == ["kid"]

    def test_disarmed_calls_are_inert(self):
        t = Tracer()
        t.enabled = False
        h = t.begin("x", parent=TraceContext(new_trace_id(), new_span_id()))
        h.finish(late=1)
        with t.span("y"):
            pass
        assert t.record("z", duration=1.0) == ""
        assert t.spans() == []


class TestEngineIntegration:
    def test_reconcile_emits_span(self):
        engine, store, _ = make_engine()
        submit_and_reconcile(engine, store, make_tpujob("traced"))
        spans = TRACER.spans("reconcile")
        assert spans and spans[-1].attrs["job"] == "default/traced"

    def test_job_milestones_share_the_job_trace(self):
        """submit/gang_bind spans land in the deterministic per-job
        trace, rooted at the self-parented job.submit span."""
        engine, store, _ = make_engine()
        job = make_tpujob("ladder")
        submit_and_reconcile(engine, store, job, times=4)
        uid = store.get(job.KIND, "ladder").metadata.uid
        ctx = trace_for_job(uid)
        spans = {s.name: s for s in TRACER.trace_spans(ctx.trace_id)}
        assert spans["job.submit"].span_id == ctx.span_id
        assert spans["job.gang_bind"].parent_id == ctx.span_id
        tree = TRACER.span_tree(ctx.trace_id)
        assert tree and tree[0]["name"] == "job.submit"
        assert "job.gang_bind" in {c["name"] for c in tree[0]["children"]}

    def test_milestones_emitted_once_per_job(self):
        engine, store, _ = make_engine()
        job = make_tpujob("once")
        # re-reconciling must not duplicate milestone spans
        submit_and_reconcile(engine, store, job, times=5)
        uid = store.get(job.KIND, "once").metadata.uid
        names = [s.name for s in
                 TRACER.trace_spans(trace_for_job(uid).trace_id)]
        assert len(names) == len(set(names)), names


# ---------------------------------------------------------------------------
# router → engine context propagation against stub replicas
# ---------------------------------------------------------------------------

class _TraceStubHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # quiet
        pass

    def _json(self, code, payload, headers=None):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path.startswith("/v1/trace"):
            self._json(200, {"enabled": True,
                             "spans": self.server.trace_spans})
            return
        self._json(200, {"queued": 0, "shed_recent": 0, "draining": False})

    def do_POST(self):
        n = int(self.headers.get("Content-Length", "0"))
        req = json.loads(self.rfile.read(n) or b"{}")
        beh = self.server.behavior
        if self.path == "/v1/cancel":
            self._json(200, {"cancelled": True})
            return
        self.server.calls.append(
            {"req": req, "trace_header": self.headers.get(TRACE_HEADER)}
        )
        shots = beh.get("fail_first", 0)
        if len(self.server.calls) <= shots:
            self._json(503, {"error": "busy", "shed": True,
                             "reason": "overloaded"}, {"Retry-After": "1"})
            return
        if beh.get("delay"):
            time.sleep(beh["delay"])
        self._json(200,
                   {"token_ids": [1, 2, 3], "served_by": self.server.name})


def _trace_stub(name, **behavior):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _TraceStubHandler)
    srv.name = name
    srv.behavior = behavior
    srv.calls = []
    srv.trace_spans = []
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


@pytest.fixture
def trace_fleet():
    servers = {}

    def make(name, **behavior):
        servers[name] = _trace_stub(name, **behavior)
        return servers[name]

    yield make, servers
    for s in servers.values():
        s.shutdown()
        s.server_close()


class TestRouterPropagation:
    def _router(self, servers, **kw):
        from kubedl_tpu.serving.router import ServingRouter

        kw.setdefault("hedge_enabled", False)
        kw.setdefault("affinity_prefix_len", 0)
        return ServingRouter(
            [(n, "127.0.0.1", s.server_port) for n, s in servers.items()],
            **kw)

    def test_forward_carries_the_trace_header(self, trace_fleet):
        make, servers = trace_fleet
        a = make("a")
        r = self._router(servers)
        caller = TraceContext(new_trace_id(), new_span_id())
        code, _, _ = r.handle_generate(
            {"prompt_ids": [1, 2], "max_tokens": 4}, 5000, trace=caller)
        assert code == 200
        sent = parse_trace_header(a.calls[0]["trace_header"])
        assert sent is not None
        assert sent.trace_id == caller.trace_id
        # the header names the FORWARD span, and the chain reads
        # caller -> router.request -> router.forward
        (root,) = TRACER.spans("router.request")
        (fwd,) = TRACER.spans("router.forward")
        assert root.parent_id == caller.span_id
        assert fwd.parent_id == root.span_id
        assert sent.span_id == fwd.span_id
        assert fwd.attrs["replica"] == "a"
        assert fwd.attrs["result"] == "ok"

    def test_retry_spans_share_the_trace_and_tag_the_attempt(
            self, trace_fleet):
        make, servers = trace_fleet
        make("a", fail_first=1)  # primary sheds once, failover retries
        make("b")
        r = self._router(servers)
        code, _, _ = r.handle_generate(
            {"prompt_ids": [1], "max_tokens": 4}, 5000)
        assert code == 200
        fwds = TRACER.spans("router.forward")
        assert len(fwds) == 2
        assert {f.attrs["retry"] for f in fwds} == {0, 1}
        assert len({f.trace_id for f in fwds}) == 1
        shed, won = sorted(fwds, key=lambda f: f.attrs["retry"])
        assert shed.attrs["result"] == "ReplicaShedding"
        assert won.attrs["result"] == "ok"

    def test_hedge_spans_tagged_winner_and_loser(self, trace_fleet):
        make, servers = trace_fleet
        make("a", delay=0.8)  # least-loaded tie-break makes "a" primary
        make("b")
        r = self._router(servers, hedge_enabled=True, hedge_floor_ms=50.0,
                         hedge_default_ms=80.0)
        code, payload, _ = r.handle_generate(
            {"prompt_ids": [7] * 8, "max_tokens": 4}, 8000)
        assert code == 200 and payload["served_by"] == "b"
        # the loser's span is recorded when its slow attempt resolves
        deadline = time.monotonic() + 3.0
        while (time.monotonic() < deadline
               and len(TRACER.spans("router.forward")) < 2):
            time.sleep(0.02)
        fwds = TRACER.spans("router.forward")
        assert len(fwds) == 2
        outcomes = {f.attrs["replica"]: f.attrs.get("outcome") for f in fwds}
        assert outcomes == {"a": "loser", "b": "winner"}
        assert len({f.trace_id for f in fwds}) == 1

    def test_fallback_leg_is_traced(self, trace_fleet):
        make, servers = trace_fleet
        dec = make("dec")
        dead = make("pre")
        port = dead.server_port
        dead.shutdown()
        dead.server_close()  # prefill leg: connection refused
        del servers["pre"]
        from kubedl_tpu.serving.router import ServingRouter

        r = ServingRouter(
            [{"name": "pre", "port": port, "role": "prefill"},
             {"name": "dec", "port": dec.server_port, "role": "decode"}],
            hedge_enabled=False, affinity_prefix_len=0)
        code, payload, _ = r.handle_generate(
            {"prompt_ids": [1, 2], "max_tokens": 4}, 5000)
        assert code == 200 and payload["served_by"] == "dec"
        (root,) = TRACER.spans("router.request")
        (leg,) = TRACER.spans("router.prefill_leg")
        (fb,) = TRACER.spans("router.fallback")
        assert leg.parent_id == root.span_id
        assert fb.parent_id == root.span_id
        assert fb.attrs["reason"] == "disagg_leg_failed"
        assert fb.trace_id == root.trace_id

    def test_flight_recorder_response_shape(self, trace_fleet):
        make, servers = trace_fleet
        a = make("a")
        # the replica's /v1/trace contribution nests under its forward span
        r = self._router(servers)
        code, payload, _ = r.handle_generate(
            {"prompt_ids": [1], "max_tokens": 4,
             "debug": {"trace": True}}, 5000)
        assert code == 200
        rec = payload["trace"]
        (root,) = TRACER.spans("router.request")
        assert rec["trace_id"] == root.trace_id
        assert rec["spans"][0]["name"] == "router.request"
        kids = {c["name"] for c in rec["spans"][0]["children"]}
        assert "router.forward" in kids

    def test_flight_recorder_merges_replica_spans(self, trace_fleet):
        make, servers = trace_fleet
        a = make("a")
        r = self._router(servers)
        caller = TraceContext(new_trace_id(), new_span_id())
        # seed the stub's /v1/trace with an engine-side span parented
        # under the forward context the router will send
        code, _, _ = r.handle_generate(
            {"prompt_ids": [1], "max_tokens": 4}, 5000, trace=caller)
        assert code == 200
        sent = parse_trace_header(a.calls[0]["trace_header"])
        a.trace_spans = [{
            "name": "engine.request", "trace_id": sent.trace_id,
            "span_id": new_span_id(), "parent_id": sent.span_id,
            "ts": time.time(), "duration_ms": 1.0, "attrs": {},
        }]
        code, payload, _ = r.handle_generate(
            {"prompt_ids": [1], "max_tokens": 4,
             "debug": {"trace": True}}, 5000)
        assert code == 200
        tree = payload["trace"]["spans"]
        # the stub serves the seeded span for any trace query; the flight
        # recorder must surface it in the merged tree
        all_names = set()

        def walk(nodes):
            for n in nodes:
                all_names.add(n["name"])
                walk(n["children"])

        walk(tree)
        assert "engine.request" in all_names


class TestSLOBurnRate:
    def _tracker(self, clock, alerts=DEFAULT_ALERTS):
        return SLOTracker(objective=0.999, latency_objective_ms=100.0,
                          alerts=alerts, clock=clock)

    def test_burn_rate_math_under_fake_clock(self):
        now = [1000.0]
        t = self._tracker(lambda: now[0])
        for _ in range(20):
            t.observe(ok=True, latency_ms=10.0)
        assert t.burn_rate(300.0) == 0.0
        for _ in range(20):
            t.observe(ok=False, latency_ms=10.0)
        # 20 bad / 40 total over every window -> 0.5 / 0.001 = 500x
        assert t.burn_rate(300.0) == pytest.approx(500.0)
        assert t.burning(DEFAULT_ALERTS[0])

    def test_latency_breach_counts_as_bad(self):
        now = [1000.0]
        t = self._tracker(lambda: now[0])
        assert t.observe(ok=True, latency_ms=50.0) is True
        assert t.observe(ok=True, latency_ms=500.0) is False  # 200 but slow
        snap = t.snapshot()
        assert snap["requests"] == 2 and snap["bad"] == 1

    def test_outage_flips_gauges_and_time_clears_them(self):
        """The acceptance drill: an injected outage flips
        kubedl_tpu_slo_burning to 1; the outage ending (time passing
        under a fake clock) clears it without new traffic."""
        now = [10_000.0]
        t = self._tracker(lambda: now[0])
        for _ in range(10):
            t.observe(ok=False, latency_ms=5.0, trace_id="f" * 32)
        text = t.metrics.registry.render()
        assert 'kubedl_tpu_slo_burning{severity="page"} 1.0' in text
        assert t.snapshot()["burning"]["page"] is True
        assert t.last_bad_trace_id == "f" * 32
        # outage over: advance past the long window, no new events
        now[0] += DEFAULT_ALERTS[0].long_s + t.bucket_s + 1.0
        t.refresh()
        text = t.metrics.registry.render()
        assert 'kubedl_tpu_slo_burning{severity="page"} 0.0' in text
        assert t.snapshot()["burning"]["page"] is False

    def test_short_window_alone_does_not_fire(self):
        """Multi-window discipline: a blip that has not yet burned the
        LONG window must not page."""
        now = [50_000.0]
        alerts = (BurnAlert("page", 10.0, 1000.0, 14.4),)
        t = self._tracker(lambda: now[0], alerts=alerts)
        # long window full of good traffic...
        for _ in range(99):
            t.observe(ok=True, latency_ms=1.0)
            now[0] += 5.0
        # ...then a 1-bucket burst of errors
        t.observe(ok=False, latency_ms=1.0)
        assert t.burn_rate(10.0) >= 14.4
        assert t.burn_rate(1000.0) < 14.4
        assert not t.burning(alerts[0])

    def test_alerts_from_config(self):
        assert alerts_from_config(None) == DEFAULT_ALERTS
        (a,) = alerts_from_config([{"severity": "ticket", "short_s": 60,
                                    "long_s": 600, "threshold": 2.5}])
        assert a == BurnAlert("ticket", 60.0, 600.0, 2.5)

    def test_exemplar_links_metrics_to_a_retrievable_trace(self):
        """A burning SLO's histogram exemplar must resolve to a trace the
        ring buffer can serve via /v1/trace."""
        now = [1000.0]
        t = self._tracker(lambda: now[0])
        tid = new_trace_id()
        TRACER.record("router.request", duration=0.2,
                      trace=TraceContext(tid, ""))
        t.observe(ok=False, latency_ms=42.0, trace_id=tid)
        text = t.metrics.registry.render()
        assert f'# {{trace_id="{tid}"}} 42.0' in text
        spans = TRACER.trace_spans(tid)
        assert spans and spans[0].name == "router.request"

    def test_router_feeds_slo_and_stats(self, trace_fleet):
        make, servers = trace_fleet
        make("a")
        from kubedl_tpu.serving.router import ServingRouter

        r = ServingRouter(
            [("a", "127.0.0.1", servers["a"].server_port)],
            hedge_enabled=False, affinity_prefix_len=0,
            slo={"objective": 0.99, "latency_objective_ms": 60_000.0})
        code, _, _ = r.handle_generate(
            {"prompt_ids": [1], "max_tokens": 4}, 5000)
        assert code == 200
        snap = r.stats()["slo"]
        assert snap["objective"] == 0.99
        assert snap["requests"] == 1 and snap["bad"] == 0
        text = r.metrics.registry.render()
        assert 'kubedl_tpu_slo_requests{result="good"} 1.0' in text

"""Tracing tests (TPU addition per SURVEY.md §5 — no reference analogue)."""

import json
import threading

from kubedl_tpu.observability.tracing import TRACER, Tracer

from tests.helpers import make_tpujob
from tests.test_engine import make_engine, submit_and_reconcile


class TestTracer:
    def test_span_records_duration_and_attrs(self):
        t = Tracer()
        with t.span("work", key="v") as attrs:
            attrs["late"] = 1
        (s,) = t.spans("work")
        assert s.duration >= 0
        assert s.attrs == {"key": "v", "late": 1}

    def test_ring_capacity(self):
        t = Tracer(capacity=8)
        for i in range(20):
            with t.span(f"s{i}"):
                pass
        assert len(t.spans()) == 8
        assert t.spans()[0].name == "s12"

    def test_summary_and_chrome_export(self):
        t = Tracer()
        for _ in range(3):
            with t.span("phase"):
                pass
        agg = t.summary()["phase"]
        assert agg["count"] == 3 and agg["total_s"] >= 0
        trace = json.loads(t.chrome_trace())
        assert len(trace["traceEvents"]) == 3
        assert trace["traceEvents"][0]["ph"] == "X"

    def test_disabled_is_free(self):
        t = Tracer()
        t.enabled = False
        with t.span("skipped"):
            pass
        assert t.spans() == []

    def test_thread_names_become_tids(self):
        t = Tracer()

        def work():
            with t.span("x"):
                pass

        th = threading.Thread(target=work, name="worker-th")
        th.start()
        th.join()
        with t.span("x"):
            pass
        trace = json.loads(t.chrome_trace())
        assert len({e["tid"] for e in trace["traceEvents"]}) == 2


class TestEngineIntegration:
    def test_reconcile_emits_span(self):
        TRACER.clear()
        engine, store, _ = make_engine()
        submit_and_reconcile(engine, store, make_tpujob("traced"))
        spans = TRACER.spans("reconcile")
        assert spans and spans[-1].attrs["job"] == "default/traced"

"""Persistent XLA compilation cache: the round-2 startup regression fix.

VERDICT r2 weak #1 / next-round #1: every gang restart, slice resize, and
suspend/resume re-paid a ~17s first-step compile because no persistent
compilation cache existed anywhere. These tests prove the full path: the
operator injects KUBEDL_COMPILE_CACHE_DIR into pods, the training entry
enables the cache before the first trace, and a second identical process
deserializes (adds zero new cache entries, compiles faster) instead of
re-lowering the unchanged program.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO_ROOT = str(Path(__file__).resolve().parents[1])


def test_enable_and_count(tmp_path, monkeypatch):
    from kubedl_tpu.utils.compile_cache import (
        cache_entry_count,
        enable_compilation_cache,
    )

    import jax

    assert cache_entry_count(str(tmp_path / "nope")) == 0
    # disabled when neither arg nor env names a dir
    monkeypatch.delenv("KUBEDL_COMPILE_CACHE_DIR", raising=False)
    assert enable_compilation_cache() == ""
    # env-driven enable creates the dir and points jax at it; jax config is
    # process-global, so restore it (tmp_path is deleted after this test)
    prev = jax.config.jax_compilation_cache_dir
    try:
        d = tmp_path / "cache"
        monkeypatch.setenv("KUBEDL_COMPILE_CACHE_DIR", str(d))
        assert enable_compilation_cache() == str(d)
        assert d.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(d)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_operator_injects_cache_env(tmp_path):
    """Every training pod carries KUBEDL_COMPILE_CACHE_DIR (user-set env
    wins); serving predictor pods get it too via InferenceController."""
    from tests.helpers import make_tpujob

    from kubedl_tpu.api.types import ReplicaType
    from kubedl_tpu.operator import Operator, OperatorOptions

    cache = str(tmp_path / "cc")
    opts = OperatorOptions(
        local_addresses=True,
        pod_log_dir=str(tmp_path / "logs"),
        artifact_registry_root=str(tmp_path / "registry"),
        compile_cache_dir=cache,
    )
    with Operator(opts) as op:
        eng = op.engines["TPUJob"]
        job = make_tpujob("cachy", workers=1, command=["true"])
        eng.controller.apply_defaults(job)
        from kubedl_tpu.api.interface import ReconcileContext

        spec = job.spec.replica_specs[ReplicaType.WORKER]
        pod = eng._new_pod(job, ReconcileContext(job), ReplicaType.WORKER, spec, 0)
        assert pod.spec.main_container().get_env(
            "KUBEDL_COMPILE_CACHE_DIR"
        ) == cache
        # user-set value is respected
        spec.template.spec.main_container().set_env(
            "KUBEDL_COMPILE_CACHE_DIR", "/custom"
        )
        pod = eng._new_pod(job, ReconcileContext(job), ReplicaType.WORKER, spec, 0)
        assert pod.spec.main_container().get_env(
            "KUBEDL_COMPILE_CACHE_DIR"
        ) == "/custom"


def _run_entry(cache_dir: str, log_dir: Path, tag: str) -> dict:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "KUBEDL_COMPILE_CACHE_DIR": cache_dir,
        "KUBEDL_TRAIN_CONFIG": json.dumps(
            {"model": "tiny", "steps": 2, "global_batch": 4, "seq_len": 32}
        ),
        "PYTHONPATH": REPO_ROOT,
    })
    out = subprocess.run(
        [sys.executable, "-m", "kubedl_tpu.training.entry"],
        env=env, capture_output=True, text=True, timeout=300,
    )
    (log_dir / f"{tag}.log").write_text(out.stdout + out.stderr)
    assert out.returncode == 0, out.stderr[-2000:]
    for line in out.stdout.splitlines():
        if '"worker_summary"' in line:
            return json.loads(line)["worker_summary"]
    raise AssertionError(f"no summary in output: {out.stdout[-500:]}")


def test_warm_restart_hits_cache(tmp_path):
    """Two identical worker processes, same cache dir: the first populates
    the persistent cache, the second deserializes — zero new entries.
    This is exactly the path a gang restart / resize / resume takes
    (fresh process, unchanged program)."""
    from kubedl_tpu.utils.compile_cache import cache_entry_count

    cache = str(tmp_path / "compile-cache")
    cold = _run_entry(cache, tmp_path, "cold")
    n_cold = cache_entry_count(cache)
    assert n_cold > 0, "cold run wrote no cache entries"
    warm = _run_entry(cache, tmp_path, "warm")
    n_warm = cache_entry_count(cache)
    assert n_warm == n_cold, (
        f"warm run recompiled: {n_warm - n_cold} new cache entries"
    )
    # warm compile must not be slower; usually it is much faster, but CPU
    # timing jitter on a tiny model makes a strict factor flaky
    assert warm["first_step_seconds"] <= cold["first_step_seconds"] * 1.5, (
        cold["first_step_seconds"], warm["first_step_seconds"],
    )

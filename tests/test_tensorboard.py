"""TensorBoard lifecycle tests (reference analogue: the tensorboard
reconcile paths exercised via controllers/tensorflow/tfjob_controller.go:171-177
and pkg/tensorboard/tensorboard.go:59-447)."""

import json
import time

from kubedl_tpu.api import constants
from kubedl_tpu.core.objects import Pod, Volume
from kubedl_tpu.core.store import ObjectStore
from kubedl_tpu.observability.tensorboard import (
    TB_PORT,
    TensorBoardReconciler,
    parse_tensorboard_spec,
    tb_name,
)

from tests.helpers import PodDriver, make_tpujob
from tests.test_engine import make_engine, submit_and_reconcile


def annotate_tb(job, **overrides):
    cfg = {"logDir": "/data/logs", "ttlSecondsAfterJobFinished": 30}
    cfg.update(overrides)
    job.metadata.annotations[constants.ANNOTATION_TENSORBOARD_CONFIG] = json.dumps(cfg)
    return job


class TestParse:
    def test_parse_roundtrip(self):
        job = annotate_tb(make_tpujob(), image="tb:v1", profile=True)
        spec = parse_tensorboard_spec(job)
        assert spec.log_dir == "/data/logs"
        assert spec.image == "tb:v1"
        assert spec.ttl_seconds_after_job_finished == 30
        assert spec.profile is True

    def test_absent_and_garbage(self):
        job = make_tpujob()
        assert parse_tensorboard_spec(job) is None
        job.metadata.annotations[constants.ANNOTATION_TENSORBOARD_CONFIG] = "{nope"
        assert parse_tensorboard_spec(job) is None


class TestReconcile:
    def test_engine_creates_tb_pod_and_service(self):
        engine, store, _ = make_engine()
        job = annotate_tb(make_tpujob("tb1"))
        submit_and_reconcile(engine, store, job)
        pod = store.get("Pod", "tb1-tensorboard")
        svc = store.get("Service", "tb1-tensorboard")
        assert isinstance(pod, Pod)
        assert "--logdir=/data/logs" in pod.spec.containers[0].command
        assert svc.spec.ports[0].port == TB_PORT
        # owner-ref points at the job so GC cascades
        assert pod.metadata.controller_ref().name == "tb1"

    def test_mirrors_master_volumes(self):
        engine, store, _ = make_engine()
        job = annotate_tb(make_tpujob("tb2"))
        from kubedl_tpu.api.types import ReplicaType

        job.spec.replica_specs[ReplicaType.WORKER].template.spec.volumes.append(
            Volume(name="logs", host_path="/mnt/logs", mount_path="/data/logs")
        )
        submit_and_reconcile(engine, store, job)
        pod = store.get("Pod", "tb2-tensorboard")
        assert [v.name for v in pod.spec.volumes] == ["logs"]

    def test_update_timestamp_recreates_pod(self):
        store = ObjectStore()
        rec = TensorBoardReconciler(store)
        job = annotate_tb(make_tpujob("tb3"), updateTimestamp=1.0)
        store.create(job)
        rec.reconcile(job)
        first_uid = store.get("Pod", tb_name(job)).metadata.uid
        rec.reconcile(job)  # same config: no churn
        assert store.get("Pod", tb_name(job)).metadata.uid == first_uid
        annotate_tb(job, updateTimestamp=2.0, image="tb:v2")
        rec.reconcile(job)
        pod = store.get("Pod", tb_name(job))
        assert pod.metadata.uid != first_uid
        assert pod.spec.containers[0].image == "tb:v2"

    def test_annotation_removed_tears_down(self):
        store = ObjectStore()
        rec = TensorBoardReconciler(store)
        job = annotate_tb(make_tpujob("tb4"))
        rec.reconcile(job)
        assert store.try_get("Pod", tb_name(job)) is not None
        del job.metadata.annotations[constants.ANNOTATION_TENSORBOARD_CONFIG]
        rec.reconcile(job)
        assert store.try_get("Pod", tb_name(job)) is None
        assert store.try_get("Service", tb_name(job)) is None


class TestTTL:
    def test_kept_until_ttl_then_deleted(self):
        store = ObjectStore()
        rec = TensorBoardReconciler(store)
        job = annotate_tb(make_tpujob("tb5"), ttlSecondsAfterJobFinished=30)
        from kubedl_tpu.api.types import JobConditionType

        job.status.set_condition(JobConditionType.SUCCEEDED, "ok", "done")
        job.status.completion_time = time.time()
        requeue = rec.reconcile(job)
        assert store.try_get("Pod", tb_name(job)) is not None
        assert requeue is not None and 0 < requeue <= 30
        job.status.completion_time = time.time() - 31
        assert rec.reconcile(job) is None
        assert store.try_get("Pod", tb_name(job)) is None

    def test_survives_job_completion_through_engine(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = annotate_tb(make_tpujob("tb6", workers=1), ttlSecondsAfterJobFinished=60)
        from kubedl_tpu.api.types import CleanPodPolicy

        job.spec.run_policy.clean_pod_policy = CleanPodPolicy.ALL
        submit_and_reconcile(engine, store, job)
        driver.run("tb6-worker-0")
        engine.reconcile("default", "tb6")
        driver.succeed("tb6-worker-0")
        requeue = engine.reconcile("default", "tb6")
        got = store.get(job.KIND, "tb6")
        assert got.status.is_succeeded()
        # worker pod cleaned up, tb pod retained until TTL
        assert store.try_get("Pod", "tb6-worker-0") is None
        assert store.try_get("Pod", "tb6-tensorboard") is not None
        assert requeue is not None and requeue <= 60

    def test_url(self):
        store = ObjectStore()
        rec = TensorBoardReconciler(store, cluster_domain="cluster.local")
        job = make_tpujob("tb7")
        assert (
            rec.url(job)
            == "http://tb7-tensorboard.default.svc.cluster.local:6006"
        )

"""Deploy surface (VERDICT r2 missing #2: no parameterization, no RBAC
analogue, no CRD-equivalent schemas): values-rendered templates + JSON
Schemas generated from the API dataclasses."""

import json
import subprocess
import sys
from pathlib import Path

import pytest
import yaml

REPO = Path(__file__).resolve().parents[1]


class TestSchemas:
    def test_every_kind_gets_a_schema(self):
        from kubedl_tpu.api.schema import workload_schemas

        schemas = workload_schemas()
        for kind in ("TPUJob", "TFJob", "PyTorchJob", "XDLJob", "XGBoostJob",
                     "MarsJob", "ElasticDLJob", "MPIJob", "Inference",
                     "Model", "ModelVersion", "Cron"):
            assert kind in schemas, kind
            s = schemas[kind]
            assert s["properties"]["kind"] == {"const": kind}
            assert s["additionalProperties"] is False

    def test_encoded_objects_validate(self):
        """The schema accepts exactly what the codec emits/accepts."""
        import jsonschema

        from kubedl_tpu.api import codec
        from kubedl_tpu.api.schema import workload_schemas
        from tests.helpers import make_tpujob

        job = make_tpujob("sch1", workers=2, command=["true"])
        data = codec.encode(job)
        schema = workload_schemas()["TPUJob"]
        jsonschema.validate(data, schema)  # must not raise
        # unknown fields rejected, like the codec
        bad = dict(data)
        bad["bogus"] = 1
        with pytest.raises(jsonschema.ValidationError):
            jsonschema.validate(bad, schema)

    def test_enum_values_enforced(self):
        import jsonschema

        from kubedl_tpu.api import codec
        from kubedl_tpu.api.schema import workload_schemas
        from tests.helpers import make_tpujob

        data = codec.encode(make_tpujob("sch2", workers=1, command=["true"]))
        data["spec"]["replica_specs"]["Worker"]["restart_policy"] = "Sometimes"
        with pytest.raises(jsonschema.ValidationError):
            jsonschema.validate(data, workload_schemas()["TPUJob"])


class TestRender:
    def test_render_substitutes_and_writes_schemas(self, tmp_path):
        out = subprocess.run(
            [sys.executable, str(REPO / "deploy" / "render.py"),
             "--out", str(tmp_path)],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        dep = yaml.safe_load((tmp_path / "operator-deployment.yaml").read_text())
        assert dep["metadata"]["name"] == "kubedl-tpu-operator"
        assert dep["spec"]["replicas"] == 2
        args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
        assert "--leader-elect=true" in args
        rbac_docs = list(yaml.safe_load_all(
            (tmp_path / "operator-rbac.yaml").read_text()
        ))
        kinds = {d["kind"] for d in rbac_docs}
        assert kinds == {"ServiceAccount", "Role", "RoleBinding"}
        schemas = list((tmp_path / "schemas").glob("*.json"))
        assert len(schemas) >= 12
        tpu = json.loads((tmp_path / "schemas" / "TPUJob.json").read_text())
        assert tpu["title"] == "TPUJob"

    def test_validate_deploy_surface_green(self):
        """`make validate-deploy` (VERDICT r3 #6): render then run the
        kubeconform-class structural validator over the rendered
        manifests, single-file bundle, Dockerfile and docker-compose."""
        render = subprocess.run(
            [sys.executable, str(REPO / "deploy" / "render.py")],
            capture_output=True, text=True, timeout=120,
        )
        assert render.returncode == 0, render.stderr
        out = subprocess.run(
            [sys.executable, str(REPO / "deploy" / "validate.py")],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert "deploy surface valid" in out.stdout
        assert "Deployment=1" in out.stdout

    def test_validator_catches_broken_manifest(self, tmp_path):
        """The validator must actually fail on malformed objects, or the
        green test above proves nothing."""
        sys.path.insert(0, str(REPO / "deploy"))
        try:
            import validate as v
        finally:
            sys.path.pop(0)
        bad = tmp_path / "bad.yaml"
        bad.write_text(
            "apiVersion: apps/v1\nkind: Deployment\n"
            "metadata: {name: UPPER_case}\n"
            "spec:\n  selector: {matchLabels: {app: x}}\n"
            "  template:\n    metadata: {labels: {app: y}}\n"
            "    spec: {containers: [{name: c}]}\n"
        )
        f = v.Findings()
        v.validate_manifests(tmp_path, f)
        text = "\n".join(f.items)
        assert "not RFC1123" in text
        assert "not present in template labels" in text
        assert "missing image" in text

    def test_non_scalar_value_rejected(self, tmp_path):
        """A nested dict/list would silently render its Python repr into
        manifests (ADVICE r3) — must be rejected naming the key."""
        vals = tmp_path / "values.yaml"
        vals.write_text("name: x\nresources:\n  cpu: 2\n")
        out = subprocess.run(
            [sys.executable, str(REPO / "deploy" / "render.py"),
             "--values", str(vals), "--out", str(tmp_path / "o")],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode != 0
        assert "resources" in out.stderr and "scalar" in out.stderr

    def test_missing_value_fails_loudly(self, tmp_path):
        vals = tmp_path / "values.yaml"
        vals.write_text("name: x\n")  # everything else missing
        out = subprocess.run(
            [sys.executable, str(REPO / "deploy" / "render.py"),
             "--values", str(vals), "--out", str(tmp_path / "o")],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode != 0
        assert "no value for placeholder" in out.stderr

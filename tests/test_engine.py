"""Engine unit tests (reference analogue: pkg/job_controller/*_test.go).

The engine is driven synchronously (no manager threads): reconcile is called
directly and pod phases are flipped by PodDriver — the fake-client pattern.
"""

import pytest

from kubedl_tpu.api import constants
from kubedl_tpu.api.types import (
    CleanPodPolicy,
    DAGCondition,
    JobConditionType,
    ReplicaPhase,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    SuccessPolicy,
)
from kubedl_tpu.core.objects import Container, PodPhase
from kubedl_tpu.core.store import ObjectStore
from kubedl_tpu.engine.job_controller import JobEngine
from kubedl_tpu.gang.slice_scheduler import SliceGangScheduler, SliceInventory
from kubedl_tpu.observability.metrics import JobMetrics, MetricsRegistry
from kubedl_tpu.workloads.tpujob import TPUJobController

from tests.helpers import PodDriver, env_of, make_tpujob, pod_names


def make_engine(store=None, inventory=None, gang=True):
    store = store or ObjectStore()
    metrics = JobMetrics(MetricsRegistry())
    scheduler = None
    if gang:
        inventory = inventory or SliceInventory()
        scheduler = SliceGangScheduler(store, inventory)
    engine = JobEngine(
        store=store,
        controller=TPUJobController(local_addresses=True),
        gang_scheduler=scheduler,
        metrics=metrics,
    )
    return engine, store, metrics


def submit_and_reconcile(engine, store, job, times=1):
    store.create(job)
    for _ in range(times):
        engine.reconcile(job.metadata.namespace, job.metadata.name)
    return store.get(job.KIND, job.metadata.name)


class TestPodCreation:
    def test_creates_pods_and_services_by_index(self):
        engine, store, _ = make_engine()
        job = make_tpujob(workers=3)
        submit_and_reconcile(engine, store, job)
        assert pod_names(store) == ["job1-worker-0", "job1-worker-1", "job1-worker-2"]
        svcs = sorted(s.metadata.name for s in store.list("Service"))
        assert svcs == ["job1-worker-0", "job1-worker-1", "job1-worker-2"]
        pod = store.get("Pod", "job1-worker-1")
        labels = pod.metadata.labels
        assert labels[constants.LABEL_JOB_NAME] == "job1"
        assert labels[constants.LABEL_REPLICA_TYPE] == "Worker"
        assert labels[constants.LABEL_REPLICA_INDEX] == "1"

    def test_bootstrap_env(self):
        engine, store, _ = make_engine()
        job = make_tpujob(workers=2)
        submit_and_reconcile(engine, store, job)
        pod = store.get("Pod", "job1-worker-1")
        env = env_of(pod)
        assert env[constants.ENV_NUM_PROCESSES] == "2"
        assert env[constants.ENV_PROCESS_ID] == "1"
        assert env[constants.ENV_TPU_WORKER_ID] == "1"
        assert env[constants.ENV_COORDINATOR_ADDRESS].startswith("127.0.0.1:")
        assert "job1-worker-0" in env[constants.ENV_TPU_WORKER_HOSTNAMES]

    def test_idempotent_no_duplicates(self):
        engine, store, _ = make_engine()
        job = make_tpujob(workers=2)
        submit_and_reconcile(engine, store, job, times=3)
        assert len(pod_names(store)) == 2

    def test_scale_down_deletes_stale_indices(self):
        engine, store, _ = make_engine()
        job = make_tpujob(workers=3)
        submit_and_reconcile(engine, store, job)
        # shrink to 1 replica
        def mutate(obj):
            obj.spec.replica_specs[ReplicaType.WORKER].replicas = 1

        store.update_with_retry("TPUJob", "job1", "default", mutate)
        engine.reconcile("default", "job1")
        assert pod_names(store) == ["job1-worker-0"]


class TestStatusMachine:
    def test_running_then_succeeded_worker0(self):
        engine, store, metrics = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=2)
        submit_and_reconcile(engine, store, job)
        driver.run("job1-worker-0")
        driver.run("job1-worker-1")
        engine.reconcile("default", "job1")
        assert store.get("TPUJob", "job1").status.phase == JobConditionType.RUNNING
        driver.succeed("job1-worker-0")
        engine.reconcile("default", "job1")
        got = store.get("TPUJob", "job1")
        assert got.status.phase == JobConditionType.SUCCEEDED
        assert got.status.completion_time is not None
        assert metrics.successful.value(kind="TPUJob") == 1

    def test_all_workers_success_policy(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=2)
        job.spec.success_policy = SuccessPolicy.ALL_WORKERS
        submit_and_reconcile(engine, store, job)
        driver.succeed("job1-worker-0")
        driver.run("job1-worker-1")
        engine.reconcile("default", "job1")
        assert store.get("TPUJob", "job1").status.phase != JobConditionType.SUCCEEDED
        driver.succeed("job1-worker-1")
        engine.reconcile("default", "job1")
        assert store.get("TPUJob", "job1").status.phase == JobConditionType.SUCCEEDED

    def test_permanent_failure_fails_job(self):
        engine, store, metrics = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=2, restart_policy=RestartPolicy.EXIT_CODE)
        submit_and_reconcile(engine, store, job)
        driver.fail("job1-worker-1", exit_code=1)  # 1-127 = permanent
        engine.reconcile("default", "job1")
        got = store.get("TPUJob", "job1")
        assert got.status.phase == JobConditionType.FAILED
        assert metrics.failed.value(kind="TPUJob") == 1

    def test_replica_status_counts(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=3, restart_policy=RestartPolicy.NEVER)
        submit_and_reconcile(engine, store, job)
        driver.run("job1-worker-0")
        driver.succeed("job1-worker-1")
        driver.evict("job1-worker-2")
        engine.reconcile("default", "job1")
        rs = store.get("TPUJob", "job1").status.replica_statuses[ReplicaType.WORKER]
        assert (rs.active, rs.succeeded, rs.failed, rs.evicted) == (1, 1, 1, 1)


class TestRestartPolicies:
    def test_exit_code_retryable_restarts_pod(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=2, restart_policy=RestartPolicy.EXIT_CODE)
        submit_and_reconcile(engine, store, job)
        driver.fail("job1-worker-1", exit_code=137)  # retryable
        engine.reconcile("default", "job1")  # deletes failed pod
        engine.reconcile("default", "job1")  # recreates it
        got = store.get("TPUJob", "job1")
        assert got.status.restart_count == 1
        pod = store.get("Pod", "job1-worker-1")
        assert pod.status.phase == PodPhase.PENDING  # fresh replacement

    def test_slice_granular_restart_nukes_gang(self):
        engine, store, metrics = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=3, restart_policy=RestartPolicy.ON_FAILURE_SLICE)
        submit_and_reconcile(engine, store, job)
        driver.run("job1-worker-0")
        driver.run("job1-worker-2")
        driver.fail("job1-worker-1", exit_code=137)
        engine.reconcile("default", "job1")
        # ALL pods of the replica group are gone (whole-slice restart)
        assert pod_names(store) == []
        got = store.get("TPUJob", "job1")
        assert got.status.phase == JobConditionType.RESTARTING
        assert metrics.restarted.value(kind="TPUJob") == 1
        engine.reconcile("default", "job1")  # rebuilds the gang
        assert len(pod_names(store)) == 3

    def test_backoff_limit_fails_job(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=1, restart_policy=RestartPolicy.ON_FAILURE_SLICE)
        job.spec.run_policy.backoff_limit = 1
        submit_and_reconcile(engine, store, job)
        for _ in range(2):
            driver.fail("job1-worker-0", exit_code=137)
            engine.reconcile("default", "job1")  # slice restart
            engine.reconcile("default", "job1")  # recreate
        got = store.get("TPUJob", "job1")
        assert got.status.restart_count == 2
        assert got.status.phase == JobConditionType.FAILED
        assert "Backoff" in got.status.conditions[-1].reason

    def test_never_leaves_failed_pod(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=1, restart_policy=RestartPolicy.NEVER)
        submit_and_reconcile(engine, store, job)
        driver.fail("job1-worker-0", exit_code=137)
        engine.reconcile("default", "job1")
        assert store.get("TPUJob", "job1").status.phase == JobConditionType.FAILED


class TestCleanPodPolicy:
    """Reference analogue: TestDeletePodsAndServices CleanPodPolicy matrix
    (job_test.go:23-130)."""

    @pytest.mark.parametrize(
        "policy,expect_remaining",
        [
            (CleanPodPolicy.ALL, 0),
            (CleanPodPolicy.RUNNING, 1),  # only the terminal pod stays
            (CleanPodPolicy.NONE, 2),
        ],
    )
    def test_cleanup_matrix(self, policy, expect_remaining):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=2)
        job.spec.run_policy.clean_pod_policy = policy
        submit_and_reconcile(engine, store, job)
        driver.succeed("job1-worker-0")  # worker-0 done -> job succeeds
        driver.run("job1-worker-1")
        engine.reconcile("default", "job1")
        assert len(pod_names(store)) == expect_remaining
        # services always cleaned on terminal
        assert store.list("Service") == []

    def test_reap_rechecks_store_not_stale_snapshot(self):
        """A worker whose terminal update lands between the reconcile's
        pod read and the reap must be spared: deleting from the stale
        snapshot would destroy its exit state (the pod looked Running
        when ctx.pods was captured, but is Succeeded by delete time)."""
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=2)
        job.spec.run_policy.clean_pod_policy = CleanPodPolicy.RUNNING
        submit_and_reconcile(engine, store, job)
        driver.run("job1-worker-1")
        stale = store.list("Pod")  # snapshot with worker-1 Running
        driver.succeed("job1-worker-0")
        driver.succeed("job1-worker-1")  # lands after the snapshot
        stored = store.get("TPUJob", "job1")
        engine._delete_pods(stored, stale, CleanPodPolicy.RUNNING)
        assert pod_names(store) == ["job1-worker-0", "job1-worker-1"]

    def test_ttl_deletes_job(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=1)
        job.spec.run_policy.ttl_seconds_after_finished = 0.0
        submit_and_reconcile(engine, store, job)
        driver.succeed("job1-worker-0")
        engine.reconcile("default", "job1")  # terminal + TTL elapsed
        engine.reconcile("default", "job1")
        assert store.try_get("TPUJob", "job1") is None


class TestDAG:
    def test_evaluator_waits_for_workers(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=2)
        ev = ReplicaSpec(
            replicas=1,
            restart_policy=RestartPolicy.NEVER,
            depends_on=[DAGCondition(ReplicaType.WORKER, ReplicaPhase.RUNNING)],
        )
        ev.template.spec.containers.append(Container())
        job.spec.replica_specs[ReplicaType.EVALUATOR] = ev
        submit_and_reconcile(engine, store, job)
        assert pod_names(store) == ["job1-worker-0", "job1-worker-1"]
        driver.run("job1-worker-0")
        engine.reconcile("default", "job1")
        assert "job1-evaluator-0" not in pod_names(store)  # not all running yet
        driver.run("job1-worker-1")
        engine.reconcile("default", "job1")
        assert "job1-evaluator-0" in pod_names(store)


class TestGang:
    def test_job_queued_until_slice_free(self):
        inventory = SliceInventory()
        inventory.add_slice("s1", "v5e-8")
        engine, store, _ = make_engine(inventory=inventory)
        from kubedl_tpu.api.topology import get_slice

        job_a = make_tpujob("job-a", workers=2, topology=get_slice("v5e-8"))
        job_b = make_tpujob("job-b", workers=2, topology=get_slice("v5e-8"))
        submit_and_reconcile(engine, store, job_a)
        assert len(pod_names(store)) == 2  # admitted: pods bound to hosts
        pod = store.get("Pod", "job-a-worker-0")
        assert pod.spec.node_name == "s1-host-0"
        assert pod.spec.slice_assignment == "s1"
        submit_and_reconcile(engine, store, job_b)
        got = store.get("TPUJob", "job-b")
        assert got.status.phase == JobConditionType.QUEUED
        assert not any("job-b" in n for n in pod_names(store))  # zero partial pods
        # finish job-a -> slice frees -> job-b admits
        driver = PodDriver(store)
        driver.succeed("job-a-worker-0")
        driver.succeed("job-a-worker-1")
        engine.reconcile("default", "job-a")
        engine.reconcile("default", "job-b")
        assert any("job-b" in n for n in pod_names(store))

    def test_deterministic_binding_across_restart(self):
        inventory = SliceInventory()
        inventory.add_slice("s1", "v5e-8")
        engine, store, _ = make_engine(inventory=inventory)
        from kubedl_tpu.api.topology import get_slice

        job = make_tpujob("job-a", workers=2, topology=get_slice("v5e-8"))
        submit_and_reconcile(engine, store, job)
        before = {
            p.metadata.name: p.spec.node_name for p in store.list("Pod")
        }
        driver = PodDriver(store)
        driver.fail("job-a-worker-1", exit_code=137)
        engine.reconcile("default", "job-a")  # slice restart
        engine.reconcile("default", "job-a")  # recreate
        after = {p.metadata.name: p.spec.node_name for p in store.list("Pod")}
        assert before == after  # mesh coordinates stable


class TestAnnotationsFeatures:
    def test_host_network_assigns_port(self):
        engine, store, _ = make_engine()
        job = make_tpujob(workers=1)
        job.metadata.annotations[constants.ANNOTATION_NETWORK_MODE] = "host"
        submit_and_reconcile(engine, store, job)
        pod = store.get("Pod", "job1-worker-0")
        assert pod.spec.host_network
        hp = pod.spec.main_container().ports[0].host_port
        assert constants.HOST_PORT_RANGE[0] <= hp < constants.HOST_PORT_RANGE[1]

    def test_concurrent_port_allocation_never_collides(self):
        """ADVICE r2 #4: two reconcile workers allocating host ports for
        the same node in the same window (before either pod lands in the
        store) must not draw the same port; unpinned allocations conflict
        with pinned ones too."""
        import threading

        engine, store, _ = make_engine()
        got, errs = [], []

        def alloc(node):
            try:
                got.append((node, engine._alloc_host_port(node)))
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=alloc, args=(n,))
                   for n in ["nodeA"] * 8 + [""] * 8]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert not errs
        ports = [p for _, p in got]
        assert len(ports) == len(set(ports)) == 16  # no dupes anywhere
        # a different pinned node may reuse a nodeA port, but never an
        # unpinned one
        hp_b = engine._alloc_host_port("nodeB")
        unpinned = {p for n, p in got if n == ""}
        assert hp_b not in unpinned

    def test_git_sync_injection(self):
        import json

        engine, store, _ = make_engine()
        job = make_tpujob(workers=1)
        job.metadata.annotations[constants.ANNOTATION_GIT_SYNC_CONFIG] = json.dumps(
            {"source": "https://example.com/repo.git", "destPath": "/w/code"}
        )
        submit_and_reconcile(engine, store, job)
        pod = store.get("Pod", "job1-worker-0")
        assert pod.spec.init_containers
        assert "clone" in " ".join(pod.spec.init_containers[0].command)
        assert pod.spec.main_container().working_dir == "/w/code"


class TestModelVersionHookup:
    def test_success_creates_model_version(self, tmp_path):
        from kubedl_tpu.api.types import ModelVersionSpecRef

        engine, store, _ = make_engine()
        driver = PodDriver(store)
        out = tmp_path / "model-out"
        out.mkdir()
        (out / "ckpt.bin").write_bytes(b"weights")
        job = make_tpujob(workers=1)
        job.spec.model_version = ModelVersionSpecRef(
            model_name="m1", image_repo="models/m1", storage_root=str(out)
        )
        submit_and_reconcile(engine, store, job)
        pod = store.get("Pod", "job1-worker-0")
        assert env_of(pod)[constants.ENV_MODEL_PATH] == str(out)
        driver.succeed("job1-worker-0")
        engine.reconcile("default", "job1")
        mvs = store.list("ModelVersion")
        assert len(mvs) == 1
        assert mvs[0].model_name == "m1"
        assert store.get("TPUJob", "job1").status.model_version == mvs[0].metadata.name


class TestRefManager:
    """Adopt/release matrix (reference:
    pkg/job_controller/service_ref_manager.go:1-158)."""

    def _orphan_pod(self, store, job, name="orphan-0", match=True):
        from kubedl_tpu.core.objects import Pod

        p = Pod()
        p.metadata.name = name
        p.metadata.namespace = "default"
        if match:
            p.metadata.labels = {
                constants.LABEL_JOB_NAME: job.metadata.name,
                constants.LABEL_JOB_KIND: "TPUJob",
            }
        return store.create(p)

    def test_adopts_matching_orphan(self):
        engine, store, _ = make_engine(gang=False)
        job = make_tpujob("adopt", workers=1, command=["x"])
        store.create(job)
        self._orphan_pod(store, job)
        engine.reconcile("default", "adopt")
        p = store.get("Pod", "orphan-0")
        ref = p.metadata.controller_ref()
        assert ref is not None and ref.uid == job.metadata.uid
        assert any(
            e.reason == "Adopted" for e in store.list("Event")
        )

    def test_terminal_job_does_not_adopt(self):
        engine, store, _ = make_engine(gang=False)
        job = make_tpujob("noadopt", workers=1, command=["x"])
        job.status.set_condition(JobConditionType.SUCCEEDED, "JobSucceeded", "done")
        store.create(job)
        self._orphan_pod(store, job)
        pods = engine.get_pods_for_job(store.get("TPUJob", "noadopt"))
        p = store.get("Pod", "orphan-0")
        assert p.metadata.controller_ref() is None
        assert pods == []

    def test_releases_on_selector_mismatch(self):
        engine, store, _ = make_engine(gang=False)
        job = make_tpujob("rel", workers=1, command=["x"])
        store.create(job)
        engine.reconcile("default", "rel")
        pods = engine.get_pods_for_job(store.get("TPUJob", "rel"))
        assert len(pods) == 1
        name = pods[0].metadata.name

        def strip(o):
            # relabel away from the job but keep the engine's job-kind
            # marker (a label-less AUX object must NOT be released)
            o.metadata.labels[constants.LABEL_JOB_NAME] = "someone-else"

        store.update_with_retry("Pod", name, "default", strip)
        engine.get_pods_for_job(store.get("TPUJob", "rel"))
        p = store.get("Pod", name)
        assert p.metadata.controller_ref() is None  # released, not deleted

    def test_never_steals_from_other_owner(self):
        from kubedl_tpu.core.objects import OwnerRef

        engine, store, _ = make_engine(gang=False)
        job = make_tpujob("steal", workers=1, command=["x"])
        store.create(job)
        p = self._orphan_pod(store, job)

        def own(o):
            o.metadata.owner_refs.append(
                OwnerRef(kind="TPUJob", name="other", uid="uid-other")
            )

        store.update_with_retry("Pod", p.metadata.name, "default", own)
        pods = engine.get_pods_for_job(store.get("TPUJob", "steal"))
        assert all(x.metadata.name != p.metadata.name for x in pods)
        got = store.get("Pod", p.metadata.name)
        assert got.metadata.controller_ref().uid == "uid-other"


class TestElasticSliceResize:
    """Elastic grow/shrink of a running TPUJob's num_slices: TPU-native
    semantics are a coordinated whole-gang restart-from-checkpoint at the
    new shape (SURVEY.md §2.5 'elastic TPU-slice resize')."""

    def _setup(self):
        from kubedl_tpu.api.topology import get_slice

        inventory = SliceInventory()
        inventory.add_slice("s1", "v5e-8")
        inventory.add_slice("s2", "v5e-8")
        engine, store, _ = make_engine(inventory=inventory)
        job = make_tpujob("el", workers=2, topology=get_slice("v5e-8"))
        submit_and_reconcile(engine, store, job)
        return engine, store

    def test_grow_restarts_gang_at_new_shape(self):
        engine, store = self._setup()
        assert len(pod_names(store)) == 2
        driver = PodDriver(store)
        driver.run("el-worker-0"); driver.run("el-worker-1")
        engine.reconcile("default", "el")
        assert store.get("TPUJob", "el").status.phase == JobConditionType.RUNNING

        def grow(j):
            j.num_slices = 2

        store.update_with_retry("TPUJob", "el", "default", grow)
        engine.reconcile("default", "el")  # detects drift: in-place resize
        got = store.get("TPUJob", "el")
        assert got.status.phase == JobConditionType.RESIZING
        assert got.status.restart_count == 1
        assert pod_names(store) == []
        engine.reconcile("default", "el")  # re-admits at 2 slices
        pods = [store.get("Pod", n) for n in pod_names(store)]
        assert len(pods) == 4  # 2 hosts/slice x 2 slices
        envs = env_of(pods[0])
        assert envs.get("MEGASCALE_NUM_SLICES") == "2"
        slices = {p.spec.slice_assignment for p in pods}
        assert slices == {"s1", "s2"}
        assert any(e.reason == "SliceResize" for e in store.list("Event"))

    def test_shrink_frees_slices_for_others(self):
        engine, store = self._setup()

        def grow(j):
            j.num_slices = 2

        store.update_with_retry("TPUJob", "el", "default", grow)
        engine.reconcile("default", "el")
        engine.reconcile("default", "el")
        assert len(pod_names(store)) == 4

        def shrink(j):
            j.num_slices = 1

        store.update_with_retry("TPUJob", "el", "default", shrink)
        engine.reconcile("default", "el")
        engine.reconcile("default", "el")
        assert len(pod_names(store)) == 2
        # the freed slice admits another job immediately
        from kubedl_tpu.api.topology import get_slice

        other = make_tpujob("fill", workers=2, topology=get_slice("v5e-8"))
        submit_and_reconcile(engine, store, other)
        assert any("fill-worker" in n for n in pod_names(store))


class TestHostPortAllocation:
    def test_no_collisions_on_same_node(self):
        """Port allocation consults live pods: even with a seeded RNG forced
        to collide, every host-network pod gets a unique port."""
        engine, store, _ = make_engine(gang=False)
        import random as _random

        class CollidingRng(_random.Random):
            """Always proposes the same port first."""
            def randrange(self, *a, **k):
                return 40000

        engine._rng = CollidingRng()
        ports = set()
        for i in range(3):
            job = make_tpujob(f"hn{i}", workers=1, command=["x"])
            job.metadata.annotations[constants.ANNOTATION_NETWORK_MODE] = (
                constants.NETWORK_MODE_HOST
            )
            submit_and_reconcile(engine, store, job)
            pod = store.get("Pod", f"hn{i}-worker-0")
            hp = pod.spec.main_container().ports[0].host_port
            assert hp not in ports, f"collision on {hp}"
            ports.add(hp)
        # first job got the preferred port; later ones were displaced
        assert 40000 in ports and len(ports) == 3


def test_tensorboard_sidecar_not_released(tmp_path):
    """Regression (r2 review): the release pass must not strip owner refs
    from TB sidecar pods/services — they are owned for GC but deliberately
    unlabeled as replicas."""
    import json

    engine, store, _ = make_engine(gang=False)
    job = make_tpujob("tbjob", workers=1, command=["x"])
    job.metadata.annotations[constants.ANNOTATION_TENSORBOARD_CONFIG] = json.dumps(
        {"logDir": str(tmp_path)}
    )
    submit_and_reconcile(engine, store, job, times=2)
    tb_pod = store.try_get("Pod", "tbjob-tensorboard")
    assert tb_pod is not None, [p for p in pod_names(store)]
    assert tb_pod.metadata.controller_ref() is not None  # still owned
    engine.get_pods_for_job(store.get("TPUJob", "tbjob"))  # claim pass
    tb_pod = store.get("Pod", "tbjob-tensorboard")
    assert tb_pod.metadata.controller_ref() is not None
    assert not any(e.reason == "Released" for e in store.list("Event"))


class TestSuspendResume:
    """Kueue-style suspend (net-new vs reference): suspending frees the
    slices for other jobs; resume re-admits with stable binding."""

    def test_suspend_frees_slice_resume_readmits(self):
        from kubedl_tpu.api.topology import get_slice

        inventory = SliceInventory()
        inventory.add_slice("s1", "v5e-8")
        engine, store, _ = make_engine(inventory=inventory)
        job = make_tpujob("sus", workers=2, topology=get_slice("v5e-8"))
        submit_and_reconcile(engine, store, job)
        assert len(pod_names(store)) == 2
        before = {p.metadata.name: p.spec.node_name for p in store.list("Pod")}

        def suspend(j):
            j.spec.run_policy.suspend = True

        store.update_with_retry("TPUJob", "sus", "default", suspend)
        engine.reconcile("default", "sus")
        got = store.get("TPUJob", "sus")
        assert got.status.phase == JobConditionType.SUSPENDED
        assert pod_names(store) == []
        assert inventory.describe()["s1"] == "<free>"  # capacity released

        # another job borrows the freed slice
        other = make_tpujob("borrower", workers=2, topology=get_slice("v5e-8"))
        submit_and_reconcile(engine, store, other)
        assert any("borrower" in n for n in pod_names(store))
        driver = PodDriver(store)
        driver.succeed("borrower-worker-0")
        driver.succeed("borrower-worker-1")
        engine.reconcile("default", "borrower")

        # resume: ordinary re-admission, binding identical to before
        def resume(j):
            j.spec.run_policy.suspend = False

        store.update_with_retry("TPUJob", "sus", "default", resume)
        engine.reconcile("default", "sus")
        engine.reconcile("default", "sus")
        after = {p.metadata.name: p.spec.node_name
                 for p in store.list("Pod")
                 if "sus-" in p.metadata.name}
        assert after == before  # deterministic host binding survives
        evs = {e.reason for e in store.list("Event")}
        assert {"Suspended", "Resumed"} <= evs

    def test_suspended_job_stays_down(self):
        engine, store, _ = make_engine(gang=False)
        job = make_tpujob("sus2", workers=1, command=["x"])
        job.spec.run_policy.suspend = True  # born suspended
        submit_and_reconcile(engine, store, job, times=2)
        got = store.get("TPUJob", "sus2")
        assert got.status.phase == JobConditionType.SUSPENDED
        assert pod_names(store) == []


def test_suspend_is_idempotent_and_clears_status():
    """r2 review: re-reconciling a suspended job must not rewrite status
    (MODIFIED-event hot loop), must clear replica counts, and must reset
    start_time so activeDeadlineSeconds ignores suspended wall-clock."""
    engine, store, _ = make_engine(gang=False)
    job = make_tpujob("susq", workers=1, command=["x"])
    job.spec.run_policy.active_deadline_seconds = 3600
    submit_and_reconcile(engine, store, job)
    driver = PodDriver(store)
    driver.run("susq-worker-0")
    engine.reconcile("default", "susq")
    assert store.get("TPUJob", "susq").status.start_time is not None

    def suspend(j):
        j.spec.run_policy.suspend = True

    store.update_with_retry("TPUJob", "susq", "default", suspend)
    engine.reconcile("default", "susq")
    got = store.get("TPUJob", "susq")
    assert got.status.phase == JobConditionType.SUSPENDED
    assert got.status.start_time is None  # deadline clock rebased
    assert got.status.replica_statuses == {}  # no phantom replicas
    rv = got.metadata.resource_version
    # steady state: further reconciles write NOTHING
    for _ in range(3):
        engine.reconcile("default", "susq")
    assert store.get("TPUJob", "susq").metadata.resource_version == rv

"""Engine unit tests (reference analogue: pkg/job_controller/*_test.go).

The engine is driven synchronously (no manager threads): reconcile is called
directly and pod phases are flipped by PodDriver — the fake-client pattern.
"""

import pytest

from kubedl_tpu.api import constants
from kubedl_tpu.api.types import (
    CleanPodPolicy,
    DAGCondition,
    JobConditionType,
    ReplicaPhase,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    SuccessPolicy,
)
from kubedl_tpu.core.objects import Container, PodPhase
from kubedl_tpu.core.store import ObjectStore
from kubedl_tpu.engine.job_controller import JobEngine
from kubedl_tpu.gang.slice_scheduler import SliceGangScheduler, SliceInventory
from kubedl_tpu.observability.metrics import JobMetrics, MetricsRegistry
from kubedl_tpu.workloads.tpujob import TPUJobController

from tests.helpers import PodDriver, env_of, make_tpujob, pod_names


def make_engine(store=None, inventory=None, gang=True):
    store = store or ObjectStore()
    metrics = JobMetrics(MetricsRegistry())
    scheduler = None
    if gang:
        inventory = inventory or SliceInventory()
        scheduler = SliceGangScheduler(store, inventory)
    engine = JobEngine(
        store=store,
        controller=TPUJobController(local_addresses=True),
        gang_scheduler=scheduler,
        metrics=metrics,
    )
    return engine, store, metrics


def submit_and_reconcile(engine, store, job, times=1):
    store.create(job)
    for _ in range(times):
        engine.reconcile(job.metadata.namespace, job.metadata.name)
    return store.get(job.KIND, job.metadata.name)


class TestPodCreation:
    def test_creates_pods_and_services_by_index(self):
        engine, store, _ = make_engine()
        job = make_tpujob(workers=3)
        submit_and_reconcile(engine, store, job)
        assert pod_names(store) == ["job1-worker-0", "job1-worker-1", "job1-worker-2"]
        svcs = sorted(s.metadata.name for s in store.list("Service"))
        assert svcs == ["job1-worker-0", "job1-worker-1", "job1-worker-2"]
        pod = store.get("Pod", "job1-worker-1")
        labels = pod.metadata.labels
        assert labels[constants.LABEL_JOB_NAME] == "job1"
        assert labels[constants.LABEL_REPLICA_TYPE] == "Worker"
        assert labels[constants.LABEL_REPLICA_INDEX] == "1"

    def test_bootstrap_env(self):
        engine, store, _ = make_engine()
        job = make_tpujob(workers=2)
        submit_and_reconcile(engine, store, job)
        pod = store.get("Pod", "job1-worker-1")
        env = env_of(pod)
        assert env[constants.ENV_NUM_PROCESSES] == "2"
        assert env[constants.ENV_PROCESS_ID] == "1"
        assert env[constants.ENV_TPU_WORKER_ID] == "1"
        assert env[constants.ENV_COORDINATOR_ADDRESS].startswith("127.0.0.1:")
        assert "job1-worker-0" in env[constants.ENV_TPU_WORKER_HOSTNAMES]

    def test_idempotent_no_duplicates(self):
        engine, store, _ = make_engine()
        job = make_tpujob(workers=2)
        submit_and_reconcile(engine, store, job, times=3)
        assert len(pod_names(store)) == 2

    def test_scale_down_deletes_stale_indices(self):
        engine, store, _ = make_engine()
        job = make_tpujob(workers=3)
        submit_and_reconcile(engine, store, job)
        # shrink to 1 replica
        def mutate(obj):
            obj.spec.replica_specs[ReplicaType.WORKER].replicas = 1

        store.update_with_retry("TPUJob", "job1", "default", mutate)
        engine.reconcile("default", "job1")
        assert pod_names(store) == ["job1-worker-0"]


class TestStatusMachine:
    def test_running_then_succeeded_worker0(self):
        engine, store, metrics = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=2)
        submit_and_reconcile(engine, store, job)
        driver.run("job1-worker-0")
        driver.run("job1-worker-1")
        engine.reconcile("default", "job1")
        assert store.get("TPUJob", "job1").status.phase == JobConditionType.RUNNING
        driver.succeed("job1-worker-0")
        engine.reconcile("default", "job1")
        got = store.get("TPUJob", "job1")
        assert got.status.phase == JobConditionType.SUCCEEDED
        assert got.status.completion_time is not None
        assert metrics.successful.value(kind="TPUJob") == 1

    def test_all_workers_success_policy(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=2)
        job.spec.success_policy = SuccessPolicy.ALL_WORKERS
        submit_and_reconcile(engine, store, job)
        driver.succeed("job1-worker-0")
        driver.run("job1-worker-1")
        engine.reconcile("default", "job1")
        assert store.get("TPUJob", "job1").status.phase != JobConditionType.SUCCEEDED
        driver.succeed("job1-worker-1")
        engine.reconcile("default", "job1")
        assert store.get("TPUJob", "job1").status.phase == JobConditionType.SUCCEEDED

    def test_permanent_failure_fails_job(self):
        engine, store, metrics = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=2, restart_policy=RestartPolicy.EXIT_CODE)
        submit_and_reconcile(engine, store, job)
        driver.fail("job1-worker-1", exit_code=1)  # 1-127 = permanent
        engine.reconcile("default", "job1")
        got = store.get("TPUJob", "job1")
        assert got.status.phase == JobConditionType.FAILED
        assert metrics.failed.value(kind="TPUJob") == 1

    def test_replica_status_counts(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=3, restart_policy=RestartPolicy.NEVER)
        submit_and_reconcile(engine, store, job)
        driver.run("job1-worker-0")
        driver.succeed("job1-worker-1")
        driver.evict("job1-worker-2")
        engine.reconcile("default", "job1")
        rs = store.get("TPUJob", "job1").status.replica_statuses[ReplicaType.WORKER]
        assert (rs.active, rs.succeeded, rs.failed, rs.evicted) == (1, 1, 1, 1)


class TestRestartPolicies:
    def test_exit_code_retryable_restarts_pod(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=2, restart_policy=RestartPolicy.EXIT_CODE)
        submit_and_reconcile(engine, store, job)
        driver.fail("job1-worker-1", exit_code=137)  # retryable
        engine.reconcile("default", "job1")  # deletes failed pod
        engine.reconcile("default", "job1")  # recreates it
        got = store.get("TPUJob", "job1")
        assert got.status.restart_count == 1
        pod = store.get("Pod", "job1-worker-1")
        assert pod.status.phase == PodPhase.PENDING  # fresh replacement

    def test_slice_granular_restart_nukes_gang(self):
        engine, store, metrics = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=3, restart_policy=RestartPolicy.ON_FAILURE_SLICE)
        submit_and_reconcile(engine, store, job)
        driver.run("job1-worker-0")
        driver.run("job1-worker-2")
        driver.fail("job1-worker-1", exit_code=137)
        engine.reconcile("default", "job1")
        # ALL pods of the replica group are gone (whole-slice restart)
        assert pod_names(store) == []
        got = store.get("TPUJob", "job1")
        assert got.status.phase == JobConditionType.RESTARTING
        assert metrics.restarted.value(kind="TPUJob") == 1
        engine.reconcile("default", "job1")  # rebuilds the gang
        assert len(pod_names(store)) == 3

    def test_backoff_limit_fails_job(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=1, restart_policy=RestartPolicy.ON_FAILURE_SLICE)
        job.spec.run_policy.backoff_limit = 1
        submit_and_reconcile(engine, store, job)
        for _ in range(2):
            driver.fail("job1-worker-0", exit_code=137)
            engine.reconcile("default", "job1")  # slice restart
            engine.reconcile("default", "job1")  # recreate
        got = store.get("TPUJob", "job1")
        assert got.status.restart_count == 2
        assert got.status.phase == JobConditionType.FAILED
        assert "Backoff" in got.status.conditions[-1].reason

    def test_never_leaves_failed_pod(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=1, restart_policy=RestartPolicy.NEVER)
        submit_and_reconcile(engine, store, job)
        driver.fail("job1-worker-0", exit_code=137)
        engine.reconcile("default", "job1")
        assert store.get("TPUJob", "job1").status.phase == JobConditionType.FAILED


class TestCleanPodPolicy:
    """Reference analogue: TestDeletePodsAndServices CleanPodPolicy matrix
    (job_test.go:23-130)."""

    @pytest.mark.parametrize(
        "policy,expect_remaining",
        [
            (CleanPodPolicy.ALL, 0),
            (CleanPodPolicy.RUNNING, 1),  # only the terminal pod stays
            (CleanPodPolicy.NONE, 2),
        ],
    )
    def test_cleanup_matrix(self, policy, expect_remaining):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=2)
        job.spec.run_policy.clean_pod_policy = policy
        submit_and_reconcile(engine, store, job)
        driver.succeed("job1-worker-0")  # worker-0 done -> job succeeds
        driver.run("job1-worker-1")
        engine.reconcile("default", "job1")
        assert len(pod_names(store)) == expect_remaining
        # services always cleaned on terminal
        assert store.list("Service") == []

    def test_ttl_deletes_job(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=1)
        job.spec.run_policy.ttl_seconds_after_finished = 0.0
        submit_and_reconcile(engine, store, job)
        driver.succeed("job1-worker-0")
        engine.reconcile("default", "job1")  # terminal + TTL elapsed
        engine.reconcile("default", "job1")
        assert store.try_get("TPUJob", "job1") is None


class TestDAG:
    def test_evaluator_waits_for_workers(self):
        engine, store, _ = make_engine()
        driver = PodDriver(store)
        job = make_tpujob(workers=2)
        ev = ReplicaSpec(
            replicas=1,
            restart_policy=RestartPolicy.NEVER,
            depends_on=[DAGCondition(ReplicaType.WORKER, ReplicaPhase.RUNNING)],
        )
        ev.template.spec.containers.append(Container())
        job.spec.replica_specs[ReplicaType.EVALUATOR] = ev
        submit_and_reconcile(engine, store, job)
        assert pod_names(store) == ["job1-worker-0", "job1-worker-1"]
        driver.run("job1-worker-0")
        engine.reconcile("default", "job1")
        assert "job1-evaluator-0" not in pod_names(store)  # not all running yet
        driver.run("job1-worker-1")
        engine.reconcile("default", "job1")
        assert "job1-evaluator-0" in pod_names(store)


class TestGang:
    def test_job_queued_until_slice_free(self):
        inventory = SliceInventory()
        inventory.add_slice("s1", "v5e-8")
        engine, store, _ = make_engine(inventory=inventory)
        from kubedl_tpu.api.topology import get_slice

        job_a = make_tpujob("job-a", workers=2, topology=get_slice("v5e-8"))
        job_b = make_tpujob("job-b", workers=2, topology=get_slice("v5e-8"))
        submit_and_reconcile(engine, store, job_a)
        assert len(pod_names(store)) == 2  # admitted: pods bound to hosts
        pod = store.get("Pod", "job-a-worker-0")
        assert pod.spec.node_name == "s1-host-0"
        assert pod.spec.slice_assignment == "s1"
        submit_and_reconcile(engine, store, job_b)
        got = store.get("TPUJob", "job-b")
        assert got.status.phase == JobConditionType.QUEUED
        assert not any("job-b" in n for n in pod_names(store))  # zero partial pods
        # finish job-a -> slice frees -> job-b admits
        driver = PodDriver(store)
        driver.succeed("job-a-worker-0")
        driver.succeed("job-a-worker-1")
        engine.reconcile("default", "job-a")
        engine.reconcile("default", "job-b")
        assert any("job-b" in n for n in pod_names(store))

    def test_deterministic_binding_across_restart(self):
        inventory = SliceInventory()
        inventory.add_slice("s1", "v5e-8")
        engine, store, _ = make_engine(inventory=inventory)
        from kubedl_tpu.api.topology import get_slice

        job = make_tpujob("job-a", workers=2, topology=get_slice("v5e-8"))
        submit_and_reconcile(engine, store, job)
        before = {
            p.metadata.name: p.spec.node_name for p in store.list("Pod")
        }
        driver = PodDriver(store)
        driver.fail("job-a-worker-1", exit_code=137)
        engine.reconcile("default", "job-a")  # slice restart
        engine.reconcile("default", "job-a")  # recreate
        after = {p.metadata.name: p.spec.node_name for p in store.list("Pod")}
        assert before == after  # mesh coordinates stable


class TestAnnotationsFeatures:
    def test_host_network_assigns_port(self):
        engine, store, _ = make_engine()
        job = make_tpujob(workers=1)
        job.metadata.annotations[constants.ANNOTATION_NETWORK_MODE] = "host"
        submit_and_reconcile(engine, store, job)
        pod = store.get("Pod", "job1-worker-0")
        assert pod.spec.host_network
        hp = pod.spec.main_container().ports[0].host_port
        assert constants.HOST_PORT_RANGE[0] <= hp < constants.HOST_PORT_RANGE[1]

    def test_git_sync_injection(self):
        import json

        engine, store, _ = make_engine()
        job = make_tpujob(workers=1)
        job.metadata.annotations[constants.ANNOTATION_GIT_SYNC_CONFIG] = json.dumps(
            {"source": "https://example.com/repo.git", "destPath": "/w/code"}
        )
        submit_and_reconcile(engine, store, job)
        pod = store.get("Pod", "job1-worker-0")
        assert pod.spec.init_containers
        assert "clone" in " ".join(pod.spec.init_containers[0].command)
        assert pod.spec.main_container().working_dir == "/w/code"


class TestModelVersionHookup:
    def test_success_creates_model_version(self, tmp_path):
        from kubedl_tpu.api.types import ModelVersionSpecRef

        engine, store, _ = make_engine()
        driver = PodDriver(store)
        out = tmp_path / "model-out"
        out.mkdir()
        (out / "ckpt.bin").write_bytes(b"weights")
        job = make_tpujob(workers=1)
        job.spec.model_version = ModelVersionSpecRef(
            model_name="m1", image_repo="models/m1", storage_root=str(out)
        )
        submit_and_reconcile(engine, store, job)
        pod = store.get("Pod", "job1-worker-0")
        assert env_of(pod)[constants.ENV_MODEL_PATH] == str(out)
        driver.succeed("job1-worker-0")
        engine.reconcile("default", "job1")
        mvs = store.list("ModelVersion")
        assert len(mvs) == 1
        assert mvs[0].model_name == "m1"
        assert store.get("TPUJob", "job1").status.model_version == mvs[0].metadata.name

"""Cross-replica sharded weight update + comm/compute overlap
(docs/performance.md "Sharded weight update & overlap").

The contract under test: shard_update/overlap_comm change ONLY where the
update runs (reduce-scatter -> 1/dp optimizer apply -> all-gather instead
of all-reduce -> replicated apply), never the math — loss trajectories are
pinned against the replicated seed path, checkpoints round-trip ACROSS
update layouts (an old replicated checkpoint restores into a sharded
trainer and vice versa), the async checkpointer handles the scattered
optimizer state, and the elastic 4 -> 2 -> 4 reshard-resume stays
loss-invariant with the sharded update on. The log_every cadence's
no-blocking-transfer discipline and the host-side gradient-bucket plan are
pinned here too.
"""

import dataclasses

import jax
import numpy as np
import pytest

from kubedl_tpu.api.topology import MeshSpec
from kubedl_tpu.models import llama
from kubedl_tpu.parallel.mesh import build_mesh
from kubedl_tpu.training.buckets import (
    MIN_SCATTER_BYTES,
    plan_grad_buckets,
)
from kubedl_tpu.training.data import SyntheticTokens
from kubedl_tpu.training.trainer import (
    TrainConfig,
    Trainer,
    state_bytes_per_device,
)

#: trajectory tolerance vs the replicated arm: the sharded update is the
#: SAME math in a different placement, so only reduction-order float32
#: noise separates the arms (measured 0.0 on pure-data meshes)
TRAJ_TOL = dict(rtol=1e-5, atol=1e-5)


def make_cfg(**kw):
    kw.setdefault("model", llama.TINY)
    kw.setdefault("global_batch", 8)
    kw.setdefault("seq_len", 16)
    kw.setdefault("steps", 6)
    return TrainConfig(**kw)


def data_at(step=0, seed=5, gb=8, sl=16):
    it = iter(SyntheticTokens(gb, sl, llama.TINY.vocab_size, seed=seed))
    for _ in range(step):
        next(it)
    return it


def run_losses(trainer, steps, state=None, **fit_kw):
    losses = []
    state, summary = trainer.fit(
        data_at(int(jax.device_get(state["step"])) if state else 0),
        state=state, steps=steps,
        on_step=lambda i, m: losses.append(m["loss"]),
        **fit_kw,
    )
    return state, summary, [float(jax.device_get(l)) for l in losses]


def mesh_of(axes, ndev=None):
    devs = jax.devices()[:ndev] if ndev else None
    return build_mesh(MeshSpec(axes), devs)


class TestUpdateLayout:
    def test_opt_state_scattered_to_1_over_dp(self):
        mesh = mesh_of({"data": 4}, 4)
        sh = Trainer(make_cfg(shard_update=True), mesh)
        rep = Trainer(make_cfg(shard_update=False), mesh)
        assert sh.update_shardings is not None
        assert rep.update_shardings is None
        b_sh = state_bytes_per_device(sh.init_state())
        b_rep = state_bytes_per_device(rep.init_state())
        assert b_sh < b_rep
        # matmul leaves (>= MIN_SCATTER_BYTES) scatter 4-way; only the
        # few-KB norm vectors stay replicated, so the reduction is
        # within 25% of the ideal 1/4
        assert b_sh < b_rep / 4 * 1.25

    def test_small_leaves_keep_param_sharding(self):
        mesh = mesh_of({"data": 4}, 4)
        tr = Trainer(make_cfg(shard_update=True), mesh)
        ups = jax.tree_util.tree_leaves(tr.update_shardings)
        pss = jax.tree_util.tree_leaves(tr.param_shardings)
        mask = list(tr.grad_bucket_plan.scatter)
        assert len(ups) == len(pss) == len(mask)
        assert any(mask) and not all(mask)  # TINY has both kinds
        for u, p, scattered in zip(ups, pss, mask):
            if scattered:
                assert u.spec != p.spec
            else:
                assert u.spec == p.spec

    def test_no_data_axis_falls_back_to_replicated(self):
        tr = Trainer(make_cfg(shard_update=True), mesh_of({"data": 1}, 1))
        assert tr.update_shardings is None

    def test_pipeline_mesh_keeps_replicated_update(self):
        tr = Trainer(
            make_cfg(shard_update=True), mesh_of({"data": 2, "pipe": 2}, 4)
        )
        assert tr.update_shardings is None

    def test_summary_reports_update_layout(self):
        tr = Trainer(
            make_cfg(shard_update=True, overlap_comm=True, steps=2),
            mesh_of({"data": 4}, 4),
        )
        _, summary, _ = run_losses(tr, 2)
        assert summary["shard_update"] is True
        assert summary["overlap_comm"] is True
        assert summary["grad_buckets"] >= 1
        assert summary["opt_state_bytes_per_device"] > 0


class TestLossTrajectoryEquivalence:
    def _run(self, cfg, mesh):
        _, _, losses = run_losses(Trainer(cfg, mesh), cfg.steps)
        return losses

    def test_sharded_and_overlap_match_replicated_data_mesh(self):
        mesh = mesh_of({"data": 4}, 4)
        base = make_cfg(grad_accum=2, shard_update=False,
                        overlap_comm=False)
        ref = self._run(base, mesh)
        assert len(ref) == base.steps
        sharded = self._run(
            dataclasses.replace(base, shard_update=True), mesh
        )
        overlap = self._run(
            dataclasses.replace(base, shard_update=True,
                                overlap_comm=True), mesh
        )
        np.testing.assert_allclose(sharded, ref, **TRAJ_TOL)
        np.testing.assert_allclose(overlap, ref, **TRAJ_TOL)

    def test_sharded_matches_replicated_on_fsdp_mesh(self):
        # data composes with fsdp: the scatter lands on the stacked-layer
        # dim (the only dim safe to carry "data" on a model-sharded mesh)
        mesh = mesh_of({"data": 2, "fsdp": 4}, 8)
        base = make_cfg(shard_update=False, overlap_comm=False)
        ref = self._run(base, mesh)
        tr = Trainer(dataclasses.replace(base, shard_update=True), mesh)
        assert tr.update_shardings is not None
        _, _, sharded = run_losses(tr, base.steps)
        np.testing.assert_allclose(sharded, ref, **TRAJ_TOL)

    def test_indivisible_scatter_falls_back_not_wrong(self):
        # data=4 x fsdp=2: TINY's stacked dim (n_layers=2) does not divide
        # the data axis and every free dim is either model-sharded or
        # excluded — the trainer must fall back to the replicated update,
        # not scatter something unsafe
        mesh = mesh_of({"data": 4, "fsdp": 2}, 8)
        tr = Trainer(make_cfg(shard_update=True), mesh)
        assert tr.update_shardings is None
        base = make_cfg(shard_update=False)
        ref = self._run(base, mesh)
        _, _, got = run_losses(tr, base.steps)
        np.testing.assert_allclose(got, ref, **TRAJ_TOL)


class TestCheckpointAcrossLayouts:
    """checkpoint.py's format is layout-independent (per-shard global
    offsets, region-lazy assembly): a checkpoint written under ONE update
    layout must restore bit-exactly under the OTHER."""

    def _train_and_save(self, cfg, mesh, ckpt):
        tr = Trainer(cfg, mesh)
        state, _, losses = run_losses(tr, 3, ckpt_dir=ckpt, ckpt_every=3)
        return state, losses

    @pytest.mark.parametrize("src_sharded,dst_sharded",
                             [(False, True), (True, False)])
    def test_restore_across_update_layouts(self, tmp_path, src_sharded,
                                           dst_sharded):
        from kubedl_tpu.training.checkpoint import restore_checkpoint

        mesh = mesh_of({"data": 4}, 4)
        ckpt = str(tmp_path / "ck")
        cfg = make_cfg(shard_update=src_sharded, ckpt_async=False)
        src_state, src_losses = self._train_and_save(cfg, mesh, ckpt)

        dst = Trainer(
            make_cfg(shard_update=dst_sharded, ckpt_async=False), mesh
        )
        restored = restore_checkpoint(ckpt, dst.init_state())
        assert restored is not None
        assert int(jax.device_get(restored["step"])) == 3
        # bit-exact params through the cross-layout assembler
        for a, b in zip(jax.tree_util.tree_leaves(src_state["params"]),
                        jax.tree_util.tree_leaves(restored["params"])):
            np.testing.assert_array_equal(jax.device_get(a),
                                          jax.device_get(b))
        # ...and the restored run continues the source trajectory
        _, _, more = run_losses(dst, 6, state=restored)
        full = Trainer(
            make_cfg(shard_update=src_sharded, ckpt_async=False), mesh
        )
        _, _, ref = run_losses(full, 6)
        np.testing.assert_allclose(src_losses + more, ref, **TRAJ_TOL)

    def test_async_checkpointer_round_trips_scattered_state(self, tmp_path):
        from kubedl_tpu.training.checkpoint import restore_checkpoint

        mesh = mesh_of({"data": 4}, 4)
        ckpt = str(tmp_path / "ck")
        cfg = make_cfg(shard_update=True, ckpt_async=True)
        tr = Trainer(cfg, mesh)
        state, _, _ = run_losses(tr, 4, ckpt_dir=ckpt, ckpt_every=2)
        # fit joined the writer before returning: latest save is step 4
        restored = restore_checkpoint(ckpt, Trainer(cfg, mesh).init_state())
        assert restored is not None
        assert int(jax.device_get(restored["step"])) == 4
        for a, b in zip(jax.tree_util.tree_leaves(state),
                        jax.tree_util.tree_leaves(restored)):
            np.testing.assert_array_equal(jax.device_get(a),
                                          jax.device_get(b))


class TestElasticReshardShardedUpdate:
    def test_4_2_4_reshard_resume_loss_invariant(self, tmp_path):
        """The sharded update re-scatters to the NEW data axis at every
        shape (4-way -> 2-way -> 4-way) while the elastic grad-accum
        rescale keeps the effective global batch constant — the
        trajectory must match the fixed-size sharded run."""
        from kubedl_tpu.elastic.resize import grad_accum_for_world
        from kubedl_tpu.training.checkpoint import restore_checkpoint

        assert jax.device_count() >= 4
        GB, SL, STEPS = 8, 16, 7

        def cfg(accum):
            return TrainConfig(
                model=llama.TINY, global_batch=GB, seq_len=SL,
                steps=STEPS, grad_accum=accum, shard_update=True,
                overlap_comm=True, ckpt_async=False)

        def run(trainer, start, stop, ckpt):
            state = trainer.init_state()
            if start > 0:
                state = restore_checkpoint(ckpt, state)
                assert state is not None
                assert int(jax.device_get(state["step"])) == start
            losses = []
            state, _ = trainer.fit(
                data_at(start, gb=GB, sl=SL), state=state, steps=stop,
                on_step=lambda i, m: losses.append(m["loss"]),
                ckpt_dir=ckpt,
            )
            return [float(jax.device_get(l)) for l in losses]

        mesh4 = mesh_of({"data": 4}, 4)
        mesh2 = mesh_of({"data": 2}, 2)
        baseline = run(Trainer(cfg(1), mesh4), 0, STEPS,
                       str(tmp_path / "base"))
        assert len(baseline) == STEPS

        accum2 = grad_accum_for_world(1, 4, 2, GB)
        assert accum2 == 2
        ck = str(tmp_path / "elastic")
        losses = run(Trainer(cfg(1), mesh4), 0, 3, ck)
        losses += run(Trainer(cfg(accum2), mesh2), 3, 5, ck)
        losses += run(Trainer(cfg(1), mesh4), 5, STEPS, ck)
        assert len(losses) == STEPS
        np.testing.assert_allclose(losses, baseline, rtol=2e-3, atol=2e-3)


class TestLogEveryNoDeviceSync:
    def _fetches(self):
        import kubedl_tpu.training.trainer as tmod

        return tmod.SCALAR_FETCHES

    def test_steps_between_logs_issue_no_blocking_transfer(self):
        tr = Trainer(make_cfg(steps=6, log_every=0), mesh_of({"data": 4}, 4))
        before = self._fetches()
        _, summary, _ = run_losses(tr, 6)
        # exactly two true barriers: the first step (first_step_seconds
        # clock) and the final step (stops the throughput clock) — the 4
        # steps in between must not fetch
        assert self._fetches() - before == 2
        assert summary["loss_log"] == []

    def test_log_every_cadence_fetches_and_records(self):
        tr = Trainer(make_cfg(steps=6, log_every=2), mesh_of({"data": 4}, 4))
        before = self._fetches()
        _, summary, _ = run_losses(tr, 6)
        # first + final + the log_every fetches at steps 2 and 4 (step 6
        # IS the final fetch, not a duplicate)
        assert self._fetches() - before == 4
        assert [s for s, _ in summary["loss_log"]] == [2, 4]
        assert all(np.isfinite(v) for _, v in summary["loss_log"])


class TestLongContextPolicy:
    def test_auto_upgrades_remat_and_chunks_loss(self):
        model = dataclasses.replace(
            llama.TINY, max_seq=8192, remat=True, remat_policy="dots_flash"
        )
        cfg = TrainConfig(model=model, global_batch=2, seq_len=4096,
                          steps=1, long_context_policy="auto")
        tr = Trainer(cfg, mesh_of({"data": 2}, 2))
        assert tr.cfg.model.remat_policy == "flash_rope"
        assert tr.cfg.model.loss_chunk == 512
        assert "remat_policy=flash_rope" in tr.long_context_policy_applied
        assert "loss_chunk=512" in tr.long_context_policy_applied

    def test_short_seq_and_off_leave_model_alone(self):
        model = dataclasses.replace(
            llama.TINY, max_seq=8192, remat=True, remat_policy="dots_flash"
        )
        short = Trainer(
            TrainConfig(model=model, global_batch=2, seq_len=128, steps=1),
            mesh_of({"data": 2}, 2),
        )
        assert short.cfg.model.remat_policy == "dots_flash"
        assert short.long_context_policy_applied == ""
        off = Trainer(
            TrainConfig(model=model, global_batch=2, seq_len=4096, steps=1,
                        long_context_policy="off"),
            mesh_of({"data": 2}, 2),
        )
        assert off.cfg.model.remat_policy == "dots_flash"


class TestGradBucketPlan:
    def test_every_leaf_in_exactly_one_bucket(self):
        sizes = [100, 5000, 3 * 2**20, 10 * 2**20, 512, 4096]
        plan = plan_grad_buckets(sizes, bucket_bytes=4 * 2**20)
        seen = sorted(i for b in plan.buckets for i in b)
        assert seen == list(range(len(sizes)))
        assert plan.total_bytes == sum(sizes)

    def test_buckets_respect_size_and_issue_order(self):
        sizes = [2 * 2**20] * 6
        plan = plan_grad_buckets(sizes, bucket_bytes=4 * 2**20)
        assert plan.n_buckets == 3
        for b in plan.buckets:
            assert sum(sizes[i] for i in b) <= 4 * 2**20
        # backward-readiness order: the LAST leaf's bucket issues first
        assert plan.buckets[0][0] == len(sizes) - 1

    def test_oversized_leaf_gets_its_own_bucket(self):
        plan = plan_grad_buckets([10 * 2**20, 100, 10 * 2**20],
                                 bucket_bytes=4 * 2**20)
        assert any(len(b) == 1 for b in plan.buckets)
        assert plan.n_buckets >= 2

    def test_scatter_flags_honor_min_bytes(self):
        sizes = [MIN_SCATTER_BYTES - 1, MIN_SCATTER_BYTES,
                 MIN_SCATTER_BYTES + 1]
        plan = plan_grad_buckets(sizes)
        assert plan.scatter == (False, True, True)
        assert plan.scattered_bytes == sum(sizes[1:])

    def test_bad_bucket_bytes_raises(self):
        with pytest.raises(ValueError):
            plan_grad_buckets([1024], bucket_bytes=0)

    def test_host_planning_within_tier1_budget(self):
        from scripts.scheduler_microbench import run_bucket_microbench

        out = run_bucket_microbench(iters=50)
        assert out["within_budget"], (
            f"bucket plan p95 {out['plan_ms_p95']} ms blew the "
            f"{out['budget_ms']} ms budget"
        )

"""Table-driven API type tests (reference analogue:
apis/training/v1alpha1/*_defaults_test.go)."""

import pytest

from kubedl_tpu.api.topology import MeshSpec, get_slice, validate_mesh_for_slice
from kubedl_tpu.api.types import (
    JobCondition,
    JobConditionType,
    JobSpec,
    JobStatus,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    is_retryable_exit_code,
    job_spec_defaults,
)


def test_exit_code_classification():
    # reference semantics: 1-127 permanent, 128-255 retryable
    assert not is_retryable_exit_code(1)
    assert not is_retryable_exit_code(127)
    assert is_retryable_exit_code(128)
    assert is_retryable_exit_code(137)
    assert is_retryable_exit_code(255)


def test_condition_transitions_newest_wins():
    st = JobStatus()
    assert st.phase is None
    assert st.set_condition(JobConditionType.CREATED)
    assert st.phase == JobConditionType.CREATED
    assert st.set_condition(JobConditionType.RUNNING)
    assert st.phase == JobConditionType.RUNNING
    # same condition again: no transition
    assert not st.set_condition(JobConditionType.RUNNING, reason="again")
    assert st.conditions[-1].reason == "again"
    # restart then run again: RUNNING entry re-appended, only one copy
    assert st.set_condition(JobConditionType.RESTARTING)
    assert st.set_condition(JobConditionType.RUNNING)
    assert sum(1 for c in st.conditions if c.type == JobConditionType.RUNNING) == 1
    assert st.phase == JobConditionType.RUNNING


def test_terminal_helpers():
    st = JobStatus()
    st.set_condition(JobConditionType.SUCCEEDED)
    assert st.is_terminal() and st.is_succeeded() and not st.is_failed()


@pytest.mark.parametrize(
    "replicas,topo,expected",
    [
        (0, None, 1),  # defaulted to 1
        (3, None, 3),
        (1, "v5e-32", 8),  # clamped to topology host count
        (99, "v5e-8", 2),
    ],
)
def test_job_spec_defaults(replicas, topo, expected):
    spec = JobSpec(
        replica_specs={
            ReplicaType.WORKER: ReplicaSpec(
                replicas=replicas, topology=get_slice(topo) if topo else None
            )
        }
    )
    job_spec_defaults(spec)
    assert spec.replica_specs[ReplicaType.WORKER].replicas == expected
    assert spec.replica_specs[ReplicaType.WORKER].template.spec.containers


def test_min_available_defaults_to_all():
    spec = JobSpec(
        replica_specs={
            ReplicaType.WORKER: ReplicaSpec(replicas=4),
            ReplicaType.EVALUATOR: ReplicaSpec(replicas=1),
        }
    )
    assert spec.total_replicas() == 5
    assert spec.min_available() == 5
    spec.run_policy.scheduling_policy.min_available = 4
    assert spec.min_available() == 4


class TestTopology:
    def test_catalog(self):
        t = get_slice("v5e-32")
        assert t.chips == 32 and t.hosts == 8 and t.chips_per_host == 4
        with pytest.raises(KeyError):
            get_slice("v9x-999")

    def test_host_mesh_and_coordinates(self):
        t = get_slice("v5e-32")  # physical 4x8, host block 2x2 -> hosts 2x4
        assert t.host_mesh() == (2, 4)
        assert t.coordinates(0) == (0, 0)
        assert t.coordinates(5) == (1, 1)

    def test_mesh_env_roundtrip(self):
        m = MeshSpec({"data": 4, "tensor": 8})
        s = m.to_env()
        assert s == "data=4,tensor=8"
        assert MeshSpec.from_env(s).axes == m.axes

    def test_mesh_for_slice(self):
        t = get_slice("v5e-32")
        m = MeshSpec.for_slice(t, tensor=4)
        assert m.axes == {"data": 8, "tensor": 4}
        assert validate_mesh_for_slice(m, t) is None
        m2 = MeshSpec({"data": 4})
        assert validate_mesh_for_slice(m2, t) is not None

    def test_mesh_for_multislice(self):
        t = get_slice("v5e-8")
        m = MeshSpec.for_slice(t, num_slices=2)
        assert m.axes == {"replica": 2, "data": 8}
        assert m.ordered()[0][0] == "replica"  # DCN axis outermost


class TestExamples:
    def test_all_examples_decode_and_submit(self, tmp_path):
        """Every shipped example YAML round-trips through the codec and is
        accepted by a live operator submit (the reference's example/ dir
        is exercised by its e2e job; here every kind's example is)."""
        import glob
        import os

        import yaml as _yaml

        from kubedl_tpu.api import codec
        from kubedl_tpu.operator import Operator, OperatorOptions
        from kubedl_tpu.runtime.executor import FakeRuntime

        examples = sorted(glob.glob(
            os.path.join(os.path.dirname(__file__), "..", "examples", "*.yaml")
        ))
        assert len(examples) >= 6
        opts = OperatorOptions(
            local_addresses=True,
            artifact_registry_root=str(tmp_path / "reg"),
        )
        op = Operator(opts, runtime=FakeRuntime())
        kinds = set()
        for path in examples:
            doc = _yaml.safe_load(open(path))
            job = codec.decode_object(doc)
            kinds.add(job.kind)
            assert job.kind in op.engines, path
            op.submit(job)  # store-level create must accept it
        assert {"TPUJob", "TFJob", "PyTorchJob", "MPIJob", "XGBoostJob"} <= kinds


class TestCodecRoundTripAllKinds:
    def test_randomized_specs_round_trip_every_kind(self):
        """Property-style: randomized-but-valid specs for every registered
        kind survive encode -> YAML -> decode -> encode identically (the
        codec is the wire format for console, client SDK, cron templates
        and examples — drift corrupts all four)."""
        import random

        import yaml as _yaml

        from kubedl_tpu.api import codec
        from kubedl_tpu.api.types import (
            CleanPodPolicy, ReplicaSpec, ReplicaType, RestartPolicy,
            SuccessPolicy,
        )
        from kubedl_tpu.core.objects import Container, EnvVar
        from kubedl_tpu.workloads.registry import WORKLOAD_REGISTRY

        rng = random.Random(7)
        kind_types = {
            "TPUJob": [ReplicaType.WORKER],
            "TFJob": [ReplicaType.PS, ReplicaType.WORKER, ReplicaType.CHIEF],
            "PyTorchJob": [ReplicaType.MASTER, ReplicaType.WORKER],
            "XDLJob": [ReplicaType.SCHEDULER, ReplicaType.PS, ReplicaType.WORKER],
            "XGBoostJob": [ReplicaType.MASTER, ReplicaType.WORKER],
            "MarsJob": [ReplicaType.SCHEDULER, ReplicaType.WORKER],
            "ElasticDLJob": [ReplicaType.MASTER],
            "MPIJob": [ReplicaType.LAUNCHER, ReplicaType.WORKER],
        }
        for kind, factory in sorted(WORKLOAD_REGISTRY.items()):
            for trial in range(5):
                controller = factory(local_addresses=True)
                job = controller.object_factory()
                job.metadata.name = f"rt-{kind.lower()}-{trial}"
                job.metadata.labels = {"team": f"t{rng.randrange(9)}"}
                job.metadata.annotations = {
                    "kubedl-tpu.io/owner": f"u{rng.randrange(9)}"
                }
                for rtype in kind_types.get(kind, [ReplicaType.WORKER]):
                    if rng.random() < 0.3 and rtype != ReplicaType.MASTER:
                        continue
                    spec = ReplicaSpec(
                        replicas=rng.randrange(1, 5),
                        restart_policy=rng.choice(list(RestartPolicy)),
                    )
                    spec.template.spec.containers.append(Container(
                        command=["python", "-c", f"print({trial})"],
                        env=[EnvVar(f"K{i}", str(rng.random()))
                             for i in range(rng.randrange(3))],
                    ))
                    job.spec.replica_specs[rtype] = spec
                if not job.spec.replica_specs:
                    default_rt = kind_types.get(kind, [ReplicaType.WORKER])[0]
                    job.spec.replica_specs[default_rt] = ReplicaSpec(replicas=1)
                job.spec.run_policy.clean_pod_policy = rng.choice(
                    list(CleanPodPolicy))
                job.spec.run_policy.backoff_limit = rng.randrange(0, 4)
                job.spec.success_policy = rng.choice(list(SuccessPolicy))

                doc1 = codec.encode(job)
                yml = _yaml.safe_dump(doc1)
                decoded = codec.decode_object(_yaml.safe_load(yml))
                doc2 = codec.encode(decoded)
                assert doc1 == doc2, (kind, trial)
                assert decoded.kind == kind
                assert decoded.spec.run_policy.backoff_limit == (
                    job.spec.run_policy.backoff_limit
                )

"""Node lifecycle: heartbeat-driven failure detection (the k8s
node-controller analogue the reference delegates to the cluster).
A host that stops heartbeating flips NotReady and its pods fail
RETRYABLY — feeding the same slice-granular gang-restart machinery a
worker crash does."""

import time

import pytest

from kubedl_tpu.core.nodes import (
    EVICT_EXIT_CODE,
    NODE_NAMESPACE,
    NodeHeartbeater,
    NodeLifecycleController,
)
from kubedl_tpu.core.objects import Node, PodPhase
from kubedl_tpu.core.store import ObjectStore


class TestHeartbeatAndEviction:
    def _setup(self, grace=10.0):
        store = ObjectStore()
        t = {"now": 1000.0}
        clock = lambda: t["now"]
        hb = NodeHeartbeater(store, ["nodeA"], clock=clock)
        ctrl = NodeLifecycleController(store, grace=grace, clock=clock)
        return store, t, hb, ctrl

    def test_heartbeat_registers_and_renews(self):
        store, t, hb, ctrl = self._setup()
        hb.beat_once()
        node = store.get("Node", "nodeA", NODE_NAMESPACE)
        assert node.ready and node.last_heartbeat == 1000.0
        t["now"] = 1005.0
        hb.beat_once()
        assert store.get("Node", "nodeA", NODE_NAMESPACE).last_heartbeat == 1005.0

    def test_fresh_node_untouched_and_requeues(self):
        store, t, hb, ctrl = self._setup(grace=10.0)
        hb.beat_once()
        requeue = ctrl.reconcile(NODE_NAMESPACE, "nodeA")
        assert requeue is not None and requeue == pytest.approx(10.05, abs=0.2)
        assert store.get("Node", "nodeA", NODE_NAMESPACE).ready

    def test_stale_node_not_ready_and_pods_evicted(self):
        from tests.helpers import make_tpujob

        store, t, hb, ctrl = self._setup(grace=10.0)
        hb.beat_once()
        # two pods on nodeA, one on nodeB (no Node object), one terminal
        from kubedl_tpu.core.objects import Pod

        def pod(name, node, phase=PodPhase.RUNNING):
            p = Pod()
            p.metadata.name = name
            p.spec.containers.append(
                __import__("kubedl_tpu.core.objects", fromlist=["Container"]).Container()
            )
            p.spec.node_name = node
            p.status.phase = phase
            store.create(p)
            return p

        pod("a1", "nodeA")
        pod("a2", "nodeA", PodPhase.PENDING)
        pod("b1", "nodeB")
        pod("a3", "nodeA", PodPhase.SUCCEEDED)

        ctrl.reconcile(NODE_NAMESPACE, "nodeA")  # observe the heartbeat
        t["now"] = 1011.0  # past grace with no new heartbeat observed
        ctrl.reconcile(NODE_NAMESPACE, "nodeA")
        node = store.get("Node", "nodeA", NODE_NAMESPACE)
        assert not node.ready and "no heartbeat" in node.reason
        for name in ("a1", "a2"):
            p = store.get("Pod", name)
            assert p.status.phase == PodPhase.FAILED
            assert p.status.container_statuses[0].exit_code == EVICT_EXIT_CODE
            assert p.is_evicted()  # retryable under EVERY restart policy
        assert store.get("Pod", "b1").status.phase == PodPhase.RUNNING
        assert store.get("Pod", "a3").status.phase == PodPhase.SUCCEEDED
        assert any(e.reason == "NodeNotReady" for e in store.list("Event", None))

    def test_heartbeat_resume_flips_ready(self):
        store, t, hb, ctrl = self._setup(grace=10.0)
        hb.beat_once()
        ctrl.reconcile(NODE_NAMESPACE, "nodeA")  # observe
        t["now"] = 1020.0
        ctrl.reconcile(NODE_NAMESPACE, "nodeA")
        assert not store.get("Node", "nodeA", NODE_NAMESPACE).ready
        hb.beat_once()  # kubelet comes back
        node = store.get("Node", "nodeA", NODE_NAMESPACE)
        assert node.ready and node.reason == "heartbeat resumed"


def test_node_loss_gang_restarts_job(tmp_path):
    """E2e: a gang job whose host dies restarts whole-slice and completes
    once the node returns — node loss takes the same recovery path as a
    worker crash."""
    from tests.helpers import make_tpujob

    from kubedl_tpu.api.types import JobConditionType, ReplicaType, RestartPolicy
    from kubedl_tpu.operator import Operator, OperatorOptions
    from kubedl_tpu.runtime.executor import SubprocessRuntime

    logs = str(tmp_path / "logs")
    opts = OperatorOptions(
        local_addresses=True, pod_log_dir=logs,
        artifact_registry_root=str(tmp_path / "reg"),
        node_grace_seconds=1.0, heartbeat_nodes=["hostX"],
    )
    marker = tmp_path / "node-recovered"
    with Operator(opts, runtime=SubprocessRuntime(logs)) as op:
        # pin the worker to hostX so the eviction targets it. The command
        # sleeps until the marker exists (flaky-job pattern): the first
        # attempt hangs, gets evicted on node loss, and the post-recovery
        # attempt exits 0.
        job = make_tpujob(
            "nodeloss", workers=1,
            command=["bash", "-c",
                     f"for i in $(seq 300); do test -f {marker} && exit 0; "
                     "sleep 1; done; exit 1"],
            restart_policy=RestartPolicy.ON_FAILURE_SLICE,
        )
        spec = job.spec.replica_specs[ReplicaType.WORKER]
        spec.template.spec.node_name = "hostX"
        op.submit(job)
        assert op.manager.wait(
            lambda: any(
                p.status.phase.value == "Running"
                for p in op.store.list("Pod")
            ), timeout=30,
        )
        # the node dies: stop heartbeating; the hung pod must be evicted
        # retryably (its local process killed) and the job gang-restart
        op.node_heartbeater.stop()
        assert op.manager.wait(
            lambda: op.store.get("TPUJob", "nodeloss").status.restart_count >= 1,
            timeout=30,
        ), "node loss never triggered a gang restart"
        # node comes back; the retried attempt can now succeed
        marker.write_text("up")
        op.node_heartbeater.start()  # restartable after stop()
        got = op.wait_for_phase(
            "TPUJob", "nodeloss",
            [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
            timeout=90,
        )
        assert got.status.phase == JobConditionType.SUCCEEDED
        evicted = [e for e in op.store.list("Event", None)
                   if e.reason == "Evicted"]
        assert evicted, "eviction event missing"


def test_heartbeat_racing_the_flip_wins():
    """Review r3: a heartbeat landing between the staleness read and the
    NotReady write must WIN — no spurious whole-gang eviction for a
    kubelet that stalled just past grace and recovered."""
    store = ObjectStore()
    t = {"now": 1000.0}
    hb = NodeHeartbeater(store, ["nodeA"], clock=lambda: t["now"])
    ctrl = NodeLifecycleController(store, grace=10.0, clock=lambda: t["now"])
    hb.beat_once()
    from kubedl_tpu.core.objects import Container, Pod

    p = Pod()
    p.metadata.name = "p1"
    p.spec.containers.append(Container())
    p.spec.node_name = "nodeA"
    p.status.phase = PodPhase.RUNNING
    store.create(p)
    ctrl.reconcile(NODE_NAMESPACE, "nodeA")  # observe
    t["now"] = 1011.0  # stale...
    # ...but the kubelet beats again before the controller's write lands:
    # the flip's in-mutate observation sees the CHANGED value and aborts
    hb.beat_once()
    ctrl.reconcile(NODE_NAMESPACE, "nodeA")
    assert store.get("Node", "nodeA", NODE_NAMESPACE).ready
    assert store.get("Pod", "p1").status.phase == PodPhase.RUNNING


def test_kubelet_never_overwrites_terminal_phase(tmp_path):
    """Review r3: the reaped kill signal (-15) must not clobber an
    eviction's retryable exit 137, and a launch must not resurrect an
    evicted pod to Running."""
    from kubedl_tpu.core.objects import Container, ContainerStatus, Pod
    from kubedl_tpu.runtime.executor import Kubelet, FakeRuntime

    store = ObjectStore()
    kubelet = Kubelet(store, FakeRuntime())
    p = Pod()
    p.metadata.name = "p1"
    p.spec.containers.append(Container(command=["true"]))
    store.create(p)
    # externally evicted (terminal, retryable)
    def evict(obj):
        obj.status.phase = PodPhase.FAILED
        obj.status.reason = "Evicted"
        obj.status.container_statuses = [ContainerStatus(exit_code=137)]
    store.update_with_retry("Pod", "p1", "default", evict)
    # a late reap stamps the kill signal -> must be a no-op
    kubelet._set_phase(store.get("Pod", "p1"), PodPhase.FAILED, exit_code=-15)
    got = store.get("Pod", "p1")
    assert got.status.container_statuses[0].exit_code == 137
    assert got.is_evicted()
    # an in-flight launch must not resurrect it either
    kubelet._set_phase(store.get("Pod", "p1"), PodPhase.RUNNING)
    assert store.get("Pod", "p1").status.phase == PodPhase.FAILED


def test_clock_skew_does_not_evict_healthy_node():
    """Review r3: staleness is judged by when THIS controller OBSERVED the
    heartbeat change, not by comparing producer vs controller wall clocks
    — a kubelet whose clock is far behind must not be evicted while its
    heartbeats keep arriving."""
    store = ObjectStore()
    ctrl_t = {"now": 10_000.0}
    kubelet_t = {"now": 0.0}  # 10,000s behind the controller's clock
    hb = NodeHeartbeater(store, ["nodeA"], clock=lambda: kubelet_t["now"])
    ctrl = NodeLifecycleController(store, grace=10.0,
                                   clock=lambda: ctrl_t["now"])
    hb.beat_once()
    ctrl.reconcile(NODE_NAMESPACE, "nodeA")  # first observation
    # heartbeats keep arriving (values change); controller time advances
    for _ in range(5):
        kubelet_t["now"] += 5.0
        ctrl_t["now"] += 5.0
        hb.beat_once()
        ctrl.reconcile(NODE_NAMESPACE, "nodeA")
    assert store.get("Node", "nodeA", NODE_NAMESPACE).ready
    # now the kubelet actually stops: observed value freezes -> NotReady
    ctrl_t["now"] += 11.0
    ctrl.reconcile(NODE_NAMESPACE, "nodeA")
    assert not store.get("Node", "nodeA", NODE_NAMESPACE).ready


def test_eviction_skips_concurrently_terminal_pod_quietly():
    """Review r3: a pod that reached a terminal phase between the list
    snapshot and the eviction write gets neither a store write nor a
    misleading Evicted event."""
    from kubedl_tpu.core.objects import Container, Pod

    store = ObjectStore()
    t = {"now": 1000.0}
    ctrl = NodeLifecycleController(store, grace=1.0, clock=lambda: t["now"])
    hb = NodeHeartbeater(store, ["nodeA"], clock=lambda: t["now"])
    hb.beat_once()
    p = Pod()
    p.metadata.name = "p1"
    p.spec.containers.append(Container())
    p.spec.node_name = "nodeA"
    p.status.phase = PodPhase.SUCCEEDED  # terminal before eviction runs
    store.create(p)
    rv = store.get("Pod", "p1").metadata.resource_version
    ctrl.reconcile(NODE_NAMESPACE, "nodeA")  # observe
    t["now"] += 2.0
    ctrl.reconcile(NODE_NAMESPACE, "nodeA")  # stale -> evict pass

    got = store.get("Pod", "p1")
    assert got.status.phase == PodPhase.SUCCEEDED
    assert got.metadata.resource_version == rv  # no no-op write
    assert not any(e.reason == "Evicted" for e in store.list("Event", None))


def test_controller_restart_does_not_resurrect_dead_node():
    """Review r3: a NotReady node must stay NotReady across a controller
    restart (empty observation map) until a REAL new heartbeat arrives."""
    store = ObjectStore()
    t = {"now": 1000.0}
    hb = NodeHeartbeater(store, ["nodeA"], clock=lambda: t["now"])
    ctrl = NodeLifecycleController(store, grace=10.0, clock=lambda: t["now"])
    hb.beat_once()
    ctrl.reconcile(NODE_NAMESPACE, "nodeA")
    t["now"] = 1020.0
    ctrl.reconcile(NODE_NAMESPACE, "nodeA")
    assert not store.get("Node", "nodeA", NODE_NAMESPACE).ready
    # controller restarts with no memory
    ctrl2 = NodeLifecycleController(store, grace=10.0, clock=lambda: t["now"])
    ctrl2.reconcile(NODE_NAMESPACE, "nodeA")
    assert not store.get("Node", "nodeA", NODE_NAMESPACE).ready  # stays dead
    # a real heartbeat flips it back (the heartbeater's own beat does too)
    t["now"] = 1025.0
    hb.beat_once()
    assert store.get("Node", "nodeA", NODE_NAMESPACE).ready

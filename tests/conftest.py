"""Test configuration: force CPU JAX with a virtual 8-device mesh so
multi-chip sharding logic is exercised without TPU hardware (SURVEY.md §4's
"multi-node-without-cluster" trick, TPU edition)."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # force: driver env may say otherwise
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("KUBEDL_CI", "true")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Lock-order witness (docs/static-analysis.md): KUBEDL_LOCKWITNESS=1 arms
# witness-instrumented Lock/RLock/Condition BEFORE any other kubedl_tpu
# import, so every lock the subsystems create at module/instance init is
# classified by creation site. Disarmed (the default) this is a no-op and
# threading primitives stay untouched.
from kubedl_tpu.analysis import lockwitness  # noqa: E402

lockwitness.install()

# Neutralize force-registered accelerator plugins (sitecustomize may have
# overridden jax_platforms already) so JAX_PLATFORMS=cpu actually holds.
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested  # noqa: E402

ensure_cpu_if_requested()


def pytest_sessionfinish(session, exitstatus):
    """Witnessed runs fail on any lock-order cycle observed across the
    whole suite (pytest reads session.exitstatus back after this hook)."""
    cycles = lockwitness.check()
    if cycles:
        w = lockwitness.active()
        sys.stderr.write("\n" + w.report() + "\n")
        session.exitstatus = 3

"""Auto-parallelism planner tests (docs/planning.md).

Layers, bottom-up: the cost-model formulas (pinned, not snapshotted),
the layout search and its simplest-within-slack ranking, golden plans
over the full slice-catalog x model-zoo admission matrix, admission-time
mesh validation, the engine integration (Planned condition / annotation /
env / metrics, PlanInfeasible failure), elastic re-planning on resize,
and the reconcile-loop overhead budget. The slow test proves the planner's
chosen meshes preserve the loss trajectory through a resize.
"""

import json

import pytest

from kubedl_tpu.api import constants
from kubedl_tpu.api.topology import (
    MeshSpec,
    SLICE_CATALOG,
    SliceTopology,
    get_slice,
    validate_mesh_for_slice,
)
from kubedl_tpu.api.types import ElasticSpec, JobConditionType
from kubedl_tpu.planner import (
    MODEL_ZOO,
    ModelDesc,
    PlanError,
    dp_baseline,
    enumerate_layouts,
    estimate,
    plan,
    search,
)
from kubedl_tpu.planner.costmodel import (
    HBM_USABLE_FRACTION,
    OVERLAP_FRACTION,
    allgather_bytes,
    allreduce_bytes,
    hbm_per_chip_gib,
    reduce_scatter_bytes,
)
from kubedl_tpu.workloads.tpujob import TPUJobController

from tests.helpers import PodDriver, env_of, make_tpujob, pod_names
from tests.test_engine import make_engine, submit_and_reconcile


class TestCostModel:
    def test_ring_collective_factors(self):
        # the standard ring factors: all-reduce 2(n-1)/n, (all-)gather (n-1)/n
        assert allreduce_bytes(4, 100.0) == pytest.approx(150.0)
        assert allgather_bytes(4, 100.0) == pytest.approx(75.0)
        assert reduce_scatter_bytes(4, 100.0) == pytest.approx(75.0)
        # a 1-way collective is free
        assert allreduce_bytes(1, 100.0) == 0.0
        assert allgather_bytes(1, 100.0) == 0.0

    def test_num_params_explicit_wins(self):
        md = ModelDesc(params=123, layers=10, hidden=1024)
        assert md.num_params() == 123
        assert md.flops_per_token() == 6.0 * 123

    def test_num_params_derived(self):
        md = ModelDesc(layers=2, hidden=64, ffn=256, vocab=256)
        per_layer = 4 * 64 * 64 + 3 * 64 * 256
        assert md.num_params() == 2 * per_layer + 256 * 64
        # ffn defaults to 4*hidden when unset
        md0 = ModelDesc(layers=2, hidden=64, vocab=256)
        assert md0.num_params() == 2 * (4 * 64 * 64 + 3 * 64 * 256) + 256 * 64

    def test_validate_catches_bad_shapes(self):
        errs = ModelDesc(dtype="int4", global_batch=0).validate("m")
        text = "; ".join(errs)
        assert "m.dtype" in text and "m.globalBatch" in text
        # params OR layers+hidden must be given
        assert any("params" in e for e in ModelDesc().validate())
        assert ModelDesc(params=1000).validate() == []

    def test_data_axis_priced_with_ring_allreduce_over_ici(self):
        topo = get_slice("v5e-8")
        md = MODEL_ZOO["tiny"]
        cost = estimate(md, topo, MeshSpec({"data": 8}))
        assert cost.feasible
        p_bytes = md.num_params() * md.bytes_per_param()
        want_ms = allreduce_bytes(8, p_bytes) / (topo.ici_gbps * 1e9) * 1e3
        assert cost.comm_ms_by_axis["data"] == pytest.approx(want_ms)
        # the sharded-update overlap hides part of the gradient collective
        # behind backward compute: only the exposed remainder is on the
        # critical path
        assert cost.step_ms == pytest.approx(
            cost.compute_ms + cost.exposed_comm_ms
        )
        hidden = min(OVERLAP_FRACTION * want_ms, cost.compute_ms)
        assert cost.exposed_comm_ms == pytest.approx(cost.comm_ms - hidden)
        # with the sharded update off the seed formula is preserved
        legacy = estimate(md, topo, MeshSpec({"data": 8}),
                          update_sharding=False)
        assert legacy.exposed_comm_ms == pytest.approx(legacy.comm_ms)
        assert legacy.step_ms == pytest.approx(
            legacy.compute_ms + legacy.comm_ms
        )

    def test_sharded_update_divides_opt_state_over_data_axis(self):
        topo = get_slice("v5e-8")
        md = MODEL_ZOO["tiny"]
        mesh = MeshSpec({"data": 8})
        sharded = hbm_per_chip_gib(md, mesh, update_sharding=True)
        replicated = hbm_per_chip_gib(md, mesh, update_sharding=False)
        # params stay replicated; grads + optimizer moments shard 8-way
        assert sharded < replicated
        # no data axis to scatter over => identical residency
        one = MeshSpec({"data": 1})
        assert hbm_per_chip_gib(md, one, update_sharding=True) == (
            hbm_per_chip_gib(md, one, update_sharding=False)
        )

    def test_replica_axis_priced_over_dcn_when_multislice(self):
        topo = get_slice("v5e-8")
        md = MODEL_ZOO["tiny"]
        mesh = MeshSpec({"replica": 2, "data": 8})
        multi = estimate(md, topo, mesh, num_slices=2)
        single = estimate(md, topo, mesh, num_slices=1)
        p_bytes = md.num_params() * md.bytes_per_param()
        assert multi.comm_ms_by_axis["replica"] == pytest.approx(
            allreduce_bytes(2, p_bytes) / (topo.dcn_gbps * 1e9) * 1e3
        )
        # same axis intra-slice rides ICI instead: much cheaper
        assert single.comm_ms_by_axis["replica"] == pytest.approx(
            allreduce_bytes(2, p_bytes) / (topo.ici_gbps * 1e9) * 1e3
        )
        assert multi.comm_ms_by_axis["replica"] > single.comm_ms_by_axis["replica"]

    def test_fsdp_axis_prices_zero3_and_shards_state(self):
        topo = get_slice("v5e-8")
        md = MODEL_ZOO["tiny"]
        dp = estimate(md, topo, MeshSpec({"data": 8}))
        zero3 = estimate(md, topo, MeshSpec({"data": 4, "fsdp": 2}))
        p_bytes = md.num_params() * md.bytes_per_param()
        # 2 all-gathers (fwd+bwd) + 1 reduce-scatter over the full buffer
        want_ms = (
            2 * allgather_bytes(2, p_bytes) + reduce_scatter_bytes(2, p_bytes)
        ) / (topo.ici_gbps * 1e9) * 1e3
        assert zero3.comm_ms_by_axis["fsdp"] == pytest.approx(want_ms)
        # ...in exchange for halved optimizer-state residency
        assert zero3.hbm_gib < dp.hbm_gib

    def test_memory_infeasible_carries_reason(self):
        # 1.3B params need ~15 GiB of state on one 8 GiB cpu stand-in chip
        cost = estimate(MODEL_ZOO["llama-1b"], get_slice("cpu-1"), MeshSpec({"data": 1}))
        assert not cost.feasible
        assert "GiB" in cost.reason
        assert cost.hbm_gib > get_slice("cpu-1").hbm_gib_per_chip * HBM_USABLE_FRACTION


class TestSearch:
    def test_layouts_tile_the_slice_exactly(self):
        topo = get_slice("v5e-8")
        layouts = enumerate_layouts(MODEL_ZOO["tiny"], topo)
        assert layouts
        for m in layouts:
            assert validate_mesh_for_slice(m, topo, num_slices=1) is None

    def test_multislice_pins_replica_to_num_slices(self):
        topo = get_slice("v5e-8")
        md = ModelDesc(layers=2, hidden=64, ffn=256, vocab=256,
                       seq_len=128, global_batch=32)
        layouts = enumerate_layouts(md, topo, num_slices=2)
        assert layouts
        for m in layouts:
            assert m.axes.get("replica") == 2
            assert validate_mesh_for_slice(m, topo, num_slices=2) is None

    def test_structural_pruning_respects_batch_divisibility(self):
        # global_batch=2: no layout may spread gradients over >2 replicas
        md = ModelDesc(layers=2, hidden=64, ffn=256, vocab=256,
                       seq_len=128, global_batch=2)
        for m in enumerate_layouts(md, get_slice("v5e-8")):
            ax = m.axes
            assert ax.get("data", 1) * ax.get("fsdp", 1) <= 2

    def test_simplicity_slack_keeps_plain_data_parallel(self):
        # tiny fits everywhere: µs-scale comm deltas between dp/sp/tensor
        # layouts must not talk the job out of pure DP
        best = search(MODEL_ZOO["tiny"], get_slice("v5e-8")).best
        assert best.mesh.axes == {"data": 8}

    def test_search_counts_every_candidate(self):
        topo = get_slice("v5e-8")
        md = MODEL_ZOO["tiny"]
        res = search(md, topo)
        assert res.evaluated == len(enumerate_layouts(md, topo))
        assert res.evaluated == len(res.ranked) + len(res.infeasible)


class TestGoldenPlans:
    """The planner contract over the full admission matrix: every catalog
    topology x zoo model yields a memory-feasible plan never modeled slower
    than naive DP — strictly better when DP is memory-infeasible — or a
    clean PlanError when nothing fits."""

    @pytest.mark.parametrize("topo_name", sorted(SLICE_CATALOG))
    @pytest.mark.parametrize("model_name", sorted(MODEL_ZOO))
    def test_plan_beats_or_matches_naive_dp(self, topo_name, model_name):
        topo = get_slice(topo_name)
        md = MODEL_ZOO[model_name]
        base = dp_baseline(md, topo)
        try:
            p = plan(md, topo)
        except PlanError:
            # nothing fits => naive DP cannot have fit either
            assert not base.feasible
            return
        assert validate_mesh_for_slice(p.mesh, topo, num_slices=1) is None
        assert p.hbm_gib <= topo.hbm_gib_per_chip * HBM_USABLE_FRACTION
        if base.feasible:
            assert p.baseline_dp_ms == pytest.approx(base.step_ms)
            assert p.step_time_ms <= base.step_ms * (1 + 1e-9)
        else:
            assert p.baseline_dp_ms is None
            if "GiB" in base.reason:
                # DP died on memory: a model-parallel axis must be doing
                # the work (this is exactly where the planner earns its keep)
                ax = p.mesh.axes
                assert any(ax.get(a, 1) > 1 for a in ("fsdp", "sp", "tensor"))

    def test_llama_1b_on_v5e_8_fits_dp_with_sharded_update(self):
        # the canonical case: 1.3B params, 16 GiB chips — a REPLICATED
        # update wants ~15 GiB of optimizer state per chip, which used to
        # force fsdp=2; the cross-replica sharded update divides that state
        # by the data axis, so plain DP now fits and simplicity keeps it
        p = plan(MODEL_ZOO["llama-1b"], get_slice("v5e-8"))
        assert p.baseline_dp_ms is not None
        assert p.mesh.axes == {"data": 8}
        # the pre-sharded-update verdict is still pinned: replicated state
        # does not fit pure DP on this slice
        old = estimate(MODEL_ZOO["llama-1b"], get_slice("v5e-8"),
                       MeshSpec({"data": 8}), update_sharding=False)
        assert not old.feasible

    def test_roomy_chips_keep_pure_dp(self):
        # same model on 95 GiB v5p chips: DP fits and simplicity keeps it
        p = plan(MODEL_ZOO["llama-1b"], get_slice("v5p-8"))
        assert p.baseline_dp_ms is not None
        assert p.mesh.axes == {"data": 8}

    def test_nothing_fits_raises_plan_error(self):
        with pytest.raises(PlanError) as ei:
            plan(MODEL_ZOO["llama-1b"], get_slice("cpu-1"))
        assert "no memory-feasible layout" in str(ei.value)

    def test_invalid_model_desc_raises_plan_error(self):
        with pytest.raises(PlanError):
            plan(ModelDesc(), get_slice("v5e-8"))


class TestAdmissionValidation:
    """Explicit mesh blocks are now checked at submit (satellite a): a bad
    mesh fails validation instead of failing inside the worker."""

    def _job(self, **kw):
        job = make_tpujob(topology=get_slice("v5e-8"), **kw)
        return job

    def test_valid_explicit_mesh_passes(self):
        job = self._job()
        job.mesh = MeshSpec({"data": 4, "tensor": 2})
        assert TPUJobController().validate(job) == []

    def test_unknown_axis_rejected(self):
        job = self._job()
        job.mesh = MeshSpec({"bogus": 8})
        errs = TPUJobController().validate(job)
        assert any("unknown mesh axis" in e for e in errs)

    def test_wrong_product_rejected(self):
        job = self._job()
        job.mesh = MeshSpec({"data": 4})  # v5e-8 has 8 chips
        errs = TPUJobController().validate(job)
        assert any("covers 4 devices" in e for e in errs)

    def test_worker_spec_mesh_checked_too(self):
        from kubedl_tpu.api.types import ReplicaType

        job = self._job()
        job.spec.replica_specs[ReplicaType.WORKER].mesh = MeshSpec({"data": 3})
        errs = TPUJobController().validate(job)
        assert any(e.startswith("worker.mesh:") for e in errs)

    def test_mesh_validated_at_elastic_clamped_size(self):
        # validation clamps num_slices exactly the way apply_defaults will:
        # min_slices=2 means the mesh must tile 2 slices, not the declared 1
        job = self._job()
        job.elastic = ElasticSpec(min_slices=2, max_slices=4)
        job.num_slices = 1
        job.mesh = MeshSpec({"data": 8})
        errs = TPUJobController().validate(job)
        assert any("16 chips" in e for e in errs)

    def test_auto_requires_model_desc(self):
        job = self._job()
        job.mesh = "auto"
        errs = TPUJobController().validate(job)
        assert any("requires a modelDesc" in e for e in errs)

    def test_arbitrary_mesh_string_rejected(self):
        job = self._job()
        job.mesh = "dp8"
        errs = TPUJobController().validate(job)
        assert any('use axis sizes or "auto"' in e for e in errs)

    def test_bad_model_desc_rejected(self):
        job = self._job()
        job.model_desc = ModelDesc(layers=2, hidden=64, dtype="int4")
        errs = TPUJobController().validate(job)
        assert any("modelDesc.dtype" in e for e in errs)

    def test_auto_mesh_round_trips_through_codec(self):
        from kubedl_tpu.api.codec import decode_object, encode

        job = self._job()
        job.mesh = "auto"
        job.model_desc = ModelDesc(layers=2, hidden=64, ffn=256, vocab=256)
        back = decode_object(json.loads(json.dumps(encode(job))))
        assert back.mesh == "auto"
        assert back.model_desc.hidden == 64
        job.mesh = MeshSpec({"data": 8})
        back = decode_object(json.loads(json.dumps(encode(job))))
        assert isinstance(back.mesh, MeshSpec)
        assert back.mesh.axes == {"data": 8}


LLAMA_1B = MODEL_ZOO["llama-1b"]


def auto_job(name="auto", topology="v5e-8", workers=2):
    job = make_tpujob(name, workers=workers, topology=get_slice(topology))
    job.mesh = "auto"
    job.model_desc = ModelDesc(
        layers=LLAMA_1B.layers, hidden=LLAMA_1B.hidden, ffn=LLAMA_1B.ffn,
        vocab=LLAMA_1B.vocab, seq_len=LLAMA_1B.seq_len,
        global_batch=LLAMA_1B.global_batch,
    )
    return job


class TestEngineAutoMesh:
    """mesh: auto end-to-end through the reconcile loop (tentpole): the
    planned layout reaches the pods via KUBEDL_MESH_AXES and the verdict is
    visible as annotation + status.plan + Planned condition/event/metrics."""

    def _setup(self):
        from kubedl_tpu.gang.slice_scheduler import SliceInventory

        inventory = SliceInventory()
        inventory.add_slice("s1", "v5e-8")
        engine, store, metrics = make_engine(inventory=inventory)
        return engine, store, metrics

    def test_planned_mesh_reaches_pods_and_status(self):
        engine, store, metrics = self._setup()
        got = submit_and_reconcile(engine, store, auto_job(), times=2)

        # the annotation is the plan cache, keyed on (topology, slices)
        ann = json.loads(got.metadata.annotations[constants.ANNOTATION_PLANNED_MESH])
        assert ann["topology"] == "v5e-8" and ann["slices"] == 1
        # llama-1b fits pure DP now that the sharded update divides the
        # optimizer state by the data axis (it needed fsdp=2 before)
        assert ann["axes"] == "data=8"
        # first plan pins the base DP degree for elastic grad-accum rescale
        assert got.metadata.annotations[constants.ANNOTATION_ELASTIC_BASE_DP] == "8"

        # status surface
        assert got.status.plan is not None
        assert got.status.plan.mesh == "data=8"
        assert got.status.plan.candidates_evaluated > 0
        conds = [c for c in got.status.conditions
                 if c.type == JobConditionType.PLANNED]
        assert conds and "data=8" in conds[0].message
        assert "dp baseline" in conds[0].message

        # the workers see exactly the planned layout
        pods = [store.get("Pod", n) for n in pod_names(store)]
        assert pods
        for pod in pods:
            assert env_of(pod)[constants.ENV_MESH_AXES] == "data=8"

        # observability: one plan, one Planned event
        assert metrics.plans.value(kind="TPUJob") == 1.0
        assert metrics.planner_candidates.value(kind="TPUJob") > 0
        events = [e for e in store.list("Event") if e.reason == "Planned"]
        assert len(events) == 1

    def test_cached_plan_is_not_recomputed(self):
        engine, store, metrics = self._setup()
        job = auto_job()
        submit_and_reconcile(engine, store, job, times=4)
        assert metrics.plans.value(kind="TPUJob") == 1.0
        assert len([e for e in store.list("Event") if e.reason == "Planned"]) == 1

    def test_explicit_mesh_skips_planning(self):
        engine, store, metrics = self._setup()
        job = make_tpujob(topology=get_slice("v5e-8"))
        job.mesh = MeshSpec({"data": 8})
        got = submit_and_reconcile(engine, store, job, times=2)
        assert constants.ANNOTATION_PLANNED_MESH not in got.metadata.annotations
        assert got.status.plan is None
        assert metrics.plans.value(kind="TPUJob") == 0.0
        pod = store.get("Pod", pod_names(store)[0])
        assert env_of(pod)[constants.ENV_MESH_AXES] == "data=8"

    def test_infeasible_model_fails_job_at_admission(self):
        engine, store, metrics = self._setup()
        job = auto_job("oom", topology="cpu-1", workers=1)
        got = submit_and_reconcile(engine, store, job)
        assert got.status.phase == JobConditionType.FAILED
        conds = [c for c in got.status.conditions
                 if c.type == JobConditionType.FAILED]
        assert conds and conds[0].reason == "PlanInfeasible"
        assert pod_names(store) == []  # fail at admission, not an OOM loop
        assert any(e.reason == "PlanInfeasible" for e in store.list("Event"))


class TestElasticReplan:
    """An elastic resize changes num_slices, which invalidates the plan
    cache key: the next reconcile re-plans for the new world size before
    the gang restarts (docs/elasticity.md §5)."""

    def _setup(self):
        from kubedl_tpu.gang.slice_scheduler import SliceInventory

        inventory = SliceInventory()
        inventory.add_slice("s1", "v5e-8")
        inventory.add_slice("s2", "v5e-8")
        engine, store, metrics = make_engine(inventory=inventory)
        job = auto_job("el")
        job.elastic = ElasticSpec(min_slices=1, max_slices=2)
        submit_and_reconcile(engine, store, job)
        return engine, store, metrics

    def test_resize_replans_for_new_world_size(self):
        engine, store, metrics = self._setup()
        got = store.get("TPUJob", "el")
        ann1 = json.loads(got.metadata.annotations[constants.ANNOTATION_PLANNED_MESH])
        assert ann1["slices"] == 1
        base_dp = got.metadata.annotations[constants.ANNOTATION_ELASTIC_BASE_DP]

        driver = PodDriver(store)
        for n in pod_names(store):
            driver.run(n)
        engine.reconcile("default", "el")
        assert store.get("TPUJob", "el").status.phase == JobConditionType.RUNNING

        def grow(j):
            j.num_slices = 2

        store.update_with_retry("TPUJob", "el", "default", grow)
        engine.reconcile("default", "el")  # re-plan + in-place resize
        engine.reconcile("default", "el")  # restart the gang at 2 slices

        got = store.get("TPUJob", "el")
        ann2 = json.loads(got.metadata.annotations[constants.ANNOTATION_PLANNED_MESH])
        assert ann2["slices"] == 2
        assert ann2["axes"].startswith("replica=2")
        assert ann2["axes"] != ann1["axes"]
        assert got.status.plan.mesh == ann2["axes"]

        # one plan per world size; the Planned event aggregates (count=2)
        # and carries the NEW verdict for the resized shape
        assert metrics.plans.value(kind="TPUJob") == 2.0
        events = [e for e in store.list("Event") if e.reason == "Planned"]
        assert len(events) == 1
        assert events[0].count == 2
        assert "2xv5e-8" in events[0].message

        # the base DP degree is pinned at first admission, NOT re-stamped:
        # grad-accum rescale compares against the shape the job was tuned at
        assert got.metadata.annotations[constants.ANNOTATION_ELASTIC_BASE_DP] == base_dp

        # the restarted gang runs the new layout
        pods = [store.get("Pod", n) for n in pod_names(store)]
        assert len(pods) == 4  # 2 hosts/slice x 2 slices
        for pod in pods:
            env = env_of(pod)
            assert env[constants.ENV_MESH_AXES] == ann2["axes"]
            assert env[constants.ENV_ELASTIC_BASE_DP] == base_dp
            assert env["MEGASCALE_NUM_SLICES"] == "2"


class TestPlannerMicrobench:
    def test_full_matrix_within_reconcile_budget(self):
        from scripts.scheduler_microbench import run_planner_microbench

        out = run_planner_microbench()
        # every catalog topology x zoo model resolves (plan or clean error)
        assert out["plans"] + out["infeasible"] == len(SLICE_CATALOG) * len(MODEL_ZOO)
        assert out["plans"] > 0 and out["candidates_evaluated"] > 0
        assert out["within_budget"], (
            f"plan() p95 {out['plan_ms_p95']} ms blew the "
            f"{out['budget_ms']} ms reconcile budget"
        )


class TestPlannedReshardResume:
    @pytest.mark.slow
    def test_planner_meshes_preserve_loss_trajectory_across_resize(self, tmp_path):
        """4 -> 2 -> 4 chip elastic run where the PLANNER picks the mesh at
        each shape and grad accumulation rescales in data-parallel units
        (elastic/resize.py data_parallel_world) — the trajectory must match
        the fixed-size run, same contract as TestReshardResume but with the
        layouts chosen by the cost model instead of typed by hand."""
        import jax
        import numpy as np

        from kubedl_tpu.elastic.resize import (
            data_parallel_world,
            grad_accum_for_world,
        )
        from kubedl_tpu.models import llama
        from kubedl_tpu.parallel.mesh import build_mesh
        from kubedl_tpu.training.checkpoint import restore_checkpoint
        from kubedl_tpu.training.data import SyntheticTokens
        from kubedl_tpu.training.trainer import TrainConfig, Trainer

        assert jax.device_count() >= 4
        model = llama.TINY
        GB, SL, STEPS = 8, 16, 9
        md = ModelDesc(layers=2, hidden=64, ffn=256, vocab=256,
                       seq_len=SL, global_batch=GB)
        # cpu stand-in shapes the catalog doesn't carry: 4 chips and 2
        topo4 = SliceTopology("cpu-4", 4, 4, 1, (4,), 0.5, 8.0, 50.0, 1.0, 0.5)
        topo2 = SliceTopology("cpu-2", 2, 2, 1, (2,), 0.5, 8.0, 50.0, 1.0, 0.5)
        p4 = plan(md, topo4)
        p2 = plan(md, topo2)
        assert p4.mesh.axes == {"data": 4}  # tiny fits: simplicity keeps DP
        assert p2.mesh.axes == {"data": 2}
        accum2 = grad_accum_for_world(
            1, data_parallel_world(p4.mesh), data_parallel_world(p2.mesh), GB
        )
        assert accum2 == 2

        def cfg(accum):
            return TrainConfig(model=model, global_batch=GB, seq_len=SL,
                               steps=STEPS, grad_accum=accum)

        def data_at(step):
            it = iter(SyntheticTokens(GB, SL, model.vocab_size, seed=5))
            for _ in range(step):
                next(it)
            return it

        def run(trainer, start, stop, ckpt):
            state = trainer.init_state()
            if start > 0:
                state = restore_checkpoint(ckpt, state)
                assert state is not None
            losses = []
            state, _ = trainer.fit(
                data_at(start), state=state, steps=stop,
                on_step=lambda i, m: losses.append(m["loss"]),
                ckpt_dir=ckpt,
            )
            return [float(jax.device_get(l)) for l in losses]

        mesh4 = build_mesh(p4.mesh, jax.devices()[:4])
        mesh2 = build_mesh(p2.mesh, jax.devices()[:2])

        baseline = run(Trainer(cfg(1), mesh4), 0, STEPS, str(tmp_path / "base"))
        ck = str(tmp_path / "elastic")
        losses = run(Trainer(cfg(1), mesh4), 0, 3, ck)
        losses += run(Trainer(cfg(accum2), mesh2), 3, 6, ck)
        losses += run(Trainer(cfg(1), mesh4), 6, STEPS, ck)
        assert len(losses) == STEPS
        np.testing.assert_allclose(losses, baseline, rtol=2e-3, atol=2e-3)

"""Model + trainer + sharding tests (CPU, 8 virtual devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubedl_tpu.api.topology import MeshSpec
from kubedl_tpu.models import llama
from kubedl_tpu.parallel.mesh import build_mesh
from kubedl_tpu.training.checkpoint import (
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)
from kubedl_tpu.training.data import SyntheticTokens
from kubedl_tpu.training.trainer import TrainConfig, Trainer

CFG = llama.TINY


@pytest.fixture(scope="module")
def params():
    return llama.llama_init(jax.random.PRNGKey(0), CFG)


class TestLlamaForward:
    def test_shapes_and_dtype(self, params):
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = llama.llama_forward(params, tokens, CFG)
        assert logits.shape == (2, 16, CFG.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self, params):
        """Changing a future token must not change past logits."""
        key = jax.random.PRNGKey(1)
        t1 = jax.random.randint(key, (1, 16), 0, CFG.vocab_size, jnp.int32)
        t2 = t1.at[0, 10].set((t1[0, 10] + 1) % CFG.vocab_size)
        l1 = llama.llama_forward(params, t1, CFG)
        l2 = llama.llama_forward(params, t2, CFG)
        np.testing.assert_allclose(l1[0, :10], l2[0, :10], atol=1e-5)
        assert not np.allclose(l1[0, 10:], l2[0, 10:], atol=1e-5)

    def test_rope_position_dependence(self):
        """Same vector at different positions -> different rotations, and
        relative position is preserved (dot product depends only on i-j)."""
        cos, sin = llama.rope_freqs(CFG, 8)
        v = jnp.ones((1, 8, 1, CFG.head_dim))
        r = llama.apply_rope(v, cos, sin)
        assert not np.allclose(r[0, 0, 0], r[0, 5, 0], atol=1e-4)
        # relative property: <r_i, r_j> == f(i - j)
        d01 = jnp.dot(r[0, 1, 0], r[0, 2, 0])
        d45 = jnp.dot(r[0, 4, 0], r[0, 5, 0])
        np.testing.assert_allclose(d01, d45, rtol=1e-5)

    def test_param_count_formula(self, params):
        actual = sum(x.size for x in jax.tree_util.tree_leaves(params))
        assert actual == CFG.num_params()

    def test_fuse_projections_parity(self, params):
        """fuse_projections rewrites QKV and gate/up as concat-and-slice
        (GQA: dq_w != dkv_w) — fused logits must equal unfused exactly
        (same dots, same order within each output column block)."""
        import dataclasses

        fused_cfg = dataclasses.replace(CFG, fuse_projections=True)
        tokens = jax.random.randint(
            jax.random.PRNGKey(3), (2, 16), 0, CFG.vocab_size, jnp.int32
        )
        l0 = llama.llama_forward(params, tokens, CFG)
        l1 = llama.llama_forward(params, tokens, fused_cfg)
        np.testing.assert_allclose(l0, l1, atol=1e-5, rtol=1e-5)

    def test_fuse_projections_disabled_on_tensor_mesh(self):
        """The trainer must force fusion OFF when the mesh has a >1
        tensor axis (concat along the megatron column-split dim would
        make GSPMD all-gather the shards)."""
        import dataclasses

        from kubedl_tpu.api.topology import MeshSpec
        from kubedl_tpu.parallel.mesh import build_mesh
        from kubedl_tpu.training.trainer import TrainConfig, Trainer

        fused = dataclasses.replace(CFG, fuse_projections=True)
        mesh = build_mesh(MeshSpec({"data": 4, "tensor": 2}), jax.devices()[:8])
        tr = Trainer(TrainConfig(model=fused, global_batch=4, seq_len=16), mesh)
        assert tr.cfg.model.fuse_projections is False
        # and stays ON for a pure data mesh
        mesh_dp = build_mesh(MeshSpec({"data": 8}), jax.devices()[:8])
        tr2 = Trainer(
            TrainConfig(model=fused, global_batch=8, seq_len=16), mesh_dp
        )
        assert tr2.cfg.model.fuse_projections is True

    def test_decode_matches_forward(self, params):
        """KV-cache decode must reproduce teacher-forced logits."""
        key = jax.random.PRNGKey(2)
        S = 8
        tokens = jax.random.randint(key, (1, S), 0, CFG.vocab_size, jnp.int32)
        full = llama.llama_forward(params, tokens, CFG)  # [1, S, V]
        cache = llama.init_cache(CFG, 1, S)
        step = jax.jit(
            lambda p, c, t: llama.decode_step(p, c, t, CFG)
        )
        for i in range(S):
            logits, cache = step(params, cache, tokens[:, i : i + 1])
            np.testing.assert_allclose(
                logits[0], full[0, i], atol=2e-2, rtol=2e-2
            )


class TestTrainer:
    def test_loss_decreases_on_memorization(self):
        cfg = TrainConfig(model=CFG, global_batch=4, seq_len=32, steps=30,
                          learning_rate=1e-2, warmup_steps=2)
        trainer = Trainer(cfg, build_mesh(MeshSpec({"data": 1}), jax.devices()[:1]))
        fixed = jax.random.randint(
            jax.random.PRNGKey(0), (4, 32), 0, CFG.vocab_size, jnp.int32
        )

        def repeat():
            while True:
                yield fixed

        state, summary = trainer.fit(repeat())
        assert summary["final_loss"] < np.log(CFG.vocab_size) * 0.8

    def test_sharded_training_dp_fsdp_tp(self):
        """Full train step over an 8-device dp2 x fsdp2 x tensor2 mesh."""
        assert jax.device_count() >= 8
        mesh = build_mesh(MeshSpec({"data": 2, "fsdp": 2, "tensor": 2}),
                          jax.devices()[:8])
        cfg = TrainConfig(model=CFG, global_batch=8, seq_len=32, steps=3)
        trainer = Trainer(cfg, mesh)
        data = SyntheticTokens(8, 32, CFG.vocab_size)
        state, summary = trainer.fit(iter(data))
        assert np.isfinite(summary["final_loss"])
        # params actually sharded: wq leaf must span multiple devices
        wq = state["params"]["layers"]["wq"]
        assert len(wq.sharding.device_set) > 1

    def test_grad_accum_matches_tokens(self):
        mesh = build_mesh(MeshSpec({"data": 2}), jax.devices()[:2])
        cfg = TrainConfig(model=CFG, global_batch=8, seq_len=16, steps=2,
                          grad_accum=2)
        trainer = Trainer(cfg, mesh)
        data = SyntheticTokens(8, 16, CFG.vocab_size)
        state, summary = trainer.fit(iter(data))
        assert np.isfinite(summary["final_loss"])
        assert int(jax.device_get(state["step"])) == 2


class TestCheckpoint:
    def test_roundtrip_and_latest(self, tmp_path):
        mesh = build_mesh(MeshSpec({"data": 2}), jax.devices()[:2])
        cfg = TrainConfig(model=CFG, global_batch=4, seq_len=16, steps=2)
        trainer = Trainer(cfg, mesh)
        data = SyntheticTokens(4, 16, CFG.vocab_size)
        state, _ = trainer.fit(iter(data))
        save_checkpoint(str(tmp_path), state, 2)
        assert latest_step(str(tmp_path)) == 2
        fresh = trainer.init_state()
        restored = restore_checkpoint(str(tmp_path), fresh)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(restored["params"]["embed"])),
            np.asarray(jax.device_get(state["params"]["embed"])),
        )
        # restored leaves keep the target shardings
        assert (
            restored["params"]["embed"].sharding
            == fresh["params"]["embed"].sharding
        )


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__ as ge

        fn, args = ge.entry()
        out = jax.jit(fn)(*args)
        jax.block_until_ready(out)
        assert out.shape[0] == args[1].shape[0]

    @pytest.mark.slow  # 8 fake XLA devices on a 1-core box: minutes of
    # compile alone, reliably past the tier-1 wall-clock budget
    def test_dryrun_multichip(self):
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)


class TestCheckpointIntegrity:
    def test_incomplete_step_falls_back_to_previous(self, tmp_path):
        """A save torn by preemption (missing shard file) must not block
        resume: restore skips it and loads the previous good step."""
        mesh = build_mesh(MeshSpec({"data": 2}), jax.devices()[:2])
        cfg = TrainConfig(model=CFG, global_batch=4, seq_len=16, steps=2)
        trainer = Trainer(cfg, mesh)
        data = SyntheticTokens(4, 16, CFG.vocab_size)
        state, _ = trainer.fit(iter(data))
        save_checkpoint(str(tmp_path), state, 2)
        # forge a torn newer save: manifest present, shard file missing
        torn = tmp_path / "step-00000004"
        torn.mkdir()
        import json as _json

        (torn / "meta.json").write_text(_json.dumps(
            {"step": 4, "nprocs": 1, "leaves": {}}))
        (tmp_path / "latest").write_text("step-00000004")
        restored = restore_checkpoint(str(tmp_path), trainer.init_state())
        assert restored is not None
        assert int(jax.device_get(restored["step"])) == 2

    def test_partial_shards_raise_not_zero_fill(self, tmp_path):
        """Missing shard pieces must raise, never restore as zeros."""
        import numpy as _np
        import json as _json

        from kubedl_tpu.training.checkpoint import IncompleteCheckpoint

        d = tmp_path / "step-00000001"
        d.mkdir()
        # claim a (4,) leaf but provide only 2 elements' worth of shard
        (d / "meta.json").write_text(_json.dumps(
            {"step": 1, "nprocs": 1,
             "leaves": {"['x']": {"shape": [4], "dtype": "float32"}}}))
        _np.savez(d / "shards-p0.npz", **{"['x']@0": _np.zeros(2, _np.float32)})
        (tmp_path / "latest").write_text("step-00000001")
        like = {"x": jnp.zeros((4,), jnp.float32)}
        with pytest.raises(IncompleteCheckpoint):
            restore_checkpoint(str(tmp_path), like, step=1)
        # without an explicit step, the torn save is skipped -> None
        assert restore_checkpoint(str(tmp_path), like) is None


class TestTrainerAttnSelection:
    def test_forced_flash_runs_in_interpret_mode(self):
        from kubedl_tpu.ops import flash_attention_module as fa

        mesh = build_mesh(MeshSpec({"data": 1}), jax.devices()[:1])
        cfg = TrainConfig(model=CFG, global_batch=2, seq_len=32, steps=1,
                          attn_impl="flash")
        before = fa.TRACE_COUNT
        trainer = Trainer(cfg, mesh)
        assert trainer.attn_impl == "flash"
        data = SyntheticTokens(2, 32, CFG.vocab_size)
        _, summary = trainer.fit(iter(data), steps=1)
        assert summary["attn_impl"] == "flash"
        assert fa.TRACE_COUNT > before  # kernel actually traced
        assert np.isfinite(summary["final_loss"])

    def test_flash_matches_dense_loss(self):
        mesh = build_mesh(MeshSpec({"data": 1}), jax.devices()[:1])
        data = SyntheticTokens(2, 32, CFG.vocab_size)
        batch = next(iter(data))
        losses = {}
        for impl in ("dense", "flash"):
            cfg = TrainConfig(model=CFG, global_batch=2, seq_len=32, steps=1,
                              attn_impl=impl, seed=7)
            trainer = Trainer(cfg, mesh)
            state = trainer.init_state()
            with trainer.mesh:
                _, metrics = trainer.train_step(state, trainer.shard_batch(batch))
            losses[impl] = float(jax.device_get(metrics["loss"]))
        assert abs(losses["dense"] - losses["flash"]) < 1e-3


class TestSanityGates:
    def test_impossible_mfu_flagged(self):
        mesh = build_mesh(MeshSpec({"data": 1}), jax.devices()[:1])
        trainer = Trainer(TrainConfig(model=CFG), mesh)
        v = trainer.sanity_check({"mfu": 5.38, "step_time_ms": 100.0,
                                  "steps": 2})
        assert any("impossible" in x for x in v)

    def test_loss_increase_flagged(self):
        mesh = build_mesh(MeshSpec({"data": 1}), jax.devices()[:1])
        trainer = Trainer(TrainConfig(model=CFG), mesh)
        v = trainer.sanity_check({"mfu": 0.3, "step_time_ms": 100.0,
                                  "steps": 20, "first_loss": 5.0,
                                  "final_loss": 5.5})
        assert any("decrease" in x for x in v)

    def test_clean_summary_passes(self):
        mesh = build_mesh(MeshSpec({"data": 1}), jax.devices()[:1])
        trainer = Trainer(TrainConfig(model=CFG), mesh)
        v = trainer.sanity_check({"mfu": 0.3, "step_time_ms": 100.0,
                                  "steps": 20, "first_loss": 5.0,
                                  "final_loss": 4.5})
        assert v == []


class TestResumeSemantics:
    def test_fit_resumes_from_restored_step(self, tmp_path):
        """steps is a TOTAL budget: a state restored at step k trains only
        steps-k more (the checkpoint-resume contract)."""
        mesh = build_mesh(MeshSpec({"data": 2}), jax.devices()[:2])
        cfg = TrainConfig(model=CFG, global_batch=4, seq_len=16, steps=6,
                          ckpt_every=2)
        trainer = Trainer(cfg, mesh)
        data = SyntheticTokens(4, 16, CFG.vocab_size)
        executed = []
        # phase 1: train 3 of 6 steps, checkpointing every 2
        trainer.fit(iter(data), steps=3, ckpt_dir=str(tmp_path),
                    on_step=lambda i, m: executed.append(i))
        assert latest_step(str(tmp_path)) == 3
        # phase 2 (the "restarted gang"): restore and finish the budget
        restored = restore_checkpoint(str(tmp_path), trainer.init_state())
        resumed_steps = []
        state, summary = trainer.fit(
            iter(data), state=restored, steps=6,
            on_step=lambda i, m: resumed_steps.append(i))
        assert resumed_steps == [3, 4, 5]
        assert int(jax.device_get(state["step"])) == 6
        assert summary["start_step"] == 3


class TestGemmaFamily:
    def test_tiny_gemma_trains_and_loss_decreases(self):
        from kubedl_tpu.models.llama import preset

        cfg_m = preset("tiny-gemma")
        mesh = build_mesh(MeshSpec({"data": 2}), jax.devices()[:2])
        cfg = TrainConfig(model=cfg_m, global_batch=4, seq_len=16, steps=12,
                          learning_rate=1e-2, warmup_steps=1)
        trainer = Trainer(cfg, mesh)
        data = SyntheticTokens(4, 16, cfg_m.vocab_size)
        _, summary = trainer.fit(iter(data))
        assert np.isfinite(summary["final_loss"])
        assert summary["final_loss"] < summary["first_loss"]

    def test_gemma_decode_matches_forward(self):
        """Batched KV-cache decode must agree with the full forward on the
        same prefix (argmax next-token parity), Gemma knobs included."""
        import jax.numpy as jnp

        from kubedl_tpu.models import llama

        cfg = llama.preset("tiny-gemma")
        params = llama.llama_init(jax.random.PRNGKey(1), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(2), (1, 7), 0,
                                  cfg.vocab_size)
        logits_full = llama.llama_forward(params, toks, cfg)  # [1, 7, V]
        cache = llama.init_batched_cache(cfg, 1, 16)
        logits = None
        for i in range(7):
            logits, cache = llama.decode_step_batched(
                params, cache, toks[:, i:i + 1], cfg
            )
        np.testing.assert_allclose(
            np.asarray(logits[0]), np.asarray(logits_full[0, -1]),
            rtol=2e-4, atol=2e-4,
        )

    def test_gemma_2b_config_sanity(self):
        from kubedl_tpu.models.llama import preset

        cfg = preset("gemma-2b")
        assert 2.4e9 < cfg.num_params() < 2.6e9
        assert cfg.head_dim == 256 and cfg.n_kv_heads == 1


def test_restore_region_reads_are_lazy(tmp_path):
    """ADVICE r2 #1: restoring a sharded leaf must assemble only the
    requested region, not the global array — non-overlapping npz entries
    are never decompressed (shard shapes ride the entry keys)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from kubedl_tpu.training import checkpoint as ck

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    big = jax.device_put(jnp.arange(64.0).reshape(8, 8), sharding)
    state = {"w": big}
    ck.save_checkpoint(str(tmp_path), state, 1)

    store = ck._ShardStore(tmp_path / "step-00000001")
    # shard keys carry their shape (no decompression needed for overlap)
    assert any("+" in k for k in store.index), list(store.index)
    # region read: rows 2..4 only
    reg = store.region("['w']", (8, 8), np.float32, (slice(2, 4), slice(0, 8)))
    np.testing.assert_array_equal(reg, np.arange(64.0).reshape(8, 8)[2:4])
    # count which entries actually get decompressed for a 1-shard region
    loads = []
    orig_files = store.files

    class Counting:
        def __init__(self, f):
            self._f = f
            self.files = f.files
        def __getitem__(self, k):
            loads.append(k)
            return self._f[k]

    store.files = [Counting(f) for f in orig_files]
    store.index = {k: (i, k2) for k, (i, k2) in store.index.items()}
    store.region("['w']", (8, 8), np.float32, (slice(0, 2), slice(0, 8)))
    assert len(loads) == 1, loads  # only the overlapping shard was read

    # full round-trip still lands every element on its sharding
    template = {"w": jax.device_put(jnp.zeros((8, 8)), sharding)}
    restored = ck.restore_checkpoint(str(tmp_path), template)
    np.testing.assert_array_equal(
        np.asarray(restored["w"]), np.arange(64.0).reshape(8, 8)
    )


def test_builder_rejects_registry_inside_model_dir(tmp_path):
    """A registry nested inside the model dir must fail loudly instead of
    copytree-ing the tree into its own subtree (unbounded recursion)."""
    import pytest

    from kubedl_tpu.lineage.builder import (
        ArtifactRegistry, BuildError, LocalBundleBuilder,
    )

    (tmp_path / "ckpt.bin").write_bytes(b"w")
    reg = ArtifactRegistry(str(tmp_path / "registry"))
    builder = LocalBundleBuilder(reg)
    with pytest.raises(BuildError, match="inside model dir"):
        builder.build(str(tmp_path), "m", "v1")


def test_torn_save_fails_uniformly_not_just_on_affected_region(tmp_path):
    """Review r3: region-lazy reads must NOT make torn-save detection
    process-local. A checkpoint missing ONE process's shard pieces must
    raise on every process — even one whose own regions are fully covered
    — so a multi-host gang never resumes from divergent steps."""
    import json as _json

    import jax
    import jax.numpy as jnp
    import numpy as np
    import pytest
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from kubedl_tpu.training import checkpoint as ck

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    state = {"w": jax.device_put(jnp.arange(64.0).reshape(8, 8), sharding)}
    ck.save_checkpoint(str(tmp_path), state, 1)
    d = tmp_path / "step-00000001"

    # forge a torn save: drop HALF the shard entries from the npz (keeps
    # the file itself present so the nprocs file-count check passes)
    f = np.load(d / "shards-p0.npz")
    keys = sorted(f.files)
    kept = {k: f[k] for k in keys[: len(keys) // 2]}
    np.savez(d / "shards-p0.npz", **kept)

    store = ck._ShardStore(d)
    # a region fully covered by the KEPT shards still assembles fine...
    first_key = sorted(kept)[0]
    base = first_key.split("@")[0]
    # ...but the global coverage check fails for the leaf
    with pytest.raises(ck.IncompleteCheckpoint):
        store.validate_coverage(base, (8, 8))
    # and restore_checkpoint refuses the step entirely (falls back to None)
    template = {"w": jax.device_put(jnp.zeros((8, 8)), sharding)}
    assert ck.restore_checkpoint(str(tmp_path), template) is None


def test_chunked_loss_matches_full(tmp_path):
    """cfg.loss_chunk must not change the loss value or its gradient —
    only the peak memory (the [B,S,V] logits never materialize)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubedl_tpu.models import llama

    cfg = llama.TINY
    params = llama.llama_init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 37), 0,
                                cfg.vocab_size)  # odd S: exercises padding
    cfg_chunked = dataclasses.replace(cfg, loss_chunk=16)
    full = jax.jit(lambda p, t: llama.llama_loss(p, t, cfg))
    chunked = jax.jit(lambda p, t: llama.llama_loss(p, t, cfg_chunked))
    np.testing.assert_allclose(float(full(params, tokens)),
                               float(chunked(params, tokens)),
                               rtol=1e-5, atol=1e-5)
    g_full = jax.jit(jax.grad(lambda p: llama.llama_loss(p, tokens, cfg)))(params)
    g_chunk = jax.jit(jax.grad(
        lambda p: llama.llama_loss(p, tokens, cfg_chunked)
    ))(params)
    for kf, kc in zip(jax.tree_util.tree_leaves(g_full),
                      jax.tree_util.tree_leaves(g_chunk)):
        np.testing.assert_allclose(np.asarray(kf), np.asarray(kc),
                                   rtol=2e-4, atol=2e-4)


class TestConvNet:
    """The MNIST-class convergence family (BASELINE target 1 analogue)."""

    def test_forward_shapes(self):
        import jax

        from kubedl_tpu.models import convnet

        cfg = convnet.ConvNetConfig(width=8, hidden=16)
        params = convnet.convnet_init(jax.random.PRNGKey(0), cfg)
        imgs = jax.numpy.zeros((4, 28, 28, 1))
        logits = convnet.convnet_forward(params, imgs, cfg)
        assert logits.shape == (4, 10)

    def test_converges_on_synthetic_digits(self):
        from kubedl_tpu.models import convnet

        cfg = convnet.ConvNetConfig(width=8, hidden=32)
        data = convnet.SyntheticDigits(cfg, batch=64)
        params, s = convnet.fit(cfg, iter(data), steps=120, learning_rate=3e-3)
        assert s["final_loss"] < s["first_loss"]
        imgs, labels = next(iter(convnet.SyntheticDigits(cfg, 256, seed=7)))
        acc = convnet.accuracy(params, imgs, labels, cfg)
        assert acc > 0.9, acc  # chance is 0.1


def test_mnist_example_through_operator(tmp_path):
    """BASELINE target 1 done-criterion: the MNIST-class workload
    CONVERGES as a pod scheduled end-to-end by the operator (the example
    script exits nonzero unless accuracy >= 90%)."""
    import sys as _sys

    from tests.helpers import make_tpujob

    from kubedl_tpu.api.types import JobConditionType
    from kubedl_tpu.operator import Operator, OperatorOptions
    from kubedl_tpu.runtime.executor import SubprocessRuntime

    logs = str(tmp_path / "logs")
    opts = OperatorOptions(
        local_addresses=True, pod_log_dir=logs,
        artifact_registry_root=str(tmp_path / "reg"),
        compile_cache_dir=str(tmp_path / "cc"),
    )
    import pathlib

    script = pathlib.Path(__file__).resolve().parents[1] / "examples" / "mnist_convnet.py"
    with Operator(opts, runtime=SubprocessRuntime(logs)) as op:
        job = make_tpujob(
            "mnist", workers=1,
            command=[_sys.executable, str(script), "--steps", "80",
                     "--batch", "64", "--min-accuracy", "0.85"],
        )
        op.submit(job)
        got = op.wait_for_phase(
            "TPUJob", "mnist",
            [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
            timeout=300,
        )
        assert got.status.phase == JobConditionType.SUCCEEDED
    log = pathlib.Path(logs) / "default" / "mnist-worker-0.log"
    import json as _json

    summary = None
    for line in log.read_text().splitlines():
        if "worker_summary" in line:
            summary = _json.loads(line)["worker_summary"]
    assert summary and summary["accuracy"] >= 0.85, summary

"""Native data loader tests (C++ prefetch ring + numpy fallback)."""

import numpy as np
import pytest

from kubedl_tpu.data import TokenFileDataset, native_available
from kubedl_tpu.data.native import NativeTokenLoader, _NumpyTokenLoader


@pytest.fixture()
def token_file(tmp_path):
    toks = np.arange(10_000, dtype=np.int32) % 1000
    p = tmp_path / "tokens.bin"
    toks.tofile(p)
    return str(p), toks


def test_native_loader_builds_and_samples(token_file):
    path, toks = token_file
    if not native_available():
        pytest.skip("no g++ in this environment")
    ld = NativeTokenLoader(path, batch=4, seq=64, seed=7)
    try:
        assert ld.n_tokens == 10_000
        b = ld.next()
        assert b.shape == (4, 64) and b.dtype == np.int32
        # every row is a contiguous window of the source stream
        for row in b:
            start = int(row[0]) if row[0] == toks[row[0]] else None
            diffs = np.diff(row.astype(np.int64)) % 1000
            assert set(diffs.tolist()) <= {1, -999 % 1000}
        # deterministic: same seed -> same batches
        ld2 = NativeTokenLoader(path, batch=4, seq=64, seed=7)
        np.testing.assert_array_equal(ld2.next(), b)
        ld2.close()
    finally:
        ld.close()


def test_native_prefetch_many_batches(token_file):
    path, _ = token_file
    if not native_available():
        pytest.skip("no g++ in this environment")
    ld = NativeTokenLoader(path, batch=8, seq=128, prefetch=4)
    try:
        for _ in range(50):
            b = ld.next()
            assert b.shape == (8, 128)
            assert (b >= 0).all() and (b < 1000).all()
    finally:
        ld.close()


def test_numpy_fallback_same_contract(token_file):
    path, _ = token_file
    ld = _NumpyTokenLoader(path, batch=4, seq=64, seed=7)
    b = ld.next()
    assert b.shape == (4, 64) and b.dtype == np.int32
    diffs = np.diff(b.astype(np.int64), axis=1) % 1000
    assert set(np.unique(diffs).tolist()) <= {1}


def test_token_file_dataset_feeds_trainer(token_file, tmp_path):
    """End to end: a token FILE (not synthetic) through the trainer."""
    import jax

    from kubedl_tpu.api.topology import MeshSpec
    from kubedl_tpu.models import llama
    from kubedl_tpu.parallel.mesh import build_mesh
    from kubedl_tpu.training.trainer import TrainConfig, Trainer

    path, _ = token_file
    mesh = build_mesh(MeshSpec({"data": 2}), jax.devices()[:2])
    cfg = TrainConfig(model=llama.TINY, global_batch=4, seq_len=32, steps=2)
    trainer = Trainer(cfg, mesh)
    data = TokenFileDataset(path, 4, 32, seed=1)
    try:
        toks_iter = (np.clip(b, 0, llama.TINY.vocab_size - 1) for b in data)
        state, summary = trainer.fit(toks_iter)
        assert np.isfinite(summary["final_loss"])
    finally:
        data.close()


def test_bad_file_raises(tmp_path):
    small = tmp_path / "small.bin"
    np.arange(4, dtype=np.int32).tofile(small)
    with pytest.raises((FileNotFoundError, RuntimeError)):
        TokenFileDataset(str(small), 2, 64)

"""Persistence subsystem tests.

Reference analogue: pkg/storage/dmo/converters/{job,pod,event}_test.go
(pure-function conversion tables) + controllers/persist behavior, exercised
here through the live operator the way the reference's persist controllers
ride real informers.
"""

import json
import time

from kubedl_tpu.api import constants
from kubedl_tpu.api.types import JobConditionType, ReplicaType
from kubedl_tpu.core.objects import (
    ContainerStatus,
    Event,
    OwnerRef,
    Pod,
    PodPhase,
)
from kubedl_tpu.operator import Operator, OperatorOptions
from kubedl_tpu.persist import Query, SQLiteBackend, default_registry
from kubedl_tpu.persist.dmo import event_to_dmo, job_to_dmo, pod_to_dmo, to_jsonable
from kubedl_tpu.runtime.executor import ThreadRuntime

from tests.helpers import make_tpujob


# ---- converters (pure functions, table style) -----------------------------


def test_job_to_dmo_basic():
    job = make_tpujob("conv", workers=2)
    job.metadata.annotations[constants.ANNOTATION_TENANCY] = "team-a"
    job.metadata.annotations[constants.ANNOTATION_OWNER] = "alice"
    job.status.set_condition(JobConditionType.RUNNING)
    job.status.start_time = 123.0
    row = job_to_dmo(job, region="us-east1")
    assert row.kind == "TPUJob"
    assert row.phase == "Running"
    assert row.tenant == "team-a"
    assert row.owner == "alice"
    assert row.region == "us-east1"
    assert row.started_at == 123.0
    payload = json.loads(row.payload)
    assert payload["metadata"]["name"] == "conv"
    # enum-keyed dicts lower to their values
    assert "Worker" in payload["spec"]["replica_specs"]


def test_pod_to_dmo_labels_and_exit_code():
    pod = Pod()
    pod.metadata.name = "conv-worker-1"
    pod.metadata.labels = {
        constants.LABEL_JOB_NAME: "conv",
        constants.LABEL_REPLICA_TYPE: "Worker",
        constants.LABEL_REPLICA_INDEX: "1",
    }
    pod.metadata.owner_refs.append(OwnerRef(kind="TPUJob", name="conv", uid="uid-1"))
    pod.spec.node_name = "host-3"
    pod.status.phase = PodPhase.FAILED
    pod.status.container_statuses = [ContainerStatus(exit_code=137)]
    row = pod_to_dmo(pod)
    assert row.job_uid == "uid-1"
    assert row.job_name == "conv"
    assert row.replica_type == "Worker"
    assert row.replica_index == 1
    assert row.node == "host-3"
    assert row.exit_code == 137
    assert row.phase == "Failed"


def test_event_to_dmo():
    ev = Event(
        involved_kind="TPUJob", involved_name="conv", type="Warning",
        reason="Failed", message="boom", count=3,
    )
    ev.metadata.name = "conv.failed"
    row = event_to_dmo(ev, region="eu")
    assert row.involved_kind == "TPUJob"
    assert row.count == 3
    assert row.region == "eu"


def test_to_jsonable_round_trips_job():
    job = make_tpujob("json", workers=1)
    blob = json.dumps(to_jsonable(job))
    back = json.loads(blob)
    assert back["spec"]["replica_specs"]["Worker"]["replicas"] == 1


# ---- SQLite backend (reference: mysql.go semantics) ----------------------


def test_sqlite_job_upsert_and_query():
    b = SQLiteBackend(":memory:")
    b.initialize()
    job = make_tpujob("q1", workers=1)
    row = job_to_dmo(job)
    b.save_job(row)
    row.phase = "Running"
    b.save_job(row)  # upsert, not duplicate
    jobs = b.list_jobs(Query())
    assert len(jobs) == 1 and jobs[0].phase == "Running"
    assert b.get_job("default", "q1").uid == row.uid

    # filters
    assert b.list_jobs(Query(kind="TPUJob"))
    assert not b.list_jobs(Query(kind="TFJob"))
    assert b.list_jobs(Query(phase="Running"))
    assert b.list_jobs(Query(name="q"))  # substring match
    assert not b.list_jobs(Query(namespace="other"))

    # soft delete keeps history
    b.mark_job_deleted("default", "q1", "TPUJob")
    got = b.get_job("default", "q1")
    assert got.deleted and not got.is_in_etcd
    assert not b.list_jobs(Query(include_deleted=False))
    b.remove_job_record("default", "q1")
    assert b.get_job("default", "q1") is None
    b.close()


def test_sqlite_pods_and_events():
    b = SQLiteBackend(":memory:")
    b.initialize()
    pod = Pod()
    pod.metadata.name = "p0"
    pod.metadata.owner_refs.append(OwnerRef(kind="TPUJob", name="j", uid="uid-9"))
    row = pod_to_dmo(pod)
    b.save_pod(row)
    row.phase = "Running"
    b.save_pod(row)
    pods = b.list_pods("uid-9")
    assert len(pods) == 1 and pods[0].phase == "Running"
    b.mark_pod_deleted("default", "p0")
    assert b.list_pods("uid-9")[0].deleted

    ev = Event(involved_kind="TPUJob", involved_name="j", reason="Created",
               message="ok")
    ev.metadata.name = "j.created"
    b.save_event(event_to_dmo(ev))
    ev.count = 2
    b.save_event(event_to_dmo(ev))  # dedup by (ns, name)
    events = b.list_events("TPUJob", "j")
    assert len(events) == 1 and events[0].count == 2
    b.close()


def test_registry_unknown_backend():
    reg = default_registry()
    try:
        reg.object_backend("mysql")
    except KeyError as e:
        assert "sqlite" in str(e)
    else:
        raise AssertionError("expected KeyError")


# ---- live mirror through the operator ------------------------------------


def test_persist_controllers_mirror_job_lifecycle(tmp_path):
    opts = OperatorOptions(
        local_addresses=True,
        artifact_registry_root=str(tmp_path / "reg"),
        meta_storage="sqlite",
        event_storage="sqlite",
        region="test-region",
    )
    with Operator(opts, runtime=ThreadRuntime()) as op:
        job = make_tpujob("mirror", workers=2, entrypoint="tests.test_persist:_noop")
        op.submit(job)
        op.wait_for_phase("TPUJob", "mirror", [JobConditionType.SUCCEEDED], timeout=120)

        backend = op.object_backend

        def mirrored() -> bool:
            row = backend.get_job("default", "mirror", "TPUJob")
            return row is not None and row.phase == "Succeeded"

        assert op.manager.wait(mirrored, timeout=60)
        row = backend.get_job("default", "mirror", "TPUJob")
        assert row.region == "test-region"
        assert row.finished_at is not None
        pods = backend.list_pods(row.uid)
        assert len(pods) == 2
        assert {p.replica_index for p in pods} == {0, 1}
        assert all(p.phase == "Succeeded" for p in pods)
        # events mirrored too
        events = op.event_backend.list_events("TPUJob", "mirror")
        assert events, "expected mirrored events"

        # deleting the live job soft-deletes the mirror row
        op.store.delete("TPUJob", "mirror")

        def soft_deleted() -> bool:
            r = backend.get_job("default", "mirror", "TPUJob")
            return r is not None and r.deleted and not r.is_in_etcd

        assert op.manager.wait(soft_deleted, timeout=60)


def _noop(env):
    return 0


# ---- second backend: the JSONL log store ----------------------------------


def _jsonl(tmp_path):
    from kubedl_tpu.persist.jsonl_backend import JSONLBackend

    b = JSONLBackend(str(tmp_path / "log"))
    b.initialize()
    return b


def test_jsonl_job_contract_matches_sqlite(tmp_path):
    """The JSONL backend honors the same ObjectStorageBackend contract the
    SQLite tests pin down (upsert, filters, soft delete, removal)."""
    b = _jsonl(tmp_path)
    job = make_tpujob("q1", workers=1)
    row = job_to_dmo(job)
    b.save_job(row)
    row.phase = "Running"
    b.save_job(row)
    jobs = b.list_jobs(Query())
    assert len(jobs) == 1 and jobs[0].phase == "Running"
    assert b.get_job("default", "q1").uid == row.uid
    assert b.list_jobs(Query(kind="TPUJob"))
    assert not b.list_jobs(Query(kind="TFJob"))
    assert b.list_jobs(Query(phase="Running"))
    assert b.list_jobs(Query(name="q"))  # substring match
    assert not b.list_jobs(Query(namespace="other"))
    b.mark_job_deleted("default", "q1", "TPUJob")
    got = b.get_job("default", "q1")
    assert got.deleted and not got.is_in_etcd
    assert not b.list_jobs(Query(include_deleted=False))
    b.remove_job_record("default", "q1")
    assert b.get_job("default", "q1") is None
    # the raw log still holds the full history (log-store property)
    raw = (tmp_path / "log" / "jobs.jsonl").read_text()
    assert raw.count("\n") >= 4
    b.close()


def test_jsonl_pods_events_and_restart_durability(tmp_path):
    b = _jsonl(tmp_path)
    pod = Pod()
    pod.metadata.name = "p0"
    pod.metadata.owner_refs.append(OwnerRef(kind="TPUJob", name="j", uid="uid-9"))
    row = pod_to_dmo(pod)
    b.save_pod(row)
    row.phase = "Running"
    b.save_pod(row)
    pods = b.list_pods("uid-9")
    assert len(pods) == 1 and pods[0].phase == "Running"
    b.mark_pod_deleted("default", "p0")
    assert b.list_pods("uid-9")[0].deleted

    ev = Event(involved_kind="TPUJob", involved_name="j", reason="Created",
               message="ok")
    ev.metadata.name = "j.created"
    b.save_event(event_to_dmo(ev))
    ev.count = 2
    b.save_event(event_to_dmo(ev))
    events = b.list_events("TPUJob", "j")
    assert len(events) == 1 and events[0].count == 2
    b.close()

    # a fresh backend over the same root sees everything (durability)
    b2 = _jsonl(tmp_path)
    assert b2.list_pods("uid-9")
    assert b2.list_events("TPUJob", "j")
    b2.close()


def test_registry_serves_both_backends(tmp_path):
    reg = default_registry(str(tmp_path / "meta.db"))
    from kubedl_tpu.persist.jsonl_backend import JSONLBackend
    from kubedl_tpu.persist.sqlite_backend import SQLiteBackend

    assert isinstance(reg.object_backend("sqlite"), SQLiteBackend)
    assert isinstance(reg.object_backend("jsonl"), JSONLBackend)
    # object + event roles share one instance per backend name
    assert reg.object_backend("jsonl") is reg.event_backend("jsonl")


def test_operator_mirrors_to_jsonl(tmp_path):
    """meta-storage=jsonl end to end: operator mirrors jobs/pods/events into
    the log files (the --meta-storage flag path, persist_controller.go)."""
    from kubedl_tpu.api.types import JobConditionType
    from kubedl_tpu.operator import Operator, OperatorOptions
    from kubedl_tpu.runtime.executor import SubprocessRuntime

    opts = OperatorOptions(
        local_addresses=True,
        pod_log_dir=str(tmp_path / "logs"),
        artifact_registry_root=str(tmp_path / "reg"),
        meta_storage="jsonl",
        event_storage="jsonl",
        storage_db_path=str(tmp_path / "meta.db"),
    )
    with Operator(opts, runtime=SubprocessRuntime(str(tmp_path / "logs"))) as op:
        job = make_tpujob("mj", workers=1, command=["python", "-c", "pass"])
        op.submit(job)
        op.wait_for_phase("TPUJob", "mj", [JobConditionType.SUCCEEDED], timeout=30)
        backend = op.object_backend
        deadline = time.time() + 10
        while time.time() < deadline:
            row = backend.get_job("default", "mj", "TPUJob")
            if row is not None and row.phase == "Succeeded":
                break
            time.sleep(0.2)
        assert row is not None and row.phase == "Succeeded"
        assert backend.list_pods(job.metadata.uid)
    root = tmp_path / "meta.db.jsonl.d"
    assert (root / "jobs.jsonl").exists()
    assert (root / "pods.jsonl").exists()

"""Sharded control plane: shard map stability, lease fencing, cross-shard
watch fan-out, N=1 behavioral identity, routed expectations, and the
shard-map routing budget."""

import os
import threading
import time

import pytest

from kubedl_tpu import chaos
from kubedl_tpu.core.leases import Lease
from kubedl_tpu.core.manager import ControllerManager, owner_mapper
from kubedl_tpu.core.objects import Event, OwnerRef, Pod
from kubedl_tpu.core.store import AlreadyExists, NotFound, ObjectStore
from kubedl_tpu.engine.expectations import ShardedExpectations
from kubedl_tpu.shards import (
    FencedOut,
    FencedWal,
    FileLeaseStore,
    ShardFence,
    ShardMap,
    ShardedObjectStore,
    acquire_shard_lease,
    shard_lease_name,
)
from kubedl_tpu.workloads.tpujob import TPUJob


def _job(name, namespace="default"):
    job = TPUJob()
    job.metadata.name = name
    job.metadata.namespace = namespace
    return job


def _pod(name, owner=None, namespace="default"):
    pod = Pod()
    pod.metadata.name = name
    pod.metadata.namespace = namespace
    if owner is not None:
        pod.metadata.owner_refs.append(OwnerRef(
            kind=owner.kind, name=owner.metadata.name,
            uid=owner.metadata.uid, controller=True,
        ))
    return pod


class TestShardMap:
    def test_deterministic_and_in_range(self):
        a, b = ShardMap(4), ShardMap(4)
        for i in range(1000):
            key = f"ns/{i}"
            assert a.lookup(key) == b.lookup(key)
            assert 0 <= a.lookup(key) < 4

    def test_single_shard_fast_path(self):
        sm = ShardMap(1)
        assert all(sm.lookup(f"k{i}") == 0 for i in range(100))

    def test_resize_stability_property(self):
        """HRW growth N -> N+1 over 10k keys: only ~1/(N+1) + eps of keys
        move, and every moved key lands ON the new shard — no shuffling
        between pre-existing shards."""
        keys = [f"ns-{i % 11}/job-{i:05d}" for i in range(10_000)]
        for n in (2, 4, 8):
            before = ShardMap(n)
            after = ShardMap(n + 1)
            moved = 0
            for key in keys:
                src, dst = before.lookup(key), after.lookup(key)
                if src != dst:
                    moved += 1
                    assert dst == n, (
                        f"key {key} moved {src}->{dst}, not onto new shard {n}"
                    )
            expected = len(keys) / (n + 1)
            # binomial spread over 10k trials: 35% relative headroom
            assert moved <= expected * 1.35, (n, moved, expected)
            assert moved >= expected * 0.65, (n, moved, expected)

    def test_spread_is_balanced(self):
        sm = ShardMap(4)
        counts = sm.spread([f"ns/{i}" for i in range(10_000)])
        assert sum(counts.values()) == 10_000
        assert min(counts.values()) > 0.8 * (10_000 / 4)
        assert max(counts.values()) < 1.2 * (10_000 / 4)

    def test_memo_cache_bounded(self):
        sm = ShardMap(4)
        for i in range(sm._CACHE_MAX * 2 + 10):
            sm.lookup(f"k{i}")
        assert len(sm._cache) <= sm._CACHE_MAX

    def test_rejects_zero_shards(self):
        with pytest.raises(ValueError):
            ShardMap(0)


class TestShardMapBudget:
    def test_routing_budget_p95(self):
        """Tier-1 gate on the routing hot path: p95 key->shard <= 5us
        over 100k keys (scripts/scheduler_microbench.py section)."""
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
        ))
        import scheduler_microbench as mb

        out = mb.run_shardmap_microbench()
        assert out["within_budget"], out
        # a degenerate hash would show up as gross imbalance here first
        assert out["spread_imbalance"] < 1.5, out


class TestRouting:
    def test_job_and_owned_objects_colocate(self):
        store = ShardedObjectStore(shards=4)
        job = store.create(_job("train-1"))
        pod = _pod("train-1-p0", owner=job)
        home = store.shard_for_object(job)
        assert store.shard_for_object(pod) == home
        assert store.shard_for_key("default", "train-1") == home

    def test_event_routes_by_involved_object(self):
        store = ShardedObjectStore(shards=4)
        job = store.create(_job("train-1"))
        ev = Event()
        ev.metadata.name = "train-1.17f"
        ev.metadata.namespace = "default"
        ev.involved_name = "train-1"
        assert store.shard_for_object(ev) == store.shard_for_object(job)


class TestCrossShardClientSurface:
    def test_crud_across_shards(self):
        store = ShardedObjectStore(shards=4)
        names = [f"job-{i:03d}" for i in range(40)]
        for name in names:
            store.create(_job(name))
        # cross-shard point reads find every object
        for name in names:
            assert store.get("TPUJob", name).metadata.name == name
        # aggregate list covers all shards, deterministically ordered
        listed = store.list("TPUJob")
        assert [j.metadata.name for j in listed] == sorted(names)
        # spread actually used more than one shard
        used = {store.shard_for_key("default", n) for n in names}
        assert len(used) > 1
        for name in names:
            store.delete("TPUJob", name)
        with pytest.raises(NotFound):
            store.get("TPUJob", names[0])

    def test_watch_fanout_exactly_once(self):
        """ADDED/MODIFIED/DELETED from every shard reach one watcher
        exactly once per object event."""
        store = ShardedObjectStore(shards=4)
        events = []
        lock = threading.Lock()

        def cb(event, obj, old):
            with lock:
                events.append((event, obj.metadata.name))

        cancel = store.watch(cb, kinds=["TPUJob"])
        names = [f"job-{i:03d}" for i in range(20)]
        for name in names:
            store.create(_job(name))
        for name in names:
            store.update_with_retry(
                "TPUJob", name, "default",
                lambda o: o.metadata.labels.update(touched="1"),
            )
        for name in names:
            store.delete("TPUJob", name)
        for kind in ("ADDED", "MODIFIED", "DELETED"):
            got = sorted(n for e, n in events if e == kind)
            assert got == names, (kind, got)
        cancel()
        store.create(_job("after-cancel"))
        assert ("ADDED", "after-cancel") not in events

    def test_watch_kind_filter(self):
        store = ShardedObjectStore(shards=4)
        seen = []
        store.watch(lambda e, o, old: seen.append(o.kind), kinds=["Pod"])
        job = store.create(_job("j1"))
        store.create(_pod("j1-p0", owner=job))
        assert seen == ["Pod"]

    def test_since_revision_replay_per_shard(self):
        """A revisions() cursor replays each shard exactly from its own
        counter — no gaps, no duplicates."""
        store = ShardedObjectStore(shards=4)
        first = [f"early-{i:02d}" for i in range(12)]
        for name in first:
            store.create(_job(name))
        cursor = store.revisions()
        later = [f"late-{i:02d}" for i in range(12)]
        for name in later:
            store.create(_job(name))
        replayed = []
        store.watch(
            lambda e, o, old: replayed.append((e, o.metadata.name)),
            kinds=["TPUJob"], since_revision=cursor,
        )
        assert sorted(n for e, n in replayed) == sorted(later)
        assert all(e == "ADDED" for e, n in replayed)

    def test_since_revision_int_broadcast(self):
        store = ShardedObjectStore(shards=4)
        names = [f"job-{i:02d}" for i in range(12)]
        for name in names:
            store.create(_job(name))
        replayed = []
        store.watch(
            lambda e, o, old: replayed.append(o.metadata.name),
            kinds=["TPUJob"], since_revision=0,
        )
        assert sorted(replayed) == names

    def test_kick_all_reaches_every_shard(self):
        """Manager resync (kick_all) synthesizes ADDED for every watched
        object on every shard exactly once."""
        store = ShardedObjectStore(shards=4)
        keys = set()
        done = threading.Event()
        names = [f"job-{i:02d}" for i in range(16)]

        def reconcile(namespace, name):
            keys.add(f"{namespace}/{name}")
            if len(keys) == len(names):
                done.set()

        manager = ControllerManager(store=store)
        manager.register("t", reconcile, watch_kinds=["TPUJob"],
                         mapper=owner_mapper("TPUJob"), workers=1)
        for name in names:
            store.create(_job(name))
        manager.start()
        try:
            assert done.wait(10.0)
            keys.clear()
            done.clear()
            manager.kick_all()
            assert done.wait(10.0), f"kick_all missed shards: {sorted(keys)}"
        finally:
            manager.stop()

    def test_collect_orphans_cross_shard(self):
        store = ShardedObjectStore(shards=4)
        job = store.create(_job("owner"))
        store.create(_pod("owner-p0", owner=job))
        ghost = _job("ghost")
        ghost.metadata.uid = "never-created"
        orphan = _pod("orphan-p0", owner=ghost)
        store.create(orphan)
        assert store.collect_orphans() == 1
        assert store.try_get("Pod", "owner-p0") is not None
        assert store.try_get("Pod", "orphan-p0") is None


class TestSingleShardIdentity:
    def test_wal_layout_matches_bare_store(self, tmp_path):
        """N=1 keeps the pre-shard on-disk layout: a WAL written by a
        bare ObjectStore replays through the facade unmoved."""
        wal_dir = str(tmp_path / "wal")
        bare = ObjectStore(wal_dir=wal_dir, wal_fsync="off")
        bare.create(_job("survivor"))
        bare.close()
        facade = ShardedObjectStore(shards=1, wal_dir=wal_dir,
                                    wal_fsync="off")
        assert facade.rehydrated
        assert facade.get("TPUJob", "survivor").metadata.name == "survivor"
        assert not os.path.isdir(os.path.join(wal_dir, "shard-0"))
        facade.close()

    def test_multi_shard_wal_segments(self, tmp_path):
        wal_dir = str(tmp_path / "wal")
        store = ShardedObjectStore(shards=4, wal_dir=wal_dir,
                                   wal_fsync="off")
        names = [f"job-{i:02d}" for i in range(16)]
        for name in names:
            store.create(_job(name))
        store.close()
        segs = [d for d in os.listdir(wal_dir) if d.startswith("shard-")]
        assert len(segs) == 4
        revived = ShardedObjectStore(shards=4, wal_dir=wal_dir,
                                     wal_fsync="off")
        assert sorted(
            j.metadata.name for j in revived.list("TPUJob")
        ) == names
        revived.close()


class TestLeaseFencing:
    def test_takeover_bumps_fencing_token(self):
        leases = ObjectStore()
        clock = [1000.0]
        t_a = acquire_shard_lease(leases, 0, "owner-a", ttl=2.0,
                                  clock=lambda: clock[0])
        assert t_a == 0
        # B cannot steal a live lease
        assert acquire_shard_lease(leases, 0, "owner-b", ttl=2.0,
                                   clock=lambda: clock[0]) is None
        clock[0] += 5.0  # A's lease expires
        t_b = acquire_shard_lease(leases, 0, "owner-b", ttl=2.0,
                                  clock=lambda: clock[0])
        assert t_b == t_a + 1

    def test_stale_token_wal_append_rejected(self, tmp_path):
        """The lease-takeover race: owner A pauses, B takes over with a
        bumped token, A resumes — A's late WAL append must raise, and the
        bytes must never reach the log."""
        leases = ObjectStore()
        clock = [1000.0]
        wal_dir = str(tmp_path / "a")
        store_a = ObjectStore(wal_dir=wal_dir, wal_fsync="off")
        t_a = acquire_shard_lease(leases, 0, "owner-a", ttl=2.0,
                                  clock=lambda: clock[0])
        fence_a = ShardFence(leases, 0, "owner-a", t_a)
        store_a._wal = FencedWal(store_a._wal, fence_a)
        store_a.create(_job("pre-pause"))  # fenced append succeeds

        clock[0] += 5.0  # A wedges; lease ages out; B takes over
        assert acquire_shard_lease(leases, 0, "owner-b", ttl=2.0,
                                   clock=lambda: clock[0]) == t_a + 1

        appends_before = store_a.wal_appends
        with pytest.raises(FencedOut):
            store_a.create(_job("post-pause"))
        assert store_a.wal_appends == appends_before
        # fencing is sticky: the domain is crash-only from here
        with pytest.raises(FencedOut):
            store_a.create(_job("post-pause-2"))
        store_a.close()
        # B's replay of A's segment sees only the pre-pause write
        store_b = ObjectStore(wal_dir=wal_dir, wal_fsync="off")
        assert store_b.try_get("TPUJob", "pre-pause") is not None
        assert store_b.try_get("TPUJob", "post-pause") is None
        store_b.close()

    def test_fence_gone_lease_deposes(self):
        leases = ObjectStore()
        token = acquire_shard_lease(leases, 3, "owner-a", ttl=2.0)
        fence = ShardFence(leases, 3, "owner-a", token)
        fence.assert_valid()
        leases.delete("Lease", shard_lease_name(3), "kubedl-system")
        with pytest.raises(FencedOut):
            fence.assert_valid()
        assert fence.deposed

    def test_chaos_shard_wal_append_site(self, tmp_path):
        store = ObjectStore(wal_dir=str(tmp_path / "w"), wal_fsync="off")
        store._wal = FencedWal(store._wal, None)
        with chaos.FaultPlan(7, {"shard.wal_append": [chaos.FaultSpec.nth(1)]}):
            with pytest.raises(chaos.FaultInjected):
                store.create(_job("doomed"))
            store.create(_job("next-is-fine"))
        store.close()

    def test_facade_fenced_takeover_replays_added(self):
        """Standby facade wins the expired lease: rehydrate-then-adopt
        fires on_shard_acquired, then replays ADDED to facade watchers,
        and the deposed owner's writes fence out."""
        leases = ObjectStore()
        owner_a = ShardedObjectStore(
            shards=1, lease_backend=leases, identity="owner-a",
            lease_ttl=0.3,
        )
        job = owner_a.create(_job("survivor"))
        adopted = []
        replayed = []
        standby = ShardedObjectStore(
            shards=1, lease_backend=leases, identity="owner-b",
            lease_ttl=0.3, own=[], standby=[0],
        )
        standby.on_shard_acquired = (
            lambda i, objs: adopted.append((i, [o.metadata.name for o in objs]))
        )
        standby.watch(
            lambda e, o, old: replayed.append((e, o.metadata.name)),
            kinds=["TPUJob"],
        )
        # crash A without releasing: B must win by expiry, like a real death
        owner_a.close()
        standby.start_campaigns()
        deadline = time.monotonic() + 10.0
        while standby.takeovers == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        try:
            assert standby.takeovers == 1, "standby never took over"
            assert standby.owned_shards() == [0]
            # the in-memory image does not cross processes (no WAL here):
            # the hook fires with whatever the segment rehydrated
            assert adopted and adopted[0][0] == 0
            with pytest.raises(FencedOut):
                owner_a.create(_job("stale-write"))
            standby.create(_job("new-owner-write"))
        finally:
            standby.release_shards()
            standby.close()
        assert job.metadata.uid  # silence unused warning

    def test_unowned_shard_write_fences_event_falls_back(self):
        leases = ObjectStore()
        # own only the shards that are ours; writes routed elsewhere fence
        store = ShardedObjectStore(
            shards=4, lease_backend=leases, identity="owner-a",
            own=[0, 1], lease_ttl=5.0,
        )
        try:
            foreign = next(
                _job(f"probe-{i}") for i in range(64)
                if store.shard_for_key("default", f"probe-{i}") in (2, 3)
            )
            with pytest.raises(FencedOut):
                store.create(foreign)
            ev = Event()
            ev.metadata.name = f"{foreign.metadata.name}.17f"
            ev.metadata.namespace = "default"
            ev.involved_name = foreign.metadata.name
            created = store.create(ev)  # falls back to an owned shard
            assert created.metadata.name == ev.metadata.name
        finally:
            store.close()


class TestFileLeaseStore:
    def test_lease_roundtrip_and_contention(self, tmp_path):
        backend = FileLeaseStore(str(tmp_path / "leases"))
        clock = [1000.0]
        t_a = acquire_shard_lease(backend, 0, "proc-a", ttl=2.0,
                                  clock=lambda: clock[0])
        assert t_a == 0
        assert acquire_shard_lease(backend, 0, "proc-b", ttl=2.0,
                                   clock=lambda: clock[0]) is None
        lease = backend.get("Lease", shard_lease_name(0), "kubedl-system")
        assert isinstance(lease, Lease)
        assert lease.holder == "proc-a"
        clock[0] += 5.0
        assert acquire_shard_lease(backend, 0, "proc-b", ttl=2.0,
                                   clock=lambda: clock[0]) == 1

    def test_racing_writers_serialize(self, tmp_path):
        """Two threads hammering update_with_retry through the flock path
        never lose an increment."""
        backend = FileLeaseStore(str(tmp_path / "leases"))
        acquire_shard_lease(backend, 0, "proc-a", ttl=60.0)
        n = 25

        def bump():
            for _ in range(n):
                backend.update_with_retry(
                    "Lease", shard_lease_name(0), "kubedl-system",
                    lambda lease: setattr(
                        lease, "transitions", lease.transitions + 1
                    ),
                    attempts=50,
                )

        threads = [threading.Thread(target=bump) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        lease = backend.get("Lease", shard_lease_name(0), "kubedl-system")
        assert lease.transitions == 2 * n


class TestShardedExpectations:
    def test_routing_and_shard_scoped_clear(self):
        sm = ShardMap(4)
        exps = ShardedExpectations(
            lambda ns, name: sm.lookup(f"{ns}/{name}"), 4
        )
        keys = [f"default/job-{i:02d}" for i in range(16)]
        for key in keys:
            exps.expect_creations(key, 2)
            assert not exps.satisfied(key)
        victim = sm.lookup(keys[0])
        exps.clear_shard(victim)
        for key in keys:
            home = sm.lookup(key)
            if home == victim:
                # failover cleared this domain: nothing suppresses the
                # new owner's reconcile
                assert exps.satisfied(key), key
            else:
                assert not exps.satisfied(key), key
        # observations still route after the partial clear
        survivor = next(k for k in keys if sm.lookup(k) != victim)
        exps.creation_observed(survivor)
        exps.creation_observed(survivor)
        assert exps.satisfied(survivor)


class TestShardedManager:
    def test_reconcile_metrics_carry_shard_label(self):
        from kubedl_tpu.observability.metrics import JobMetrics, MetricsRegistry

        store = ShardedObjectStore(shards=2)
        registry = MetricsRegistry()
        metrics = JobMetrics(registry)
        done = threading.Event()
        seen = set()
        names = [f"job-{i}" for i in range(8)]

        def reconcile(namespace, name):
            seen.add(name)
            if len(seen) == len(names):
                done.set()

        manager = ControllerManager(store=store, metrics=metrics)
        manager.register("t", reconcile, watch_kinds=["TPUJob"],
                         mapper=owner_mapper("TPUJob"), workers=1)
        manager.start()
        try:
            for name in names:
                store.create(_job(name))
            assert done.wait(10.0)
        finally:
            manager.stop()
        body = registry.render()
        shard_labels = {
            line.split('shard="')[1].split('"')[0]
            for line in body.splitlines()
            if line.startswith("kubedl_tpu_reconcile_total{")
        }
        used = {str(store.shard_for_key("default", n)) for n in names}
        assert shard_labels == used

    def test_wal_fsync_floor_serializes_one_log(self, tmp_path):
        """The bench's durable-medium model: with a commit floor, N
        writes through ONE store pay >= N floors back to back, and the
        floor rides the constructor chain down to every shard WAL."""
        import time as _time

        floor = 0.005
        store = ShardedObjectStore(
            shards=1, wal_dir=str(tmp_path / "w"), wal_fsync_floor=floor
        )
        t0 = _time.perf_counter()
        for i in range(10):
            store.create(_job(f"floor-{i}"))
        elapsed = _time.perf_counter() - t0
        store.close()
        assert elapsed >= 10 * floor
        sharded = ShardedObjectStore(
            shards=4, wal_dir=str(tmp_path / "s"), wal_fsync_floor=floor
        )
        assert all(
            sharded.shard_store(i)._wal._wal.fsync_floor == floor
            for i in range(4)
        )
        sharded.close()

    def test_churn_smoke_both_arms(self):
        """The bench harness end-to-end at toy scale: every job completes
        and milestones land, 1-shard and 4-shard."""
        from kubedl_tpu.shards.churn import run_churn

        for shards in (1, 4):
            out = run_churn(shards=shards, jobs=24, pods_per_job=3,
                            workers_per_shard=2, wave=8, stall_timeout=30.0)
            assert out["completed"] == 24, out
            assert out["reconciles"] >= 24
            assert out["launch_p50_ms"] >= 0.0


class TestCrossShardReadContention:
    """PR 19 regression guards for the 4-shard exec-latency cliff: the
    facade's cross-shard probes (delete's holding-shard lookup, list's
    snapshot views) must never serialize behind OTHER shards' write locks
    — r18 measured delete-heavy reconciles at ~4x single-shard p99
    because the old probe took every shard's lock in turn."""

    def test_cross_shard_ops_survive_a_wedged_shard(self):
        """Deterministic form: wedge one shard's write lock from another
        thread; deletes, gets and (warm) lists touching OTHER shards must
        complete instead of queueing behind it."""
        store = ShardedObjectStore(shards=4)
        jobs = [store.create(_job(f"lf-{i}")) for i in range(12)]
        store.list("TPUJob")  # warm the per-shard snapshot views
        wedge = store.shard_for_object(jobs[0])
        victims = [j for j in jobs if store.shard_for_object(j) != wedge]
        held = threading.Event()
        release = threading.Event()

        def holder():
            with store.shard_store(wedge)._lock:
                held.set()
                release.wait(10.0)

        t = threading.Thread(target=holder, daemon=True)
        t.start()
        assert held.wait(2.0)
        try:
            done = threading.Event()

            def ops():
                v = victims[0]
                store.try_get("TPUJob", v.metadata.name,
                              v.metadata.namespace)
                store.delete("TPUJob", v.metadata.name,
                             v.metadata.namespace)
                store.list("TPUJob")  # unwedged shards' views are warm
                done.set()

            w = threading.Thread(target=ops, daemon=True)
            w.start()
            assert done.wait(2.0), (
                "cross-shard read/delete blocked on an unrelated shard's "
                "write lock (the r18 contention regression)"
            )
        finally:
            release.set()
            t.join(2.0)

    def test_delete_p99_under_writers_within_2x_of_single_shard(self):
        """Statistical form (the ISSUE acceptance shape): facade delete
        p99 under concurrent writers. At 1 shard the writers necessarily
        share the victim's lock — that arm IS full contention. At 4
        shards the writers live on OTHER shards, so a contention-free
        probe keeps delete p99 within 2x of that bound (GIL noise only);
        the old all-locks probe queued behind every writer and landed at
        ~3-4x."""
        import sys

        def run(shards: int) -> float:
            store = ShardedObjectStore(shards=shards)
            n = 150
            victims = []
            i = 0
            while len(victims) < n:
                j = _job(f"del-{i}")
                if shards == 1 or store.shard_for_object(j) == 0:
                    victims.append(store.create(j))
                i += 1
            hot = []
            i = 0
            while len(hot) < 8:
                j = _job(f"hot-{i}")
                if shards == 1 or store.shard_for_object(j) != 0:
                    hot.append(store.create(j))
                i += 1
            stop = threading.Event()

            def writer(job):
                while not stop.is_set():
                    store.update_with_retry(
                        "TPUJob", job.metadata.name,
                        job.metadata.namespace,
                        lambda o: o.metadata.labels.update(t="x"),
                    )

            threads = [threading.Thread(target=writer, args=(j,),
                                        daemon=True) for j in hot]
            for t in threads:
                t.start()
            samples = []
            try:
                for v in victims:
                    t0 = time.perf_counter()
                    store.delete("TPUJob", v.metadata.name,
                                 v.metadata.namespace)
                    samples.append(time.perf_counter() - t0)
            finally:
                stop.set()
                for t in threads:
                    t.join(2.0)
            samples.sort()
            return samples[int(0.99 * (len(samples) - 1))]

        interval = sys.getswitchinterval()
        sys.setswitchinterval(0.001)  # tighten GIL slices: measure locks
        try:
            p99_one = run(1)
            p99_four = run(4)
        finally:
            sys.setswitchinterval(interval)
        floor = 0.005  # absorb scheduler noise when both arms are fast
        assert p99_four <= max(2.0 * p99_one, floor), (
            f"4-shard delete p99 {p99_four * 1e3:.3f}ms vs 1-shard "
            f"{p99_one * 1e3:.3f}ms — cross-shard probe is contending"
        )


class TestEventShardLabel:
    def test_recorder_stamps_shard_label(self):
        from kubedl_tpu.core.manager import SHARD_LABEL, EventRecorder

        store = ShardedObjectStore(shards=4)
        job = store.create(_job("train-1"))
        recorder = EventRecorder(store)
        recorder.event(job, "Normal", "Tested", "stamped")
        evs = store.list("Event")
        assert len(evs) == 1
        assert evs[0].metadata.labels[SHARD_LABEL] == str(
            store.shard_for_object(job)
        )

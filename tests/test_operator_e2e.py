"""End-to-end: operator + real pod processes (reference analogue: the kind
e2e running a distributed TF mnist job, scripts/run_tf_test_job.sh)."""

import os
import sys
import time

import pytest

from kubedl_tpu.api import constants
from kubedl_tpu.api.types import JobConditionType, ModelVersionSpecRef, ReplicaType
from kubedl_tpu.lineage.types import ModelVersionPhase
from kubedl_tpu.operator import Operator, OperatorOptions
from kubedl_tpu.runtime.executor import SubprocessRuntime, ThreadRuntime

from tests.helpers import make_tpujob

def _phase_deadline(base: float) -> float:
    """CPU-adaptive wait_for_phase deadline: multi-process worker gangs
    (each a full python + jax import + compile) serialize on starved
    boxes, so a deadline sized for a multi-core CI host times out on a
    1-core one while the gang is still making progress. Scale the base
    deadline by how far below 4 cores the box sits (measured: the
    2-worker jax.distributed jobs finish in ~100s at 4 cores but need
    ~5x that wall time at 1 core)."""
    try:  # cgroup/affinity-aware (cpu_count ignores container quotas)
        cores = len(os.sched_getaffinity(0))
    except AttributeError:
        cores = os.cpu_count() or 1
    return base * (5 if cores < 2 else (2 if cores < 4 else 1))


CHECK_ENV = (
    "import os,sys;"
    "req=['KUBEDL_COORDINATOR_ADDRESS','KUBEDL_NUM_PROCESSES','KUBEDL_PROCESS_ID',"
    "'TPU_WORKER_HOSTNAMES','TPU_WORKER_ID'];"
    "missing=[k for k in req if k not in os.environ];"
    "sys.exit(1 if missing else 0)"
)


def test_subprocess_job_lifecycle(tmp_path):
    opts = OperatorOptions(
        local_addresses=True,
        pod_log_dir=str(tmp_path / "logs"),
        artifact_registry_root=str(tmp_path / "registry"),
    )
    with Operator(opts, runtime=SubprocessRuntime(str(tmp_path / "logs"))) as op:
        job = make_tpujob("e2e", workers=2, command=["python", "-c", CHECK_ENV])
        op.submit(job)
        got = op.wait_for_phase(
            "TPUJob", "e2e", [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
            timeout=_phase_deadline(30),
        )
        assert got.status.phase == JobConditionType.SUCCEEDED, got.status.conditions
        # launch-delay metrics observed
        count, _ = op.metrics.first_pod_launch_delay.summary(kind="TPUJob")
        assert count == 1
        rendered = op.render_metrics()
        assert "kubedl_tpu_jobs_successful" in rendered


def _train_entry(env):
    """Thread-runtime entrypoint: writes a fake checkpoint to the model path."""
    import os
    import pathlib

    out = env.get(constants.ENV_MODEL_PATH, "")
    if out:
        pathlib.Path(out).mkdir(parents=True, exist_ok=True)
        (pathlib.Path(out) / f"shard-{env['KUBEDL_PROCESS_ID']}.bin").write_bytes(
            b"\x00" * 128
        )
    return 0


def test_thread_job_builds_model_version(tmp_path):
    out_dir = tmp_path / "model-out"
    opts = OperatorOptions(
        local_addresses=True, artifact_registry_root=str(tmp_path / "registry")
    )
    with Operator(opts, runtime=ThreadRuntime()) as op:
        job = make_tpujob(
            "train", workers=2, entrypoint=f"{__name__}:_train_entry"
        )
        job.spec.model_version = ModelVersionSpecRef(
            model_name="flagship", image_repo="models/flagship",
            storage_root=str(out_dir),
        )
        op.submit(job)
        got = op.wait_for_phase(
            "TPUJob", "train", [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
            timeout=_phase_deadline(30),
        )
        assert got.status.phase == JobConditionType.SUCCEEDED
        # lineage: ModelVersion built into the artifact registry
        assert op.manager.wait(
            lambda: any(
                mv.phase == ModelVersionPhase.SUCCEEDED
                for mv in op.store.list("ModelVersion")
            ),
            timeout=10,
        )
        mv = op.store.list("ModelVersion")[0]
        assert op.artifact_registry.exists("models/flagship", mv.image_tag())
        model = op.store.get("Model", "flagship")
        assert model.latest_version == mv.metadata.name


def test_failed_process_marks_job_failed(tmp_path):
    opts = OperatorOptions(local_addresses=True,
                           artifact_registry_root=str(tmp_path / "r"))
    from kubedl_tpu.api.types import RestartPolicy

    with Operator(opts, runtime=SubprocessRuntime()) as op:
        job = make_tpujob(
            "boom", workers=1,
            command=["python", "-c", "import sys; sys.exit(7)"],
            restart_policy=RestartPolicy.EXIT_CODE,  # exit 7 = permanent
        )
        op.submit(job)
        got = op.wait_for_phase(
            "TPUJob", "boom", [JobConditionType.FAILED, JobConditionType.SUCCEEDED],
            timeout=_phase_deadline(30),
        )
        assert got.status.phase == JobConditionType.FAILED
        assert op.metrics.failed.value(kind="TPUJob") == 1


def test_workload_gate_parsing():
    from kubedl_tpu.workloads.registry import parse_workload_gate

    known = ["TPUJob", "TorchXLAJob", "MPIJob"]
    assert parse_workload_gate("*", known) == known
    assert parse_workload_gate("TPUJob", known) == ["TPUJob"]
    assert parse_workload_gate("-MPIJob", known) == ["TPUJob", "TorchXLAJob"]
    assert parse_workload_gate("TPUJob,MPIJob", known) == ["TPUJob", "MPIJob"]


def test_gang_restart_resumes_from_checkpoint(tmp_path):
    """VERDICT r1 #3 done-criterion: a worker dies retryably mid-training,
    the gang restarts, and the job completes having RESUMED (total trained
    steps < 2x the budget), proving slice-granular restart-from-checkpoint
    (SURVEY.md §7 hard-part b; reference restart machinery analogue:
    pkg/job_controller/pod.go:305-317)."""
    import json

    from kubedl_tpu.core.objects import EnvVar
    from kubedl_tpu.training import entry as entry_mod

    ckpt_dir = tmp_path / "ckpts"
    marker = tmp_path / "fault-fired"
    opts = OperatorOptions(
        local_addresses=True,
        pod_log_dir=str(tmp_path / "logs"),
        artifact_registry_root=str(tmp_path / "registry"),
    )
    cfg = {"model": "tiny", "steps": 8, "global_batch": 8, "seq_len": 32,
           "ckpt_every": 2}
    with Operator(opts, runtime=ThreadRuntime()) as op:
        job = make_tpujob(
            "resume", workers=1,
            entrypoint="kubedl_tpu.training.entry:train_main",
        )
        spec = job.spec.replica_specs[ReplicaType.WORKER]
        spec.template.spec.containers[0].env = [
            EnvVar("KUBEDL_TRAIN_CONFIG", json.dumps(cfg)),
            EnvVar("KUBEDL_CKPT_DIR", str(ckpt_dir)),
            EnvVar("KUBEDL_FAULT_ONCE_AT_STEP", "5"),
            EnvVar("KUBEDL_FAULT_MARKER", str(marker)),
        ]
        op.submit(job)
        got = op.wait_for_phase(
            "TPUJob", "resume",
            [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
            timeout=120,
        )
        assert got.status.phase == JobConditionType.SUCCEEDED, got.status.conditions
        assert got.status.restart_count >= 1  # the fault actually fired
        assert marker.exists()
    summary = entry_mod.LAST_SUMMARY
    # the restarted attempt resumed from a saved step, not from 0
    assert summary["start_step"] >= 2, summary
    # and trained only the remainder: resumed steps + pre-fault steps < 2x
    assert summary["steps"] <= 8 - summary["start_step"], summary


REPO_ROOT = str(__import__("pathlib").Path(__file__).resolve().parents[1])

DIST_PSUM = (
    "import os, sys\n"
    f"sys.path.insert(0, {REPO_ROOT!r})\n"
    "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
    "os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=1'\n"
    "from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested\n"
    "ensure_cpu_if_requested()\n"
    "from kubedl_tpu.parallel.mesh import initialize_from_env\n"
    "initialize_from_env()\n"
    "import jax\n"
    "import jax.numpy as jnp\n"
    "assert jax.process_count() == 2, jax.process_count()\n"
    "assert jax.device_count() == 2, jax.device_count()\n"
    "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
    "mesh = Mesh(jax.devices(), ('data',))\n"
    "rank = jax.process_index()\n"
    "local = jnp.ones((1,), jnp.float32) * (rank + 1)\n"
    "garr = jax.make_array_from_process_local_data(\n"
    "    NamedSharding(mesh, P('data')), local, global_shape=(2,))\n"
    "total = jax.jit(lambda x: x.sum(),\n"
    "    out_shardings=NamedSharding(mesh, P()))(garr)\n"
    "assert float(total) == 3.0, float(total)\n"
    "print('psum-ok rank', rank)\n"
)


def test_two_process_jax_distributed_rendezvous(tmp_path):
    """VERDICT r1 #7: two real OS processes do a jax.distributed.initialize
    rendezvous off the operator-injected env (coordinator address, process
    count/id) and run a cross-process global reduction — the operator's
    bootstrap wiring proven end to end, not just env-presence-checked
    (reference e2e bar: scripts/run_tf_test_job.sh)."""
    script = tmp_path / "dist_psum.py"
    script.write_text(DIST_PSUM)
    opts = OperatorOptions(
        local_addresses=True,
        pod_log_dir=str(tmp_path / "logs"),
        artifact_registry_root=str(tmp_path / "registry"),
    )
    with Operator(opts, runtime=SubprocessRuntime(str(tmp_path / "logs"))) as op:
        job = make_tpujob("dist2", workers=2,
                          command=[sys.executable, str(script)])
        op.submit(job)
        got = op.wait_for_phase(
            "TPUJob", "dist2",
            [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
            timeout=_phase_deadline(120),
        )
        assert got.status.phase == JobConditionType.SUCCEEDED, [
            c.message for c in got.status.conditions
        ]
    logs = tmp_path / "logs" / "default"
    merged = "".join(p.read_text() for p in logs.glob("dist2-worker-*.log"))
    assert "psum-ok rank 0" in merged and "psum-ok rank 1" in merged, merged


def test_gang_release_nudges_queued_job(tmp_path):
    """VERDICT r1 #8: a queued job admits within one reconcile of a slice
    freeing (PodGroup-deletion nudge), not via the slow fallback poll."""
    from kubedl_tpu.api.topology import get_slice
    from kubedl_tpu.gang.slice_scheduler import SliceInventory

    inv = SliceInventory()
    inv.add_slice("s1", "v5e-8")
    opts = OperatorOptions(
        local_addresses=True,
        pod_log_dir=str(tmp_path / "logs"),
        artifact_registry_root=str(tmp_path / "registry"),
    )
    topo = get_slice("v5e-8")
    with Operator(opts, runtime=SubprocessRuntime(str(tmp_path / "logs")),
                  inventory=inv) as op:
        j1 = make_tpujob("holder", workers=2,
                         command=[sys.executable, "-c", "import time; time.sleep(4)"],
                         topology=topo)
        op.submit(j1)
        deadline = time.time() + 20
        while time.time() < deadline:
            pods = [p for p in op.store.list("Pod")
                    if p.metadata.labels.get(
                        "kubedl-tpu.io/job-name") == "holder"]
            if len(pods) == 2:
                break
            time.sleep(0.1)
        assert len(pods) == 2
        j2 = make_tpujob("waiter", workers=2,
                         command=[sys.executable, "-c", "print('ok')"],
                         topology=topo)
        op.submit(j2)
        time.sleep(1.0)
        w = op.store.get("TPUJob", "waiter")
        assert w.status.phase == JobConditionType.QUEUED
        got1 = op.wait_for_phase("TPUJob", "holder",
                                 [JobConditionType.SUCCEEDED], timeout=30)
        t_free = time.time()
        # admitted well inside the 5s fallback poll -> the nudge fired
        deadline = time.time() + 3.0
        admitted = False
        while time.time() < deadline:
            w = op.store.get("TPUJob", "waiter")
            if w.status.phase != JobConditionType.QUEUED:
                admitted = True
                break
            time.sleep(0.05)
        assert admitted, f"waiter still QUEUED {time.time() - t_free:.1f}s after slice freed"
        op.wait_for_phase("TPUJob", "waiter", [JobConditionType.SUCCEEDED],
                          timeout=30)


TRAIN_DIST = (
    "import os, sys\n"
    f"sys.path.insert(0, {REPO_ROOT!r})\n"
    "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
    "os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=1'\n"
    "from kubedl_tpu.training.entry import train_main\n"
    "sys.exit(train_main())\n"
)


def test_shared_storage_two_worker_train_build_serve(tmp_path):
    """VERDICT r1 #6 done-criterion: a 2-worker (2-process jax.distributed)
    job writes sharded checkpoint output to a SHARED storage root, the
    ModelVersion build consumes it, and the serving engine loads the
    restored weights (reference union: storage_provider.go:1-35)."""
    import json

    import numpy as np

    from kubedl_tpu.core.objects import EnvVar
    from kubedl_tpu.lineage.types import ModelVersionPhase

    shared_root = tmp_path / "shared" / "out"
    script = tmp_path / "train_dist.py"
    script.write_text(TRAIN_DIST)
    opts = OperatorOptions(
        local_addresses=True,
        pod_log_dir=str(tmp_path / "logs"),
        artifact_registry_root=str(tmp_path / "registry"),
    )
    cfg = {"model": "tiny", "steps": 2, "global_batch": 4, "seq_len": 32}
    with Operator(opts, runtime=SubprocessRuntime(str(tmp_path / "logs"))) as op:
        job = make_tpujob("shared2", workers=2,
                          command=[sys.executable, str(script)])
        spec = job.spec.replica_specs[ReplicaType.WORKER]
        spec.template.spec.containers[0].env = [
            EnvVar("KUBEDL_TRAIN_CONFIG", json.dumps(cfg)),
        ]
        job.spec.model_version = ModelVersionSpecRef(
            model_name="shared-model", storage_root=str(shared_root),
            storage_provider="shared",
        )
        op.submit(job)
        got = op.wait_for_phase(
            "TPUJob", "shared2",
            [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
            timeout=_phase_deadline(120),
        )
        assert got.status.phase == JobConditionType.SUCCEEDED, [
            c.message for c in got.status.conditions
        ]
        # both processes wrote their shard files into the shared root
        import glob as _glob

        shard_files = _glob.glob(str(shared_root / "step-*" / "shards-p*.npz"))
        pids = {f.rsplit("shards-", 1)[1] for f in shard_files}
        assert {"p0.npz", "p1.npz"} <= pids, shard_files
        # MV build consumed the shared artifact (re-read the job each
        # poll: the MV name now rides the success status write, but a
        # hedge against any stale snapshot keeps this loop robust)
        deadline = time.time() + 30
        mv = None
        while time.time() < deadline:
            mv_name = op.store.get(
                "TPUJob", "shared2", "default"
            ).status.model_version
            mv = (
                op.store.try_get("ModelVersion", mv_name, "default")
                if mv_name else None
            )
            if mv is not None and mv.phase in (
                ModelVersionPhase.SUCCEEDED, ModelVersionPhase.FAILED
            ):
                break
            time.sleep(0.3)
        assert mv is not None and mv.phase == ModelVersionPhase.SUCCEEDED, (
            getattr(mv, "message", None)
        )
        assert mv.storage_provider == "shared"
    # serving loads the trained weights from the shared root
    from kubedl_tpu.serving.server import LlamaEngine

    eng = LlamaEngine(preset="tiny", ckpt_dir=str(shared_root))
    import jax as _jax

    from kubedl_tpu.models import llama as _llama

    fresh = _llama.llama_init(_jax.random.PRNGKey(0), _llama.TINY)
    trained = eng.params
    diff = np.abs(
        np.asarray(_jax.device_get(trained["embed"]))
        - np.asarray(_jax.device_get(fresh["embed"]))
    ).max()
    assert diff > 0  # engine serves TRAINED weights, not the fresh init


def test_node_local_storage_rejects_cross_node_build(tmp_path):
    """Node-pinned artifacts must fail the build with a clear error when
    the builder is not co-located (the LocalStorage nodeName contract)."""
    from kubedl_tpu.lineage.storage import (
        NodeLocalProvider, SharedDirProvider, StorageError,
        get_storage_provider,
    )
    from kubedl_tpu.lineage.types import ModelVersion

    mv = ModelVersion(storage_root="/data/m", storage_provider="local",
                      node_name="host-7")
    with pytest.raises(StorageError):
        NodeLocalProvider().artifact_dir(mv, local_node="host-1")
    assert NodeLocalProvider().artifact_dir(mv, local_node="host-7") == "/data/m"
    # registry + aliases
    assert isinstance(get_storage_provider("nfs"), SharedDirProvider)
    assert isinstance(get_storage_provider("efs"), SharedDirProvider)
    assert isinstance(get_storage_provider(""), SharedDirProvider)
    with pytest.raises(StorageError):
        get_storage_provider("bogus")


def test_invariants_hold_through_full_lifecycle(tmp_path):
    """The consistency checker (control-plane sanitizer the reference
    lacks, SURVEY.md §5 'no -race') finds nothing after a busy scenario:
    gang contention + restart + success + deletion."""
    from kubedl_tpu.api.topology import get_slice
    from kubedl_tpu.gang.slice_scheduler import SliceInventory
    from kubedl_tpu.utils.invariants import check_invariants

    inv = SliceInventory()
    inv.add_slice("s1", "v5e-8")
    opts = OperatorOptions(
        local_addresses=True,
        pod_log_dir=str(tmp_path / "logs"),
        artifact_registry_root=str(tmp_path / "reg"),
    )
    topo = get_slice("v5e-8")
    with Operator(opts, runtime=SubprocessRuntime(str(tmp_path / "logs")),
                  inventory=inv) as op:
        marker = tmp_path / "flaky"
        j1 = make_tpujob("busy1", workers=2, topology=topo, command=[
            sys.executable, "-c",
            f"import os,sys; m={str(marker)!r}; d=os.path.exists(m); "
            "open(m,'w').write('x'); sys.exit(0 if d else 137)"])
        j2 = make_tpujob("busy2", workers=2, topology=topo,
                         command=[sys.executable, "-c", "print('ok')"])
        op.submit(j1)
        op.submit(j2)
        for name in ("busy1", "busy2"):
            got = op.wait_for_phase("TPUJob", name,
                                    [JobConditionType.SUCCEEDED,
                                     JobConditionType.FAILED], timeout=60)
            assert got.status.phase == JobConditionType.SUCCEEDED
        op.store.delete("TPUJob", "busy1")
        deadline = time.time() + 15
        while time.time() < deadline:
            violations = check_invariants(op)
            if not violations:
                break
            time.sleep(0.5)  # GC pass may still be collecting
        assert violations == [], violations


def test_invariants_catch_planted_inconsistencies(tmp_path):
    from kubedl_tpu.core.objects import OwnerRef, Pod
    from kubedl_tpu.utils.invariants import check_invariants

    opts = OperatorOptions(
        local_addresses=True,
        artifact_registry_root=str(tmp_path / "reg"),
    )
    from kubedl_tpu.runtime.executor import FakeRuntime

    op = Operator(opts, runtime=FakeRuntime())
    # plant: pod owned by a job that doesn't exist
    p = Pod()
    p.metadata.name = "ghost-pod"
    p.metadata.owner_refs.append(
        OwnerRef(kind="TPUJob", name="never-existed", uid="uid-x"))
    op.store.create(p)
    violations = check_invariants(op)
    assert any(v.startswith("I1") for v in violations), violations


TORCH_DDP = (
    "import os, sys\n"
    f"sys.path.insert(0, {REPO_ROOT!r})\n"
    "import torch\n"
    "import torch.distributed as dist\n"
    "rank = int(os.environ['RANK']); world = int(os.environ['WORLD_SIZE'])\n"
    "dist.init_process_group('gloo', init_method='env://',\n"
    "                        rank=rank, world_size=world)\n"
    "model = torch.nn.Linear(4, 1)\n"
    "for p in model.parameters():\n"
    "    dist.broadcast(p.data, src=0)\n"
    "opt = torch.optim.SGD(model.parameters(), lr=0.1)\n"
    "torch.manual_seed(rank)\n"
    "for _ in range(3):\n"
    "    x = torch.randn(8, 4); y = x.sum(dim=1, keepdim=True)\n"
    "    loss = ((model(x) - y) ** 2).mean()\n"
    "    opt.zero_grad(); loss.backward()\n"
    "    for p in model.parameters():\n"
    "        dist.all_reduce(p.grad); p.grad /= world\n"
    "    opt.step()\n"
    "flat = torch.cat([p.data.flatten() for p in model.parameters()])\n"
    "gathered = [torch.zeros_like(flat) for _ in range(world)]\n"
    "dist.all_gather(gathered, flat)\n"
    "assert all(torch.allclose(g, flat) for g in gathered), 'replicas diverged'\n"
    "print('ddp-ok rank', rank)\n"
    "dist.destroy_process_group()\n"
)


def test_pytorchjob_runs_real_torch_ddp(tmp_path):
    """BASELINE.md target 2 wiring proven with REAL torch.distributed:
    the operator-injected MASTER_ADDR/PORT/RANK/WORLD_SIZE drives a gloo
    process group across master + workers; allreduce keeps replicas in
    lockstep (asserted in-job via all_gather)."""
    pytest.importorskip("torch")  # torch is optional for the framework
    from kubedl_tpu.api.types import ReplicaSpec, RestartPolicy
    from kubedl_tpu.core.objects import Container
    from kubedl_tpu.workloads.pytorchjob import PyTorchJob

    script = tmp_path / "ddp.py"
    script.write_text(TORCH_DDP)
    opts = OperatorOptions(
        local_addresses=True,
        pod_log_dir=str(tmp_path / "logs"),
        artifact_registry_root=str(tmp_path / "reg"),
    )
    with Operator(opts, runtime=SubprocessRuntime(str(tmp_path / "logs"))) as op:
        job = PyTorchJob()
        job.metadata.name = "ddp"
        for rtype, n in ((ReplicaType.MASTER, 1), (ReplicaType.WORKER, 2)):
            spec = ReplicaSpec(replicas=n, restart_policy=RestartPolicy.ON_FAILURE)
            spec.template.spec.containers.append(
                Container(command=[sys.executable, str(script)])
            )
            job.spec.replica_specs[rtype] = spec
        op.submit(job)
        got = op.wait_for_phase(
            "PyTorchJob", "ddp",
            [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
            timeout=120,
        )
        assert got.status.phase == JobConditionType.SUCCEEDED, [
            c.message for c in got.status.conditions
        ]
    logs = tmp_path / "logs" / "default"
    merged = "".join(p.read_text() for p in logs.glob("ddp-*.log"))
    for rank in (0, 1, 2):
        assert f"ddp-ok rank {rank}" in merged, merged[-2000:]


def test_suspend_resume_preserves_training_progress(tmp_path):
    """Suspend a LIVE training job mid-run (kueue-style preemption), then
    resume: the job completes having restored from its checkpoint rather
    than retraining (start_step > 0, total trained < 2x budget)."""
    import json

    from kubedl_tpu.core.objects import EnvVar
    from kubedl_tpu.training import entry as entry_mod

    ckpt_dir = tmp_path / "ckpts"
    opts = OperatorOptions(
        local_addresses=True,
        pod_log_dir=str(tmp_path / "logs"),
        artifact_registry_root=str(tmp_path / "reg"),
    )
    cfg = {"model": "tiny", "steps": 200, "global_batch": 8, "seq_len": 32,
           "ckpt_every": 2}
    with Operator(opts, runtime=ThreadRuntime()) as op:
        job = make_tpujob(
            "presus", workers=1,
            entrypoint="kubedl_tpu.training.entry:train_main",
        )
        spec = job.spec.replica_specs[ReplicaType.WORKER]
        spec.template.spec.containers[0].env = [
            EnvVar("KUBEDL_TRAIN_CONFIG", json.dumps(cfg)),
            EnvVar("KUBEDL_CKPT_DIR", str(ckpt_dir)),
        ]
        op.submit(job)
        # wait until at least one periodic checkpoint landed
        deadline = time.time() + 60
        while time.time() < deadline:
            if (ckpt_dir / "latest").exists():
                break
            time.sleep(0.2)
        assert (ckpt_dir / "latest").exists()

        def suspend(j):
            j.spec.run_policy.suspend = True

        op.store.update_with_retry("TPUJob", "presus", "default", suspend)
        got = op.wait_for_phase("TPUJob", "presus",
                                [JobConditionType.SUSPENDED], timeout=30)
        assert got.status.phase == JobConditionType.SUSPENDED
        pods = [p for p in op.store.list("Pod")
                if p.metadata.labels.get("kubedl-tpu.io/job-name") == "presus"]
        assert pods == []

        # shrink the remaining budget so the resumed run finishes quickly,
        # then unsuspend
        short = dict(cfg, steps=30)

        def resume(j):
            j.spec.run_policy.suspend = False
            j.spec.replica_specs[ReplicaType.WORKER].template.spec.\
                containers[0].set_env("KUBEDL_TRAIN_CONFIG", json.dumps(short))

        op.store.update_with_retry("TPUJob", "presus", "default", resume)
        got = op.wait_for_phase(
            "TPUJob", "presus",
            [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
            timeout=120,
        )
        assert got.status.phase == JobConditionType.SUCCEEDED, [
            c.message for c in got.status.conditions
        ]
    summary = entry_mod.LAST_SUMMARY
    assert summary["start_step"] >= 2, summary  # resumed, not retrained
    assert summary["steps"] <= 30 - summary["start_step"], summary

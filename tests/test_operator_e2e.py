"""End-to-end: operator + real pod processes (reference analogue: the kind
e2e running a distributed TF mnist job, scripts/run_tf_test_job.sh)."""

import sys
import time

import pytest

from kubedl_tpu.api import constants
from kubedl_tpu.api.types import JobConditionType, ModelVersionSpecRef, ReplicaType
from kubedl_tpu.lineage.types import ModelVersionPhase
from kubedl_tpu.operator import Operator, OperatorOptions
from kubedl_tpu.runtime.executor import SubprocessRuntime, ThreadRuntime

from tests.helpers import make_tpujob

CHECK_ENV = (
    "import os,sys;"
    "req=['KUBEDL_COORDINATOR_ADDRESS','KUBEDL_NUM_PROCESSES','KUBEDL_PROCESS_ID',"
    "'TPU_WORKER_HOSTNAMES','TPU_WORKER_ID'];"
    "missing=[k for k in req if k not in os.environ];"
    "sys.exit(1 if missing else 0)"
)


def test_subprocess_job_lifecycle(tmp_path):
    opts = OperatorOptions(
        local_addresses=True,
        pod_log_dir=str(tmp_path / "logs"),
        artifact_registry_root=str(tmp_path / "registry"),
    )
    with Operator(opts, runtime=SubprocessRuntime(str(tmp_path / "logs"))) as op:
        job = make_tpujob("e2e", workers=2, command=["python", "-c", CHECK_ENV])
        op.submit(job)
        got = op.wait_for_phase(
            "TPUJob", "e2e", [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
            timeout=30,
        )
        assert got.status.phase == JobConditionType.SUCCEEDED, got.status.conditions
        # launch-delay metrics observed
        count, _ = op.metrics.first_pod_launch_delay.summary(kind="TPUJob")
        assert count == 1
        rendered = op.render_metrics()
        assert "kubedl_tpu_jobs_successful" in rendered


def _train_entry(env):
    """Thread-runtime entrypoint: writes a fake checkpoint to the model path."""
    import os
    import pathlib

    out = env.get(constants.ENV_MODEL_PATH, "")
    if out:
        pathlib.Path(out).mkdir(parents=True, exist_ok=True)
        (pathlib.Path(out) / f"shard-{env['KUBEDL_PROCESS_ID']}.bin").write_bytes(
            b"\x00" * 128
        )
    return 0


def test_thread_job_builds_model_version(tmp_path):
    out_dir = tmp_path / "model-out"
    opts = OperatorOptions(
        local_addresses=True, artifact_registry_root=str(tmp_path / "registry")
    )
    with Operator(opts, runtime=ThreadRuntime()) as op:
        job = make_tpujob(
            "train", workers=2, entrypoint=f"{__name__}:_train_entry"
        )
        job.spec.model_version = ModelVersionSpecRef(
            model_name="flagship", image_repo="models/flagship",
            storage_root=str(out_dir),
        )
        op.submit(job)
        got = op.wait_for_phase(
            "TPUJob", "train", [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
            timeout=30,
        )
        assert got.status.phase == JobConditionType.SUCCEEDED
        # lineage: ModelVersion built into the artifact registry
        assert op.manager.wait(
            lambda: any(
                mv.phase == ModelVersionPhase.SUCCEEDED
                for mv in op.store.list("ModelVersion")
            ),
            timeout=10,
        )
        mv = op.store.list("ModelVersion")[0]
        assert op.artifact_registry.exists("models/flagship", mv.image_tag())
        model = op.store.get("Model", "flagship")
        assert model.latest_version == mv.metadata.name


def test_failed_process_marks_job_failed(tmp_path):
    opts = OperatorOptions(local_addresses=True,
                           artifact_registry_root=str(tmp_path / "r"))
    from kubedl_tpu.api.types import RestartPolicy

    with Operator(opts, runtime=SubprocessRuntime()) as op:
        job = make_tpujob(
            "boom", workers=1,
            command=["python", "-c", "import sys; sys.exit(7)"],
            restart_policy=RestartPolicy.EXIT_CODE,  # exit 7 = permanent
        )
        op.submit(job)
        got = op.wait_for_phase(
            "TPUJob", "boom", [JobConditionType.FAILED, JobConditionType.SUCCEEDED],
            timeout=30,
        )
        assert got.status.phase == JobConditionType.FAILED
        assert op.metrics.failed.value(kind="TPUJob") == 1


def test_workload_gate_parsing():
    from kubedl_tpu.workloads.registry import parse_workload_gate

    known = ["TPUJob", "TorchXLAJob", "MPIJob"]
    assert parse_workload_gate("*", known) == known
    assert parse_workload_gate("TPUJob", known) == ["TPUJob"]
    assert parse_workload_gate("-MPIJob", known) == ["TPUJob", "TorchXLAJob"]
    assert parse_workload_gate("TPUJob,MPIJob", known) == ["TPUJob", "MPIJob"]

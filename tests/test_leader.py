"""Operator HA: lease-based leader election (VERDICT r2 #7; reference:
main.go:76-84 "kubedl-election"). Two operators share one object world;
only the lease holder reconciles, and when the holder dies the follower
takes over within the lease TTL."""

import time

import pytest

from kubedl_tpu.core.leases import Lease, LeaderElector
from kubedl_tpu.core.store import ObjectStore


class TestLeaderElector:
    def test_first_candidate_wins_second_waits(self):
        store = ObjectStore()
        t = {"now": 100.0}
        a = LeaderElector(store, identity="a", ttl=5.0, clock=lambda: t["now"])
        b = LeaderElector(store, identity="b", ttl=5.0, clock=lambda: t["now"])
        assert a._try_acquire()
        assert not b._try_acquire()
        lease = store.get("Lease", a.name, a.namespace)
        assert lease.holder == "a" and lease.transitions == 0

    def test_takeover_only_after_expiry_and_fencing_bump(self):
        store = ObjectStore()
        t = {"now": 100.0}
        a = LeaderElector(store, identity="a", ttl=5.0, clock=lambda: t["now"])
        b = LeaderElector(store, identity="b", ttl=5.0, clock=lambda: t["now"])
        assert a._try_acquire()
        t["now"] += 4.0
        assert not b._try_acquire()  # not expired yet
        t["now"] += 2.0  # 6s since renew > ttl
        assert b._try_acquire()
        lease = store.get("Lease", b.name, b.namespace)
        assert lease.holder == "b"
        assert lease.transitions == 1  # fencing token bumped
        # deposed holder cannot renew
        assert not a._renew()

    def test_check_fence_rejects_deposed_leader(self):
        """The unfenced window: a deposed leader still believes it leads
        until its next renew tick — check_fence() must say no anyway,
        because the fencing token moved on (ADVICE r3: nothing stamped or
        checked `transitions`)."""
        store = ObjectStore()
        t = {"now": 100.0}
        a = LeaderElector(store, identity="a", ttl=5.0, clock=lambda: t["now"])
        b = LeaderElector(store, identity="b", ttl=5.0, clock=lambda: t["now"])
        assert a._try_acquire()
        a._leader = True  # what the campaign loop would set
        assert a.check_fence()  # holding and un-deposed
        t["now"] += 6.0
        assert b._try_acquire()  # expiry takeover bumps transitions
        b._leader = True
        # a has NOT ticked its renew loop: is_leader still lies...
        assert a.is_leader
        # ...but the fence catches it
        assert not a.check_fence()
        assert b.check_fence()
        assert b.fence_token == 1

    def test_release_allows_immediate_takeover(self):
        store = ObjectStore()
        t = {"now": 100.0}
        a = LeaderElector(store, identity="a", ttl=60.0, clock=lambda: t["now"])
        b = LeaderElector(store, identity="b", ttl=60.0, clock=lambda: t["now"])
        assert a._try_acquire()
        a.release()
        assert b._try_acquire()  # no TTL wait after clean release


class TestOperatorHA:
    def test_only_holder_reconciles_and_failover(self, tmp_path):
        """The VERDICT done-criterion: two operators, one store; only the
        holder reconciles; kill it and the follower takes over within the
        lease TTL (and actually completes work)."""
        from tests.helpers import make_tpujob

        from kubedl_tpu.api.types import JobConditionType
        from kubedl_tpu.operator import Operator, OperatorOptions
        from kubedl_tpu.runtime.executor import SubprocessRuntime

        store = ObjectStore()
        logs = str(tmp_path / "logs")

        def opts(ident):
            return OperatorOptions(
                local_addresses=True, pod_log_dir=logs,
                artifact_registry_root=str(tmp_path / f"reg-{ident}"),
                leader_elect=True, leader_identity=ident,
                leader_lease_ttl=0.6,
            )

        op1 = Operator(opts("op1"), runtime=SubprocessRuntime(logs), store=store)
        op2 = Operator(opts("op2"), runtime=SubprocessRuntime(logs), store=store)
        op1.start()
        # op1 campaigns alone first so leadership is deterministic
        deadline = time.time() + 5
        while time.time() < deadline and not op1.elector.is_leader:
            time.sleep(0.02)
        assert op1.elector.is_leader
        op2.start()
        time.sleep(1.0)  # give op2 time to (NOT) steal
        assert not op2.elector.is_leader
        assert op1.manager._running and not op2.manager._running

        try:
            # work completes under the leader
            job = make_tpujob("ha1", workers=1, command=["true"])
            op1.submit(job)
            got = op1.wait_for_phase(
                "TPUJob", "ha1",
                [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
                timeout=60,
            )
            assert got.status.phase == JobConditionType.SUCCEEDED

            # kill the holder WITHOUT a clean release (simulated crash:
            # stop its campaign thread and its manager, keep the lease)
            op1.elector._stop.set()
            op1.elector._thread.join(timeout=2)
            op1._on_deposed()

            # follower takes over within ~TTL
            deadline = time.time() + 10
            while time.time() < deadline and not op2.elector.is_leader:
                time.sleep(0.05)
            assert op2.elector.is_leader
            assert op2.manager._running
            lease = store.get("Lease", "kubedl-election", "kubedl-system")
            assert lease.holder == "op2" and lease.transitions == 1

            # and actually reconciles new work
            job2 = make_tpujob("ha2", workers=1, command=["true"])
            op2.submit(job2)
            got2 = op2.wait_for_phase(
                "TPUJob", "ha2",
                [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
                timeout=60,
            )
            assert got2.status.phase == JobConditionType.SUCCEEDED
        finally:
            op1.stop()
            op2.stop()

"""Operator HA: lease-based leader election (VERDICT r2 #7; reference:
main.go:76-84 "kubedl-election"). Two operators share one object world;
only the lease holder reconciles, and when the holder dies the follower
takes over within the lease TTL."""

import time

import pytest

from kubedl_tpu.core.leases import Lease, LeaderElector
from kubedl_tpu.core.store import ObjectStore


class TestLeaderElector:
    def test_first_candidate_wins_second_waits(self):
        store = ObjectStore()
        t = {"now": 100.0}
        a = LeaderElector(store, identity="a", ttl=5.0, clock=lambda: t["now"])
        b = LeaderElector(store, identity="b", ttl=5.0, clock=lambda: t["now"])
        assert a._try_acquire()
        assert not b._try_acquire()
        lease = store.get("Lease", a.name, a.namespace)
        assert lease.holder == "a" and lease.transitions == 0

    def test_takeover_only_after_expiry_and_fencing_bump(self):
        store = ObjectStore()
        t = {"now": 100.0}
        a = LeaderElector(store, identity="a", ttl=5.0, clock=lambda: t["now"])
        b = LeaderElector(store, identity="b", ttl=5.0, clock=lambda: t["now"])
        assert a._try_acquire()
        t["now"] += 4.0
        assert not b._try_acquire()  # not expired yet
        t["now"] += 2.0  # 6s since renew > ttl
        assert b._try_acquire()
        lease = store.get("Lease", b.name, b.namespace)
        assert lease.holder == "b"
        assert lease.transitions == 1  # fencing token bumped
        # deposed holder cannot renew
        assert not a._renew()

    def test_check_fence_rejects_deposed_leader(self):
        """The unfenced window: a deposed leader still believes it leads
        until its next renew tick — check_fence() must say no anyway,
        because the fencing token moved on (ADVICE r3: nothing stamped or
        checked `transitions`)."""
        store = ObjectStore()
        t = {"now": 100.0}
        a = LeaderElector(store, identity="a", ttl=5.0, clock=lambda: t["now"])
        b = LeaderElector(store, identity="b", ttl=5.0, clock=lambda: t["now"])
        assert a._try_acquire()
        a._leader = True  # what the campaign loop would set
        assert a.check_fence()  # holding and un-deposed
        t["now"] += 6.0
        assert b._try_acquire()  # expiry takeover bumps transitions
        b._leader = True
        # a has NOT ticked its renew loop: is_leader still lies...
        assert a.is_leader
        # ...but the fence catches it
        assert not a.check_fence()
        assert b.check_fence()
        assert b.fence_token == 1

    def test_release_allows_immediate_takeover(self):
        store = ObjectStore()
        t = {"now": 100.0}
        a = LeaderElector(store, identity="a", ttl=60.0, clock=lambda: t["now"])
        b = LeaderElector(store, identity="b", ttl=60.0, clock=lambda: t["now"])
        assert a._try_acquire()
        a.release()
        assert b._try_acquire()  # no TTL wait after clean release


class TestOperatorHA:
    def test_only_holder_reconciles_and_failover(self, tmp_path):
        """The VERDICT done-criterion: two operators, one store; only the
        holder reconciles; kill it and the follower takes over within the
        lease TTL (and actually completes work)."""
        from tests.helpers import make_tpujob

        from kubedl_tpu.api.types import JobConditionType
        from kubedl_tpu.operator import Operator, OperatorOptions
        from kubedl_tpu.runtime.executor import SubprocessRuntime

        store = ObjectStore()
        logs = str(tmp_path / "logs")

        def opts(ident):
            return OperatorOptions(
                local_addresses=True, pod_log_dir=logs,
                artifact_registry_root=str(tmp_path / f"reg-{ident}"),
                leader_elect=True, leader_identity=ident,
                leader_lease_ttl=0.6,
            )

        op1 = Operator(opts("op1"), runtime=SubprocessRuntime(logs), store=store)
        op2 = Operator(opts("op2"), runtime=SubprocessRuntime(logs), store=store)
        op1.start()
        # op1 campaigns alone first so leadership is deterministic
        deadline = time.time() + 5
        while time.time() < deadline and not op1.elector.is_leader:
            time.sleep(0.02)
        assert op1.elector.is_leader
        op2.start()
        time.sleep(1.0)  # give op2 time to (NOT) steal
        assert not op2.elector.is_leader
        assert op1.manager._running and not op2.manager._running

        try:
            # work completes under the leader
            job = make_tpujob("ha1", workers=1, command=["true"])
            op1.submit(job)
            got = op1.wait_for_phase(
                "TPUJob", "ha1",
                [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
                timeout=60,
            )
            assert got.status.phase == JobConditionType.SUCCEEDED

            # kill the holder WITHOUT a clean release (simulated crash:
            # stop its campaign thread and its manager, keep the lease)
            op1.elector._stop.set()
            op1.elector._thread.join(timeout=2)
            op1._on_deposed()

            # follower takes over within ~TTL
            deadline = time.time() + 10
            while time.time() < deadline and not op2.elector.is_leader:
                time.sleep(0.05)
            assert op2.elector.is_leader
            assert op2.manager._running
            lease = store.get("Lease", "kubedl-election", "kubedl-system")
            assert lease.holder == "op2" and lease.transitions == 1

            # and actually reconciles new work
            job2 = make_tpujob("ha2", workers=1, command=["true"])
            op2.submit(job2)
            got2 = op2.wait_for_phase(
                "TPUJob", "ha2",
                [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
                timeout=60,
            )
            assert got2.status.phase == JobConditionType.SUCCEEDED
        finally:
            op1.stop()
            op2.stop()


class TestFailoverTiming:
    def test_clean_stop_hands_over_faster_than_crash(self):
        """Satellite of the crash-recovery PR: a clean stop() releases the
        lease, so the standby acquires within ~one renew interval; after a
        crash (no release) it must wait out the remaining TTL. The two
        delays are measured with real clocks and must be cleanly ordered."""
        ttl = 1.5  # renew interval = ttl/3 = 0.5

        def wait_leader(elector, timeout):
            deadline = time.time() + timeout
            while time.time() < deadline and not elector.is_leader:
                time.sleep(0.02)
            return elector.is_leader

        # clean handoff
        store = ObjectStore()
        a = LeaderElector(store, identity="a", ttl=ttl)
        b = LeaderElector(store, identity="b", ttl=ttl)
        a.start()
        assert wait_leader(a, 5)
        b.start()
        t0 = time.time()
        a.stop()  # releases the lease
        assert wait_leader(b, ttl * 4)
        clean_delay = time.time() - t0
        b.stop()

        # crash (campaign thread dies, lease NOT released)
        store2 = ObjectStore()
        c = LeaderElector(store2, identity="c", ttl=ttl)
        d = LeaderElector(store2, identity="d", ttl=ttl)
        c.start()
        assert wait_leader(c, 5)
        d.start()
        c._stop.set()
        c._thread.join(timeout=2)
        t0 = time.time()
        assert wait_leader(d, ttl * 4)
        crash_delay = time.time() - t0
        d.stop()

        # clean handoff beats TTL expiry: within ~one renew interval
        # (generous CI slack) vs. most of the TTL
        assert clean_delay < ttl * 0.6, clean_delay
        assert crash_delay > ttl * 0.55, crash_delay
        assert clean_delay < crash_delay


class TestFailoverDrill:
    def test_standby_takeover_adopts_pods_and_slices(self, tmp_path):
        """The leader-failover drill (docs/robustness.md): kill the lease
        holder WITHOUT touching its pods; the standby must take over and
        run the same rehydrate-then-adopt path a cold restart does —
        re-reserving gang slices into ITS inventory and adopting the
        running processes instead of relaunching them."""
        import sys

        from tests.helpers import make_tpujob

        from kubedl_tpu.api.topology import get_slice
        from kubedl_tpu.api.types import JobConditionType
        from kubedl_tpu.gang.slice_scheduler import SliceInventory
        from kubedl_tpu.operator import Operator, OperatorOptions
        from kubedl_tpu.runtime.executor import SubprocessRuntime

        store = ObjectStore()
        logs = str(tmp_path / "logs")

        def inventory():
            inv = SliceInventory()
            inv.add_slice("s1", "v5e-8")
            return inv

        def opts(ident):
            return OperatorOptions(
                local_addresses=True, pod_log_dir=logs,
                artifact_registry_root=str(tmp_path / f"reg-{ident}"),
                leader_elect=True, leader_identity=ident,
                leader_lease_ttl=0.6,
            )

        op1 = Operator(opts("op1"), runtime=SubprocessRuntime(logs),
                       store=store, inventory=inventory())
        op2 = Operator(opts("op2"), runtime=SubprocessRuntime(logs),
                       store=store, inventory=inventory())
        try:
            op1.start()
            deadline = time.time() + 5
            while time.time() < deadline and not op1.elector.is_leader:
                time.sleep(0.02)
            assert op1.elector.is_leader
            op2.start()  # standby

            job = make_tpujob(
                "drill", workers=2,
                command=[sys.executable, "-c", "import time; time.sleep(60)"],
                topology=get_slice("v5e-8"),
            )
            op1.submit(job)
            op1.wait_for_phase("TPUJob", "drill", JobConditionType.RUNNING,
                               timeout=30)

            def running(s):
                from kubedl_tpu.core.objects import PodPhase
                return {p.metadata.name: p.metadata.uid
                        for p in s.list("Pod")
                        if p.status.phase == PodPhase.RUNNING}

            assert op1.manager.wait(lambda: len(running(store)) == 2,
                                    timeout=20)
            before = running(store)

            # crash the leader but leave its pods alive (stop the campaign
            # thread so the lease is NOT released, drop kubelet handles)
            op1.elector._stop.set()
            op1.elector._thread.join(timeout=2)
            op1.manager.stop()
            op1.node_heartbeater.stop()
            op1.kubelet._running.clear()
            op1.kubelet._running_uid.clear()

            deadline = time.time() + 10
            while time.time() < deadline and not op2.elector.is_leader:
                time.sleep(0.05)
            assert op2.elector.is_leader

            # same pods, same uids, adopted not relaunched
            assert op2.manager.wait(
                lambda: op2.kubelet.adopted_count == 2, timeout=10)
            assert running(store) == before
            assert op2.kubelet.launch_count == 0
            # gang slices re-reserved into the NEW leader's inventory
            gang = store.get("PodGroup", "drill-gang")
            assert sorted(op2.inventory.owned_slices(
                "default/drill-gang")) == sorted(gang.assigned_slices)
            assert gang.assigned_slices == ["s1"]
        finally:
            op2.stop()
            op1.stop()

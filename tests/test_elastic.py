"""Elastic slice scaling (tier-1): preemption-aware grow/shrink with
reshard-resume (kubedl_tpu/elastic/, docs/elasticity.md).

Invariants asserted here:
- draining slices are never reserved and the console detail exposes the
  drain state; an elastic shrink releases the draining slice first;
- the ElasticSpec range is schema-validated (min >= 1, max >= min) and
  defaulted, for both TPUJob's ``elastic:`` block and ElasticDLJob's
  first-class min/max/num fields;
- ``grad_accum_for_world`` preserves the effective global batch while
  keeping the per-device microbatch at its tuned size;
- a seeded ``elastic.preempt`` fault drives the full loop end to end —
  notice -> drain -> shrink -> clear -> grow — with restart counts and
  the final world size matching the fault plan exactly;
- the grow path is flap-damped (per-job cooldown; shrinks bypass it);
- resize failures count against the reconcile quarantine budget (a
  poisoned resize parks the job, never hot-loops the workqueue);
- a 4 -> 2 -> 4 reshard-resume reproduces the fixed-size loss trajectory
  (checkpoint assembly across shardings + grad-accum rescaling).
"""

import os
import time

import numpy as np
import pytest

from kubedl_tpu import chaos
from kubedl_tpu.api.topology import get_slice
from kubedl_tpu.api.types import ElasticSpec, JobConditionType
from kubedl_tpu.chaos import FaultPlan, FaultSpec
from kubedl_tpu.elastic.resize import goodput, grad_accum_for_world
from kubedl_tpu.gang.slice_scheduler import (
    SliceGangScheduler,
    SliceInventory,
    owner_key,
)

from tests.helpers import PodDriver, make_tpujob, pod_names


@pytest.fixture(autouse=True)
def _disarmed():
    chaos.disarm()
    yield
    chaos.disarm()


# --------------------------------------------------------------------------
# Inventory: draining semantics
# --------------------------------------------------------------------------


class TestInventoryDraining:
    def _inv(self):
        inv = SliceInventory()
        inv.add_slice("sa", "cpu-1")
        inv.add_slice("sb", "cpu-1")
        return inv

    def test_try_reserve_skips_draining(self):
        inv = self._inv()
        assert inv.mark_draining("sb", "maintenance")
        assert inv.try_reserve("cpu-1", 2, "ns/j-gang") == []  # all-or-nothing
        assert inv.try_reserve("cpu-1", 1, "ns/j-gang") == ["sa"]

    def test_mark_and_clear_are_edge_triggered(self):
        inv = self._inv()
        assert inv.mark_draining("sb") is True
        assert inv.mark_draining("sb") is False  # already draining
        assert inv.clear_draining("sb") is True
        assert inv.clear_draining("sb") is False
        assert inv.mark_draining("nope") is False  # unknown slice

    def test_detail_exposes_drain_state(self):
        inv = self._inv()
        inv.mark_draining("sb", "preempt notice on sb-host-0")
        by_name = {d["name"]: d for d in inv.detail()}
        assert by_name["sb"]["draining"] is True
        assert by_name["sb"]["drain_reason"] == "preempt notice on sb-host-0"
        assert by_name["sa"]["draining"] is False

    def test_shrink_owner_releases_draining_first(self):
        inv = self._inv()
        owner = owner_key("default", "j")
        assert inv.try_reserve("cpu-1", 2, owner) == ["sa", "sb"]
        inv.mark_draining("sa", "victim")  # lowest name, but draining
        assert inv.shrink_owner(owner, 1) == ["sb"]  # healthy one kept
        assert inv.owned_slices(owner) == ["sb"]
        # the draining slice is free again (for after its notice clears)
        assert inv.draining_slices() == ["sa"]
        assert inv.free_slices("cpu-1") == []  # but not reservable yet

    def test_slice_of_host_maps_notice_to_slice(self):
        inv = self._inv()
        assert inv.slice_of_host("sb-host-0") == "sb"
        assert inv.slice_of_host("unknown-host") is None


# --------------------------------------------------------------------------
# Controller: a RETRACTED notice must un-drain (clear_preemption path)
# --------------------------------------------------------------------------


class TestPreemptionRetract:
    """The mark path is pinned by the E2E drill; this pins the retract
    path in isolation: clear_preemption -> next beat wipes Node.preempt_at
    -> reconcile clears the drain -> the slice is reservable again."""

    def _rig(self):
        from kubedl_tpu.core.nodes import NodeHeartbeater
        from kubedl_tpu.core.store import ObjectStore
        from kubedl_tpu.elastic.preemption import PreemptionController

        store = ObjectStore()
        inv = SliceInventory()
        inv.add_slice("sa", "cpu-1")
        hb = NodeHeartbeater(store, ["sa-host-0"], clock=lambda: 100.0)
        ctl = PreemptionController(store, inv)
        return store, inv, hb, ctl

    def test_clear_preemption_undrains_and_restores_reservation(self):
        from kubedl_tpu.core.nodes import NODE_NAMESPACE

        store, inv, hb, ctl = self._rig()
        hb.announce_preemption("sa-host-0", "spot reclaim in 60s")
        hb.beat_once()
        ctl.reconcile(NODE_NAMESPACE, "sa-host-0")
        assert inv.draining_slices() == ["sa"]
        assert inv.try_reserve("cpu-1", 1, "ns/j-gang") == []

        hb.clear_preemption("sa-host-0")
        hb.beat_once()
        assert store.get("Node", "sa-host-0", NODE_NAMESPACE).preempt_at == 0.0
        ctl.reconcile(NODE_NAMESPACE, "sa-host-0")
        assert inv.draining_slices() == []
        assert inv.try_reserve("cpu-1", 1, "ns/j-gang") == ["sa"]
        reasons = [e.reason for e in store.list("Event", None)]
        assert "PreemptionNotice" in reasons
        assert "PreemptionCleared" in reasons

    def test_multi_host_slice_clears_only_after_last_notice(self):
        from kubedl_tpu.core.nodes import NODE_NAMESPACE, NodeHeartbeater
        from kubedl_tpu.core.store import ObjectStore
        from kubedl_tpu.elastic.preemption import PreemptionController

        store = ObjectStore()
        inv = SliceInventory()
        inv.add_slice("sa", "cpu-1", hosts=["sa-host-0", "sa-host-1"])
        hb = NodeHeartbeater(
            store, ["sa-host-0", "sa-host-1"], clock=lambda: 100.0
        )
        ctl = PreemptionController(store, inv)

        hb.announce_preemption("sa-host-0")
        hb.announce_preemption("sa-host-1")
        hb.beat_once()
        for host in ("sa-host-0", "sa-host-1"):
            ctl.reconcile(NODE_NAMESPACE, host)
        assert inv.draining_slices() == ["sa"]

        # first host's withdrawal must NOT return the slice to service
        hb.clear_preemption("sa-host-0")
        hb.beat_once()
        ctl.reconcile(NODE_NAMESPACE, "sa-host-0")
        assert inv.draining_slices() == ["sa"]
        assert inv.try_reserve("cpu-1", 1, "ns/j-gang") == []

        hb.clear_preemption("sa-host-1")
        hb.beat_once()
        ctl.reconcile(NODE_NAMESPACE, "sa-host-1")
        assert inv.draining_slices() == []
        assert inv.try_reserve("cpu-1", 1, "ns/j-gang") == ["sa"]


# --------------------------------------------------------------------------
# Spec validation + defaulting (TPUJob elastic block, ElasticDLJob fields)
# --------------------------------------------------------------------------


class TestElasticSpecValidation:
    def test_elastic_spec_rules(self):
        assert ElasticSpec(min_slices=1, max_slices=2).validate() == []
        assert any("minSlices" in e for e in ElasticSpec(min_slices=0).validate())
        assert any(
            "maxSlices" in e
            for e in ElasticSpec(min_slices=3, max_slices=2).validate()
        )
        assert any(
            "cooldown" in e
            for e in ElasticSpec(cooldown_seconds=-1.0).validate()
        )

    def test_clamp(self):
        spec = ElasticSpec(min_slices=2, max_slices=4)
        assert spec.clamp(1) == 2
        assert spec.clamp(3) == 3
        assert spec.clamp(9) == 4

    def test_tpujob_submit_rejects_bad_range(self, tmp_path):
        from kubedl_tpu.operator import Operator, OperatorOptions, ValidationError

        op = Operator(OperatorOptions(
            local_addresses=True, artifact_registry_root=str(tmp_path / "r")))
        try:
            job = make_tpujob("badel", workers=1, topology=get_slice("cpu-1"))
            job.elastic = ElasticSpec(min_slices=2, max_slices=1)
            with pytest.raises(ValidationError, match="maxSlices"):
                op.submit(job)
        finally:
            op.stop()

    def test_tpujob_defaults_clamp_and_stamp_base_world(self):
        from kubedl_tpu.api import constants
        from kubedl_tpu.workloads.tpujob import TPUJobController

        ctrl = TPUJobController(local_addresses=True)
        job = make_tpujob("el", workers=2, topology=get_slice("cpu-1"))
        job.num_slices = 5  # above the elastic ceiling
        job.elastic = ElasticSpec(min_slices=1, max_slices=2)
        ctrl.apply_defaults(job)
        assert job.num_slices == 2
        assert (
            job.metadata.annotations[constants.ANNOTATION_ELASTIC_BASE_WORLD]
            == "2"  # cpu-1: 1 host/slice x 2 slices
        )
        # the stamp is sticky across resizes: base world never re-derives
        ctrl.set_num_slices(job, 1)
        ctrl.apply_defaults(job)
        assert (
            job.metadata.annotations[constants.ANNOTATION_ELASTIC_BASE_WORLD]
            == "2"
        )
        assert ctrl.elastic_range(job) == (1, 2)

    def test_elasticdljob_validation_and_defaults(self):
        from kubedl_tpu.api.types import ReplicaSpec, ReplicaType
        from kubedl_tpu.core.objects import Container
        from kubedl_tpu.workloads.elasticdljob import (
            ElasticDLJob,
            ElasticDLJobController,
        )

        ctrl = ElasticDLJobController(local_addresses=True)
        job = ElasticDLJob(min_slices=0, max_slices=2)
        job.metadata.name = "edl"
        spec = ReplicaSpec(replicas=1, topology=get_slice("cpu-1"))
        spec.template.spec.containers.append(Container())
        job.spec.replica_specs[ReplicaType.MASTER] = spec
        assert any("minSlices" in e for e in ctrl.validate(job))
        job.min_slices, job.max_slices = 3, 1
        assert any("maxSlices" in e for e in ctrl.validate(job))
        job.min_slices, job.max_slices = 2, 3
        assert ctrl.validate(job) == []
        ctrl.apply_defaults(job)  # num_slices unset -> min_slices
        assert job.num_slices == 2
        assert spec.replicas == 2  # 1 host/slice x 2 slices
        assert ctrl.elastic_range(job) == (2, 3)

    def test_schemas_carry_the_elastic_fields(self):
        import json
        from pathlib import Path

        schemas = Path(__file__).resolve().parent.parent / "deploy" / "rendered" / "schemas"
        tpu = json.loads((schemas / "TPUJob.json").read_text())
        assert "elastic" in tpu["properties"]
        edl = json.loads((schemas / "ElasticDLJob.json").read_text())
        for f in ("min_slices", "max_slices", "num_slices"):
            assert f in edl["properties"]


# --------------------------------------------------------------------------
# Batch-semantics math
# --------------------------------------------------------------------------


class TestGradAccumForWorld:
    def test_shrink_raises_accum_inversely(self):
        assert grad_accum_for_world(1, 4, 2, 8) == 2  # half the world -> 2x
        assert grad_accum_for_world(2, 4, 1, 8) == 8
        assert grad_accum_for_world(1, 4, 4, 8) == 1  # no change

    def test_grow_lowers_accum(self):
        assert grad_accum_for_world(4, 2, 4, 8) == 2
        assert grad_accum_for_world(1, 4, 8, 8) == 1  # never below 1

    def test_walks_down_to_a_divisor(self):
        # target 8*3//4=6 does not divide 8 -> walk to 4
        assert grad_accum_for_world(8, 3, 4, 8) == 4
        # never above global_batch
        assert grad_accum_for_world(64, 8, 1, 16) == 16

    def test_goodput_clamped(self):
        assert goodput(8.0, 10.0) == 0.8
        assert goodput(12.0, 10.0) == 1.0
        assert goodput(1.0, 0.0) == 0.0


# --------------------------------------------------------------------------
# Policy: hysteresis + drain-shrink priority
# --------------------------------------------------------------------------


class TestPolicyHysteresis:
    def _policy(self, cooldown=30.0, slices=3):
        from kubedl_tpu.core.store import ObjectStore
        from kubedl_tpu.elastic.policy import ElasticPolicy
        from kubedl_tpu.workloads.tpujob import TPUJobController

        store = ObjectStore()
        inv = SliceInventory()
        for i in range(slices):
            inv.add_slice(f"s{i}", "cpu-1")
        gang = SliceGangScheduler(store, inv)
        ctrl = TPUJobController(local_addresses=True)
        t = {"now": 1000.0}
        policy = ElasticPolicy(
            store, inv, gang, {"TPUJob": ctrl},
            cooldown=cooldown, clock=lambda: t["now"],
        )
        job = make_tpujob("hj", workers=1, topology=get_slice("cpu-1"))
        job.elastic = ElasticSpec(min_slices=1, max_slices=3)
        ctrl.apply_defaults(job)
        job.status.set_condition(JobConditionType.RUNNING, "test")
        store.create(job)
        return policy, store, inv, t

    def _slices(self, store):
        return store.get("TPUJob", "hj").num_slices

    def test_at_most_one_grow_per_cooldown_window(self):
        policy, store, inv, t = self._policy(cooldown=30.0, slices=3)
        # hold s0 so only 1 slice is free: the first grow takes 1 -> 2
        owner = owner_key("default", "hj")
        assert inv.try_reserve("cpu-1", 1, owner) == ["s0"]
        inv.try_reserve("cpu-1", 1, "default/other-gang")  # s1 parked
        assert policy.reconcile(*policy.KEY) is None
        assert self._slices(store) == 2
        # capacity oscillates: other job frees its slice inside the window
        inv.release("default/other-gang")
        requeue = policy.reconcile(*policy.KEY)
        assert self._slices(store) == 2  # damped: no second grow yet
        assert requeue is not None and requeue > 0
        t["now"] += 31.0  # window closes
        assert policy.reconcile(*policy.KEY) is None
        assert self._slices(store) == 3

    def test_shrink_bypasses_cooldown(self):
        policy, store, inv, t = self._policy(cooldown=30.0, slices=2)
        owner = owner_key("default", "hj")
        assert inv.try_reserve("cpu-1", 2, owner) == ["s0", "s1"]
        store.update_with_retry(
            "TPUJob", "hj", "default", lambda j: setattr(j, "num_slices", 2)
        )
        policy.reconcile(*policy.KEY)  # stamp the cooldown via a no-op scan
        inv.mark_draining("s1", "reclaim in 60s")
        policy.reconcile(*policy.KEY)  # immediately, no window wait
        assert self._slices(store) == 1
        assert any(
            e.reason == "ElasticResize" for e in store.list("Event", None)
        )

    def test_no_shrink_without_draining_and_floor_respected(self):
        policy, store, inv, t = self._policy(cooldown=0.0, slices=1)
        owner = owner_key("default", "hj")
        assert inv.try_reserve("cpu-1", 1, owner) == ["s0"]
        policy.reconcile(*policy.KEY)
        assert self._slices(store) == 1  # nothing free, nothing draining
        inv.mark_draining("s0", "victim")
        policy.reconcile(*policy.KEY)
        # at min_slices the job stays put (eviction path is the fallback)
        assert self._slices(store) == 1

    def test_hands_off_terminal_and_fixed_size_jobs(self):
        policy, store, inv, t = self._policy(cooldown=0.0, slices=3)
        store.update_with_retry(
            "TPUJob", "hj", "default",
            lambda j: j.status.set_condition(JobConditionType.SUCCEEDED, "done"),
        )
        assert policy.reconcile(*policy.KEY) is None
        assert self._slices(store) == 1
        # fixed-size job (no elastic block): untouched even while RUNNING
        fixed = make_tpujob("fx", workers=1, topology=get_slice("cpu-1"))
        fixed.status.set_condition(JobConditionType.RUNNING, "test")
        store.create(fixed)
        policy.reconcile(*policy.KEY)
        assert store.get("TPUJob", "fx").num_slices == 1


# --------------------------------------------------------------------------
# Engine: in-place resize + quarantine interaction
# --------------------------------------------------------------------------


class TestResizeQuarantine:
    def test_resize_failures_count_against_reconcile_budget(self):
        from tests.test_engine import make_engine

        inv = SliceInventory()
        inv.add_slice("qa", "cpu-1")
        inv.add_slice("qb", "cpu-1")
        engine, store, metrics = make_engine(inventory=inv)
        job = make_tpujob("qz", workers=1, topology=get_slice("cpu-1"))
        job.elastic = ElasticSpec(min_slices=1, max_slices=2)
        engine.controller.apply_defaults(job)
        store.create(job)
        engine.reconcile("default", "qz")
        PodDriver(store).run_all(store)
        engine.reconcile("default", "qz")
        assert store.get("TPUJob", "qz").status.phase == JobConditionType.RUNNING

        def boom(job, gang, count):
            raise RuntimeError("resize blew up")

        engine.gang.resize_gang = boom
        engine.quarantine_budget = 3
        store.update_with_retry(
            "TPUJob", "qz", "default", lambda j: setattr(j, "num_slices", 2)
        )
        for _ in range(2):
            with pytest.raises(RuntimeError):
                engine.reconcile("default", "qz")
        assert engine.reconcile("default", "qz") is None  # parked
        got = store.get("TPUJob", "qz")
        assert got.status.phase == JobConditionType.QUARANTINED
        assert got.status.conditions[-1].reason == "ReconcileBudgetExhausted"
        assert metrics.quarantined.value(kind="TPUJob") == 1.0


# --------------------------------------------------------------------------
# E2E: seeded preemption notice -> drain -> shrink -> clear -> grow
# --------------------------------------------------------------------------

_STOP = {"path": ""}


def _gated_worker(env):
    """ThreadRuntime entrypoint: runs until the test touches the stop file;
    resize/restart cancellation exits retryably (the SIGKILL class)."""
    cancel = (env or {}).get("_KUBEDL_CANCEL")
    while not (_STOP["path"] and os.path.exists(_STOP["path"])):
        if cancel is not None and getattr(cancel, "is_set", lambda: False)():
            raise SystemExit(137)
        time.sleep(0.02)
    return 0


class TestElasticE2E:
    def test_preempt_shrink_clear_grow_under_seeded_chaos(self, tmp_path):
        from kubedl_tpu.operator import Operator, OperatorOptions
        from kubedl_tpu.runtime.executor import ThreadRuntime

        _STOP["path"] = str(tmp_path / "stop")
        inv = SliceInventory()
        inv.add_slice("sa", "cpu-1")  # hosts: sa-host-0
        inv.add_slice("sb", "cpu-1")  # hosts: sb-host-0
        opts = OperatorOptions(
            local_addresses=True,
            artifact_registry_root=str(tmp_path / "reg"),
            heartbeat_nodes=["sa-host-0", "sb-host-0"],
            node_grace_seconds=2.0,  # beat interval ~0.67s
        )
        plan = FaultPlan(23, sites={"elastic.preempt": [FaultSpec.nth(2)]})
        with Operator(opts, runtime=ThreadRuntime(), inventory=inv) as op:
            job = make_tpujob(
                "ejob", workers=2, topology=get_slice("cpu-1"),
                entrypoint=f"{__name__}:_gated_worker",
            )
            job.elastic = ElasticSpec(
                min_slices=1, max_slices=2, cooldown_seconds=0.2
            )
            job.num_slices = 2  # start at the ceiling: no startup grow
            op.submit(job)
            op.wait_for_phase("TPUJob", "ejob", JobConditionType.RUNNING,
                              timeout=60)

            with plan:
                # beats visit nodes in heartbeat_nodes order, so nth(2)
                # deterministically notices sb-host-0 -> slice sb drains
                def shrunk():
                    got = op.store.try_get("TPUJob", "ejob")
                    return (
                        got is not None
                        and got.num_slices == 1
                        and got.status.restart_count >= 1
                        and len(pod_names(op.store)) == 1
                    )

                assert op.manager.wait(shrunk, timeout=60), \
                    "job never shrank off the draining slice"
                detail = {d["name"]: d for d in inv.detail()}
                assert detail["sb"]["draining"] is True
                assert detail["sa"]["allocated_to"] == "default/ejob-gang"
                got = op.store.get("TPUJob", "ejob")
                assert any(
                    c.type == JobConditionType.RESIZING
                    for c in got.status.conditions
                )

                # notice withdrawn: capacity returns, the policy grows back
                op.node_heartbeater.clear_preemption("sb-host-0")

                def grown():
                    got = op.store.try_get("TPUJob", "ejob")
                    return (
                        got is not None
                        and got.num_slices == 2
                        and got.status.restart_count >= 2
                        and len(pod_names(op.store)) == 2
                    )

                assert op.manager.wait(grown, timeout=60), \
                    "job never grew back after the notice cleared"
                assert not inv.draining_slices()

                with open(_STOP["path"], "w") as f:
                    f.write("done")
                got = op.wait_for_phase(
                    "TPUJob", "ejob",
                    [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
                    timeout=60,
                )
            # deterministic: exactly the planned single injected notice
            assert plan.faults("elastic.preempt") == 1
            assert got.status.phase == JobConditionType.SUCCEEDED
            assert got.num_slices == 2  # final world matches the fault plan
            assert got.status.restart_count == 2  # shrink + grow, no extras
            assert op.metrics.resizes.value(kind="TPUJob") == 2.0
            assert op.metrics.preemption_notices.value() == 1.0
            assert op.metrics.slices_draining.value() == 0.0
            reasons = {e.reason for e in op.store.list("Event", None)}
            assert "PreemptionNotice" in reasons
            assert "PreemptionCleared" in reasons
            assert "ElasticResize" in reasons


# --------------------------------------------------------------------------
# Reshard-resume equivalence: 4 -> 2 -> 4 matches fixed-size
# --------------------------------------------------------------------------


class TestReshardResume:
    @pytest.mark.slow
    def test_4_2_4_loss_trajectory_matches_fixed_size(self, tmp_path):
        import jax

        from kubedl_tpu.api.topology import MeshSpec
        from kubedl_tpu.models import llama
        from kubedl_tpu.parallel.mesh import build_mesh
        from kubedl_tpu.training.checkpoint import restore_checkpoint
        from kubedl_tpu.training.data import SyntheticTokens
        from kubedl_tpu.training.trainer import TrainConfig, Trainer

        assert jax.device_count() >= 4
        model = llama.TINY
        GB, SL, STEPS = 8, 16, 9

        def cfg(accum):
            return TrainConfig(model=model, global_batch=GB, seq_len=SL,
                               steps=STEPS, grad_accum=accum)

        def data_at(step):
            it = iter(SyntheticTokens(GB, SL, model.vocab_size, seed=5))
            for _ in range(step):
                next(it)  # fit consumes one batch per step
            return it

        def run(trainer, start, stop, ckpt):
            state = trainer.init_state()
            if start > 0:
                state = restore_checkpoint(ckpt, state)
                assert state is not None
                assert int(jax.device_get(state["step"])) == start
            losses = []
            state, _ = trainer.fit(
                data_at(start), state=state, steps=stop,
                on_step=lambda i, m: losses.append(m["loss"]),
                ckpt_dir=ckpt,
            )
            return [float(jax.device_get(l)) for l in losses]

        mesh4 = build_mesh(MeshSpec({"data": 4}), jax.devices()[:4])
        mesh2 = build_mesh(MeshSpec({"data": 2}), jax.devices()[:2])

        baseline = run(Trainer(cfg(1), mesh4), 0, STEPS,
                       str(tmp_path / "base"))
        assert len(baseline) == STEPS

        # elastic: 4 devices for steps 0-2, shrink to 2 (grad_accum
        # rescaled by the same helper the worker entrypoint uses), grow
        # back to 4 — resuming through the cross-sharding assembler
        ck = str(tmp_path / "elastic")
        accum2 = grad_accum_for_world(1, 4, 2, GB)
        assert accum2 == 2
        losses = run(Trainer(cfg(1), mesh4), 0, 3, ck)
        losses += run(Trainer(cfg(accum2), mesh2), 3, 6, ck)
        losses += run(Trainer(cfg(1), mesh4), 6, STEPS, ck)
        assert len(losses) == STEPS

        # the effective global batch never changed, so the trajectory is
        # the fixed-size one (modulo reduction-order float noise)
        np.testing.assert_allclose(losses, baseline, rtol=2e-3, atol=2e-3)

"""Object store, watches, workqueue, manager GC."""

import threading
import time

import pytest

from kubedl_tpu.core.manager import ControllerManager
from kubedl_tpu.core.objects import ConfigMap, OwnerRef, Pod
from kubedl_tpu.core.store import AlreadyExists, Conflict, NotFound, ObjectStore
from kubedl_tpu.core.workqueue import WorkQueue


class TestStore:
    def test_crud_roundtrip(self):
        store = ObjectStore()
        pod = Pod()
        pod.metadata.name = "p1"
        created = store.create(pod)
        assert created.metadata.resource_version > 0
        got = store.get("Pod", "p1")
        assert got.metadata.uid == created.metadata.uid
        with pytest.raises(AlreadyExists):
            store.create(pod)
        store.delete("Pod", "p1")
        with pytest.raises(NotFound):
            store.get("Pod", "p1")

    def test_deep_copy_isolation(self):
        store = ObjectStore()
        pod = Pod()
        pod.metadata.name = "p1"
        store.create(pod)
        got = store.get("Pod", "p1")
        got.metadata.labels["x"] = "y"  # mutating the copy
        assert "x" not in store.get("Pod", "p1").metadata.labels

    def test_optimistic_conflict(self):
        store = ObjectStore()
        pod = Pod()
        pod.metadata.name = "p1"
        store.create(pod)
        a = store.get("Pod", "p1")
        b = store.get("Pod", "p1")
        store.update(a)
        with pytest.raises(Conflict):
            store.update(b)
        # retry helper wins
        store.update_with_retry("Pod", "p1", "default", lambda o: o.metadata.labels.update(r="1"))
        assert store.get("Pod", "p1").metadata.labels["r"] == "1"

    def test_label_selector_list(self):
        store = ObjectStore()
        for i, role in enumerate(["a", "b", "a"]):
            p = Pod()
            p.metadata.name = f"p{i}"
            p.metadata.labels["role"] = role
            store.create(p)
        assert len(store.list("Pod", selector={"role": "a"})) == 2

    def test_watch_events(self):
        store = ObjectStore()
        events = []
        cancel = store.watch(lambda e, o, old: events.append((e, o.metadata.name)), ["Pod"])
        p = Pod()
        p.metadata.name = "p1"
        store.create(p)
        store.update_with_retry("Pod", "p1", "default", lambda o: None)
        store.delete("Pod", "p1")
        assert events == [("ADDED", "p1"), ("MODIFIED", "p1"), ("DELETED", "p1")]
        cancel()
        store.create(p)
        assert len(events) == 3  # unsubscribed

    def test_orphan_gc(self):
        store = ObjectStore()
        owner = ConfigMap()
        owner.metadata.name = "owner"
        owner = store.create(owner)
        child = Pod()
        child.metadata.name = "child"
        child.metadata.owner_refs.append(
            OwnerRef(kind="ConfigMap", name="owner", uid=owner.metadata.uid)
        )
        store.create(child)
        assert store.collect_orphans() == 0
        store.delete("ConfigMap", "owner")
        assert store.collect_orphans() == 1
        assert store.try_get("Pod", "child") is None


class TestWorkQueue:
    def test_dedup(self):
        q = WorkQueue()
        q.add("a")
        q.add("a")
        q.add("b")
        assert q.get(0.1) == "a"
        assert q.get(0.1) == "b"
        assert q.get(0.05) is None

    def test_readd_while_processing(self):
        q = WorkQueue()
        q.add("a")
        item = q.get(0.1)
        q.add("a")  # while processing
        assert q.get(0.01) is None  # not handed out twice concurrently
        q.done(item)
        assert q.get(0.1) == "a"

    def test_delayed(self):
        q = WorkQueue()
        q.add_after("x", 0.05)
        t0 = time.time()
        assert q.get(1.0) == "x"
        assert time.time() - t0 >= 0.04

    def test_rate_limit_backoff_grows(self):
        q = WorkQueue(base_delay=0.01)
        q.add_rate_limited("x")
        assert q.num_requeues("x") == 1
        q.add_rate_limited("x")
        assert q.num_requeues("x") == 2
        q.forget("x")
        assert q.num_requeues("x") == 0

    def test_get_batch_drains_in_order(self):
        q = WorkQueue()
        for item in ("a", "b", "c", "d"):
            q.add(item)
        assert q.get_batch(max_items=3, timeout=0.1) == ["a", "b", "c"]
        assert q.get_batch(max_items=3, timeout=0.1) == ["d"]
        assert q.get_batch(max_items=3, timeout=0.02) == []


class TestCoalescing:
    """Burst coalescing (``coalesce_window > 0``): a storm of N events on
    one key costs at most ceil(N-ish / window) reconciles, at least 1, and
    the final state is never dropped — the coalesced re-add always fires
    AFTER the last absorbed event."""

    def test_burst_collapses_to_bounded_pickups(self):
        win = 0.05
        q = WorkQueue(coalesce_window=win)
        n = 50
        q.add("job")
        assert q.get(0.1) == "job"
        q.done("job")
        # the burst: N rapid-fire events right after the pickup
        for _ in range(n):
            q.add("job")
        pickups = 0
        deadline = time.time() + 2.0
        while time.time() < deadline:
            item = q.get(timeout=win)
            if item is None:
                if len(q) == 0:
                    break
                continue
            pickups += 1
            q.done(item)
        # >= 1 (never dropped), and nowhere near one pickup per event:
        # the whole sub-window burst rides one scheduled re-add
        assert 1 <= pickups <= 3, pickups
        assert q.coalesced >= n - pickups

    def test_final_state_never_dropped(self):
        """An event that lands while the item is mid-processing (or mid-
        cooldown) must still produce a later pickup — coalescing absorbs
        duplicates, never the last level."""
        q = WorkQueue(coalesce_window=0.03)
        q.add("k")
        assert q.get(0.1) == "k"
        q.add("k")  # lands while processing -> dirty
        q.done("k")  # -> coalesced cooldown, not a drop
        assert q.get(1.0) == "k"  # fires at the window edge
        q.done("k")
        assert q.get(0.05) is None  # and exactly once

    def test_readd_after_window_is_immediate(self):
        win = 0.03
        q = WorkQueue(coalesce_window=win)
        q.add("k")
        assert q.get(0.1) == "k"
        q.done("k")
        time.sleep(win * 2)  # quiet period: the window has passed
        t0 = time.time()
        q.add("k")
        assert q.get(0.5) == "k"
        assert time.time() - t0 < win  # no cooldown applied

    def test_zero_window_is_exact_historical_behavior(self):
        q = WorkQueue(coalesce_window=0.0)
        q.add("k")
        assert q.get(0.1) == "k"
        q.done("k")
        q.add("k")
        assert q.get(0.05) == "k"  # immediate, no cooling
        assert q.coalesced == 0


class TestFairBatch:
    """A drain pass claims only the worker's fair share of the backlog:
    a shallow queue must stay single-key pickups — bulk-claiming it would
    serialize keys (a gang's pod launches) that idle sibling workers
    could have run in parallel."""

    def test_shallow_backlog_is_single_key(self):
        assert ControllerManager.fair_batch(depth=2, workers=4) == 1
        assert ControllerManager.fair_batch(depth=0, workers=4) == 1
        assert ControllerManager.fair_batch(depth=3, workers=4) == 1

    def test_deep_backlog_amortizes_to_full_batches(self):
        assert ControllerManager.fair_batch(depth=100, workers=4) == (
            ControllerManager.GET_BATCH
        )
        assert ControllerManager.fair_batch(depth=9, workers=3) == 3

    def test_single_worker_takes_whole_shallow_queue(self):
        assert ControllerManager.fair_batch(depth=5, workers=1) == 5

    def test_degenerate_worker_count(self):
        assert ControllerManager.fair_batch(depth=10, workers=0) == (
            ControllerManager.GET_BATCH
        )


class TestManager:
    def test_reconcile_driven_by_watch(self):
        mgr = ControllerManager()
        seen = []
        lock = threading.Lock()

        def reconcile(ns, name):
            with lock:
                seen.append((ns, name))
            return None

        from kubedl_tpu.core.manager import owner_mapper

        mgr.register("test", reconcile, ["ConfigMap"], owner_mapper("ConfigMap"))
        mgr.start()
        try:
            cm = ConfigMap()
            cm.metadata.name = "c1"
            mgr.store.create(cm)
            assert mgr.wait(lambda: ("default", "c1") in seen, timeout=5)
        finally:
            mgr.stop()


class TestWorkQueueBudget:
    def test_coalescing_storm_budget(self):
        """scripts/scheduler_microbench.py's workqueue arm as a tier-1
        gate: an enqueue storm on reconciled keys must cost ~1 pickup per
        key (never one per event), never drop the final state, and keep
        the absorbed-add hot path at dict-probe cost."""
        import sys
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
        from scripts.scheduler_microbench import run_workqueue_microbench

        out = run_workqueue_microbench(keys=100, events_per_key=30)
        assert out["within_budget"], out
        assert out["coalesced"] >= out["events"] - out["storm_pickups"], out


class TestExpectationsUnderCoalescing:
    def test_accounting_exact_when_reconciles_coalesce(self):
        """Coalescing absorbs RECONCILES, never watch events: every pod
        ADDED still decrements the expectation cache exactly once, so the
        gang is created exactly once and the counter lands on exactly
        zero — not negative (over-observation), not positive (a dropped
        event would wedge the job until expiry)."""
        from kubedl_tpu.core.manager import owner_mapper
        from kubedl_tpu.engine.expectations import (
            ControllerExpectations, expectation_key,
        )

        gang = 8
        store = ObjectStore()
        mgr = ControllerManager(store=store)
        exps = ControllerExpectations()
        created_batches = []
        lock = threading.Lock()

        def exp_key(ns, name):
            return expectation_key(f"{ns}/{name}", "worker", "pods")

        # the engine's watch-side accounting: one observed() per event
        def on_event(event, obj, _old):
            if obj.kind != "Pod" or event != "ADDED":
                return
            owner = obj.metadata.owner_refs[0]
            exps.creation_observed(
                exp_key(obj.metadata.namespace, owner.name))

        store.watch(on_event, ["Pod"])

        def reconcile(ns, name):
            if store.try_get("ConfigMap", name, ns) is None:
                return None
            key = exp_key(ns, name)
            if not exps.satisfied(key):
                return None  # cache behind: creating again = duplicates
            owner = store.get("ConfigMap", name, ns)
            missing = [
                k for k in range(gang)
                if store.try_get("Pod", f"{name}-p{k}", ns) is None
            ]
            if not missing:
                return None
            exps.expect_creations(key, len(missing))
            pods = []
            for k in missing:
                p = Pod()
                p.metadata.name = f"{name}-p{k}"
                p.metadata.namespace = ns
                p.metadata.owner_refs.append(OwnerRef(
                    kind="ConfigMap", name=name,
                    uid=owner.metadata.uid, controller=True,
                ))
                pods.append(p)
            with lock:
                created_batches.append(len(missing))
            store.create_many(pods)
            return None

        mgr.register("gang", reconcile, ["ConfigMap", "Pod"],
                     owner_mapper("ConfigMap"), coalesce_window=0.02)
        mgr.start()
        try:
            cm = ConfigMap()
            cm.metadata.name = "job"
            store.create(cm)
            assert mgr.wait(
                lambda: len(store.list("Pod")) == gang, timeout=5)
            time.sleep(0.1)  # let the coalesced follow-up reconcile land
            key = exp_key("default", "job")
            assert exps.satisfied(key)
            # exact: all 8 ADDED events observed, none double-counted
            assert exps._exps[key].adds == 0
            # and the gang was created exactly once, in one batch
            assert created_batches == [gang]
        finally:
            mgr.stop()

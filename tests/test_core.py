"""Object store, watches, workqueue, manager GC."""

import threading
import time

import pytest

from kubedl_tpu.core.manager import ControllerManager
from kubedl_tpu.core.objects import ConfigMap, OwnerRef, Pod
from kubedl_tpu.core.store import AlreadyExists, Conflict, NotFound, ObjectStore
from kubedl_tpu.core.workqueue import WorkQueue


class TestStore:
    def test_crud_roundtrip(self):
        store = ObjectStore()
        pod = Pod()
        pod.metadata.name = "p1"
        created = store.create(pod)
        assert created.metadata.resource_version > 0
        got = store.get("Pod", "p1")
        assert got.metadata.uid == created.metadata.uid
        with pytest.raises(AlreadyExists):
            store.create(pod)
        store.delete("Pod", "p1")
        with pytest.raises(NotFound):
            store.get("Pod", "p1")

    def test_deep_copy_isolation(self):
        store = ObjectStore()
        pod = Pod()
        pod.metadata.name = "p1"
        store.create(pod)
        got = store.get("Pod", "p1")
        got.metadata.labels["x"] = "y"  # mutating the copy
        assert "x" not in store.get("Pod", "p1").metadata.labels

    def test_optimistic_conflict(self):
        store = ObjectStore()
        pod = Pod()
        pod.metadata.name = "p1"
        store.create(pod)
        a = store.get("Pod", "p1")
        b = store.get("Pod", "p1")
        store.update(a)
        with pytest.raises(Conflict):
            store.update(b)
        # retry helper wins
        store.update_with_retry("Pod", "p1", "default", lambda o: o.metadata.labels.update(r="1"))
        assert store.get("Pod", "p1").metadata.labels["r"] == "1"

    def test_label_selector_list(self):
        store = ObjectStore()
        for i, role in enumerate(["a", "b", "a"]):
            p = Pod()
            p.metadata.name = f"p{i}"
            p.metadata.labels["role"] = role
            store.create(p)
        assert len(store.list("Pod", selector={"role": "a"})) == 2

    def test_watch_events(self):
        store = ObjectStore()
        events = []
        cancel = store.watch(lambda e, o, old: events.append((e, o.metadata.name)), ["Pod"])
        p = Pod()
        p.metadata.name = "p1"
        store.create(p)
        store.update_with_retry("Pod", "p1", "default", lambda o: None)
        store.delete("Pod", "p1")
        assert events == [("ADDED", "p1"), ("MODIFIED", "p1"), ("DELETED", "p1")]
        cancel()
        store.create(p)
        assert len(events) == 3  # unsubscribed

    def test_orphan_gc(self):
        store = ObjectStore()
        owner = ConfigMap()
        owner.metadata.name = "owner"
        owner = store.create(owner)
        child = Pod()
        child.metadata.name = "child"
        child.metadata.owner_refs.append(
            OwnerRef(kind="ConfigMap", name="owner", uid=owner.metadata.uid)
        )
        store.create(child)
        assert store.collect_orphans() == 0
        store.delete("ConfigMap", "owner")
        assert store.collect_orphans() == 1
        assert store.try_get("Pod", "child") is None


class TestWorkQueue:
    def test_dedup(self):
        q = WorkQueue()
        q.add("a")
        q.add("a")
        q.add("b")
        assert q.get(0.1) == "a"
        assert q.get(0.1) == "b"
        assert q.get(0.05) is None

    def test_readd_while_processing(self):
        q = WorkQueue()
        q.add("a")
        item = q.get(0.1)
        q.add("a")  # while processing
        assert q.get(0.01) is None  # not handed out twice concurrently
        q.done(item)
        assert q.get(0.1) == "a"

    def test_delayed(self):
        q = WorkQueue()
        q.add_after("x", 0.05)
        t0 = time.time()
        assert q.get(1.0) == "x"
        assert time.time() - t0 >= 0.04

    def test_rate_limit_backoff_grows(self):
        q = WorkQueue(base_delay=0.01)
        q.add_rate_limited("x")
        assert q.num_requeues("x") == 1
        q.add_rate_limited("x")
        assert q.num_requeues("x") == 2
        q.forget("x")
        assert q.num_requeues("x") == 0


class TestManager:
    def test_reconcile_driven_by_watch(self):
        mgr = ControllerManager()
        seen = []
        lock = threading.Lock()

        def reconcile(ns, name):
            with lock:
                seen.append((ns, name))
            return None

        from kubedl_tpu.core.manager import owner_mapper

        mgr.register("test", reconcile, ["ConfigMap"], owner_mapper("ConfigMap"))
        mgr.start()
        try:
            cm = ConfigMap()
            cm.metadata.name = "c1"
            mgr.store.create(cm)
            assert mgr.wait(lambda: ("default", "c1") in seen, timeout=5)
        finally:
            mgr.stop()

"""Paged KV-block allocator + paged serving engine tests.

Three layers, mirroring the subsystem's split:

- `BlockAllocator` (pure host bookkeeping): alloc/free/refcount/COW
  invariants, fragmentation behavior, watermark hysteresis — property
  style where a random workload must conserve blocks.
- Model-level exactness: the paged prefill/decode twins produce
  bit-identical outputs to the contiguous functions (the tier-1 gate's
  foundation), including the graft-spill case the contiguous path needed
  a dispatch-time fixup for (trash routing retires it).
- Engine-level: paged vs contiguous bit-identity, prefix-entry block
  sharing (incref, not copy), watermark shedding, and block-exhaustion
  preempt-and-requeue under the `serving.kv_alloc` chaos site.
"""

import random

import pytest

from kubedl_tpu.serving.kv_blocks import TRASH_BLOCK, BlockAllocator


class TestAllocator:
    def test_trash_block_reserved(self):
        a = BlockAllocator(num_blocks=8, block_size=16)
        assert a.total == 7
        assert a.refcount(TRASH_BLOCK) == 1
        got = a.alloc(7)
        assert got is not None and TRASH_BLOCK not in got
        # trash is immune to free/incref bookkeeping
        a.free([TRASH_BLOCK])
        a.incref([TRASH_BLOCK])
        assert a.refcount(TRASH_BLOCK) == 1

    def test_alloc_all_or_nothing(self):
        a = BlockAllocator(num_blocks=5, block_size=16)
        assert a.alloc(4) is not None
        assert a.free_count == 0
        # nothing left: a partial grant must not happen
        assert a.alloc(1) is None
        assert a.stats()["alloc_failures"] == 1

    def test_free_returns_blocks_lifo(self):
        a = BlockAllocator(num_blocks=6, block_size=16)
        got = a.alloc(3)
        a.free(got)
        again = a.alloc(3)
        # LIFO: the just-freed blocks come back first (dense working set)
        assert set(again) == set(got)

    def test_refcount_sharing(self):
        a = BlockAllocator(num_blocks=6, block_size=16)
        (b,) = a.alloc(1)
        a.incref([b])
        assert a.refcount(b) == 2
        assert a.is_shared(b)
        assert a.shared_count == 1
        # first free drops a ref but does not reclaim
        assert a.free([b]) == 0
        assert a.refcount(b) == 1
        assert not a.is_shared(b)
        # second free reclaims
        assert a.free([b]) == 1
        assert a.free_count == a.total

    def test_double_free_raises(self):
        a = BlockAllocator(num_blocks=4, block_size=16)
        (b,) = a.alloc(1)
        a.free([b])
        with pytest.raises(ValueError):
            a.free([b])
        with pytest.raises(ValueError):
            a.incref([b])

    def test_cow_unshared_is_identity(self):
        a = BlockAllocator(num_blocks=6, block_size=16)
        (b,) = a.alloc(1)
        assert a.cow(b) == b
        assert a.stats()["cow_copies"] == 0

    def test_cow_shared_allocates_replacement(self):
        a = BlockAllocator(num_blocks=6, block_size=16)
        (b,) = a.alloc(1)
        a.incref([b])  # a prefix entry now shares it
        new = a.cow(b)
        assert new is not None and new != b
        assert a.refcount(new) == 1
        assert a.refcount(b) == 1  # the entry keeps its reference
        assert a.stats()["cow_copies"] == 1

    def test_cow_after_other_owner_leaves_is_identity(self):
        a = BlockAllocator(num_blocks=6, block_size=16)
        (b,) = a.alloc(1)
        a.incref([b])
        a.free([b])  # the other owner left first
        # back to sole ownership: no copy needed, writes are private
        assert a.cow(b) == b
        assert a.stats()["cow_copies"] == 0

    def test_blocks_for(self):
        a = BlockAllocator(num_blocks=8, block_size=16)
        assert a.blocks_for(0) == 0
        assert a.blocks_for(1) == 1
        assert a.blocks_for(16) == 1
        assert a.blocks_for(17) == 2
        assert a.blocks_for(64) == 4

    def test_watermark_hysteresis(self):
        a = BlockAllocator(num_blocks=11, block_size=16,
                           low_watermark=0.2, high_watermark=0.5)
        assert a.admission_open()
        got = a.alloc(9)  # 1/10 free = 0.1 < low
        assert not a.admission_open()
        a.free(got[:3])  # 4/10 free = 0.4: still below high -> stays shut
        assert not a.admission_open()
        a.free(got[3:5])  # 6/10 free = 0.6 >= high -> reopens
        assert a.admission_open()

    def test_property_random_workload_conserves_blocks(self):
        """Random alloc/incref/free/cow sequence: the allocator never
        loses or duplicates a block, and free+used == total throughout
        — the fragmentation-safety property (blocks are fixed-size, so
        any free block satisfies any request)."""
        rng = random.Random(7)
        a = BlockAllocator(num_blocks=33, block_size=16)
        refs = {}  # block -> references this test holds
        for _ in range(2000):
            op = rng.random()
            blocks = list(refs)
            if op < 0.4:
                got = a.alloc(rng.randint(1, 4))
                if got is not None:
                    for b in got:
                        refs[b] = refs.get(b, 0) + 1
            elif op < 0.55 and blocks:
                b = rng.choice(blocks)
                a.incref([b])
                refs[b] += 1
            elif op < 0.9 and blocks:
                b = rng.choice(blocks)
                a.free([b])
                refs[b] -= 1
                if refs[b] == 0:
                    del refs[b]
            elif blocks:
                b = rng.choice(blocks)
                new = a.cow(b)
                if new is not None and new != b:
                    refs[b] -= 1  # cow dropped this owner's reference
                    if refs[b] == 0:
                        del refs[b]
                    refs[new] = refs.get(new, 0) + 1
            # invariant: every block is either free or referenced
            st = a.stats()
            assert st["free"] + st["used"] == st["total"]
            assert st["used"] == len(refs)
        # drain every held reference: all blocks must come home
        for b, r in list(refs.items()):
            a.free([b] * r)
        assert a.free_count == a.total
        assert a.shared_count == 0

    def test_ctor_validation(self):
        with pytest.raises(ValueError):
            BlockAllocator(num_blocks=1, block_size=16)
        with pytest.raises(ValueError):
            BlockAllocator(num_blocks=4, block_size=0)
        with pytest.raises(ValueError):
            BlockAllocator(num_blocks=4, block_size=16,
                           low_watermark=0.5, high_watermark=0.2)


class TestPagedModelExactness:
    """The device half: every paged function is bit-identical to its
    contiguous twin over the same logical positions."""

    def _setup(self, batch=2, max_seq=64, block_size=16):
        import jax
        import jax.numpy as jnp

        from kubedl_tpu.models import llama

        cfg = llama.preset("tiny")
        params = llama.llama_init(jax.random.PRNGKey(0), cfg)
        cache_c = llama.init_cache(cfg, batch, max_seq)
        nb = 1 + batch * (max_seq // block_size)
        cache_p = llama.init_paged_cache(cfg, batch, max_seq, nb, block_size)
        # identity-ish block table: row b owns blocks [1 + b*mb, ...)
        mb = max_seq // block_size
        bt = jnp.arange(1, 1 + batch * mb, dtype=jnp.int32).reshape(batch, mb)
        cache_p["bt"] = bt
        return llama, cfg, params, cache_c, cache_p

    def test_prefill_bit_identical(self):
        import jax.numpy as jnp
        import numpy as np

        llama, cfg, params, cache_c, cache_p = self._setup()
        toks = jnp.asarray(np.array([[5, 9, 13, 0], [1, 2, 0, 0]], np.int32))
        lens = jnp.asarray(np.array([3, 2], np.int32))
        lc, cache_c = llama.prefill_batched(params, cache_c, toks, lens, cfg)
        lp, cache_p = llama.paged_prefill_batched(
            params, cache_p, toks, lens, cfg
        )
        assert np.array_equal(np.asarray(lc), np.asarray(lp))

    def test_decode_chain_bit_identical(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        llama, cfg, params, cache_c, cache_p = self._setup()
        toks = jnp.asarray(np.array([[5, 9, 13, 0], [1, 2, 0, 0]], np.int32))
        lens = jnp.asarray(np.array([3, 2], np.int32))
        lc, cache_c = llama.prefill_batched(params, cache_c, toks, lens, cfg)
        lp, cache_p = llama.paged_prefill_batched(
            params, cache_p, toks, lens, cfg
        )
        nxt = jnp.argmax(lc, axis=-1).astype(jnp.int32)[:, None]
        temps = jnp.zeros((2,), jnp.float32)
        key = jax.random.PRNGKey(1)
        tc, _, _, cache_c = llama.decode_segment(
            params, cache_c, nxt, temps, key, cfg, n_steps=8, greedy=True
        )
        tp, _, _, cache_p = llama.paged_decode_segment(
            params, cache_p, nxt, temps, key, cfg, n_steps=8, greedy=True
        )
        assert np.array_equal(np.asarray(tc), np.asarray(tp))

    def test_overflow_fixup_retired_by_trash_routing(self):
        """PR 4's contiguous engine needed a dispatch-time fixup: a graft
        whose start + prefill bucket spilled past max_seq would have
        clamped writes onto live tail positions. The paged suffix
        forward routes every beyond-lens / beyond-max_seq write to the
        trash block instead — prove the spill case leaves real rows
        bit-identical."""
        import jax.numpy as jnp
        import numpy as np

        llama, cfg, params, _, cache_p = self._setup(batch=2, max_seq=64)
        # row 0: start so deep that start + padded bucket > max_seq
        start = 60
        cache_p["pos"] = jnp.asarray(np.array([start, 0], np.int32))
        toks = np.zeros((2, 16), np.int32)  # bucket 16: 60 + 16 > 64
        toks[0, :3] = [5, 9, 13]
        toks[1, :2] = [1, 2]
        lens = jnp.asarray(np.array([3, 2], np.int32))
        starts = jnp.asarray(np.array([start, 0], np.int32))
        before = np.asarray(cache_p["k"][:, TRASH_BLOCK]).copy()
        logits, cache_p = llama.paged_prefill_from(
            params, cache_p, jnp.asarray(toks), lens, starts, cfg
        )
        # row 1 (start 0, no spill) matches a clean prefill of its own
        _, cfg2, params2, _, fresh = self._setup(batch=2, max_seq=64)
        l2, _ = llama.paged_prefill_batched(
            params2, fresh, jnp.asarray(toks), lens, cfg2
        )
        assert np.array_equal(np.asarray(logits[1]), np.asarray(l2[1]))
        # and the spill landed in the trash block, not in live rows
        after = np.asarray(cache_p["k"][:, TRASH_BLOCK])
        assert not np.array_equal(before, after)


def _oracle(eng, prompt, n):
    """Single-sequence contiguous decode loop — the exactness oracle."""
    import jax
    import jax.numpy as jnp

    from kubedl_tpu.models import llama

    cfg = eng.cfg
    decode = jax.jit(lambda p, c, t: llama.decode_step(p, c, t, cfg))
    cache = llama.init_cache(cfg, 1, eng.max_seq)
    logits = None
    for tok in prompt:
        logits, cache = decode(eng.params, cache,
                               jnp.full((1, 1), int(tok), jnp.int32))
    out = []
    for _ in range(n):
        nxt = int(logits[0].argmax())
        out.append(nxt)
        logits, cache = decode(eng.params, cache,
                               jnp.full((1, 1), nxt, jnp.int32))
    return out


class TestPagedEngine:
    def test_paged_matches_contiguous_bit_identical(self):
        """THE exactness gate: same prompts, greedy, paged vs contiguous
        engines produce identical token ids (multi-block rows included)."""
        from kubedl_tpu.serving.server import LlamaEngine

        prompts = [
            [5, 9, 13],
            [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18],
            [7],
        ]
        results = {}
        for layout in ("contiguous", "paged"):
            eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                              kv_layout=layout)
            try:
                results[layout] = [
                    eng.generate(p, max_tokens=8)["token_ids"]
                    for p in prompts
                ]
            finally:
                eng.close()
        assert results["paged"] == results["contiguous"]

    def test_paged_matches_oracle(self):
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged")
        try:
            prompt = [5, 9, 13]
            got = eng.generate(prompt, max_tokens=6)
            assert got["token_ids"] == _oracle(eng, prompt, 6)
        finally:
            eng.close()

    def test_blocks_freed_on_completion(self):
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", prefix_cache_mb=0)
        try:
            eng.generate([5, 9, 13], max_tokens=6)
            st = eng.stats()["kv_blocks"]
            assert st["used"] == 0
            assert st["free"] == st["total"]
            assert st["allocs"] > 0 and st["frees"] == st["allocs"]
        finally:
            eng.close()

    def test_prefix_entry_shares_row_blocks(self):
        """Paged prefix insert SHARES the row's full blocks (incref) and
        device-copies only the partial tail; a later identical prompt
        grafts from the shared blocks and still matches the oracle."""
        from kubedl_tpu.serving.server import LlamaEngine

        # prompt spans 2 full blocks (block_size 4: 8 prompt tokens
        # = 2 full + the engine's +1 suffix need)
        prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]
        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", kv_block_size=4,
                          prefix_min_len=4)
        try:
            want = _oracle(eng, prompt, 6)
            r1 = eng.generate(prompt, max_tokens=6, cache_prefix=True)
            assert r1["token_ids"] == want
            st = eng.stats()["kv_blocks"]
            # the entry holds block references while no row is resident
            assert st["used"] > 0
            r2 = eng.generate(prompt, max_tokens=6)
            assert r2["token_ids"] == want
            assert r2["cached_prefix_len"] > 0
            # sharing happened by reference, never by whole-prefix copy:
            # at most one COW/tail copy alloc beyond the suffix blocks
            assert eng.stats()["prefix_cache"]["hits"] >= 1
        finally:
            eng.close()

    def test_prefix_entry_blocks_freed_on_eviction(self):
        from kubedl_tpu.serving.server import LlamaEngine

        prompt = list(range(1, 11))
        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", kv_block_size=4,
                          prefix_min_len=4)
        try:
            eng.generate(prompt, max_tokens=4, cache_prefix=True)
            held = eng.stats()["kv_blocks"]["used"]
            assert held > 0
            # reclaim evicts the (unpinned) entry -> blocks come home
            freed = eng._pcache.reclaim(10**9)
            assert freed > 0
            st = eng.stats()["kv_blocks"]
            assert st["used"] == 0
        finally:
            eng.close()

    def test_low_watermark_sheds_503(self):
        """Once the free fraction crosses the low watermark, generate()
        rejects at the door with Retry-After instead of queueing."""
        from kubedl_tpu.serving.server import EngineOverloaded, LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", prefix_cache_mb=0)
        try:
            # drain the pool host-side: admission gate shuts
            grabbed = eng._alloc.alloc(eng._alloc.free_count)
            assert not eng._alloc.admission_open()
            with pytest.raises(EngineOverloaded) as ei:
                eng.generate([1, 2, 3], max_tokens=2)
            assert ei.value.retry_after_s > 0
            assert eng.stats()["kv_sheds"] == 1
            eng._alloc.free(grabbed)
            assert eng._alloc.admission_open()
            # recovered: requests flow again
            out = eng.generate([5, 9, 13], max_tokens=4)
            assert len(out["token_ids"]) == 4
        finally:
            eng.close()

    def test_chaos_kv_alloc_preempts_and_requeues(self):
        """The `serving.kv_alloc` chaos site injects one block-allocation
        failure mid-decode: the engine preempts the youngest resident
        row, requeues it, and EVERY request still completes with exactly
        the greedy oracle's tokens (preemption re-prefills from scratch,
        so outputs never change)."""
        import threading

        from kubedl_tpu import chaos
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", prefix_cache_mb=0)
        try:
            prompts = [[5, 9, 13], [1, 2], [7, 11]]
            want = [_oracle(eng, p, 6) for p in prompts]
            results = [None] * len(prompts)

            def worker(i):
                results[i] = eng.generate(prompts[i], max_tokens=6)

            with chaos.FaultPlan(seed=3, sites={
                "serving.kv_alloc": [chaos.FaultSpec.nth(1)],
            }):
                threads = [threading.Thread(target=worker, args=(i,))
                           for i in range(len(prompts))]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=120)
            assert [r["token_ids"] for r in results] == want
            # the injected failure was consumed by the reserve path
            st = eng.stats()["kv_blocks"]
            assert st["used"] == 0  # everything returned home
        finally:
            eng.close()

    def test_preempt_requeue_exhaustion(self):
        """A pool too small for two full-length rows: the second row's
        growth preempts the younger resident, which requeues and still
        finishes with oracle-exact output."""
        from kubedl_tpu.serving.server import LlamaEngine
        import threading

        # mb = 64/16 = 4; kv_blocks=6 -> 5 usable: two rows needing up
        # to 3 blocks each cannot BOTH grow to 3 (6 > 5)
        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", kv_blocks=6,
                          kv_low_watermark=0.0, kv_high_watermark=0.0,
                          prefix_cache_mb=0)
        try:
            prompts = [[5, 9, 13], [1, 2, 3]]
            want = [_oracle(eng, p, 30) for p in prompts]
            results = [None] * 2

            def worker(i):
                results[i] = eng.generate(prompts[i], max_tokens=30,
                                          timeout_s=120)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            assert [r["token_ids"] for r in results] == want
        finally:
            eng.close()

    def test_kv_metrics_exported(self):
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged")
        try:
            eng.generate([5, 9, 13], max_tokens=4)
            body = eng.metrics.registry.render()
            for fam in ("kubedl_tpu_serving_kv_blocks_total",
                        "kubedl_tpu_serving_kv_blocks_free",
                        "kubedl_tpu_serving_kv_blocks_shared",
                        "kubedl_tpu_serving_kv_preemptions",
                        "kubedl_tpu_serving_kv_block_sheds"):
                assert fam in body, fam
            st = eng.stats()
            assert st["kv_blocks"]["total"] > 0
            assert "kv_preemptions" in st and "kv_sheds" in st
        finally:
            eng.close()

    def test_contiguous_engine_unchanged_no_kv_stats(self):
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="contiguous")
        try:
            out = eng.generate([5, 9, 13], max_tokens=4)
            assert len(out["token_ids"]) == 4
            assert "kv_blocks" not in eng.stats()
        finally:
            eng.close()

    def test_unknown_layout_rejected(self):
        from kubedl_tpu.serving.server import LlamaEngine

        with pytest.raises(ValueError):
            LlamaEngine(preset="tiny", kv_layout="interleaved")


class TestChunkedAdmission:
    """Continuous batching: prompts prefill in block-sized chunks
    interleaved with decode, so admission latency is bounded by the
    chunk budget instead of the longest queued prompt. The exactness
    gate is unchanged — chunking may only change WHEN work happens."""

    def test_chunked_bit_identical_to_slot_granularity(self):
        from kubedl_tpu.serving.server import LlamaEngine

        prompts = [
            [5, 9, 13],
            list(range(1, 19)),
            [7],
            list(range(3, 40)),  # spans 3 chunks at budget 16
        ]
        results = {}
        for name, kw in (("plain", {}),
                         ("chunked", {"prefill_chunk_tokens": 16})):
            eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                              kv_layout="paged", **kw)
            try:
                results[name] = [
                    eng.generate(p, max_tokens=8)["token_ids"]
                    for p in prompts
                ]
            finally:
                eng.close()
        assert results["chunked"] == results["plain"]

    def test_chunk_budget_rounds_to_blocks(self):
        """The knob is block-aligned (intermediate chunks must never
        split a KV block across two dispatches) and paged-only."""
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", kv_block_size=16,
                          prefill_chunk_tokens=25)
        try:
            assert eng.prefill_chunk_tokens == 16  # floor to block size
        finally:
            eng.close()
        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", kv_block_size=16,
                          prefill_chunk_tokens=7)
        try:
            assert eng.prefill_chunk_tokens == 16  # never below a block
        finally:
            eng.close()
        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="contiguous", prefill_chunk_tokens=16)
        try:
            assert eng.prefill_chunk_tokens == 0  # paged-only
        finally:
            eng.close()

    def test_admission_metrics_and_stats(self):
        """Chunk dispatches are counted, and per-request queue wait
        surfaces as p50/p95 in stats() — the number the chunk budget
        exists to bound."""
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", prefill_chunk_tokens=16)
        try:
            # 37 prompt tokens at budget 16 -> 3 chunks
            eng.generate(list(range(3, 40)), max_tokens=4)
            eng.generate([5, 9, 13], max_tokens=4)  # 1 chunk
            body = eng.metrics.registry.render()
            line = [l for l in body.splitlines()
                    if l.startswith("kubedl_tpu_serving_admission_chunks ")]
            assert line and float(line[0].split()[-1]) == 4.0, line
            assert "kubedl_tpu_serving_queue_wait_ms" in body
            st = eng.stats()
            assert st["queue_wait_ms_p50"] >= 0.0
            assert st["queue_wait_ms_p95"] >= st["queue_wait_ms_p50"]
        finally:
            eng.close()

    def test_chunked_with_spec_and_prefix_cache(self):
        """Chunked admission composes with speculation and prefix reuse
        without perturbing outputs (repeat prompts ride the cache; their
        FIRST chunk starts at the grafted length)."""
        from kubedl_tpu.serving.server import LlamaEngine

        prompts = [list(range(1, 19)), list(range(1, 19)), [5, 9, 13]]
        results = {}
        for name, kw in (
            ("plain", {}),
            ("chunked", {"prefill_chunk_tokens": 16, "spec_k": 3}),
        ):
            eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                              kv_layout="paged", **kw)
            try:
                results[name] = [
                    eng.generate(p, max_tokens=6)["token_ids"]
                    for p in prompts
                ]
            finally:
                eng.close()
        assert results["chunked"] == results["plain"]

    def test_chaos_chunk_admit_scheduler_survives(self):
        """An injected fault at the chunk dispatch fails the in-flight
        request loudly and the engine keeps serving — same contract as
        `serving.dispatch` (docs/robustness.md)."""
        from kubedl_tpu import chaos
        from kubedl_tpu.serving.server import LlamaEngine

        eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64,
                          kv_layout="paged", prefill_chunk_tokens=16)
        try:
            with chaos.FaultPlan(seed=7, sites={
                "serving.chunk_admit": [chaos.FaultSpec.nth(1)],
            }):
                r1 = eng.generate([5, 9, 13], max_tokens=4)
            assert "error" in r1, r1
            r2 = eng.generate([5, 9, 13], max_tokens=4)
            assert len(r2["token_ids"]) == 4
            assert eng.stats()["kv_blocks"]["used"] == 0
        finally:
            eng.close()

"""Boot the operator from the rendered Deployment's OWN argv/env/ports.

The kind-cluster e2e (scripts/kind-e2e.sh) exits 2 where docker/kind are
absent, so until round 5 nothing ever executed the control flow it
encodes — and the rendered manifest had drifted from the CLI (it passed
--leader-elect=true, which `kubedl-tpu-operator` did not accept: the
deployed image would have crash-looped). This test closes that hole
without a cluster (reference recipe: /root/reference/.github/workflows/
ci.yaml e2e-tests + scripts/run_tf_test_job.sh):

1. parse deploy/rendered/operator-deployment.yaml — container args, env,
   ports, readiness probe, volume mounts;
2. stand the volumeMounts up as tmpdirs (what the kubelet does) and
   remap path-valued args/env under them;
3. launch the manifest's EXACT argv through the image's entrypoint
   (pyproject console script kubedl-tpu-operator -> kubedl_tpu.cli:main
   — asserted, so the Dockerfile ENTRYPOINT stays honest);
4. wait for the manifest's readiness probe (same path, same port);
5. run the SAME submit-TFJob-and-wait-Succeeded smoke the kind lane runs
   (scripts/e2e_smoke.py).

A flag the CLI does not accept, a dead console port, a wrong probe path,
or a console that cannot actually run a job all fail here, on every run.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest
import yaml

REPO = Path(__file__).resolve().parent.parent


def _operator_container():
    doc = yaml.safe_load(
        (REPO / "deploy" / "rendered" / "operator-deployment.yaml").read_text()
    )
    assert doc["kind"] == "Deployment"
    spec = doc["spec"]["template"]["spec"]
    return spec["containers"][0]


def test_console_script_matches_image_entrypoint():
    """Dockerfile ENTRYPOINT is the console script; pyproject must bind it
    to the module this test boots, or the test would validate the wrong
    program."""
    py = (REPO / "pyproject.toml").read_text()
    assert 'kubedl-tpu-operator = "kubedl_tpu.cli:main"' in py
    docker = (REPO / "Dockerfile").read_text()
    assert 'ENTRYPOINT ["kubedl-tpu-operator"]' in docker


def test_rendered_deployment_boots_and_runs_a_job(tmp_path):
    c = _operator_container()
    # --- kubelet-style volume materialization -------------------------
    mounts = {m["mountPath"]: tmp_path / m["name"] for m in c["volumeMounts"]}
    for d in mounts.values():
        d.mkdir(parents=True, exist_ok=True)

    def remap(value: str) -> str:
        for mp, real in sorted(mounts.items(), key=lambda kv: -len(kv[0])):
            if value == mp or value.startswith(mp + "/"):
                return str(real) + value[len(mp):]
        return value

    args = []
    for a in c["args"]:
        if "=" in a:
            flag, _, val = a.partition("=")
            args.append(f"{flag}={remap(val)}")
        else:
            args.append(a)
    env = dict(os.environ)
    env.update({e["name"]: remap(e.get("value", "")) for e in c.get("env", [])})
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO)
    # subprocess pods must resolve each other on this one host
    args.append("--local-addresses")

    port = next(p["containerPort"] for p in c["ports"] if p["name"] == "console")
    probe = c["readinessProbe"]["httpGet"]
    assert probe["port"] == port
    base = f"http://127.0.0.1:{port}"

    proc = subprocess.Popen(
        [sys.executable, "-m", "kubedl_tpu.cli", *args],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=str(tmp_path),
    )
    try:
        # --- readiness: the manifest's own probe ----------------------
        deadline = time.time() + 90
        ready = False
        while time.time() < deadline:
            if proc.poll() is not None:
                out = proc.stdout.read() if proc.stdout else ""
                pytest.fail(
                    f"operator exited {proc.returncode} before ready "
                    f"(argv drift?):\n{out[-2000:]}"
                )
            try:
                with urllib.request.urlopen(
                    base + probe["path"], timeout=5
                ) as r:
                    if r.status == 200:
                        ready = True
                        break
            except (urllib.error.URLError, ConnectionError, OSError):
                time.sleep(0.5)
        assert ready, f"readiness probe {probe['path']} never went 200"

        # --- the kind lane's own smoke, verbatim ----------------------
        sys.path.insert(0, str(REPO / "scripts"))
        try:
            from e2e_smoke import run_smoke
        finally:
            sys.path.pop(0)
        rc = run_smoke(base, timeout=120)
        assert rc == 0, f"e2e smoke exited {rc}"
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()

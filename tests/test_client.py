"""Client SDK tests (reference analogue: generated clientset + fake
clientset usage in controller suites)."""

import pytest

from kubedl_tpu.api.types import JobConditionType
from kubedl_tpu.client import ApiException, InProcessClient, KubeDLClient
from kubedl_tpu.console import ConsoleServer
from kubedl_tpu.operator import Operator, OperatorOptions
from kubedl_tpu.runtime.executor import SubprocessRuntime

from tests.helpers import make_tpujob


@pytest.fixture()
def stack(tmp_path):
    opts = OperatorOptions(
        local_addresses=True,
        pod_log_dir=str(tmp_path / "logs"),
        artifact_registry_root=str(tmp_path / "reg"),
    )
    op = Operator(opts, runtime=SubprocessRuntime(str(tmp_path / "logs")))
    srv = ConsoleServer(op)
    op.start(); srv.start()
    try:
        host, port = srv.address
        yield op, f"http://{host}:{port}"
    finally:
        srv.stop(); op.stop()


def _roundtrip(client, op):
    job = make_tpujob("cl1", workers=1, command=["python", "-c", "print('log-line')"])
    r = client.tpu_jobs.create(job)
    assert r["name"] == "cl1"
    got = client.tpu_jobs.wait("cl1", ["Succeeded", "Failed"], timeout=30)
    assert got.kind == "TPUJob"
    assert got.status.phase == JobConditionType.SUCCEEDED
    # typed list returns decoded objects
    jobs = client.tpu_jobs.list()
    assert [j.metadata.name for j in jobs] == ["cl1"]
    assert jobs[0].spec.replica_specs  # real dataclass, not a dict
    # stats + overview
    assert client.statistics()["totalJobCount"] == 1
    assert "podTotal" in client.overview() or "podRunning" in client.overview()
    # logs through the client
    pods = [p for p in op.store.list("Pod")
            if p.metadata.labels.get("kubedl-tpu.io/job-name") == "cl1"]
    if pods:
        logs = "".join(client.job_logs(pods[0].metadata.name))
        assert "log-line" in logs
    # unknown kind -> typed error
    with pytest.raises(ApiException) as ei:
        client.get_job("Pod", "x")
    assert ei.value.status == 400
    with pytest.raises(ApiException) as ei:
        client.tpu_jobs.get("nope")
    assert ei.value.status == 404
    # delete
    client.tpu_jobs.delete("cl1")
    with pytest.raises(ApiException):
        client.tpu_jobs.get("cl1")


def test_http_client_roundtrip(stack):
    op, base = stack
    _roundtrip(KubeDLClient(base), op)


def test_inprocess_client_roundtrip(stack):
    op, _ = stack
    _roundtrip(InProcessClient(op), op)


def test_stop_via_client(stack):
    op, base = stack
    client = KubeDLClient(base)
    job = make_tpujob("cl-stop", workers=1,
                      command=["python", "-c", "import time; time.sleep(30)"])
    client.tpu_jobs.create(job)
    import time
    deadline = time.time() + 15
    while time.time() < deadline:
        j = op.store.get("TPUJob", "cl-stop")
        if j.status.phase == JobConditionType.RUNNING:
            break
        time.sleep(0.2)
    client.tpu_jobs.stop("cl-stop")
    got = client.tpu_jobs.wait("cl-stop", ["Failed"], timeout=30)
    assert got.status.phase == JobConditionType.FAILED


def test_kind_accessors_cover_all_workloads(stack):
    op, base = stack
    client = KubeDLClient(base)
    for attr in ("tpu_jobs", "tf_jobs", "pytorch_jobs", "xdl_jobs",
                 "xgboost_jobs", "mars_jobs", "elasticdl_jobs", "mpi_jobs"):
        assert hasattr(client, attr)
    assert client.kind_client("TFJob").list() == []

// kubedl-tpu native data loader.
//
// The reference delegates all data loading to in-container frameworks; the
// TPU build makes host-side input a framework concern: training steps are
// sub-second, so batch assembly must never appear on the critical path.
// This loader memory-maps a binary token file, samples windows with a
// seeded xorshift PRNG, and keeps a ring of pre-assembled batches filled
// by background threads — the consumer thread only memcpy's.
//
// C ABI (consumed via ctypes from kubedl_tpu/data/native.py):
//   void* kdl_loader_open(path, batch, seq, seed, prefetch, token_bytes)
//   int   kdl_loader_next(handle, int32* out)   // blocking; 0 = ok
//   long  kdl_loader_tokens(handle)             // total tokens in file
//   void  kdl_loader_close(handle)
//
// Build: g++ -O3 -shared -fPIC -pthread -o libkdl_data.so dataloader.cpp

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Batch {
  std::vector<int32_t> data;
};

struct Loader {
  const uint8_t* base = nullptr;
  size_t file_bytes = 0;
  int fd = -1;
  long n_tokens = 0;
  int token_bytes = 4;  // 2 (uint16) or 4 (uint32)
  int batch = 0;
  int seq = 0;
  uint64_t rng = 0;

  std::mutex mu;
  std::condition_variable cv_full, cv_empty;
  std::deque<Batch> ring;
  size_t ring_cap = 0;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};

  ~Loader() {
    stop.store(true);
    cv_full.notify_all();
    cv_empty.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    if (base) munmap(const_cast<uint8_t*>(base), file_bytes);
    if (fd >= 0) close(fd);
  }

  // xorshift64*: deterministic, one state per loader (workers draw window
  // starts under the lock, so a given seed yields a fixed SET of windows)
  uint64_t next_rand() {
    uint64_t x = rng;
    x ^= x >> 12;
    x ^= x << 25;
    x ^= x >> 27;
    rng = x;
    return x * 0x2545F4914F6CDD1DULL;
  }

  int32_t token_at(long i) const {
    if (token_bytes == 2)
      return reinterpret_cast<const uint16_t*>(base)[i];
    return reinterpret_cast<const int32_t*>(base)[i];
  }

  void fill_batch(Batch& b, const std::vector<long>& starts) {
    b.data.resize(static_cast<size_t>(batch) * seq);
    for (int r = 0; r < batch; ++r) {
      long s = starts[r];
      if (token_bytes == 4) {
        std::memcpy(b.data.data() + static_cast<size_t>(r) * seq,
                    reinterpret_cast<const int32_t*>(base) + s,
                    static_cast<size_t>(seq) * 4);
      } else {
        for (int c = 0; c < seq; ++c)
          b.data[static_cast<size_t>(r) * seq + c] = token_at(s + c);
      }
    }
  }

  void worker() {
    while (!stop.load()) {
      std::vector<long> starts(batch);
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_full.wait(lk, [&] { return stop.load() || ring.size() < ring_cap; });
        if (stop.load()) return;
        long span = n_tokens - seq;
        for (int r = 0; r < batch; ++r)
          starts[r] = span > 0 ? static_cast<long>(next_rand() % span) : 0;
      }
      Batch b;
      fill_batch(b, starts);
      {
        std::unique_lock<std::mutex> lk(mu);
        if (ring.size() < ring_cap) {
          ring.push_back(std::move(b));
          cv_empty.notify_one();
        }
      }
    }
  }
};

}  // namespace

extern "C" {

void* kdl_loader_open(const char* path, int batch, int seq, uint64_t seed,
                      int prefetch, int token_bytes) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < token_bytes * (long)seq) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  auto* L = new Loader();
  L->fd = fd;
  L->base = static_cast<const uint8_t*>(base);
  L->file_bytes = st.st_size;
  L->token_bytes = token_bytes == 2 ? 2 : 4;
  L->n_tokens = st.st_size / L->token_bytes;
  L->batch = batch;
  L->seq = seq;
  L->rng = seed ? seed : 0x9E3779B97F4A7C15ULL;
  L->ring_cap = prefetch > 0 ? prefetch : 2;
  int n_threads = prefetch > 1 ? 2 : 1;
  for (int i = 0; i < n_threads; ++i)
    L->workers.emplace_back([L] { L->worker(); });
  return L;
}

long kdl_loader_tokens(void* h) {
  return h ? static_cast<Loader*>(h)->n_tokens : 0;
}

int kdl_loader_next(void* h, int32_t* out) {
  if (!h) return -1;
  auto* L = static_cast<Loader*>(h);
  Batch b;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_empty.wait(lk, [&] { return L->stop.load() || !L->ring.empty(); });
    if (L->stop.load()) return -1;
    b = std::move(L->ring.front());
    L->ring.pop_front();
    L->cv_full.notify_one();
  }
  std::memcpy(out, b.data.data(), b.data.size() * 4);
  return 0;
}

void kdl_loader_close(void* h) {
  delete static_cast<Loader*>(h);
}

}  // extern "C"

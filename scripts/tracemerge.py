#!/usr/bin/env python
"""Fuse per-replica trace dumps into ONE Chrome/Perfetto trace.

Every process exports its own spans — router + each serving replica via
``GET /v1/trace``, or ``Tracer.dump()`` Chrome-trace files — with
timestamps already anchored to the wall-clock epoch
(kubedl_tpu/observability/tracing.py), so fusing is pure bookkeeping:
assign each input file a distinct ``pid`` (Perfetto renders one process
track per pid), emit a ``process_name`` metadata event naming the source
file, and concatenate the events. Cross-process spans line up on the
shared epoch timeline, and span/parent ids (carried in ``args``) let you
follow one request router → prefill replica → decode replica.

Accepted input shapes, sniffed per file:

* Chrome trace JSON: ``{"traceEvents": [...]}``
* flight-recorder / ``/v1/trace`` JSON: ``{"spans": [<span dicts>]}``
  (also a bare list of span dicts)

Usage::

    python scripts/tracemerge.py router.json prefill.json decode.json \
        -o merged.json [--trace-id <32 hex>]

Open ``merged.json`` in https://ui.perfetto.dev or chrome://tracing.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Dict, List


def _span_to_event(span: Dict[str, Any], pid: int,
                   tids: Dict[str, int]) -> Dict[str, Any]:
    """Span dict (span_to_dict shape) -> Chrome 'X' complete event."""
    tid = tids.setdefault(str(span.get("thread", "main")), len(tids) + 1)
    args = dict(span.get("attrs") or {})
    for key in ("trace_id", "span_id", "parent_id"):
        if span.get(key):
            args[key] = span[key]
    return {
        "name": span.get("name", "?"),
        "ph": "X",
        "ts": float(span.get("ts", 0.0)) * 1e6,  # epoch s -> µs
        "dur": float(span.get("duration_ms", 0.0)) * 1e3,  # ms -> µs
        "pid": pid,
        "tid": tid,
        "args": args,
    }


def load_events(path: Path, pid: int) -> List[Dict[str, Any]]:
    """Read one dump (either shape), rewriting every event onto ``pid``."""
    data = json.loads(path.read_text())
    if isinstance(data, dict) and "traceEvents" in data:
        events = []
        for ev in data["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue  # replaced by our own per-file metadata event
            events.append(ev)
        return events
    spans = data.get("spans", data) if isinstance(data, dict) else data
    if not isinstance(spans, list):
        raise ValueError(f"{path}: unrecognized trace dump shape")
    tids: Dict[str, int] = {}
    return [_span_to_event(s, pid, tids) for s in spans]


def merge(paths: List[Path], trace_id: str = "") -> Dict[str, Any]:
    events: List[Dict[str, Any]] = []
    for pid, path in enumerate(paths, start=1):
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": path.stem},
        })
        for ev in load_events(path, pid):
            if trace_id and ev.get("ph") == "X" and (
                (ev.get("args") or {}).get("trace_id") != trace_id
            ):
                continue
            events.append(ev)
    return {"traceEvents": events}


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("inputs", nargs="+", type=Path,
                    help="per-process trace dumps (chrome-trace or span JSON)")
    ap.add_argument("-o", "--output", type=Path, default=Path("merged.json"))
    ap.add_argument("--trace-id", default="",
                    help="keep only spans of one trace (32 hex chars)")
    args = ap.parse_args(argv)
    out = merge(args.inputs, args.trace_id)
    args.output.write_text(json.dumps(out, indent=1))
    n = sum(1 for e in out["traceEvents"] if e.get("ph") == "X")
    print(f"{args.output}: {n} spans from {len(args.inputs)} process(es)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

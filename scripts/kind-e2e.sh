#!/usr/bin/env bash
# kind-cluster e2e for the operator deploy surface.
#
# Mirrors the reference's CI recipe (/root/reference/.github/workflows/
# ci.yaml e2e-tests job; scripts/deploy_kubedl.sh; run_tf_test_job.sh):
# stand up a kind cluster, build + load the operator image, apply the
# rendered manifests, wait for the operator Deployment to go Ready, then
# submit a small distributed job through the console API and wait for
# Succeeded.
#
# Requires docker + kind + kubectl on PATH; exits 2 (skip) when absent so
# CI lanes without a cluster toolchain stay green — the structural half
# of this validation always runs via `make validate-deploy`.
set -euo pipefail

cd "$(dirname "$0")/.."

for tool in docker kind kubectl; do
  if ! command -v "$tool" >/dev/null 2>&1; then
    echo "kind-e2e: $tool not on PATH — skipping (structural validation" \
         "still runs via 'make validate-deploy')" >&2
    exit 2
  fi
done

CLUSTER=${KUBEDL_KIND_CLUSTER:-kubedl-tpu-e2e}
IMG=kubedl-tpu:latest

echo "== build operator image"
docker build -t "$IMG" .

echo "== (re)create kind cluster $CLUSTER"
kind delete cluster --name "$CLUSTER" >/dev/null 2>&1 || true
kind create cluster --name "$CLUSTER" --wait 120s
trap 'kind delete cluster --name "$CLUSTER"' EXIT

echo "== load image into cluster"
kind load docker-image "$IMG" --name "$CLUSTER"

echo "== render + validate + apply manifests"
python deploy/render.py
python deploy/validate.py
kubectl create namespace kubedl-system --dry-run=client -o yaml | kubectl apply -f -
kubectl apply -f deploy/rendered/

echo "== wait for operator ready"
kubectl -n kubedl-system rollout status deployment/kubedl-tpu-operator --timeout=180s

echo "== submit a smoke job through the console API"
kubectl -n kubedl-system port-forward deployment/kubedl-tpu-operator 9090:9090 &
PF=$!
trap 'kill $PF 2>/dev/null; kind delete cluster --name "$CLUSTER"' EXIT
sleep 3
# shared with tests/test_deploy_boot.py, which runs the SAME submit->wait
# path against a subprocess operator booted from the rendered
# Deployment's argv — so this control flow is exercised on every CI run,
# not only when docker/kind exist
python scripts/e2e_smoke.py http://127.0.0.1:9090 120
echo "== kind e2e OK"

#!/usr/bin/env bash
# kind-cluster e2e for the operator deploy surface.
#
# Mirrors the reference's CI recipe (/root/reference/.github/workflows/
# ci.yaml e2e-tests job; scripts/deploy_kubedl.sh; run_tf_test_job.sh):
# stand up a kind cluster, build + load the operator image, apply the
# rendered manifests, wait for the operator Deployment to go Ready, then
# submit a small distributed job through the console API and wait for
# Succeeded.
#
# Requires docker + kind + kubectl on PATH; exits 2 (skip) when absent so
# CI lanes without a cluster toolchain stay green — the structural half
# of this validation always runs via `make validate-deploy`.
set -euo pipefail

cd "$(dirname "$0")/.."

for tool in docker kind kubectl; do
  if ! command -v "$tool" >/dev/null 2>&1; then
    echo "kind-e2e: $tool not on PATH — skipping (structural validation" \
         "still runs via 'make validate-deploy')" >&2
    exit 2
  fi
done

CLUSTER=${KUBEDL_KIND_CLUSTER:-kubedl-tpu-e2e}
IMG=kubedl-tpu:latest

echo "== build operator image"
docker build -t "$IMG" .

echo "== (re)create kind cluster $CLUSTER"
kind delete cluster --name "$CLUSTER" >/dev/null 2>&1 || true
kind create cluster --name "$CLUSTER" --wait 120s
trap 'kind delete cluster --name "$CLUSTER"' EXIT

echo "== load image into cluster"
kind load docker-image "$IMG" --name "$CLUSTER"

echo "== render + validate + apply manifests"
python deploy/render.py
python deploy/validate.py
kubectl create namespace kubedl-system --dry-run=client -o yaml | kubectl apply -f -
kubectl apply -f deploy/rendered/

echo "== wait for operator ready"
kubectl -n kubedl-system rollout status deployment/kubedl-tpu-operator --timeout=180s

echo "== submit a smoke job through the console API"
kubectl -n kubedl-system port-forward deployment/kubedl-tpu-operator 9090:9090 &
PF=$!
trap 'kill $PF 2>/dev/null; kind delete cluster --name "$CLUSTER"' EXIT
sleep 3
python - <<'PY'
import json, time, urllib.request

job = {
    "kind": "TFJob",
    "metadata": {"name": "e2e-smoke", "namespace": "default"},
    "spec": {"replicaSpecs": {"Worker": {
        "replicas": 2,
        "template": {"spec": {"containers": [{
            "command": ["python", "-c",
                        "import os, json; json.loads(os.environ['TF_CONFIG'])"],
        }]}},
    }}},
}
req = urllib.request.Request(
    "http://127.0.0.1:9090/api/v1/job/submit",
    data=json.dumps(job).encode(),
    headers={"Content-Type": "application/json"}, method="POST",
)
with urllib.request.urlopen(req, timeout=30) as r:
    print("submit:", r.status)
deadline = time.time() + 120
while time.time() < deadline:
    with urllib.request.urlopen(
        "http://127.0.0.1:9090/api/v1/job/list?kind=TFJob", timeout=10
    ) as r:
        jobs = json.loads(r.read())["data"]["jobInfos"]
    phase = next((j["jobStatus"] for j in jobs if j["name"] == "e2e-smoke"), "")
    if phase in ("Succeeded", "Failed"):
        print("terminal phase:", phase)
        raise SystemExit(0 if phase == "Succeeded" else 1)
    time.sleep(2)
raise SystemExit("timeout waiting for e2e-smoke")
PY
echo "== kind e2e OK"

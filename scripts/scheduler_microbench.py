"""Host-side scheduler microbench: per-tick overhead with the device
stubbed out.

The double-buffered pipeline's whole point is that host work (dispatch
bookkeeping, harvest copy-out handling, slot finalization, admission)
hides behind device compute — which only works while that host work stays
small. This bench replaces every jitted model call on a real
`LlamaEngine` with an instant stub, drives `_loop_once` directly, and
reports the tick timings the engine itself accounts
(`pipeline_stats()`). With a no-op device, tick time IS host overhead.

Runs as part of tier-1 (`pytest -m 'not slow'` via
tests/test_serving.py::TestSchedulerMicrobench) so a host-overhead
regression fails CI instead of waiting for a full bench run, and
standalone:

    JAX_PLATFORMS=cpu python scripts/scheduler_microbench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

#: p50 per-tick host-overhead budget (ms) asserted by the tier-1 test.
#: A steady-state tick is slot bookkeeping + one device_get of a tiny
#: [B, k] int32 array + admission — well under a millisecond on any
#: CPU; 5 ms leaves ~10x headroom for slow shared CI machines while
#: still catching an accidental O(vocab) host copy or per-token Python
#: loop (the r5 overhead bug class this guards against).
TICK_BUDGET_MS = 5.0

#: p50 per-tick budget (ms) for ticks that ride the PREFIX-CACHE path:
#: admission additionally walks the observation trie, matches the
#: prompt, and dispatches a graft. All host-side trie work on prompts of
#: a few hundred tokens — the same 5 ms envelope must hold, or prefix
#: reuse would pay back its prefill savings as scheduler overhead.
PREFIX_BUDGET_MS = 5.0

#: p50 per-tick budget (ms) for the PAGED layout: on top of the plain
#: tick, every dispatch re-uploads the pos/block-table mirrors (two tiny
#: int32 arrays, [B] + [B, max_seq/block_size]) and admission/finalize
#: run allocator alloc/free. All of it is O(batch * blocks-per-row) host
#: work on arrays of a few dozen ints — the same 5 ms envelope must
#: hold, or paging's occupancy win would be paid back as per-tick
#: scheduler overhead.
PAGED_BUDGET_MS = 5.0

#: p50 per-tick budget (ms) for the paged engine running the BLOCKED
#: attention kernel (kubedl_tpu/models/paged_attention.py): the kernel
#: is pure device compute, so the scheduler tick — mirror uploads, slot
#: bookkeeping, the kv_attention plumbing itself — must cost exactly
#: what the gather tick costs. A separate per-dispatch timing guards the
#: compiled kernel's HOST dispatch cost: the lax path lowers to a
#: scan-heavy executable with far more XLA ops than one gather, and an
#: accidental re-trace per call (e.g. a non-hashable kwarg breaking the
#: jit cache) would show up here as milliseconds, not microseconds.
BLOCKED_BUDGET_MS = 5.0

#: p95 per-plan budget (ms) for the auto-parallelism planner (kubedl_tpu/
#: planner/): plan() runs inside reconcile_job, so it must stay a rounding
#: error next to the engine's per-pass work. The search space is the
#: divisor lattice of a slice's chips (≤ ~200 candidates at 256 chips),
#: each priced by a handful of closed-form collective formulas — pure
#: Python arithmetic. 50 ms leaves ~10x headroom over the worst observed
#: catalog entry on a shared CI machine while still catching an
#: accidental combinatorial blow-up or per-candidate allocation storm.
PLANNER_BUDGET_MS = 50.0

#: p95 budget (ms) for planning the gradient-bucket scatter layout
#: (kubedl_tpu/training/buckets.py): plan_grad_buckets runs on the host
#: inside Trainer.__init__ for every (re)build — greedy first-fit over a
#: few hundred parameter leaves, pure Python arithmetic, no jax. 5 ms
#: leaves ~50x headroom on a shared CI machine while catching an
#: accidental O(leaves^2) pass or a stray device round-trip sneaking
#: into trainer construction.
BUCKET_BUDGET_MS = 5.0

#: p50 per-call budget (µs) for a DISARMED tracer span. Every hot path
#: — scheduler tick, router dispatch, reconcile — calls TRACER.span /
#: TRACER.record unconditionally; with `enabled = False` the call must
#: collapse to one attribute test returning a shared null handle.
#: Sub-microsecond on any CPU; 5 µs leaves slack for slow shared CI
#: machines while catching an accidental allocation, lock acquisition,
#: or id-minting sneaking onto the disarmed path.
TRACING_DISARMED_US = 5.0

#: p50 per-tick budget (ms) for CHUNKED admission (continuous batching):
#: on top of the paged tick, every admission tick runs the FIFO chunk
#: scheduler — sort the not-yet-prefilled rows by arrival, carve the
#: token budget into block-aligned chunks, and advance per-row progress
#: cursors. All O(batch) host arithmetic; the same 5 ms envelope must
#: hold, or bounding TTFT with chunking would pay itself back as
#: per-tick scheduler overhead on every decode step.
CHUNKED_BUDGET_MS = 5.0

#: p95 per-key budget (µs) for the shard-map route (kubedl_tpu/shards/
#: shardmap.py): every workqueue enqueue, store write, and watch
#: delivery in the sharded control plane calls ``lookup(key)``, so HRW
#: scoring must stay noise next to the reconcile it routes. One crc32
#: per shard over a short string (memoized for hot keys); 5 µs leaves
#: wide headroom on shared CI machines while catching an accidental
#: per-call allocation storm, a busted memo cache, or a switch to a
#: Python-level hash loop.
SHARDMAP_LOOKUP_BUDGET_US = 5.0

#: per-event budget (µs) for a COALESCED workqueue add — the absorbed
#: path (item already dirty/cooling) every event storm rides: one lock
#: round-trip, two set probes, a counter bump. 10 µs leaves headroom on
#: shared CI machines while catching an accidental heap push, dict
#: rebuild, or timestamp scan sneaking onto the hot absorb path.
WORKQUEUE_ADD_BUDGET_US = 10.0

#: pickups-per-key ceiling for an event storm under coalescing: a burst
#: of N events on an already-reconciled key must cost ~1 follow-up
#: pickup (the window-edge re-add), not N. 3 allows the window to roll
#: over once on a slow machine while still failing the
#: reconcile-per-event shape this guards against.
WORKQUEUE_STORM_PICKUPS_PER_KEY = 3.0


def build_stub_engine(max_batch: int = 4, max_seq: int = 128,
                      kv_layout: str = "contiguous",
                      kv_attention: str = "gather",
                      prefill_chunk_tokens: int = 0):
    """A real LlamaEngine whose device calls are instant stubs: the
    scheduler loop, slot machinery, chain/pending bookkeeping, and
    accounting all run for real; only the model math is elided."""
    import jax
    import jax.numpy as jnp

    from kubedl_tpu.serving.server import LlamaEngine

    eng = LlamaEngine(preset="tiny", max_batch=max_batch, max_seq=max_seq,
                      kv_layout=kv_layout, kv_attention=kv_attention,
                      prefill_chunk_tokens=prefill_chunk_tokens)
    # freeze the background scheduler: the bench thread drives ticks
    with eng._cv:
        eng._stop = True
        eng._cv.notify_all()
    eng._thread.join(timeout=10)
    eng._stop = False

    B = eng.max_batch
    last = jnp.ones((B, 1), jnp.int32)
    ids = jnp.ones((B,), jnp.int32)
    logits = jnp.zeros((B, 8), jnp.float32)  # shape never inspected
    seg_toks = {}
    jax.block_until_ready((last, ids, logits))

    eng._prefill = lambda p, c, t, l: (logits, c)
    eng._sample_logits = lambda lg, temps, key: ids
    eng._merge_chain = lambda lastv, i, m: lastv

    def segment_fn(k, greedy):
        toks = seg_toks.get(k)
        if toks is None:
            toks = jax.block_until_ready(jnp.ones((B, k), jnp.int32))
            seg_toks[k] = toks
        return lambda p, c, tok, temps, key: (toks, last, key, c)

    eng._segment_fn = segment_fn
    return eng


def _drive(eng, slots, budget_ticks: int):
    """Queue ``slots``, warm one tick, reset counters, then tick the
    pipeline to completion. Returns (wall_ms, tokens, pipeline_stats)."""
    with eng._cv:
        eng._waiting.extend(slots)
        eng._cv.notify_all()
    # warm tick (first segment-size/temps paths), then reset counters
    eng._loop_once()
    with eng._cv:
        for k in eng._pipe:
            eng._pipe[k] = 0.0 if isinstance(eng._pipe[k], float) else 0
        eng._pipe_recent.clear()
    t0 = time.perf_counter()
    ticks = 0
    while not all(s.done.is_set() for s in slots):
        eng._loop_once()
        ticks += 1
        if ticks > budget_ticks:
            raise RuntimeError("microbench did not converge")
    wall_ms = (time.perf_counter() - t0) * 1e3
    tokens = sum(len(s.out_ids) for s in slots)
    return wall_ms, tokens, eng.pipeline_stats()


def run_microbench(requests: int = 32, max_tokens: int = 32,
                   max_batch: int = 4) -> dict:
    """Push ``requests`` stub requests through the pipeline tick-by-tick
    and return the engine's own per-tick accounting plus derived
    per-token host overhead."""
    from kubedl_tpu.serving.server import _Slot

    eng = build_stub_engine(max_batch=max_batch)
    try:
        slots = [
            _Slot([1, 2, 3], max_tokens, 0.0) for _ in range(requests)
        ]
        wall_ms, tokens, pipe = _drive(
            eng, slots, requests * max_tokens + 100
        )
        assert all(
            len(s.out_ids) == max_tokens for s in slots
        ), "stub pipeline dropped tokens"
        return {
            "requests": requests,
            "max_tokens": max_tokens,
            "max_batch": max_batch,
            "ticks": pipe["ticks"],
            "tokens": tokens,
            "wall_ms": round(wall_ms, 2),
            "tick_ms_p50": pipe.get("tick_ms_p50", 0.0),
            "dispatch_ms_p50": pipe.get("dispatch_ms_p50", 0.0),
            "harvest_ms_p50": pipe.get("harvest_ms_p50", 0.0),
            "host_ms_p50": pipe.get("host_ms_p50", 0.0),
            "host_overhead_ms_per_token": round(wall_ms / max(tokens, 1), 4),
            "budget_ms": TICK_BUDGET_MS,
            "within_budget": pipe.get("tick_ms_p50", 0.0) <= TICK_BUDGET_MS,
        }
    finally:
        eng.close()


def run_prefix_microbench(requests: int = 32, max_tokens: int = 8,
                          max_batch: int = 4, prefix_len: int = 64) -> dict:
    """Host overhead of the prefix-cache admission path: every request
    shares a ``prefix_len``-token prefix already stored in the cache, so
    each admission walks the observation trie, longest-prefix-matches,
    pins, and dispatches a (stubbed) graft + suffix prefill. Reports the
    engine's tick accounting plus an isolated match+graft microtiming."""
    import numpy as np

    from kubedl_tpu.serving.server import _Slot

    eng = build_stub_engine(max_batch=max_batch)
    try:
        eng._graft = lambda c, k, v, row, n: c
        eng._extract = lambda c, i, p: (None, None)
        eng._prefill_from = lambda p, c, t, l, st: (
            eng._prefill(p, c, t, l)
        )
        prefix = list(range(3, 3 + prefix_len))
        payload = np.zeros((1,), np.float32)
        assert eng._pcache is not None, "stub engine must enable the cache"
        assert eng._pcache.insert(prefix, payload, payload, prefix_len)
        # isolated host cost of one match (trie walk + pin) + graft
        # dispatch, without the rest of the tick around it
        probe = prefix + [999]
        iters = 2000
        t0 = time.perf_counter()
        for _ in range(iters):
            e, n = eng._pcache.match(probe)
            eng._graft(eng._cache, e.k, e.v, 0, n)
            eng._pcache.unpin(e)
        match_graft_ms = (time.perf_counter() - t0) * 1e3 / iters
        hits0 = eng._pcache.stats()["hits"]

        slots = [
            _Slot(prefix + [1000 + j], max_tokens, 0.0)
            for j in range(requests)
        ]
        _drive(eng, slots, requests * max_tokens + 100)
        st = eng._pcache.stats()
        pipe = eng.pipeline_stats()
        tick_p50 = pipe.get("tick_ms_p50", 0.0)
        return {
            "requests": requests,
            "prefix_len": prefix_len,
            "hits": st["hits"] - hits0,
            "tokens_saved": st["tokens_saved"],
            "ticks": pipe["ticks"],
            "tick_ms_p50": tick_p50,
            "match_graft_ms": round(match_graft_ms, 4),
            "budget_ms": PREFIX_BUDGET_MS,
            "within_budget": (
                tick_p50 <= PREFIX_BUDGET_MS
                and match_graft_ms <= PREFIX_BUDGET_MS
            ),
        }
    finally:
        eng.close()


def run_paged_microbench(requests: int = 32, max_tokens: int = 32,
                         max_batch: int = 4) -> dict:
    """Host overhead of the PAGED layout's block-table bookkeeping:
    every dispatch re-uploads the pos/block-table mirrors and admission/
    finalize run allocator alloc/free, all on top of the plain tick.
    Reports the engine's tick accounting, an isolated mirror-upload
    microtiming, and proves block conservation (the pool drains back to
    empty once every request finishes)."""
    import jax

    from kubedl_tpu.serving.server import _Slot

    eng = build_stub_engine(max_batch=max_batch, kv_layout="paged")
    try:
        # isolated host cost of one mirror upload pair (pos + block
        # table), the per-dispatch tax unique to the paged layout
        iters = 2000
        t0 = time.perf_counter()
        for _ in range(iters):
            jax.block_until_ready((
                eng._upload_mirror(eng._pos_host),
                eng._upload_mirror(eng._bt_host),
            ))
        mirror_upload_ms = (time.perf_counter() - t0) * 1e3 / iters

        slots = [
            # distinct prompts so no run rides the prefix cache: this
            # bench isolates the block-table path
            _Slot([1, 2, 3 + j], max_tokens, 0.0)
            for j in range(requests)
        ]
        wall_ms, tokens, pipe = _drive(
            eng, slots, requests * max_tokens + 100
        )
        assert all(
            len(s.out_ids) == max_tokens for s in slots
        ), "stub paged pipeline dropped tokens"
        st = eng._alloc.stats()
        assert st["used"] == 0, f"block leak: {st}"
        tick_p50 = pipe.get("tick_ms_p50", 0.0)
        return {
            "requests": requests,
            "max_tokens": max_tokens,
            "max_batch": max_batch,
            "kv_blocks": eng.kv_blocks,
            "block_size": eng.kv_block_size,
            "ticks": pipe["ticks"],
            "tokens": tokens,
            "wall_ms": round(wall_ms, 2),
            "tick_ms_p50": tick_p50,
            "host_ms_p50": pipe.get("host_ms_p50", 0.0),
            "mirror_upload_ms": round(mirror_upload_ms, 4),
            "blocks_leaked": st["used"],
            "budget_ms": PAGED_BUDGET_MS,
            "within_budget": (
                tick_p50 <= PAGED_BUDGET_MS
                and mirror_upload_ms <= PAGED_BUDGET_MS
            ),
        }
    finally:
        eng.close()


def run_chunked_admission_microbench(requests: int = 16,
                                     prompt_len: int = 48,
                                     max_tokens: int = 8,
                                     max_batch: int = 4,
                                     chunk: int = 16) -> dict:
    """Host overhead of CHUNKED admission (continuous batching): every
    tick with queued prompts runs the FIFO chunk scheduler — arrival
    sort, block-aligned budget carving, per-row progress cursors — on
    top of the paged tick. With the device stubbed, the tick must fit
    the same envelope as slot-granularity admission; reports chunk
    accounting so a budget miscount (chunks != ceil(len/budget)) fails
    loudly too."""
    from kubedl_tpu.serving.server import _Slot

    eng = build_stub_engine(max_batch=max_batch, kv_layout="paged",
                            prefill_chunk_tokens=chunk)
    try:
        eng._prefill_from = lambda p, c, t, l, st: (
            eng._prefill(p, c, t, l)
        )
        assert eng.prefill_chunk_tokens == chunk
        slots = [
            # distinct multi-chunk prompts (no prefix-cache rides)
            _Slot([j + 1] + list(range(5, 4 + prompt_len)), max_tokens, 0.0)
            for j in range(requests)
        ]
        wall_ms, tokens, pipe = _drive(
            eng, slots, requests * (max_tokens + prompt_len) + 100
        )
        assert all(
            len(s.out_ids) == max_tokens for s in slots
        ), "chunked stub pipeline dropped tokens"
        body = eng.metrics.registry.render()
        chunks = next(
            float(l.split()[-1]) for l in body.splitlines()
            if l.startswith("kubedl_tpu_serving_admission_chunks ")
        )
        want = requests * -(-prompt_len // chunk)  # ceil per request
        assert chunks == want, (chunks, want)
        st = eng._alloc.stats()
        assert st["used"] == 0, f"block leak: {st}"
        tick_p50 = pipe.get("tick_ms_p50", 0.0)
        return {
            "requests": requests,
            "prompt_len": prompt_len,
            "chunk_tokens": chunk,
            "chunks": int(chunks),
            "ticks": pipe["ticks"],
            "tokens": tokens,
            "wall_ms": round(wall_ms, 2),
            "tick_ms_p50": tick_p50,
            "host_ms_p50": pipe.get("host_ms_p50", 0.0),
            "blocks_leaked": st["used"],
            "budget_ms": CHUNKED_BUDGET_MS,
            "within_budget": tick_p50 <= CHUNKED_BUDGET_MS,
        }
    finally:
        eng.close()


def run_blocked_attention_microbench(requests: int = 32,
                                     max_tokens: int = 32,
                                     max_batch: int = 4,
                                     iters: int = 200) -> dict:
    """Host overhead of the blocked paged-attention path: (1) drive the
    stub paged engine with ``kv_attention="blocked"`` — the tick must fit
    the same envelope as the gather tick, proving the kernel selection
    plumbing adds no per-tick host work; (2) time one dispatch of the
    COMPILED blocked kernel at a trivial shape where device compute is
    negligible, so per-call wall is the host dispatch + jit-cache-lookup
    cost of the scan-heavy executable."""
    import jax
    import jax.numpy as jnp

    from kubedl_tpu.serving.server import _Slot

    eng = build_stub_engine(max_batch=max_batch, kv_layout="paged",
                            kv_attention="blocked")
    try:
        assert eng.kv_attention == "blocked"
        slots = [
            _Slot([1, 2, 3 + j], max_tokens, 0.0)
            for j in range(requests)
        ]
        wall_ms, tokens, pipe = _drive(
            eng, slots, requests * max_tokens + 100
        )
        assert all(
            len(s.out_ids) == max_tokens for s in slots
        ), "stub blocked pipeline dropped tokens"
        st = eng._alloc.stats()
        assert st["used"] == 0, f"block leak: {st}"
        tick_p50 = pipe.get("tick_ms_p50", 0.0)
    finally:
        eng.close()

    # isolated compiled-kernel dispatch at a tiny decode shape: S=1,
    # 4 rows, 8 blocks/row of 16 — microseconds of compute on any host
    from kubedl_tpu.models import paged_attention as pa

    B, S, H, KV, hd, BS, MB = 4, 1, 4, 2, 16, 16, 8
    NB = 1 + B * MB
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, S, H, hd), jnp.float32)
    kp = jax.random.normal(key, (NB, BS, KV, hd), jnp.float32)
    vp = jax.random.normal(key, (NB, BS, KV, hd), jnp.float32)
    bt = jnp.arange(1, 1 + B * MB, dtype=jnp.int32).reshape(B, MB)
    starts = jnp.full((B,), BS * MB - 2, jnp.int32)
    fn = jax.jit(lambda q, kp, vp, bt, st: pa.paged_attention(
        q, kp, vp, bt, st, kernel="lax"))
    jax.block_until_ready(fn(q, kp, vp, bt, starts))  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn(q, kp, vp, bt, starts)
    jax.block_until_ready(r)
    dispatch_ms = (time.perf_counter() - t0) * 1e3 / iters

    return {
        "requests": requests,
        "max_tokens": max_tokens,
        "max_batch": max_batch,
        "ticks": pipe["ticks"],
        "tokens": tokens,
        "wall_ms": round(wall_ms, 2),
        "tick_ms_p50": tick_p50,
        "host_ms_p50": pipe.get("host_ms_p50", 0.0),
        "kernel_dispatch_ms": round(dispatch_ms, 4),
        "blocks_leaked": st["used"],
        "budget_ms": BLOCKED_BUDGET_MS,
        "within_budget": (
            tick_p50 <= BLOCKED_BUDGET_MS
            and dispatch_ms <= BLOCKED_BUDGET_MS
        ),
    }


def run_planner_microbench() -> dict:
    """Host overhead of plan(): every catalog topology x every zoo model
    (the full admission matrix), reporting per-plan wall-time percentiles
    against PLANNER_BUDGET_MS. Infeasible combinations (PlanError) count —
    proving infeasibility walks the same candidate lattice."""
    from kubedl_tpu.api.topology import SLICE_CATALOG
    from kubedl_tpu.planner import MODEL_ZOO, PlanError, plan

    times = []
    candidates = 0
    plans = 0
    infeasible = 0
    for topo in SLICE_CATALOG.values():
        for model in MODEL_ZOO.values():
            t0 = time.perf_counter()
            try:
                p = plan(model, topo)
                candidates += p.candidates_evaluated
                plans += 1
            except PlanError:
                infeasible += 1
            times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    p50 = times[len(times) // 2]
    p95 = times[int(len(times) * 0.95)]
    return {
        "plans": plans,
        "infeasible": infeasible,
        "candidates_evaluated": candidates,
        "plan_ms_p50": round(p50, 3),
        "plan_ms_p95": round(p95, 3),
        "plan_ms_max": round(times[-1], 3),
        "budget_ms": PLANNER_BUDGET_MS,
        "within_budget": p95 <= PLANNER_BUDGET_MS,
    }


def run_bucket_microbench(iters: int = 200) -> dict:
    """Host overhead of the gradient-bucket scatter plan: price a
    realistic large-model leaf census (a few hundred leaves spanning
    norm-scale bytes to embedding GiBs) ``iters`` times and report the
    per-plan percentiles against BUCKET_BUDGET_MS."""
    from kubedl_tpu.training.buckets import plan_grad_buckets

    # ~8B-class census: 80 stacked layers x (7 matmul leaves + 2 norms)
    # + embed/head/final-norm, fp32 grad bytes
    leaf_bytes = []
    for _ in range(80):
        leaf_bytes += [4 * 4096 * 4096] * 4   # attention projections
        leaf_bytes += [4 * 4096 * 14336] * 3  # ffn
        leaf_bytes += [4 * 4096] * 2          # rms norms
    leaf_bytes += [4 * 128256 * 4096] * 2 + [4 * 4096]
    times = []
    plan = None
    for _ in range(iters):
        t0 = time.perf_counter()
        plan = plan_grad_buckets(leaf_bytes)
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    p50 = times[len(times) // 2]
    p95 = times[int(len(times) * 0.95)]
    return {
        "leaves": len(leaf_bytes),
        "buckets": plan.n_buckets,
        "scattered_fraction": round(plan.scattered_fraction, 4),
        "plan_ms_p50": round(p50, 4),
        "plan_ms_p95": round(p95, 4),
        "plan_ms_max": round(times[-1], 4),
        "budget_ms": BUCKET_BUDGET_MS,
        "within_budget": p95 <= BUCKET_BUDGET_MS,
    }


def run_shardmap_microbench(keys: int = 100_000, shards: int = 4) -> dict:
    """Per-key cost of the HRW shard route over ``keys`` distinct
    ``ns/name`` keys (every lookup a memo MISS — the worst case; hot
    reconcile keys hit the memo and cost a dict probe), plus the memo-hit
    path timed separately, against SHARDMAP_LOOKUP_BUDGET_US. Also
    reports the balance spread so a degenerate hash (everything on one
    shard) fails loudly here, not in a scale run."""
    from kubedl_tpu.shards.shardmap import ShardMap

    all_keys = [f"ns-{i % 7}/job-{i:06d}" for i in range(keys)]
    sm = ShardMap(shards)
    # per-key timing over cold keys (every one a memo miss): individual
    # samples make the p95 robust to scheduler preemption on shared CI —
    # a descheduling poisons only the keys it lands on, not a whole
    # batch average. perf_counter_ns call-pair overhead (~0.1 µs) rides
    # inside each sample; it is noise against the 5 µs budget.
    ns = time.perf_counter_ns
    lookup = sm.lookup
    times = []
    for k in all_keys:
        t0 = ns()
        lookup(k)
        times.append((ns() - t0) / 1e3)
    times.sort()
    p50 = times[len(times) // 2]
    p95 = times[int(len(times) * 0.95)]

    hot = all_keys[-1]
    iters = 100_000
    t0 = time.perf_counter()
    for _ in range(iters):
        sm.lookup(hot)
    hit_us = (time.perf_counter() - t0) * 1e6 / iters

    counts = sm.spread(all_keys)
    lo, hi = min(counts.values()), max(counts.values())
    return {
        "keys": keys,
        "shards": shards,
        "lookup_us_p50": round(p50, 4),
        "lookup_us_p95": round(p95, 4),
        "memo_hit_us": round(hit_us, 4),
        "spread_min": lo,
        "spread_max": hi,
        "spread_imbalance": round(hi / max(lo, 1), 3),
        "budget_us": SHARDMAP_LOOKUP_BUDGET_US,
        "within_budget": p95 <= SHARDMAP_LOOKUP_BUDGET_US,
    }


def run_workqueue_microbench(keys: int = 200,
                             events_per_key: int = 50) -> dict:
    """Workqueue burst coalescing under an enqueue storm: ``keys``
    already-reconciled keys each take ``events_per_key`` rapid-fire
    re-adds (the 10-pods-churn-per-job shape), then the queue drains.
    Reports dequeue count vs event count — the whole point of coalescing
    is that the storm costs ~1 follow-up pickup per key, not one per
    event — plus the per-event cost of the absorbed-add hot path."""
    from kubedl_tpu.core.workqueue import WorkQueue

    window = 0.02
    q = WorkQueue(coalesce_window=window)
    # phase 1: every key reconciled once (stamps its last-get time)
    for i in range(keys):
        q.add(i)
    while True:
        batch = q.get_batch(max_items=64, timeout=0.01)
        if not batch:
            break
        for item in batch:
            q.done(item)
    # phase 2: the storm, timed — every add lands within the window of
    # its key's pickup, so adds 2..N ride the absorbed fast path
    events = keys * events_per_key
    t0 = time.perf_counter()
    for i in range(keys):
        for _ in range(events_per_key):
            q.add(i)
    add_us = (time.perf_counter() - t0) * 1e6 / events
    # phase 3: drain — count how many pickups the storm actually cost
    pickups = 0
    deadline = time.time() + 5.0
    while time.time() < deadline:
        batch = q.get_batch(max_items=64, timeout=window)
        if batch:
            pickups += len(batch)
            for item in batch:
                q.done(item)
        elif len(q) == 0:
            break
    per_key = pickups / max(keys, 1)
    return {
        "keys": keys,
        "events": events,
        "coalesce_window_ms": window * 1e3,
        "storm_pickups": pickups,
        "pickups_per_key": round(per_key, 3),
        "coalesced": q.coalesced,
        "add_us": round(add_us, 4),
        "add_budget_us": WORKQUEUE_ADD_BUDGET_US,
        "pickups_per_key_budget": WORKQUEUE_STORM_PICKUPS_PER_KEY,
        "within_budget": (
            per_key <= WORKQUEUE_STORM_PICKUPS_PER_KEY
            and pickups >= keys  # final state never dropped
            and add_us <= WORKQUEUE_ADD_BUDGET_US
        ),
    }


def run_tracing_microbench(calls: int = 200_000) -> dict:
    """Per-call cost of the DISARMED tracing fast path: a fresh local
    Tracer with ``enabled = False``, timing the three hot-path entry
    points (``span`` context manager, ``begin``/``finish``, ``record``)
    against TRACING_DISARMED_US. Uses a local instance so the shared
    TRACER singleton's arm state is untouched."""
    from kubedl_tpu.observability.tracing import Tracer

    t = Tracer()
    t.enabled = False

    t0 = time.perf_counter()
    for _ in range(calls):
        with t.span("bench.noop"):
            pass
    span_us = (time.perf_counter() - t0) * 1e6 / calls

    t0 = time.perf_counter()
    for _ in range(calls):
        t.begin("bench.noop").finish()
    begin_us = (time.perf_counter() - t0) * 1e6 / calls

    t0 = time.perf_counter()
    for _ in range(calls):
        t.record("bench.noop", duration=0.0)
    record_us = (time.perf_counter() - t0) * 1e6 / calls

    assert not t.spans(), "disarmed tracer must record nothing"
    worst = max(span_us, begin_us, record_us)
    return {
        "calls": calls,
        "span_us": round(span_us, 4),
        "begin_finish_us": round(begin_us, 4),
        "record_us": round(record_us, 4),
        "budget_us": TRACING_DISARMED_US,
        "within_budget": worst <= TRACING_DISARMED_US,
    }


def main() -> int:
    out = run_microbench()
    out["prefix"] = run_prefix_microbench()
    out["paged"] = run_paged_microbench()
    out["chunked_admission"] = run_chunked_admission_microbench()
    out["blocked_attention"] = run_blocked_attention_microbench()
    out["planner"] = run_planner_microbench()
    out["buckets"] = run_bucket_microbench()
    out["tracing"] = run_tracing_microbench()
    out["shardmap"] = run_shardmap_microbench()
    out["workqueue"] = run_workqueue_microbench()
    print(json.dumps(out, indent=2))
    ok = (out["within_budget"] and out["prefix"]["within_budget"]
          and out["paged"]["within_budget"]
          and out["chunked_admission"]["within_budget"]
          and out["blocked_attention"]["within_budget"]
          and out["planner"]["within_budget"]
          and out["buckets"]["within_budget"]
          and out["tracing"]["within_budget"]
          and out["shardmap"]["within_budget"]
          and out["workqueue"]["within_budget"])
    return 0 if ok else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())

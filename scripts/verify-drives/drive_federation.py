"""Drive multi-operator federation with REAL process faults
(docs/architecture.md "Multi-operator federation", docs/robustness.md
federation runbook):

1. three child processes each run one full federation member over a
   SHARED lease/WAL root — fenced :class:`ShardedObjectStore` (6 shards,
   group commit), flock-backed :class:`FileLeaseStore`,
   :class:`FederationMember` (rank-staggered standby campaigns,
   lease-root heartbeats, WAL-tail reads) and a ControllerManager
   churning jobs through the create-pods/observe/tear-down reconcile
   loop. Each member submits only the jobs the deterministic plan routes
   to its shards, gated to a bounded in-flight window so the
   ``job.pod_launch`` trace milestone measures reconcile latency;
2. the driver SIGKILLs the seeded victim mid-churn and asserts: the
   survivors' staggered campaigns absorb the victim's shards within ~the
   lease TTL, launch milestones resume and time-to-launch recovers,
   and the victim's orphaned jobs drain;
3. then the nastiest fencing schedule, cross-process (the in-process
   twin is tests/test_federation.py::TestFencedTakeoverSchedule): a
   survivor is SIGSTOP'd past its lease TTL, the last member takes over
   ALL shards and keeps launching, the stopped member is SIGCONT'd —
   every actuation it had queued must be rejected with FencedOut (its
   fences verify against leases now held elsewhere and depose sticky),
   it ends up owning nothing, and it keeps running — observing, never
   acting. (Read-only DEMOTION is the lease-root-partition response,
   driven by the ``federation.lease_io``/``federation.heartbeat`` chaos
   sites in tests/test_federation.py::TestPartitionDemotion — a
   SIGSTOP'd member resumes to a healthy root, so fencing, not the
   heartbeat deadline, is what stops it);
4. ground truth at the end: a full WAL replay
   (:func:`kubedl_tpu.federation.duplicate_creates`) proves no pod was
   ever launched twice while live — across a SIGKILL, a SIGSTOP/CONT,
   and every takeover — and the shared launches.log ledger agrees.

Job volume is env-tunable: KUBEDL_DRIVE_FED_JOBS (default 720 — sized
so an idle 1-core box cannot drain the churn before the SIGSTOP lands;
the committed BENCH_r20_federation.json kill arm runs the same harness
at 10k), KUBEDL_DRIVE_FED_SEED picks the SIGKILL victim.

Run with `python scripts/verify-drives/drive_federation.py`
(CPU only; control plane only — no jax needed).
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

LEASE_TTL = 1.0
#: expiry (ttl) + staggered standby campaign + scheduling slop
TAKEOVER_BUDGET_S = LEASE_TTL * 4 + 2.0
SHARDS = 6
MEMBERS = ["fed-a", "fed-b", "fed-c"]
JOBS = int(os.environ.get("KUBEDL_DRIVE_FED_JOBS", "720"))
SEED = int(os.environ.get("KUBEDL_DRIVE_FED_SEED", "20"))
PODS_PER_JOB = 3


def _read_status(path):
    try:
        with open(path) as fh:
            return json.loads(fh.read())
    except (OSError, ValueError):
        return None


def parent_main():
    from kubedl_tpu.federation import duplicate_creates, plan_assignment
    from kubedl_tpu.shards import ShardMap
    from kubedl_tpu.shards.fencing import (
        SHARD_LEASE_NAMESPACE, FileLeaseStore, shard_lease_name,
    )

    ok = []

    def check(name, cond, detail=""):
        ok.append(bool(cond))
        print(("PASS" if cond else "FAIL"), name, detail)

    def poll(path, pred, timeout):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            st = _read_status(path)
            if st is not None and pred(st):
                return st
            time.sleep(0.05)
        return _read_status(path)

    tmp = tempfile.mkdtemp(prefix="kdl-fed-drive-")
    wal_root = os.path.join(tmp, "wal")
    lease_dir = os.path.join(tmp, "leases")
    launch_log = os.path.join(tmp, "launches.log")
    stop_path = os.path.join(tmp, "stop")
    open(launch_log, "w").close()
    status = {m: os.path.join(tmp, f"status_{m}.json") for m in MEMBERS}
    backend = FileLeaseStore(lease_dir)

    # the same static math every member derives: which jobs are whose
    plan = plan_assignment(SHARDS, MEMBERS)
    shard_owner = {i: m for m, shards in plan.items() for i in shards}
    smap = ShardMap(SHARDS)
    share = {m: 0 for m in MEMBERS}
    for i in range(JOBS):
        share[shard_owner[smap.lookup(f"default/fed-{i:05d}")]] += 1

    victim = MEMBERS[SEED % len(MEMBERS)]
    survivors = [m for m in MEMBERS if m != victim]
    stopped, last = survivors[0], survivors[1]
    print(f"jobs={JOBS} seed={SEED}: SIGKILL {victim}, "
          f"SIGSTOP {stopped}, {last} inherits everything")

    def holders():
        out = {}
        for i in range(SHARDS):
            lease = backend.try_get(
                "Lease", shard_lease_name(i), SHARD_LEASE_NAMESPACE)
            out[i] = lease.holder if lease is not None else None
        return out

    procs = {}
    try:
        for m in MEMBERS:
            cfg = {
                "mode": "member", "identity": m, "peers": MEMBERS,
                "shards": SHARDS, "jobs": JOBS,
                "pods_per_job": PODS_PER_JOB,
                "lease_dir": lease_dir, "wal_dir": wal_root,
                "lease_ttl": LEASE_TTL, "group_window_ms": 5.0,
                "coalesce_ms": 20.0, "wave": 8, "max_inflight": 24,
                "launch_telemetry": True, "launch_log": launch_log,
                "status_path": status[m], "stop_path": stop_path,
            }
            procs[m] = subprocess.Popen(
                [sys.executable, "-m", "kubedl_tpu.federation.bench_worker",
                 json.dumps(cfg)],
                stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))),
            )

        # --- phase 1: healthy federation churning -------------------------
        sts = {m: poll(status[m], lambda s: s["completed"] >= 10, 90.0)
               for m in MEMBERS}
        check("all three members own their planned shards and churn",
              all(sts[m] and sts[m]["completed"] >= 10
                  and sorted(sts[m]["owned"]) == sorted(plan[m])
                  for m in MEMBERS),
              " ".join(f"{m}:{sts[m] and sts[m]['completed']}"
                       for m in MEMBERS))
        if not all(sts.values()):
            return finish(ok, tmp, procs)
        baseline_ms = max(sts[m]["recent_launch_ms"] for m in survivors)

        # --- phase 2: seeded SIGKILL mid-churn ----------------------------
        check("victim killed with jobs in flight",
              sts[victim]["submitted"] > sts[victim]["completed"],
              str({k: sts[victim][k] for k in ("submitted", "completed")}))
        t_kill = time.perf_counter()
        t_kill_wall = time.time()
        procs[victim].send_signal(signal.SIGKILL)
        procs[victim].wait(timeout=10)

        deadline = time.perf_counter() + TAKEOVER_BUDGET_S + 10.0
        reconverge_s = None
        while time.perf_counter() < deadline:
            h = holders()
            if all(h[i] in survivors for i in plan[victim]):
                reconverge_s = time.perf_counter() - t_kill
                break
            time.sleep(0.05)
        check(f"survivors absorbed the victim's shards "
              f"(<{TAKEOVER_BUDGET_S:.0f}s)",
              reconverge_s is not None
              and reconverge_s < TAKEOVER_BUDGET_S,
              f"{reconverge_s and f'{reconverge_s:.2f}'}s "
              f"holders={holders()}")

        # --- phase 3: SIGSTOP a survivor past its TTL ---------------------
        # freeze RIGHT after reconvergence, while the survivor is still
        # MID-SUBMISSION with a live in-flight window — the queued
        # reconciles must get fenced on resume, and the next submit wave
        # it attempts must be rejected at assert_fenced_actuation (both
        # print FencedOut to its stderr). Poll for a fresh status showing
        # both conditions rather than trusting one stale read: on an idle
        # box the churn drains fast enough to close the window.
        st = poll(status[stopped],
                  lambda s: s["submitted"] < share[stopped]
                  and s["submitted"] - s["completed"] >= 4,
                  30.0)
        os.kill(procs[stopped].pid, signal.SIGSTOP)
        check("survivor frozen mid-submission with jobs in flight",
              st and st["submitted"] < share[stopped]
              and st["submitted"] - st["completed"] >= 4,
              str(st and {k: st[k] for k in
                          ("submitted", "completed")})
              + f" share={share[stopped]}")
        t_stop = time.perf_counter()
        st = poll(status[last],
                  lambda s: sorted(s["owned"]) == list(range(SHARDS)),
                  TAKEOVER_BUDGET_S * 2 + 10.0)
        check("last member took over ALL shards from the stopped one",
              st and sorted(st["owned"]) == list(range(SHARDS)),
              f"{time.perf_counter() - t_stop:.2f}s "
              f"owned={st and st['owned']}")
        # hold the freeze past the TTL so the resume is unambiguously
        # stale, then let the old owner's queued actuations fire
        time.sleep(max(0.0, LEASE_TTL * 1.5 - (time.perf_counter() - t_stop)))
        os.kill(procs[stopped].pid, signal.SIGCONT)
        st = poll(status[stopped], lambda s: s.get("owned") == [], 30.0)
        check("resumed member observes but owns nothing",
              st and st.get("owned") == [], str(st))

        st = poll(status[last],
                  lambda s: s["last_launch_at"] > t_kill_wall, 60.0)
        check("launch milestones resumed after the faults",
              st and st["last_launch_at"] > t_kill_wall, str(st))

        # --- phase 4: drain + ground truth --------------------------------
        st = poll(
            status[last],
            lambda s: s["submitted"] >= share[last]
            and s["remaining_jobs"] == 0,
            240.0,
        )
        check("last member drained every live job on all shards",
              st and st["remaining_jobs"] == 0
              and st["submitted"] >= share[last], str(st))
        check("time-to-launch recovered after the takeovers",
              st and st["recent_launch_ms"] < TAKEOVER_BUDGET_S * 1e3,
              f"baseline={baseline_ms:.0f}ms "
              f"final={st and st['recent_launch_ms']:.0f}ms")

        open(stop_path, "w").write("x")
        for m in (stopped, last):
            try:
                procs[m].wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
        check("surviving members exited cleanly on the stop signal",
              all(procs[m].returncode == 0 for m in (stopped, last)),
              str({m: procs[m].returncode for m in (stopped, last)}))

        stopped_err = (procs[stopped].stderr.read()
                       if procs[stopped].stderr else "")
        check("resumed member's queued actuations were fenced",
              "FencedOut" in stopped_err,
              f"{stopped_err.count('FencedOut')} FencedOut rejections "
              "in its log")

        dups = duplicate_creates(wal_root, SHARDS)
        check("WAL replay: zero duplicate pod launches", dups == [],
              f"dups={dups[:5]}")
        lines = [l for l in open(launch_log).read().splitlines() if l]
        relaunches = len(lines) - len(set(lines))
        # the ledger may legitimately re-list a pod whose delete was
        # durable before the SIGKILL (see duplicate_creates docstring) —
        # the WAL audit above is the gate; the ledger must stay close
        check("launch ledger consistent with the WAL audit",
              relaunches <= PODS_PER_JOB,
              f"{len(lines)} launches, {relaunches} ledger re-lists")
    finally:
        for p in procs.values():
            if p.poll() is None:
                try:
                    os.kill(p.pid, signal.SIGCONT)
                except OSError:
                    pass
                p.kill()
                p.wait(timeout=10)
    return finish(ok, tmp, procs)


def finish(ok, tmp, procs):
    for m, p in procs.items():
        if p.stderr is not None and p.returncode not in (None, -signal.SIGKILL):
            err = p.stderr.read()[-400:]
            if err:
                print(f"--- member {m} stderr ---\n{err}")
    shutil.rmtree(tmp, ignore_errors=True)
    print(f"\n{sum(ok)}/{len(ok)} checks passed")
    return 0 if all(ok) and ok else 1


if __name__ == "__main__":
    sys.exit(parent_main())

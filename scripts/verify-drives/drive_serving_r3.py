"""Drive: round-3 serving — batched prefill TTFT, configurable bind host,
timeout slot release — end to end through the operator + real HTTP."""
import json, os, sys, tempfile, time, urllib.request

os.environ["JAX_PLATFORMS"] = "cpu"
import pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

from kubedl_tpu.lineage.types import ModelVersion, ModelVersionPhase
from kubedl_tpu.operator import Operator, OperatorOptions
from kubedl_tpu.runtime.executor import ThreadRuntime
from kubedl_tpu.serving.types import Framework, Inference, Predictor
from kubedl_tpu.utils.invariants import check_invariants

checks = []
def check(name, ok, detail=""):
    checks.append((name, ok))
    print(("PASS " if ok else "FAIL ") + name + (f" — {detail}" if detail else ""))

tmp = tempfile.mkdtemp(prefix="kdl-serve-r3-")
opts = OperatorOptions(
    local_addresses=True, artifact_registry_root=os.path.join(tmp, "reg"),
    compile_cache_dir=os.path.join(tmp, "cc"),
)
port = 18091
with Operator(opts, runtime=ThreadRuntime()) as op:
    mv = ModelVersion(model_name="m1", storage_root=os.path.join(tmp, "model"),
                      phase=ModelVersionPhase.PENDING)
    mv.metadata.name = "mv1"
    op.store.create(mv)
    pred = Predictor(name="main", model_version="mv1")
    # explicit non-loopback-capable host config (0.0.0.0 binds all ifaces)
    pred.template.spec.main_container().set_env(
        "KUBEDL_SERVE_CONFIG",
        json.dumps({"port": port, "preset": "tiny", "host": "0.0.0.0",
                    "max_batch": 2}),
    )
    inf = Inference(framework=Framework.JAX, predictors=[pred])
    inf.metadata.name = "inf1"
    os.makedirs(os.path.join(tmp, "model"), exist_ok=True)
    op.store.create(inf)

    def post(prompt, n, timeout=30):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/generate",
            data=json.dumps({"prompt_ids": prompt, "max_tokens": n}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())

    deadline = time.time() + 90
    result = None
    while time.time() < deadline and result is None:
        try:
            result = post([1, 2, 3], 4)
        except Exception:
            time.sleep(0.5)
    check("server answered (0.0.0.0 bind)", result is not None)
    check("short prompt generates", result and len(result["token_ids"]) == 4)

    # long prompt: prefill makes this 1 forward + n decode steps
    long_prompt = list(range(1, 60))
    t0 = time.perf_counter()
    r2 = post(long_prompt, 3)
    dt = (time.perf_counter() - t0) * 1e3
    check("59-token prompt served", len(r2["token_ids"]) == 3, f"{dt:.0f}ms")
    check("prompt_len recorded", r2["prompt_len"] == 59)

    # prefill path: compare latency vs per-token feeding expectation: a
    # 59-token prompt must NOT cost ~59x a decode step. Engine decode step
    # on CPU tiny ~ a few ms; allow generous bound.
    r3 = post([5], 3)
    t0 = time.perf_counter()
    r4 = post(long_prompt, 1)
    dt_long = (time.perf_counter() - t0) * 1e3
    t0 = time.perf_counter()
    r5 = post([7], 1)
    dt_short = (time.perf_counter() - t0) * 1e3
    check("long-prompt TTFT not ~O(prompt_len) decode steps",
          dt_long < dt_short * 8 + 200, f"long {dt_long:.0f}ms short {dt_short:.0f}ms")
    stats = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/v1/stats", timeout=5).read())
    check("stats served", stats["requests"] >= 5, str(stats.get("requests")))
    bad = check_invariants(op)
    check("invariants green", not bad, str(bad))

failed = [n for n, ok in checks if not ok]
print(f"\n{len(checks) - len(failed)}/{len(checks)} checks passed")
sys.exit(1 if failed else 0)

"""Drive the chaos layer end to end through the PUBLIC surface: a real
Operator under an armed FaultPlan (injected worker crashes -> gang
restarts, restart count == plan), seeded determinism, store-conflict
retries through the shared RetryPolicy, poison-pill quarantine
(Quarantined condition + metric + event), serving load shedding over
real HTTP (503 + Retry-After + shed counter on /metrics), and a torn
checkpoint save falling back to the last good step."""
import json
import os
import shutil
import sys
import tempfile
import threading
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
os.environ["JAX_PLATFORMS"] = "cpu"
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

ok = []
def check(name, cond, detail=""):
    ok.append(bool(cond))
    print(("PASS" if cond else "FAIL"), name, detail)

from kubedl_tpu import chaos
from kubedl_tpu.chaos import FaultPlan, FaultSpec

tmp = tempfile.mkdtemp(prefix="kdl-chaos-drive-")

# 1. determinism: same seed -> identical trace
def run_trace(seed):
    plan = FaultPlan(seed, sites={"x": [FaultSpec.prob(0.4, 30)]})
    with plan:
        for _ in range(30):
            try:
                chaos.check("x")
            except chaos.FaultInjected:
                pass
    return plan.trace_tuples()
check("same seed -> identical fault trace", run_trace(7) == run_trace(7))
check("different seed -> different trace", run_trace(7) != run_trace(8))

# 2. store conflicts ride the shared retry policy
from kubedl_tpu.core.store import Conflict, ObjectStore
from kubedl_tpu.workloads.tpujob import TPUJob
store = ObjectStore()
j = TPUJob(); j.metadata.name = "drive"
store.create(j)
with FaultPlan(1, sites={"store.update": [
        FaultSpec.first(3, exc=lambda s: Conflict(s))]}) as plan:
    got = store.update_with_retry(
        "TPUJob", "drive", "default",
        lambda o: o.metadata.labels.update({"hit": "yes"}))
check("update_with_retry survives 3 injected conflicts",
      got.metadata.labels.get("hit") == "yes"
      and plan.faults("store.update") == 3)

# 3. armed plan through a REAL operator: injected worker crashes ->
#    slice-granular gang restarts; restart count matches the plan
from kubedl_tpu.api.types import JobConditionType, ReplicaSpec, ReplicaType, RestartPolicy
from kubedl_tpu.core.objects import Container
from kubedl_tpu.operator import Operator, OperatorOptions
from kubedl_tpu.runtime.executor import ThreadRuntime

def _crashy(env):
    if chaos.should_fail("worker.crash"):
        raise SystemExit(137)
    return 0

sys.modules["__drive_chaos__"] = sys.modules[__name__]
opts = OperatorOptions(local_addresses=True,
                       artifact_registry_root=os.path.join(tmp, "reg"))
plan = FaultPlan(11, sites={"worker.crash": [FaultSpec.first(2)]})
with plan, Operator(opts, runtime=ThreadRuntime()) as op:
    job = TPUJob(); job.metadata.name = "chaos-e2e"
    spec = ReplicaSpec(replicas=1,
                       restart_policy=RestartPolicy.ON_FAILURE_SLICE)
    spec.template.spec.containers.append(
        Container(entrypoint="__drive_chaos__:_crashy"))
    job.spec.replica_specs[ReplicaType.WORKER] = spec
    op.submit(job)
    got = op.wait_for_phase(
        "TPUJob", "chaos-e2e",
        [JobConditionType.SUCCEEDED, JobConditionType.FAILED], timeout=60)
    check("job terminal under injected crash plan",
          got.status.phase == JobConditionType.SUCCEEDED,
          f"phase={got.status.phase}")
    check("restart count matches the plan",
          got.status.restart_count == 2 == plan.faults("worker.crash"),
          f"restarts={got.status.restart_count} faults={plan.faults('worker.crash')}")

# 4. poison-pill quarantine through a real operator's engine
opts2 = OperatorOptions(local_addresses=True,
                        artifact_registry_root=os.path.join(tmp, "reg2"))
with Operator(opts2, runtime=ThreadRuntime()) as op:
    job = TPUJob(); job.metadata.name = "poison"
    spec = ReplicaSpec(replicas=1,
                       restart_policy=RestartPolicy.ON_FAILURE_SLICE)
    spec.template.spec.containers.append(
        Container(entrypoint="__drive_chaos__:_crashy"))
    job.spec.replica_specs[ReplicaType.WORKER] = spec
    engine = op.engines["TPUJob"]
    engine.quarantine_budget = 3
    engine.reconcile_job = lambda j: (_ for _ in ()).throw(
        RuntimeError("poison pill"))
    op.submit(job)
    got = op.wait_for_phase(
        "TPUJob", "poison", [JobConditionType.QUARANTINED], timeout=30)
    check("poison job parked Quarantined",
          got.status.phase == JobConditionType.QUARANTINED
          and got.status.conditions[-1].reason == "ReconcileBudgetExhausted")
    check("quarantine observable (metric + event)",
          op.metrics.quarantined.value(kind="TPUJob") == 1.0
          and any(e.reason == "Quarantined"
                  for e in op.store.list("Event", None))
          and "kubedl_tpu_jobs_quarantined" in op.render_metrics())

# 5. serving load shedding over REAL HTTP: 503 + Retry-After + counter
from http.server import ThreadingHTTPServer
from kubedl_tpu.serving.server import LlamaEngine, make_handler
eng = LlamaEngine(preset="tiny", max_batch=2, max_seq=64, max_queue_depth=2)
srv = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(eng, "tiny"))
port = srv.server_address[1]
threading.Thread(target=srv.serve_forever, daemon=True).start()

codes, retry_afters = [], []
lock = threading.Lock()
barrier = threading.Barrier(12)
def hit(i):
    barrier.wait()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps({"prompt_ids": [i + 1], "max_tokens": 40}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            with lock:
                codes.append(r.status)
    except urllib.error.HTTPError as e:
        with lock:
            codes.append(e.code)
            retry_afters.append(e.headers.get("Retry-After"))
threads = [threading.Thread(target=hit, args=(i,)) for i in range(12)]
for t in threads: t.start()
for t in threads: t.join(timeout=120)
shed = codes.count(503)
check("burst sheds boundedly over HTTP",
      len(codes) == 12 and shed >= 1 and codes.count(200) >= 1,
      f"200s={codes.count(200)} 503s={shed}")
check("503 carries Retry-After",
      retry_afters and all(ra and int(ra) >= 1 for ra in retry_afters),
      f"retry_afters={retry_afters[:3]}")
with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
    metrics_text = r.read().decode()
check("shed counter exported on /metrics",
      f"kubedl_tpu_serving_shed_requests {float(shed)}" in metrics_text
      and eng.stats()["shed"] == shed)
r = eng.generate([5], max_tokens=3)
check("engine alive after the storm", len(r["token_ids"]) == 3)
srv.shutdown(); eng.close()

# 6. torn checkpoint save -> restore falls back to last good step
import jax.numpy as jnp
import numpy as np
from kubedl_tpu.training.checkpoint import (
    latest_step, restore_checkpoint, save_checkpoint)
ckpt = os.path.join(tmp, "ckpt")
save_checkpoint(ckpt, {"step": jnp.asarray(1), "w": jnp.arange(4.0)}, 1)
try:
    with FaultPlan(3, sites={"checkpoint.torn": [FaultSpec.nth(1)]}):
        save_checkpoint(ckpt, {"step": jnp.asarray(2),
                               "w": jnp.arange(4.0) * 2}, 2)
    torn_raised = False
except chaos.FaultInjected:
    torn_raised = True
restored = restore_checkpoint(ckpt, {"step": jnp.asarray(0),
                                     "w": jnp.zeros(4)})
check("torn save detected; restore falls back to step 1",
      torn_raised and latest_step(ckpt) == 1
      and int(restored["step"]) == 1
      and np.allclose(np.asarray(restored["w"]), np.arange(4.0)))

shutil.rmtree(tmp, ignore_errors=True)
print(f"\n{sum(ok)}/{len(ok)} checks passed")
sys.exit(0 if all(ok) else 1)

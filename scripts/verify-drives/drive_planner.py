"""Drive the auto-parallelism planner end to end through the PUBLIC
surface: a real Operator plans a `mesh: auto` TPUJob at admission (the
chosen layout reaches live workers via KUBEDL_MESH_AXES, the verdict is
visible as annotation + status.plan + Planned condition/event/metrics),
fails an impossible model with PlanInfeasible instead of admitting an
OOM loop, validates explicit mesh blocks at submit, and RE-PLANS a live
elastic job when its num_slices changes mid-run (docs/planning.md)."""
import json
import os
import sys
import tempfile
import shutil
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
os.environ["JAX_PLATFORMS"] = "cpu"
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

ok = []
def check(name, cond, detail=""):
    ok.append(bool(cond))
    print(("PASS" if cond else "FAIL"), name, detail)

from kubedl_tpu.api import constants
from kubedl_tpu.api.topology import MeshSpec, get_slice
from kubedl_tpu.api.types import (
    ElasticSpec, JobConditionType, ReplicaSpec, ReplicaType, RestartPolicy)
from kubedl_tpu.core.objects import Container
from kubedl_tpu.gang.slice_scheduler import SliceInventory
from kubedl_tpu.operator import Operator, OperatorOptions, ValidationError
from kubedl_tpu.planner import MODEL_ZOO, ModelDesc, PlanError, plan
from kubedl_tpu.runtime.executor import ThreadRuntime
from kubedl_tpu.utils.invariants import check_invariants
from kubedl_tpu.workloads.tpujob import TPUJob

tmp = tempfile.mkdtemp(prefix="kdl-planner-drive-")

# 1. the planner library itself: a REPLICATED update could not pure-DP
#    llama-1b on 16 GiB v5e chips (~15 GiB of optimizer state per chip);
#    the cross-replica sharded update divides that state by the data axis,
#    so plain DP fits and the simplicity tie-break keeps it
p = plan(MODEL_ZOO["llama-1b"], get_slice("v5e-8"))
check("llama-1b on v5e-8 fits pure DP under the sharded update",
      p.baseline_dp_ms is not None and p.mesh.axes == {"data": 8},
      p.mesh.to_env())
try:
    plan(MODEL_ZOO["llama-1b"], get_slice("cpu-1"))
    check("impossible shape raises PlanError", False)
except PlanError as e:
    check("impossible shape raises PlanError",
          "no memory-feasible layout" in str(e))

SEEN = {"auto": [], "elastic": []}

def _auto_worker(env):
    SEEN["auto"].append((env.get("KUBEDL_MESH_AXES"),
                         env.get("KUBEDL_PROCESS_ID")))
    return 0

_GATE = {"path": os.path.join(tmp, "release")}

def _gated_worker(env):
    SEEN["elastic"].append((env.get("KUBEDL_MESH_AXES"),
                            env.get("KUBEDL_ELASTIC_BASE_DP")))
    cancel = (env or {}).get("_KUBEDL_CANCEL")
    while not os.path.exists(_GATE["path"]):
        if cancel is not None and cancel.is_set():
            raise SystemExit(137)
        time.sleep(0.02)
    return 0

sys.modules["__drive_planner__"] = sys.modules[__name__]

LLAMA_1B = MODEL_ZOO["llama-1b"]

def _auto_job(name, topo_name, workers, entrypoint, model=None):
    job = TPUJob()
    job.metadata.name = name
    spec = ReplicaSpec(replicas=workers, topology=get_slice(topo_name),
                       restart_policy=RestartPolicy.ON_FAILURE_SLICE)
    spec.template.spec.containers.append(Container(entrypoint=entrypoint))
    job.spec.replica_specs[ReplicaType.WORKER] = spec
    job.mesh = "auto"
    m = model or LLAMA_1B
    job.model_desc = ModelDesc(
        layers=m.layers, hidden=m.hidden, ffn=m.ffn, vocab=m.vocab,
        seq_len=m.seq_len, global_batch=m.global_batch)
    return job

inv = SliceInventory()
inv.add_slice("v8a", "v5e-8")
inv.add_slice("ca", "cpu-1")
inv.add_slice("cb", "cpu-1")
opts = OperatorOptions(
    local_addresses=True,
    artifact_registry_root=os.path.join(tmp, "reg"),
)
with Operator(opts, runtime=ThreadRuntime(), inventory=inv) as op:
    # 2. admission validation: a bad explicit mesh fails the SUBMIT
    bad = _auto_job("bad", "v5e-8", 2, "__drive_planner__:_auto_worker")
    bad.mesh = MeshSpec({"data": 4})  # v5e-8 has 8 chips
    try:
        op.submit(bad)
        check("wrong-product mesh rejected at submit", False)
    except ValidationError as e:
        check("wrong-product mesh rejected at submit", "devices" in str(e))
    noauto = _auto_job("noauto", "v5e-8", 2, "__drive_planner__:_auto_worker")
    noauto.model_desc = None
    try:
        op.submit(noauto)
        check("mesh auto without modelDesc rejected", False)
    except ValidationError as e:
        check("mesh auto without modelDesc rejected", "modelDesc" in str(e))

    # 3. mesh: auto end to end — the planned layout reaches live workers
    op.submit(_auto_job("auto", "v5e-8", 2, "__drive_planner__:_auto_worker"))
    got = op.wait_for_phase("TPUJob", "auto",
                            [JobConditionType.SUCCEEDED,
                             JobConditionType.FAILED], timeout=60)
    ann = json.loads(got.metadata.annotations[constants.ANNOTATION_PLANNED_MESH])
    check("auto job succeeds with the planned annotation",
          got.status.phase == JobConditionType.SUCCEEDED
          and ann["topology"] == "v5e-8" and ann["slices"] == 1
          and ann["axes"] == p.mesh.to_env(), json.dumps(ann))
    check("workers saw exactly the planned KUBEDL_MESH_AXES",
          len(SEEN["auto"]) == 2
          and all(m == ann["axes"] for m, _ in SEEN["auto"]),
          str(SEEN["auto"]))
    check("status.plan + Planned condition carry the verdict",
          got.status.plan is not None
          and got.status.plan.mesh == ann["axes"]
          and got.status.plan.candidates_evaluated > 0
          and any(c.type == JobConditionType.PLANNED
                  for c in got.status.conditions))
    check("Planned event + planner metrics exported",
          any(e.reason == "Planned" for e in op.store.list("Event", None))
          and "kubedl_tpu_planner_plans" in op.render_metrics()
          and "kubedl_tpu_planner_plan_ms" in op.render_metrics())

    # 4. an impossible model FAILS at admission — zero pods, no OOM loop
    op.submit(_auto_job("oom", "cpu-1", 1, "__drive_planner__:_auto_worker"))
    got = op.wait_for_phase("TPUJob", "oom",
                            [JobConditionType.SUCCEEDED,
                             JobConditionType.FAILED], timeout=60)
    check("infeasible model fails with PlanInfeasible and zero pods",
          got.status.phase == JobConditionType.FAILED
          and any(c.reason == "PlanInfeasible"
                  for c in got.status.conditions)
          and not [pp for pp in op.store.list("Pod", "default")
                   if pp.metadata.name.startswith("oom-")])

    # 5. live elastic resize re-plans: tiny model on cpu-1 slices, grow
    #    1 -> 2 mid-run; the new gang must carry the re-planned mesh
    # max_slices starts at 1 so the ElasticPolicy cannot auto-grow into
    # the free second slice before we read the 1-slice plan (a fresh job
    # has no cooldown stamp, so grow-at-RUNNING is otherwise immediate);
    # the explicit grow below raises the ceiling and the size together
    el = _auto_job("el", "cpu-1", 1, "__drive_planner__:_gated_worker",
                   model=MODEL_ZOO["tiny"])
    el.elastic = ElasticSpec(min_slices=1, max_slices=1,
                             cooldown_seconds=0.1)
    op.submit(el)
    op.wait_for_phase("TPUJob", "el", JobConditionType.RUNNING, timeout=60)
    got = op.store.get("TPUJob", "el")
    ann1 = json.loads(got.metadata.annotations[constants.ANNOTATION_PLANNED_MESH])
    base_dp = got.metadata.annotations[constants.ANNOTATION_ELASTIC_BASE_DP]
    check("elastic auto job planned at 1 slice",
          ann1["slices"] == 1 and base_dp == "1", json.dumps(ann1))

    def grow(j):
        j.elastic = ElasticSpec(min_slices=1, max_slices=2,
                                cooldown_seconds=0.1)
        j.num_slices = 2
    op.store.update_with_retry("TPUJob", "el", "default", grow)

    def replanned():
        g = op.store.try_get("TPUJob", "el")
        if g is None:
            return False
        a = json.loads(g.metadata.annotations.get(
            constants.ANNOTATION_PLANNED_MESH, "{}"))
        return (a.get("slices") == 2
                and len([pp for pp in op.store.list("Pod", "default")
                         if pp.metadata.name.startswith("el-")]) == 2)
    check("grow re-plans for 2 slices and restarts the gang",
          op.manager.wait(replanned, timeout=60))
    got = op.store.get("TPUJob", "el")
    ann2 = json.loads(got.metadata.annotations[constants.ANNOTATION_PLANNED_MESH])
    check("re-planned mesh spans the slices via the replica axis",
          ann2["axes"].startswith("replica=2") and ann2["axes"] != ann1["axes"]
          and got.status.plan.mesh == ann2["axes"], ann2["axes"])
    check("base DP degree pinned from the FIRST plan",
          got.metadata.annotations[constants.ANNOTATION_ELASTIC_BASE_DP]
          == base_dp)

    with open(_GATE["path"], "w") as f:
        f.write("done")
    got = op.wait_for_phase("TPUJob", "el",
                            [JobConditionType.SUCCEEDED,
                             JobConditionType.FAILED], timeout=60)
    planned_event = [e for e in op.store.list("Event", None)
                     if e.reason == "Planned"
                     and e.involved_name == "el"][0]
    check("job finishes clean; Planned event aggregated the re-plan",
          got.status.phase == JobConditionType.SUCCEEDED
          and planned_event.count == 2
          and "2xcpu-1" in planned_event.message,
          f"count={planned_event.count}")
    restarted = [m for m, _ in SEEN["elastic"]]
    check("restarted workers ran the re-planned mesh in DP units",
          ann2["axes"] in restarted
          and all(d == base_dp for _, d in SEEN["elastic"]),
          str(SEEN["elastic"]))
    probs = check_invariants(op)
    check("invariants hold after plan/fail/resize traffic", probs == [],
          str(probs))

# 6. the reconcile-loop overhead budget (same sweep tier-1 pins)
from scripts.scheduler_microbench import run_planner_microbench
mb = run_planner_microbench()
check("full catalog x zoo sweep within the 50 ms p95 budget",
      mb["within_budget"] and mb["plans"] > 0,
      f"p95={mb['plan_ms_p95']}ms over {mb['plans']} plans")

shutil.rmtree(tmp, ignore_errors=True)
print(f"\n{sum(ok)}/{len(ok)} checks passed")
sys.exit(0 if all(ok) else 1)

"""Drive: cross-replica sharded weight update through the real operator path.

Two single-worker TPUJobs run `python -m kubedl_tpu.training.entry` as
real subprocesses on an 8-virtual-device CPU mesh (pods inherit the
operator env's XLA_FLAGS): one with the default sharded update + overlap,
one pinned to the seed replicated path (shard_update=false). The worker
summaries must show the scattered layout compiled (shard_update true,
grad buckets planned, per-device optimizer-state bytes reduced vs the
replicated job) and the two loss trajectories must agree — same math,
placement-only change — end to end through entry.py's config plumbing.
"""
import json, os, sys, tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

from kubedl_tpu.api.types import (
    JobConditionType, ReplicaSpec, ReplicaType, RestartPolicy,
)
from kubedl_tpu.core.objects import Container, EnvVar
from kubedl_tpu.operator import Operator, OperatorOptions
from kubedl_tpu.runtime.executor import SubprocessRuntime
from kubedl_tpu.utils.invariants import check_invariants
from kubedl_tpu.workloads.tpujob import TPUJob

checks = []
def check(name, ok, detail=""):
    checks.append((name, ok))
    print(("PASS " if ok else "FAIL ") + name + (f" — {detail}" if detail else ""))

tmp = tempfile.mkdtemp(prefix="kdl-shupd-drive-")
logs = os.path.join(tmp, "logs")
base_cfg = {"model": "tiny", "steps": 4, "global_batch": 8, "seq_len": 16,
            "grad_accum": 2, "log_every": 2}

def run(op, name, extra):
    cfg = dict(base_cfg); cfg.update(extra)
    job = TPUJob(); job.metadata.name = name
    spec = ReplicaSpec(replicas=1, restart_policy=RestartPolicy.ON_FAILURE_SLICE)
    spec.template.spec.containers.append(Container(
        command=[sys.executable, "-m", "kubedl_tpu.training.entry"],
        env=[EnvVar("KUBEDL_TRAIN_CONFIG", json.dumps(cfg)),
             EnvVar("PYTHONPATH", "/root/repo")],
    ))
    job.spec.replica_specs[ReplicaType.WORKER] = spec
    op.submit(job)
    got = op.wait_for_phase("TPUJob", name,
        [JobConditionType.SUCCEEDED, JobConditionType.FAILED], timeout=300)
    summary = None
    with open(os.path.join(logs, "default", f"{name}-worker-0.log")) as f:
        for line in f:
            if '"worker_summary"' in line:
                summary = json.loads(line)["worker_summary"]
    return got, summary

opts = OperatorOptions(
    local_addresses=True, pod_log_dir=logs,
    artifact_registry_root=os.path.join(tmp, "reg"),
)
with Operator(opts, runtime=SubprocessRuntime(logs)) as op:
    got_s, ss = run(op, "sharded", {})
    check("sharded-update job SUCCEEDED",
          got_s.status.phase == JobConditionType.SUCCEEDED)
    check("summary shows the scattered layout compiled",
          ss is not None and ss["shard_update"] and ss["overlap_comm"],
          json.dumps({k: ss.get(k) for k in
                      ("shard_update", "overlap_comm")} if ss else {}))
    check("grad buckets planned", ss["grad_buckets"] >= 1,
          f"{ss['grad_buckets']} buckets")
    check("loss logged on the log_every cadence (no per-step sync)",
          ss["log_every"] == 2 and len(ss["loss_log"]) >= 1,
          json.dumps(ss["loss_log"]))

    got_r, sr = run(op, "replicated", {"shard_update": False})
    check("replicated-baseline job SUCCEEDED",
          got_r.status.phase == JobConditionType.SUCCEEDED
          and sr is not None and not sr["shard_update"])
    check("optimizer state per device reduced vs replicated",
          ss["opt_state_bytes_per_device"] < sr["opt_state_bytes_per_device"],
          f"{ss['opt_state_bytes_per_device']} < "
          f"{sr['opt_state_bytes_per_device']} bytes")
    check("loss trajectory matches the replicated path",
          abs(ss["final_loss"] - sr["final_loss"]) < 1e-4
          and abs(ss["first_loss"] - sr["first_loss"]) < 1e-4,
          f"final {ss['final_loss']:.6f} vs {sr['final_loss']:.6f}")
    bad = check_invariants(op)
    check("invariants green", not bad, str(bad))

failed = [n for n, ok in checks if not ok]
print(f"\n{len(checks) - len(failed)}/{len(checks)} checks passed")
sys.exit(1 if failed else 0)

"""Drive elastic slice scaling end to end through the PUBLIC surface: a
real Operator under an armed `elastic.preempt` FaultPlan. The injected
preemption notice drains a slice, the ElasticPolicy shrinks the gang off
it (in-place resize + Resizing condition + restart), clearing the notice
grows it back, and the job still finishes clean — with restart count,
resize/notice metrics, drain gauge and events all matching the plan.
Plus: draining slices are unreservable, grad-accum rescaling preserves
the effective global batch, and goodput math clamps sanely."""
import os
import sys
import tempfile
import shutil
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
os.environ["JAX_PLATFORMS"] = "cpu"
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

ok = []
def check(name, cond, detail=""):
    ok.append(bool(cond))
    print(("PASS" if cond else "FAIL"), name, detail)

from kubedl_tpu.api.topology import get_slice
from kubedl_tpu.api.types import (
    ElasticSpec, JobConditionType, ReplicaSpec, ReplicaType, RestartPolicy)
from kubedl_tpu.chaos import FaultPlan, FaultSpec
from kubedl_tpu.core.objects import Container
from kubedl_tpu.elastic.resize import goodput, grad_accum_for_world
from kubedl_tpu.gang.slice_scheduler import SliceInventory
from kubedl_tpu.operator import Operator, OperatorOptions
from kubedl_tpu.runtime.executor import ThreadRuntime
from kubedl_tpu.workloads.tpujob import TPUJob

tmp = tempfile.mkdtemp(prefix="kdl-elastic-drive-")

# 1. batch-semantics math: effective global batch is world-invariant
check("grad accum rescales inversely with world",
      grad_accum_for_world(1, 4, 2, 8) == 2
      and grad_accum_for_world(2, 4, 1, 8) == 8
      and grad_accum_for_world(4, 2, 4, 8) == 2)
check("grad accum clamps to a divisor of global batch",
      grad_accum_for_world(8, 3, 4, 8) == 4
      and grad_accum_for_world(64, 8, 1, 16) == 16)
check("goodput clamps to [0, 1]",
      goodput(8.0, 10.0) == 0.8 and goodput(12.0, 10.0) == 1.0
      and goodput(1.0, 0.0) == 0.0)

# 2. draining slices leave the allocatable pool
inv0 = SliceInventory()
inv0.add_slice("da", "cpu-1")
inv0.mark_draining("da", "drill")
check("draining slice is unreservable and visible in detail()",
      inv0.try_reserve("cpu-1", 1, "x/y-gang") == []
      and inv0.detail()[0]["draining"] is True
      and inv0.detail()[0]["drain_reason"] == "drill")
inv0.clear_draining("da")
check("cleared slice is reservable again",
      inv0.try_reserve("cpu-1", 1, "x/y-gang") == ["da"])

# 3. the full loop under seeded chaos: notice -> drain -> shrink ->
#    clear -> grow -> clean finish
_STOP = {"path": os.path.join(tmp, "stop")}

def _gated_worker(env):
    cancel = (env or {}).get("_KUBEDL_CANCEL")
    while not os.path.exists(_STOP["path"]):
        if cancel is not None and cancel.is_set():
            raise SystemExit(137)
        time.sleep(0.02)
    return 0

sys.modules["__drive_elastic__"] = sys.modules[__name__]

inv = SliceInventory()
inv.add_slice("sa", "cpu-1")  # host sa-host-0
inv.add_slice("sb", "cpu-1")  # host sb-host-0
opts = OperatorOptions(
    local_addresses=True,
    artifact_registry_root=os.path.join(tmp, "reg"),
    heartbeat_nodes=["sa-host-0", "sb-host-0"],
    node_grace_seconds=2.0,
)
# beats visit nodes in heartbeat_nodes order: nth(2) deterministically
# notices sb-host-0 on the first armed beat
plan = FaultPlan(23, sites={"elastic.preempt": [FaultSpec.nth(2)]})
with Operator(opts, runtime=ThreadRuntime(), inventory=inv) as op:
    job = TPUJob()
    job.metadata.name = "drill"
    spec = ReplicaSpec(replicas=2, topology=get_slice("cpu-1"),
                       restart_policy=RestartPolicy.ON_FAILURE_SLICE)
    spec.template.spec.containers.append(
        Container(entrypoint="__drive_elastic__:_gated_worker"))
    job.spec.replica_specs[ReplicaType.WORKER] = spec
    job.num_slices = 2
    job.elastic = ElasticSpec(min_slices=1, max_slices=2,
                              cooldown_seconds=0.2)
    op.submit(job)
    op.wait_for_phase("TPUJob", "drill", JobConditionType.RUNNING,
                      timeout=60)

    with plan:
        def shrunk():
            got = op.store.try_get("TPUJob", "drill")
            return (got is not None and got.num_slices == 1
                    and len(list(op.store.list("Pod", "default"))) == 1)
        check("injected notice shrinks the gang off the draining slice",
              op.manager.wait(shrunk, timeout=60))
        detail = {d["name"]: d for d in inv.detail()}
        check("victim slice draining; survivor keeps the gang",
              detail["sb"]["draining"] is True
              and detail["sa"]["allocated_to"] == "default/drill-gang")
        got = op.store.get("TPUJob", "drill")
        check("Resizing condition recorded",
              any(c.type == JobConditionType.RESIZING
                  for c in got.status.conditions))
        check("drain gauge reflects the notice",
              op.metrics.slices_draining.value() == 1.0)

        op.node_heartbeater.clear_preemption("sb-host-0")

        def grown():
            got = op.store.try_get("TPUJob", "drill")
            return (got is not None and got.num_slices == 2
                    and len(list(op.store.list("Pod", "default"))) == 2)
        check("cleared notice grows the gang back",
              op.manager.wait(grown, timeout=60))

        with open(_STOP["path"], "w") as f:
            f.write("done")
        got = op.wait_for_phase(
            "TPUJob", "drill",
            [JobConditionType.SUCCEEDED, JobConditionType.FAILED],
            timeout=60)
    check("job finishes clean at the grown shape",
          got.status.phase == JobConditionType.SUCCEEDED
          and got.num_slices == 2,
          f"phase={got.status.phase} slices={got.num_slices}")
    check("exactly the planned single notice was injected",
          plan.faults("elastic.preempt") == 1
          and got.status.restart_count == 2,
          f"faults={plan.faults('elastic.preempt')} "
          f"restarts={got.status.restart_count}")
    reasons = {e.reason for e in op.store.list("Event", None)}
    check("observable: metrics + events",
          op.metrics.resizes.value(kind="TPUJob") == 2.0
          and op.metrics.preemption_notices.value() == 1.0
          and op.metrics.slices_draining.value() == 0.0
          and {"PreemptionNotice", "PreemptionCleared",
               "ElasticResize", "SliceResize"} <= reasons,
          f"reasons={sorted(reasons)}")
    check("drain gauge exported",
          "kubedl_tpu_slices_draining" in op.render_metrics())

shutil.rmtree(tmp, ignore_errors=True)
print(f"\n{sum(ok)}/{len(ok)} checks passed")
sys.exit(0 if all(ok) else 1)

"""Drive the model-lifecycle rollout end to end against a REAL
subprocess fleet (`python -m kubedl_tpu.serving.server`, 2 colocated
tiny replicas per scenario), per docs/serving.md "Model lifecycle".

Scenario A — healthy canary promotes: hot-load a v2 checkpoint on every
replica over `/admin/load_version`, then let a `RolloutController` walk
the weight ladder 1→10→50→100 on a real soak timer while requests flow
through the router. Every response must be 200 and bit-identical to a
COLD-STARTED in-process engine serving that version alone (base from
init weights, v2 from its checkpoint dir) — both versions must actually
serve traffic, and promotion ends at {base: 0, v2: 100}.

Scenario B — degraded canary auto-rolls-back: a FRESH fleet arms a
seeded latency fault via `KUBEDL_SERVE_CONFIG["chaos"]` on the
`serving.canary_dispatch` site (2 s per NON-default-version dispatch
tick — baseline ticks on the same replica are untouched). The canary's
own SLO partition burns on the latency objective, the controller rolls
back in ONE weight flip mid-ladder, the RolledBack condition carries
the burning window + a trace-id exemplar, and the canary is fenced from
re-promotion. Zero requests are dropped at any point (the degradation
is latency, never errors), and baseline outputs stay bit-identical
before, during, and after the rollback."""
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
os.environ["JAX_PLATFORMS"] = "cpu"
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

ok = []
def check(name, cond, detail=""):
    ok.append(bool(cond))
    print(("PASS" if cond else "FAIL"), name, detail)

from kubedl_tpu.serving.rollout import (
    COMPLETE,
    ROLLED_BACK,
    RolloutController,
    RolloutFenced,
)
from kubedl_tpu.serving.router import ServingRouter

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

PROMPTS = [(3, 1, 4, 1, 5, 9), (2, 7, 1, 8, 2, 8), (1, 1, 2, 3, 5, 8)]
GEN = 8
#: the canary's own partition pages when BOTH windows burn >= 2x against
#: a 90% objective with a 2.5 s latency SLO. Decode is segment-based
#: (an 8-token generate is ~3-4 dispatch ticks), so the injected
#: 2 s/tick fault puts every v2 request past ~6 s while warmed requests
#: finish in well under a second even on a loaded 1-core box — wide
#: margin on BOTH sides of the objective.
SLO = {
    "objective": 0.9,
    "latency_objective_ms": 2500.0,
    "alerts": [{"severity": "page", "short_s": 5.0, "long_s": 20.0,
                "threshold": 2.0}],
}


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def spawn_replica(port, chaos_cfg=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    cfg = {"preset": "tiny", "port": port, "max_batch": 4}
    if chaos_cfg:
        cfg["chaos"] = chaos_cfg
    env["KUBEDL_SERVE_CONFIG"] = json.dumps(cfg)
    env.pop("KUBEDL_MODEL_PATH", None)
    return subprocess.Popen(
        [sys.executable, "-m", "kubedl_tpu.serving.server"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def wait_healthy(port, timeout=180.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=2
            ) as r:
                if r.status == 200:
                    return True
        except Exception:
            time.sleep(0.3)
    return False


def post(port, path, payload, timeout=60.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def cold_references(v2_dir):
    """Outputs from cold-started engines each serving ONE version alone —
    the bit-identity oracle for everything the fleet answers."""
    from kubedl_tpu.serving.server import LlamaEngine

    refs = {"base": {}, "v2": {}}
    eng = LlamaEngine(preset="tiny", max_batch=4)
    try:
        import jax

        from kubedl_tpu.models import llama
        from kubedl_tpu.training.checkpoint import save_checkpoint

        params = llama.llama_init(jax.random.PRNGKey(0), eng.cfg)
        params = jax.tree_util.tree_map(lambda x: x * 1.5, params)
        save_checkpoint(v2_dir, {"params": params}, 1)
        for p in PROMPTS:
            refs["base"][p] = eng.generate(
                list(p), max_tokens=GEN, temperature=0.0)["token_ids"]
    finally:
        eng.close()
    eng = LlamaEngine(preset="tiny", max_batch=4, ckpt_dir=v2_dir)
    try:
        for p in PROMPTS:
            refs["v2"][p] = eng.generate(
                list(p), max_tokens=GEN, temperature=0.0)["token_ids"]
    finally:
        eng.close()
    return refs


def build_fleet(v2_dir, chaos_cfg=None):
    ports = [free_port(), free_port()]
    procs = [spawn_replica(p, chaos_cfg) for p in ports]
    up = all(wait_healthy(p) for p in ports)
    if up:
        for p in ports:
            st, out = post(p, "/admin/load_version",
                           {"version": "v2", "ckpt_dir": v2_dir})
            assert st == 200 and out["loaded"] == ["base", "v2"], out
            # warm BOTH versions (full decode length) so drill
            # latencies are steady-state — the first generate on a
            # freshly loaded version pays its weight upload, which must
            # not be billed to the canary's SLO partition (these warm
            # requests go direct to the replica, not through the router)
            for ver in ("base", "v2"):
                post(p, "/v1/generate",
                     {"prompt_ids": list(PROMPTS[0]), "max_tokens": GEN,
                      "temperature": 0.0, "model_version": ver},
                     timeout=300.0)
    router = ServingRouter(
        [{"name": f"r{i}", "host": "127.0.0.1", "port": p,
          "model": "tiny"} for i, p in enumerate(ports)],
        probe_interval_s=0.5, probe_timeout_s=2.0,
        hedge_enabled=False, slo=SLO,
    )
    router.start()
    router.probe_once()
    return ports, procs, router, up


def run_traffic(router, refs, n, codes, mismatches, served):
    for j in range(n):
        p = PROMPTS[j % len(PROMPTS)]
        code, payload, _ = router.handle_generate(
            {"prompt_ids": list(p), "max_tokens": GEN,
             "temperature": 0.0})
        codes.append(code)
        if code != 200:
            continue
        v = payload.get("model_version", "")
        served[v] = served.get(v, 0) + 1
        if v not in refs or payload["token_ids"] != refs[v][p]:
            mismatches.append((v, p))


with tempfile.TemporaryDirectory() as tmp:
    v2_dir = os.path.join(tmp, "v2")
    refs = cold_references(v2_dir)
    check("cold per-version references differ (v2 is a real new model)",
          all(refs["base"][p] != refs["v2"][p] for p in PROMPTS))

    # ---- scenario A: healthy canary walks the ladder and promotes ----
    ports, procs, router, up = build_fleet(v2_dir)
    try:
        check("scenario A fleet up with v2 hot-loaded on every replica",
              up)
        ctrl = RolloutController(router, canary_version="v2",
                                 baseline_version="base",
                                 steps=(1, 10, 50, 100), soak_s=1.5)
        ctrl.begin()
        codes, mism, served = [], [], {}
        result, deadline = "", time.time() + 180
        while time.time() < deadline:
            run_traffic(router, refs, 3, codes, mism, served)
            result = ctrl.tick()
            if result in ("promoted", "rolled_back"):
                break
            time.sleep(0.3)
        check("healthy canary PROMOTES to 100% through the soak ladder",
              result == "promoted" and ctrl.phase == COMPLETE,
              f"result={result} status={ctrl.status()}")
        check("promotion ends at {base: 0, v2: 100}",
              router.version_weights() == {"base": 0, "v2": 100})
        check("zero dropped requests through the whole promotion",
              codes and all(c == 200 for c in codes),
              f"n={len(codes)} non200={[c for c in codes if c != 200]}")
        check("both versions actually served canary traffic",
              served.get("base", 0) > 0 and served.get("v2", 0) > 0,
              f"served={served}")
        check("every response bit-identical to its version's cold engine",
              not mism, f"mismatches={mism[:3]}")
        router.stop()
    finally:
        for pr in procs:
            try:
                pr.send_signal(signal.SIGKILL)
            except Exception:
                pass
    for pr in procs:
        pr.wait(timeout=10)

    # ---- scenario B: degraded canary burns its SLO and rolls back ----
    chaos_cfg = {"seed": 17, "sites": {"serving.canary_dispatch": [
        {"mode": "latency", "latency_ms": 2000.0, "every": 1}]}}
    ports, procs, router, up = build_fleet(v2_dir, chaos_cfg)
    try:
        check("scenario B fleet up with the seeded canary latency fault",
              up)
        ctrl = RolloutController(router, canary_version="v2",
                                 baseline_version="base",
                                 steps=(50, 100), soak_s=60.0)
        ctrl.begin()
        codes, mism, served = [], [], {}
        # a few requests before the first tick so the canary partition
        # holds real exemplars, then tick until the burn gate fires
        run_traffic(router, refs, 6, codes, mism, served)
        result, deadline = "", time.time() + 120
        while time.time() < deadline:
            result = ctrl.tick()
            if result == "rolled_back":
                break
            run_traffic(router, refs, 2, codes, mism, served)
            time.sleep(0.2)
        check("degraded canary AUTO-ROLLS-BACK on its own SLO burn",
              result == "rolled_back" and ctrl.phase == ROLLED_BACK,
              f"result={result}")
        check("rollback is one flip to {base: 100, v2: 0}",
              router.version_weights() == {"base": 100, "v2": 0})
        cond = ctrl.conditions[-1] if ctrl.conditions else {}
        check("RolledBack condition carries burning window + exemplar",
              cond.get("type") == "RolledBack"
              and cond.get("severity") == "page"
              and cond.get("short_burn", 0) >= 2.0
              and cond.get("long_burn", 0) >= 2.0
              and bool(cond.get("trace_id")),
              f"cond={cond}")
        check("baseline partition stayed healthy while the canary burned",
              not router.version_tracker("base").burning(
                  router.version_tracker("base").alerts[0]))
        fenced = False
        try:
            ctrl.begin()
        except RolloutFenced:
            fenced = True
        check("rolled-back canary is fenced from re-promotion", fenced)

        # after the flip: traffic keeps flowing on baseline only,
        # still bit-identical, still zero drops
        before_v2 = served.get("v2", 0)
        run_traffic(router, refs, 8, codes, mism, served)
        check("post-rollback traffic all lands on baseline",
              served.get("v2", 0) == before_v2
              and served.get("base", 0) >= 8, f"served={served}")
        check("zero dropped requests across the WHOLE degraded drill",
              codes and all(c == 200 for c in codes),
              f"n={len(codes)} non200={[c for c in codes if c != 200]}")
        check("baseline outputs bit-identical before/during/after",
              not mism, f"mismatches={mism[:3]}")
        router.stop()
    finally:
        for pr in procs:
            try:
                pr.send_signal(signal.SIGKILL)
            except Exception:
                pass

print(f"\n{sum(ok)}/{len(ok)} checks passed")
sys.exit(0 if all(ok) else 1)

"""Drive: round-3 batch 2 — console static assets + charts over real HTTP,
HA leader election failover, remote blob store, Mars IngressRoute."""
import json, os, sys, tempfile, time, urllib.request

os.environ["JAX_PLATFORMS"] = "cpu"
import pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

from kubedl_tpu.api.types import JobConditionType, ReplicaSpec, ReplicaType, RestartPolicy
from kubedl_tpu.console import ConsoleServer
from kubedl_tpu.core.objects import Container
from kubedl_tpu.core.store import ObjectStore
from kubedl_tpu.operator import Operator, OperatorOptions
from kubedl_tpu.remote import RemoteStoreServer, list_blobs, put_blob
from kubedl_tpu.runtime.executor import SubprocessRuntime
from kubedl_tpu.utils.invariants import check_invariants
from kubedl_tpu.workloads.marsjob import MarsJob
from kubedl_tpu.workloads.registry import WORKLOAD_REGISTRY

checks = []
def check(name, ok, detail=""):
    checks.append((name, ok))
    print(("PASS " if ok else "FAIL ") + name + (f" — {detail}" if detail else ""))

tmp = tempfile.mkdtemp(prefix="kdl-r3b-")
logs = os.path.join(tmp, "logs")
store = ObjectStore()

def mkop(ident):
    return Operator(OperatorOptions(
        local_addresses=True, pod_log_dir=logs,
        artifact_registry_root=os.path.join(tmp, f"reg-{ident}"),
        leader_elect=True, leader_identity=ident, leader_lease_ttl=0.6,
    ), runtime=SubprocessRuntime(logs), store=store)

op1, op2 = mkop("op1"), mkop("op2")
op1.start()
t0 = time.time()
while time.time() - t0 < 5 and not op1.elector.is_leader:
    time.sleep(0.02)
op2.start()
time.sleep(0.8)
check("op1 leads, op2 follows",
      op1.elector.is_leader and not op2.elector.is_leader)
check("only leader reconciles", op1.manager._running and not op2.manager._running)

srv = ConsoleServer(op1)
srv.start()
host, port = srv.address

def get(path, raw=False):
    with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=10) as r:
        body = r.read()
        return body if raw else json.loads(body)

def submit(op, name):
    job = WORKLOAD_REGISTRY["TPUJob"]().object_factory()
    job.metadata.name = name
    spec = ReplicaSpec(replicas=1, restart_policy=RestartPolicy.ON_FAILURE)
    spec.template.spec.containers.append(Container(command=["true"]))
    job.spec.replica_specs[ReplicaType.WORKER] = spec
    op.submit(job)
    return op.wait_for_phase("TPUJob", name,
        [JobConditionType.SUCCEEDED, JobConditionType.FAILED], timeout=60)

got = submit(op1, "d1")
check("job under leader SUCCEEDED", got.status.phase == JobConditionType.SUCCEEDED)

# console: static split + charts fed by real launch metrics
idx = get("/", raw=True).decode()
check("index references static bundle",
      "/static/app.js" in idx and "/static/style.css" in idx)
app = get("/static/app.js", raw=True).decode()
check("charts view shipped", "VIEWS.charts" in app and "data/charts" in app)
charts = get("/api/v1/data/charts")["data"]
fp = charts["launch_delay"]["first_pod"]
check("launch-delay histogram populated",
      bool(fp) and fp[0]["total"] >= 1 and sum(fp[0]["counts"]) >= 1)
created = {r["labels"].get("kind"): r["value"] for r in charts["counters"]["created"]}
check("created counter per kind", created.get("TPUJob", 0) >= 1)

# failover: kill leader hard; follower takes over and completes work
op1.elector._stop.set(); op1.elector._thread.join(timeout=2); op1._on_deposed()
t0 = time.time()
while time.time() - t0 < 10 and not op2.elector.is_leader:
    time.sleep(0.05)
check("follower took over within TTL", op2.elector.is_leader,
      f"{time.time()-t0:.2f}s")
got2 = submit(op2, "d2")
check("job under new leader SUCCEEDED",
      got2.status.phase == JobConditionType.SUCCEEDED)

# Mars IngressRoute object
mars = MarsJob(); mars.metadata.name = "marsd"; mars.web_host = "mars.example.com"
for rt in (ReplicaType.SCHEDULER, ReplicaType.WEBSERVICE):
    sp = ReplicaSpec(replicas=1, restart_policy=RestartPolicy.ON_FAILURE)
    sp.template.spec.containers.append(Container(command=["sleep", "5"]))
    mars.spec.replica_specs[rt] = sp
op2.submit(mars)
t0 = time.time()
route = None
while time.time() - t0 < 15 and route is None:
    route = store.try_get("IngressRoute", "marsd-web")
    time.sleep(0.1)
check("Mars IngressRoute created", route is not None and
      route.host == "mars.example.com" and route.path == "/default/marsd")

# remote blob store over real HTTP
with RemoteStoreServer(os.path.join(tmp, "blob-root")) as rs:
    put_blob(rs.base_url, "m/x.bin", b"abc")
    check("remote blob roundtrip", list_blobs(rs.base_url, "m") == ["m/x.bin"])

bad = check_invariants(op2)
check("invariants green", not bad, str(bad))

srv.stop(); op1.stop(); op2.stop()
failed = [n for n, ok in checks if not ok]
print(f"\n{len(checks) - len(failed)}/{len(checks)} checks passed")
sys.exit(1 if failed else 0)

"""Verify drive: prefix-aware KV cache reuse (serving PR, 2026-08-06).

Drives the prefix cache through the PUBLIC serving surface — a real
LlamaEngine behind the real HTTP handler — and checks the contracts
docs/serving.md "Prefix cache" promises:

  1. a shared-system-prompt fleet over HTTP auto-populates the cache
     (observation trie: no tagging) and later requests hit;
  2. greedy outputs are bit-identical to a cache-off engine;
  3. per-request ttft_ms rides the response, p50/p95 ride /v1/stats;
  4. /v1/stats carries the prefix_cache section (hits/tokens_saved...);
  5. /metrics serves the kubedl_tpu_serving_prefix_cache_* family;
  6. "cache_prefix": true in the body inserts on FIRST sight;
  7. prefix_cache_mb=0 disables the cache (no stats section, no hits);
  8. a tiny byte budget evicts LRU entries instead of growing;
  9. KUBEDL_SERVE_CONFIG plumbing (engine_kwargs carries prefix_cache_mb);
 10. host-side match+graft overhead stays under the tier-1 budget.

Run: python scripts/verify-drives/drive_prefix.py  (CPU-forced, ~60s)
"""

import json
import os
import sys
import threading
import urllib.request
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested  # noqa: E402

ensure_cpu_if_requested()

CHECKS = []


def check(name, ok, detail=""):
    CHECKS.append((name, bool(ok), detail))
    print(f"  [{'ok' if ok else 'FAIL'}] {name}" + (f" — {detail}" if detail else ""))


def post(port, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        return json.loads(resp.read())


def get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/{path.lstrip('/')}", timeout=30
    ) as resp:
        return resp.read()


def serve(eng, name):
    import http.server

    from kubedl_tpu.serving.server import make_handler

    srv = http.server.ThreadingHTTPServer(
        ("127.0.0.1", 0), make_handler(eng, name)
    )
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]


def main():
    from kubedl_tpu.serving.server import LlamaEngine, engine_kwargs

    shared = list(range(3, 51))  # 48-token shared system prompt
    prompts = [shared + [500 + j, 600 + j] for j in range(6)]

    print("== cache-off reference ==")
    ref = LlamaEngine(preset="tiny", max_seq=128, max_batch=4,
                      prefix_cache_mb=0)
    try:
        want = [ref.generate(p, max_tokens=6)["token_ids"] for p in prompts]
        st_off = ref.stats()
        check("cache-off stats has no prefix_cache section",
              "prefix_cache" not in st_off)
    finally:
        ref.close()

    print("== shared-prompt fleet over HTTP (auto-detection) ==")
    eng = LlamaEngine(preset="tiny", max_seq=128, max_batch=4,
                      prefix_cache_mb=8, prefix_min_len=8)
    srv, port = serve(eng, "tiny")
    try:
        got = [post(port, {"prompt_ids": p, "max_tokens": 6})
               for p in prompts]
        check("greedy outputs bit-identical to cache-off over HTTP",
              [r["token_ids"] for r in got] == want)
        check("later requests rode a grafted prefix",
              any(r.get("cached_prefix_len", 0) >= len(shared)
                  for r in got[2:]),
              f"cached_prefix_len={[r.get('cached_prefix_len') for r in got]}")
        check("per-request ttft_ms in the HTTP response",
              all(isinstance(r.get("ttft_ms"), (int, float)) for r in got))
        stats = json.loads(get(port, "/v1/stats"))
        pc = stats.get("prefix_cache") or {}
        check("/v1/stats prefix_cache: hits>0 and tokens_saved>0",
              pc.get("hits", 0) > 0 and pc.get("tokens_saved", 0) > 0,
              f"hits={pc.get('hits')} saved={pc.get('tokens_saved')} "
              f"hit_rate={pc.get('hit_rate')}")
        check("no pins leaked after all requests finished",
              pc.get("pinned", -1) == 0)
        check("/v1/stats carries ttft_ms_p50/p95",
              "ttft_ms_p50" in stats and "ttft_ms_p95" in stats,
              f"p50={stats.get('ttft_ms_p50')} p95={stats.get('ttft_ms_p95')}")
        metrics = get(port, "/metrics").decode()
        check("/metrics serves kubedl_tpu_serving_prefix_cache_* family",
              "kubedl_tpu_serving_prefix_cache_hits" in metrics
              and "kubedl_tpu_serving_prefix_cache_tokens_saved" in metrics)

        print("== tagged first-sight insertion ==")
        tag_prompt = list(range(60, 80))
        post(port, {"prompt_ids": tag_prompt, "max_tokens": 2,
                    "cache_prefix": True})
        r2 = post(port, {"prompt_ids": tag_prompt + [99], "max_tokens": 2})
        check("cache_prefix=true in body inserts on first sight",
              r2.get("cached_prefix_len", 0) >= 8,
              f"cached_prefix_len={r2.get('cached_prefix_len')}")
    finally:
        srv.shutdown()
        eng.close()

    print("== tiny budget evicts LRU ==")
    # one tiny-model 16-bucket entry is 8KB (fp32 k+v); 0.01MB holds one
    small = LlamaEngine(preset="tiny", max_seq=64, max_batch=2,
                        prefix_cache_mb=0.01, prefix_min_len=4)
    try:
        for base in (100, 300):
            p = [base + t for t in range(10)]
            small.generate(p, max_tokens=2, cache_prefix=True)
        st = small.stats()["prefix_cache"]
        check("byte budget enforced via LRU eviction",
              st["evictions"] >= 1 and st["bytes"] <= st["budget_bytes"],
              f"evictions={st['evictions']} bytes={st['bytes']}"
              f"/{st['budget_bytes']}")
    finally:
        small.close()

    print("== config plumbing + host-overhead budget ==")
    kw = engine_kwargs({"prefix_cache_mb": 2.5}, "")
    check("KUBEDL_SERVE_CONFIG prefix_cache_mb reaches engine_kwargs",
          kw.get("prefix_cache_mb") == 2.5
          and engine_kwargs({}, "").get("prefix_cache_mb") == 64.0)
    from scripts.scheduler_microbench import run_prefix_microbench

    mb = run_prefix_microbench(requests=8, max_tokens=8)
    check("match+graft host overhead within tier-1 budget",
          mb["within_budget"] and mb["hits"] == 8,
          f"tick_p50={mb['tick_ms_p50']}ms match_graft={mb['match_graft_ms']}ms")

    failed = [c for c in CHECKS if not c[1]]
    print(f"\n{len(CHECKS) - len(failed)}/{len(CHECKS)} checks passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

"""Drive the parameter-service tier across REAL process boundaries
(docs/elasticity.md "Parameter-service mode"):

1. the parent hosts a real ParameterService (WAL-backed shards) behind a
   real PSServer (HTTP); three SUBPROCESS workers run
   `python -m kubedl_tpu.training.entry` in ``train_mode: ps``, each
   writing progress beacons;
2. mid-run, worker-2 is SIGKILLed with NO notice and evicted the way a
   watchdog fire would evict it (in-flight discarded) — the surviving
   workers' beacons must KEEP ADVANCING, no gang restart, no stall;
3. then PS shard 0 is killed through the admin surface; the next push
   drives a lease-fenced failover (TTL wait + fencing-token bump + WAL
   replay) — survivors must advance straight through it;
4. at the end both survivors must have finished every step, trained
   (finite final loss, below the first loss), agree with each other
   within the pinned tolerance, and the service must report exactly one
   silent-death eviction and at least one shard failover.

Run with `python scripts/verify-drives/drive_ps.py`
(CPU only; sets JAX_PLATFORMS=cpu itself).
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
os.environ["JAX_PLATFORMS"] = "cpu"
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

ok = []
def check(name, cond, detail=""):
    ok.append(bool(cond))
    print(("PASS" if cond else "FAIL"), name, detail)

from kubedl_tpu.api import constants
from kubedl_tpu.core.store import ObjectStore
from kubedl_tpu.models import llama
from kubedl_tpu.observability.metrics import PSMetrics
from kubedl_tpu.ps import ParameterService, PSConfig
from kubedl_tpu.ps.server import PSServer
from kubedl_tpu.training.trainer import TrainConfig, Trainer
from kubedl_tpu.watchdog.beacon import read_beacon

STEPS = 600
PUSH_EVERY = 5
#: survivors' final losses must agree within this band (the asynchrony
#: tolerance the bench pins against the sync baseline — bench.py PS_LOSS_TOL)
LOSS_BAND = 0.5
#: after each injected failure, every survivor must advance within this
STALL_BUDGET_S = 15.0

tmp = tempfile.mkdtemp(prefix="kdl-ps-drive-")
beacon_of = {i: os.path.join(tmp, f"beacon-{i}.json") for i in range(3)}
log_of = {i: os.path.join(tmp, f"worker-{i}.log") for i in range(3)}


def beacon_step(i):
    b = read_beacon(beacon_of[i])
    return int(b["step"]) if b else -1


def wait_until(cond, budget, what):
    deadline = time.time() + budget
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.2)
    print(f"TIMEOUT waiting for {what}")
    return False


def assert_survivors_advance(tag):
    """Both survivors' beacon step counters must strictly advance —
    the 'survivors never stall' contract."""
    marks = {i: beacon_step(i) for i in (0, 1)}
    for i in (0, 1):
        moved = wait_until(
            lambda i=i: beacon_step(i) > marks[i] or not (procs[i].poll() is None and beacon_step(i) < STEPS),
            STALL_BUDGET_S, f"worker-{i} advance after {tag}",
        )
        done = beacon_step(i) >= STEPS or procs[i].poll() is not None
        check(f"worker-{i} advances after {tag}",
              moved and (beacon_step(i) > marks[i] or done),
              f"step {marks[i]} -> {beacon_step(i)}")


# -- the service: WAL-backed shards, short lease so failover is quick ----
seed_trainer = Trainer(TrainConfig(
    model=llama.TINY, global_batch=4, seq_len=16, steps=1, seed=0,
))
init_params = Trainer._host_params(seed_trainer.init_state()["params"])
svc = ParameterService(
    init_params,
    PSConfig(num_shards=2, max_staleness=4, decay=0.5,
             wal_root=os.path.join(tmp, "wal"), fsync="off",
             lease_ttl=0.5),
    store=ObjectStore(), metrics=PSMetrics(),
)
server = PSServer(svc).start()
print(f"ps server at {server.addr}, params={len(init_params)} tensors")

train_cfg = {
    "model": "tiny", "global_batch": 4, "seq_len": 16, "steps": STEPS,
    "learning_rate": 3e-3, "train_mode": "ps",
}

procs = {}
for i in range(3):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "KUBEDL_TRAIN_CONFIG": json.dumps(train_cfg),
        constants.ENV_PS_ADDR: server.addr,
        constants.ENV_PROCESS_ID: str(i),
        constants.ENV_PS_PUSH_EVERY: str(PUSH_EVERY),
        constants.ENV_BEACON_FILE: beacon_of[i],
    })
    procs[i] = subprocess.Popen(
        [sys.executable, "-m", "kubedl_tpu.training.entry"],
        env=env, stdout=open(log_of[i], "w"), stderr=subprocess.STDOUT,
    )

try:
    # every worker past compile and into the loop, mid-run
    check("all workers reach step 20",
          wait_until(lambda: all(beacon_step(i) >= 20 for i in range(3)),
                     180.0, "all workers at step 20"),
          f"steps={[beacon_step(i) for i in range(3)]}")

    # -- failure 1: silent worker death (SIGKILL, no notice) -------------
    procs[2].send_signal(signal.SIGKILL)
    procs[2].wait(timeout=30)
    # the watchdog-fire path: evict the silently-dead member; its staged
    # in-flight contribution is discarded, survivors untouched
    svc.evict_silent_death("worker-2")
    assert_survivors_advance("worker-2 SIGKILL + eviction")

    # -- failure 2: PS shard death -> lease-fenced failover --------------
    req = urllib.request.Request(
        f"http://{server.addr}/ps/admin",
        data=json.dumps({"op": "fail_shard", "shard": 0}).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        check("admin fail_shard accepted", resp.status == 200)
    assert_survivors_advance("shard-0 failover")

    # -- drain to completion --------------------------------------------
    for i in (0, 1):
        rc = None
        try:
            rc = procs[i].wait(timeout=300)
        except subprocess.TimeoutExpired:
            procs[i].kill()
        check(f"worker-{i} exits 0", rc == 0, f"rc={rc}")

    summaries = {}
    for i in (0, 1):
        with open(log_of[i]) as f:
            for line in f:
                if '"worker_summary"' in line:
                    try:
                        summaries[i] = json.loads(line)["worker_summary"]
                    except json.JSONDecodeError:
                        continue
    check("both survivors report a summary", set(summaries) == {0, 1})
    for i, s in sorted(summaries.items()):
        check(f"worker-{i} finished all steps",
              s.get("steps") == STEPS, f"steps={s.get('steps')}")
        check(f"worker-{i} pushed through both failures",
              s.get("ps_pushes", 0) > 0 and s.get("train_mode") == "ps",
              f"pushes={s.get('ps_pushes')} dropped={s.get('ps_dropped')} "
              f"rejected={s.get('ps_rejected')}")
        fl, ll = s.get("first_loss"), s.get("final_loss")
        check(f"worker-{i} trained",
              fl is not None and ll is not None and ll == ll and ll < fl,
              f"loss {fl} -> {ll}")
    if set(summaries) == {0, 1}:
        gap = abs(summaries[0]["final_loss"] - summaries[1]["final_loss"])
        check("survivor losses within pinned band",
              gap <= LOSS_BAND, f"gap={gap:.4f} tol={LOSS_BAND}")

    stats = svc.stats()
    check("exactly one silent-death eviction",
          svc.metrics.ps_evictions.value(reason="silent_death") == 1.0,
          f"evicted={stats['evicted']}")
    check("shard failover happened", stats["failovers"] >= 1,
          f"failovers={stats['failovers']}")
    check("shard versions advanced past the failover",
          all(v > 0 for v in stats["versions"]),
          f"versions={stats['versions']}")
finally:
    for p in procs.values():
        if p.poll() is None:
            p.kill()
    server.stop()
    shutil.rmtree(tmp, ignore_errors=True)

print(("OK" if all(ok) else "FAILED"), f"{sum(ok)}/{len(ok)} checks passed")
sys.exit(0 if all(ok) else 1)

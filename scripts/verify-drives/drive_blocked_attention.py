"""Verify drive: blocked paged attention + model-based drafts (PR 11).

Drives the blocked-attention decode kernel and model-draft speculation
through the PUBLIC surface — real LlamaEngines behind the real HTTP
handler — and checks the contracts docs/serving.md "Blocked paged
attention" / "Model drafts" promise:

  1. greedy outputs over HTTP with kv_attention="blocked" are
     bit-identical to the gather-oracle engine (the exactness gate,
     end to end, ragged prompts included);
  2. /v1/stats carries kv_blocks.attention_kernel and /metrics serves
     the kv gauges with the attention_kernel label;
  3. spec_draft="model" (early-exit slice of the target) stays
     bit-identical over HTTP on the blocked kernel, with acceptance
     > 0.5 on the tiny-deep proxy pair and draft wall time on the
     books (draft_ms_p50 + the spec_draft_ms metric, draft label);
  4. multi-candidate verification (spec_candidates=2) accepts >= the
     single-candidate run on the same requests, with candidates
     actually scored;
  5. KUBEDL_SERVE_CONFIG plumbing (kv_attention/spec_draft/
     spec_candidates/spec_draft_layers reach engine_kwargs; gather
     stays the default) and Predictor field plumbing through
     framework._jax_setter;
  6. raw-kernel parity: the lax blocked kernel matches a float64
     dense reference on a ragged hand-built pool (trash-block row
     included);
  7. blocked-attention host overhead stays under the tier-1 budget.

Run: python scripts/verify-drives/drive_blocked_attention.py
(CPU-forced, ~2 min)
"""

import json
import os
import sys
import threading
import urllib.request
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested  # noqa: E402

ensure_cpu_if_requested()

CHECKS = []


def check(name, ok, detail=""):
    CHECKS.append((name, bool(ok), detail))
    print(f"  [{'ok' if ok else 'FAIL'}] {name}" + (f" — {detail}" if detail else ""))


def post(port, payload, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/{path.lstrip('/')}", timeout=30
    ) as resp:
        return resp.read()


def serve(eng, name):
    import http.server

    from kubedl_tpu.serving.server import make_handler

    srv = http.server.ThreadingHTTPServer(
        ("127.0.0.1", 0), make_handler(eng, name)
    )
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]


PROMPTS = [[5, 9, 13], [7, 3, 3, 11, 2, 6, 1], [1], [4, 4, 4, 4]]


def run_engine(prompts, max_tokens=16, **kw):
    """Spin an engine behind real HTTP, run prompts, return (outs, stats,
    metrics body)."""
    from kubedl_tpu.serving.server import LlamaEngine

    base = dict(preset="tiny", max_batch=2, max_seq=64, kv_layout="paged",
                kv_block_size=4, kv_blocks=48, prefix_cache_mb=0)
    eng = LlamaEngine(**{**base, **kw})
    srv, port = serve(eng, "drive11")
    try:
        outs = [
            post(port, {"token_ids": p, "max_tokens": max_tokens})["token_ids"]
            for p in prompts
        ]
        stats = json.loads(get(port, "/v1/stats"))
        body = get(port, "/metrics").decode()
        return outs, stats, body
    finally:
        srv.shutdown()
        eng.close()


def main():
    from kubedl_tpu.serving.server import engine_kwargs

    print("== 1-2: blocked kernel bit-identity over HTTP + accounting ==")
    g_outs, g_stats, _ = run_engine(PROMPTS)
    b_outs, b_stats, b_body = run_engine(PROMPTS, kv_attention="blocked")
    check("greedy outputs blocked == gather over HTTP", b_outs == g_outs,
          f"{len(PROMPTS)} ragged prompts x 16 tokens")
    check("stats attention_kernel",
          g_stats["kv_blocks"].get("attention_kernel") == "gather"
          and b_stats["kv_blocks"].get("attention_kernel") == "blocked")
    check("metrics attention_kernel label",
          'attention_kernel="blocked"' in b_body
          and "kubedl_tpu_serving_kv_blocks_total" in b_body)

    print("== 3-4: model drafts on the blocked kernel ==")
    # tiny-deep zero-inits the deep residual branches, so the 2-of-4
    # layer slice is bit-identical to the target at init.
    deep = dict(preset="tiny-deep", kv_attention="blocked")
    ref_outs, _, _ = run_engine(PROMPTS, **deep)
    m_outs, m_stats, m_body = run_engine(
        PROMPTS, spec_k=3, spec_draft="model", spec_draft_layers=2, **deep)
    sp = m_stats["speculative"]
    check("model-draft outputs bit-identical", m_outs == ref_outs)
    check("model-draft acceptance > 0.5",
          sp["acceptance_rate"] > 0.5, f"rate={sp['acceptance_rate']:.2f}")
    check("draft wall time recorded",
          sp.get("draft_ms_p50", 0) > 0
          and "kubedl_tpu_serving_spec_draft_ms" in m_body
          and 'draft="model"' in m_body,
          f"draft_ms_p50={sp.get('draft_ms_p50', 0):.2f}")
    mc_outs, mc_stats, _ = run_engine(
        PROMPTS, spec_k=3, spec_draft="model", spec_draft_layers=2,
        spec_candidates=2, **deep)
    mcsp = mc_stats["speculative"]
    check("multi-candidate outputs bit-identical", mc_outs == ref_outs)
    check("multi accepted >= single, candidates scored",
          mcsp["accepted"] >= sp["accepted"]
          and mcsp.get("candidates_scored", 0) > 0,
          f"multi={mcsp['accepted']} single={sp['accepted']} "
          f"scored={mcsp.get('candidates_scored', 0)}")

    print("== 5: config plumbing ==")
    kw = engine_kwargs(
        {"kv_attention": "blocked", "spec_draft": "model",
         "spec_candidates": 2, "spec_draft_layers": 2}, "/x")
    dflt = engine_kwargs({}, "/x")
    check("engine_kwargs plumbing",
          kw["kv_attention"] == "blocked" and kw["spec_draft"] == "model"
          and kw["spec_candidates"] == 2 and kw["spec_draft_layers"] == 2
          and dflt["kv_attention"] == "gather"
          and dflt["spec_candidates"] == 1)
    from kubedl_tpu.serving.types import Predictor
    pred = Predictor(model_name="m", attention_kernel="blocked", spec_k=3,
                     spec_draft="model", spec_candidates=2)
    check("Predictor carries kernel/draft fields",
          pred.attention_kernel == "blocked" and pred.spec_draft == "model"
          and pred.spec_candidates == 2)

    print("== 6: raw-kernel parity vs float64 dense reference ==")
    import numpy as np
    import jax.numpy as jnp
    from kubedl_tpu.models.paged_attention import paged_attention

    rng = np.random.default_rng(11)
    H, KV, hd, BS, NB, MB, B = 4, 2, 8, 4, 10, 4, 3
    kp = rng.standard_normal((NB, BS, KV, hd)).astype(np.float32)
    vp = rng.standard_normal((NB, BS, KV, hd)).astype(np.float32)
    kp[0], vp[0] = 37.0, -29.0  # poisoned trash block
    q = rng.standard_normal((B, 1, H, hd)).astype(np.float32)
    bt = np.array([[1, 2, 3, 4], [5, 6, 0, 0], [0, 0, 0, 0]], np.int32)
    starts = np.array([13, 6, 0], np.int32)  # partial tail, mid, trash row
    out = np.asarray(paged_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(bt), jnp.asarray(starts), kernel="lax"))
    ok = np.isfinite(out).all()
    scale = 1.0 / np.sqrt(hd)
    for b in range(B):
        # the query at position starts[b] attends to pool slots
        # t <= starts[b] (its own KV is already written there)
        n = min(int(starts[b]) + 1, MB * BS)
        keys = kp[bt[b]].reshape(-1, KV, hd)[:n].astype(np.float64)
        vals = vp[bt[b]].reshape(-1, KV, hd)[:n].astype(np.float64)
        for h in range(H):
            g = h * KV // H
            s = keys[:, g] @ q[b, 0, h].astype(np.float64) * scale
            w = np.exp(s - s.max())
            w /= w.sum()
            ref = w @ vals[:, g]
            ok = ok and np.allclose(out[b, 0, h], ref, atol=1e-5)
    check("lax blocked kernel matches float64 dense reference", ok,
          "ragged rows + poisoned trash block, finite everywhere")

    print("== 7: host-overhead budget ==")
    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from scheduler_microbench import run_blocked_attention_microbench

    mb = run_blocked_attention_microbench(iters=50)
    check("blocked host overhead within budget", mb["within_budget"],
          f"tick_p50={mb['tick_ms_p50']:.2f}ms "
          f"dispatch={mb['kernel_dispatch_ms']:.2f}ms")

    failed = [c for c in CHECKS if not c[1]]
    print(f"\n{len(CHECKS) - len(failed)}/{len(CHECKS)} checks passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

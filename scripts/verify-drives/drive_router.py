"""Drive the fault-tolerant serving router end to end: three REAL engine
replicas as subprocesses (`python -m kubedl_tpu.serving.server`), a
seeded FaultPlan choosing the moment one is SIGKILLed under client load.
Acceptance (docs/serving.md "Router"): every queued not-yet-dispatched
request completes via failover (zero lost), only work in flight on the
dead replica is retried — at most once, inside its deadline — the
breaker ejects the dead replica and readmits it after restart, greedy
outputs through the router are bit-identical to a direct engine call,
expired deadlines never dispatch, and a draining replica stops taking
new work without dropping anything."""
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
os.environ["JAX_PLATFORMS"] = "cpu"
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

ok = []
def check(name, cond, detail=""):
    ok.append(bool(cond))
    print(("PASS" if cond else "FAIL"), name, detail)

from kubedl_tpu import chaos
from kubedl_tpu.chaos import FaultPlan, FaultSpec
from kubedl_tpu.serving import router_policy as policy
from kubedl_tpu.serving.router import ServingRouter

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def spawn_replica(port):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["KUBEDL_SERVE_CONFIG"] = json.dumps({
        "preset": "tiny", "port": port, "max_batch": 2,
        "drain_grace_s": 5.0,
    })
    env.pop("KUBEDL_MODEL_PATH", None)
    return subprocess.Popen(
        [sys.executable, "-m", "kubedl_tpu.serving.server"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def wait_healthy(port, timeout=180.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=2
            ) as r:
                if r.status == 200:
                    return True
        except Exception:
            time.sleep(0.3)
    return False


def get_json(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as r:
        return json.loads(r.read())


def post_generate(port, body, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


ports = {f"r{i}": free_port() for i in range(3)}
procs = {n: spawn_replica(p) for n, p in ports.items()}
try:
    up = all(wait_healthy(p) for p in ports.values())
    check("3 engine replicas come up", up)
    if not up:
        raise SystemExit(1)

    router = ServingRouter(
        [(n, "127.0.0.1", p) for n, p in sorted(ports.items())],
        probe_interval_s=0.2, probe_timeout_s=1.0,
        eject_threshold=3, readmit_cooldown_s=1.0,
        hedge_enabled=True, hedge_default_ms=3000.0,
        max_retries=1, default_deadline_ms=30_000.0,
    )
    router.start()
    router.probe_once()

    # -- bit-identity: the router must never change RESULTS ---------------
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    direct = post_generate(ports["r0"], {"prompt_ids": prompt,
                                         "max_tokens": 8,
                                         "temperature": 0.0})
    code, via, _ = router.handle_generate(
        {"prompt_ids": prompt, "max_tokens": 8, "temperature": 0.0})
    check("greedy outputs through router bit-identical to direct call",
          code == 200 and via["token_ids"] == direct["token_ids"],
          f"direct={direct['token_ids']} routed={via.get('token_ids')}")

    # -- expired deadline: never dispatched, not even once -----------------
    before = sum(get_json(p, "/v1/stats")["requests"]
                 for p in ports.values())
    code, _, _ = router.handle_generate({"prompt_ids": [1]}, deadline_ms=0)
    after = sum(get_json(p, "/v1/stats")["requests"]
                for p in ports.values())
    check("expired deadline is 504 with zero dispatches",
          code == 504 and after == before)

    # -- SIGKILL one replica under load, moment chosen by a seeded plan ----
    N = 36
    plan = FaultPlan(seed=11, sites={"replica.kill": [FaultSpec.nth(9)]})
    victim = "r1"
    results = [None] * N
    killed_at = {"i": None}

    def client(i):
        # deterministic greedy workload; every prompt long enough to get
        # affinity so the fleet spreads by prefix, not randomness
        body = {"prompt_ids": [(i % 7) + 2] * 8 + [100 + i],
                "max_tokens": 4, "temperature": 0.0}
        code, payload, _ = router.handle_generate(body, deadline_ms=20_000)
        results[i] = (code, payload)

    threads = []
    with plan:
        for i in range(N):
            if chaos.should_fail("replica.kill"):
                killed_at["i"] = i
                procs[victim].send_signal(signal.SIGKILL)
            t = threading.Thread(target=client, args=(i,), daemon=True)
            t.start()
            threads.append(t)
            time.sleep(0.03)  # sustained load, queue never fully drains
        for t in threads:
            t.join(timeout=30)
    check("seeded plan injected exactly one kill",
          plan.faults("replica.kill") == 1 and killed_at["i"] == 8,
          f"killed before request #{killed_at['i']}")

    codes = [r[0] for r in results if r is not None]
    lost = N - len(codes)
    failures = [c for c in codes if c != 200]
    check("zero lost requests: every queued request completed via failover",
          lost == 0 and not failures,
          f"lost={lost} non200={failures[:5]}")
    retries = router.metrics.retries.value()
    transport = sum(
        router.metrics.transport_errors.value(replica=n) for n in ports
    )
    check("only in-flight-on-dead-replica work retried, bounded burst",
          0 < retries <= transport <= 6,
          f"retries={retries} transport_errors={transport}")
    check("at most one retry per request (budget-capped)",
          retries <= router.retry_budget.spent + 0
          and router.max_retries == 1)

    deadline = time.time() + 10
    while time.time() < deadline:
        if router.stats()["replicas"][victim]["state"] == policy.OPEN:
            break
        time.sleep(0.1)
    st = router.stats()["replicas"][victim]
    check("breaker ejected the dead replica",
          st["state"] == policy.OPEN and st["ejections"] >= 1,
          f"state={st['state']} ejections={st['ejections']}")

    # -- restart the victim on the same port: the probe readmits it -------
    procs[victim].wait(timeout=10)
    procs[victim] = spawn_replica(ports[victim])
    check("victim restarted", wait_healthy(ports[victim]))
    deadline = time.time() + 30
    while time.time() < deadline:
        if router.stats()["replicas"][victim]["state"] == policy.CLOSED:
            break
        time.sleep(0.1)
    st = router.stats()["replicas"][victim]
    check("half-open probe readmitted the restarted replica",
          st["state"] == policy.CLOSED and st["readmissions"] >= 1,
          f"state={st['state']} readmissions={st['readmissions']}")

    served = set()
    for i in range(24):
        code, payload, _ = router.handle_generate(
            {"prompt_ids": [i + 2] * 9, "max_tokens": 2,
             "temperature": 0.0}, deadline_ms=20_000)
        if code == 200:
            served.add(payload.get("served_by", ""))
    # engine payloads don't carry names; infer from per-replica counters
    reqs = {n: get_json(p, "/v1/stats")["requests"]
            for n, p in ports.items()}
    check("readmitted replica takes traffic again",
          reqs[victim] > 0, f"requests={reqs}")

    # -- graceful drain: distinguishable 503, router routes around --------
    drain_target = "r2"
    req = urllib.request.Request(
        f"http://127.0.0.1:{ports[drain_target]}/admin/drain", data=b"{}")
    urllib.request.urlopen(req, timeout=5).read()
    check("engine reports draining in stats",
          get_json(ports[drain_target], "/v1/stats")["draining"] is True)
    try:
        post_generate(ports[drain_target], {"prompt_ids": [1]})
        direct_503 = None
    except urllib.error.HTTPError as e:
        direct_503 = (e.code, json.loads(e.read()))
    check("drain 503 is distinguishable (reason: draining)",
          direct_503 is not None and direct_503[0] == 503
          and direct_503[1].get("reason") == "draining")
    spent_before = router.retry_budget.spent
    okc = 0
    for i in range(12):
        code, _, _ = router.handle_generate(
            {"prompt_ids": [50 + i] * 8, "max_tokens": 2}, 20_000)
        okc += (code == 200)
    check("router routes around the draining replica, free of budget",
          okc == 12 and router.stats()["replicas"][drain_target]["draining"],
          f"ok={okc} spent_delta={router.retry_budget.spent - spent_before}")

    router.stop()
finally:
    for p in procs.values():
        try:
            p.send_signal(signal.SIGKILL)
        except Exception:
            pass

print(f"\n{sum(ok)}/{len(ok)} checks passed")
sys.exit(0 if all(ok) else 1)

"""Drive: persistent compile cache through the real operator path.

Two sequential single-worker TPUJobs run `python -m kubedl_tpu.training.entry`
as real subprocesses with the operator-injected KUBEDL_COMPILE_CACHE_DIR.
Job 1 (cold) populates the cache; job 2 (warm — the gang-restart shape)
must add zero entries and compile faster.
"""
import json, os, sys, tempfile, time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

from kubedl_tpu.api.types import (
    JobConditionType, ReplicaSpec, ReplicaType, RestartPolicy,
)
from kubedl_tpu.core.objects import Container, EnvVar
from kubedl_tpu.operator import Operator, OperatorOptions
from kubedl_tpu.runtime.executor import SubprocessRuntime
from kubedl_tpu.utils.compile_cache import cache_entry_count
from kubedl_tpu.utils.invariants import check_invariants
from kubedl_tpu.workloads.tpujob import TPUJob

checks = []
def check(name, ok, detail=""):
    checks.append((name, ok))
    print(("PASS " if ok else "FAIL ") + name + (f" — {detail}" if detail else ""))

tmp = tempfile.mkdtemp(prefix="kdl-cache-drive-")
logs = os.path.join(tmp, "logs")
cache = os.path.join(tmp, "compile-cache")
cfg = {"model": "tiny", "steps": 3, "global_batch": 4, "seq_len": 32}

def run(op, name):
    job = TPUJob(); job.metadata.name = name
    spec = ReplicaSpec(replicas=1, restart_policy=RestartPolicy.ON_FAILURE_SLICE)
    spec.template.spec.containers.append(Container(
        command=[sys.executable, "-m", "kubedl_tpu.training.entry"],
        env=[EnvVar("KUBEDL_TRAIN_CONFIG", json.dumps(cfg)),
             EnvVar("PYTHONPATH", "/root/repo")],
    ))
    job.spec.replica_specs[ReplicaType.WORKER] = spec
    op.submit(job)
    got = op.wait_for_phase("TPUJob", name,
        [JobConditionType.SUCCEEDED, JobConditionType.FAILED], timeout=300)
    log = os.path.join(logs, "default", f"{name}-worker-0.log")
    summary = None
    with open(log) as f:
        for line in f:
            if '"worker_summary"' in line:
                summary = json.loads(line)["worker_summary"]
    return got, summary

opts = OperatorOptions(
    local_addresses=True, pod_log_dir=logs,
    artifact_registry_root=os.path.join(tmp, "reg"),
    compile_cache_dir=cache,
)
with Operator(opts, runtime=SubprocessRuntime(logs)) as op:
    got1, s1 = run(op, "cold")
    check("cold job SUCCEEDED", got1.status.phase == JobConditionType.SUCCEEDED)
    check("cold summary parsed", s1 is not None)
    n1 = cache_entry_count(cache)
    check("cache populated by cold run", n1 > 0, f"{n1} entries")
    got2, s2 = run(op, "warm")
    check("warm job SUCCEEDED", got2.status.phase == JobConditionType.SUCCEEDED)
    n2 = cache_entry_count(cache)
    check("warm run added no cache entries", n2 == n1, f"{n1} -> {n2}")
    # tolerance: on the tiny CPU model both first steps are ~0.1s and the
    # comparison is scheduler noise (0.09 vs 0.10 observed on a loaded
    # 1-core box); the structural proof is the zero-new-entries check
    # above — this one only guards against gross recompiles
    check("warm first-step not slower (50ms tolerance)",
          s2["first_step_seconds"] < s1["first_step_seconds"] + 0.05,
          f"{s1['first_step_seconds']:.2f}s -> {s2['first_step_seconds']:.2f}s")
    bad = check_invariants(op)
    check("invariants green", not bad, str(bad))

failed = [n for n, ok in checks if not ok]
print(f"\n{len(checks) - len(failed)}/{len(checks)} checks passed")
sys.exit(1 if failed else 0)

"""Drive the silent-hang watchdog + async replicated checkpointing end to
end through the PUBLIC surface: a real Operator under an armed
`trainer.step_stall` FaultPlan wedges a real training step loop WITHOUT
the pod exiting; the watchdog classifies the hang from beacons riding the
kubelet heartbeat, fails the pod retryably (exit 137), stamps the
HangDetected condition, and the normal gang restart resumes from the
latest ASYNC checkpoint instead of step 0. Plus: fake-clock
classification (hang vs silent death vs straggler), and peer-replicated
restore after the local shard dir is deleted."""
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
os.environ["JAX_PLATFORMS"] = "cpu"
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

ok = []
def check(name, cond, detail=""):
    ok.append(bool(cond))
    print(("PASS" if cond else "FAIL"), name, detail)

import json

from kubedl_tpu.api import constants
from kubedl_tpu.api.types import JobConditionType, ReplicaType
from kubedl_tpu.chaos import FaultPlan, FaultSpec
from kubedl_tpu.core.nodes import NODE_NAMESPACE, NodeHeartbeater
from kubedl_tpu.core.objects import Container, EnvVar, Pod, PodPhase
from kubedl_tpu.core.store import ObjectStore
from kubedl_tpu.watchdog import WatchdogConfig, WatchdogController

tmp = tempfile.mkdtemp(prefix="kdl-watchdog-drive-")

# 1. fake-clock classification: hang (ts fresh, step frozen) fires
#    retryably; a healthy replica and a straggler never do
store = ObjectStore()
t = {"now": 1000.0}
hb = NodeHeartbeater(store, ["hostX"], clock=lambda: t["now"])
wd = WatchdogController(
    store, clock=lambda: t["now"],
    config=WatchdogConfig(multiplier=3.0, min_budget_seconds=5.0,
                          startup_grace_seconds=50.0),
)
for name in ("w0", "w1"):
    p = Pod()
    p.metadata.name = name
    p.metadata.labels = {constants.LABEL_JOB_NAME: "drill",
                         constants.LABEL_JOB_KIND: "TPUJob"}
    p.spec.containers.append(Container())
    p.spec.node_name = "hostX"
    p.status.phase = PodPhase.RUNNING
    store.create(p)
steps = {"w0": 0, "w1": 0}
def tick(advance, stamp=("w0", "w1")):
    """1s of fake time; advance some counters, re-stamp fresh ts for
    every name in `stamp` (a wedged loop's beacon thread keeps stamping)."""
    t["now"] += 1.0
    for name in advance:
        steps[name] += advance[name]
    for name in stamp:
        hb.announce_progress("hostX", f"default/{name}",
                             step=steps[name], ts=t["now"])
    hb.beat_once()
    wd.reconcile(NODE_NAMESPACE, "hostX")
for _ in range(8):          # both advance: w0 10 steps/s, w1 1 step/s
    tick({"w0": 10, "w1": 1})
check("straggler flagged observationally (no restart)",
      any(tr.straggler for tr in wd._tracks.values())
      and store.get("Pod", "w1").status.phase == PodPhase.RUNNING
      and wd.fired == {"hang": 0, "silent_death": 0})
for _ in range(8):          # w0 wedges: ts stays fresh, step frozen
    tick({"w1": 1}, stamp=("w0", "w1"))
w0 = store.get("Pod", "w0")
check("hang fires retryably past the EWMA budget",
      w0.status.phase == PodPhase.FAILED
      and w0.status.reason == "HangDetected"
      and w0.status.container_statuses[0].exit_code == 137
      and wd.fired["hang"] == 1)
for _ in range(8):          # w1's beacons stop entirely, pod still RUNNING
    t["now"] += 1.0
    wd.reconcile(NODE_NAMESPACE, "hostX")
check("silent death fires when beacons stop",
      store.get("Pod", "w1").status.phase == PodPhase.FAILED
      and wd.fired["silent_death"] == 1)

# 2. the acceptance drill: injected hang -> HangDetected -> gang restart
#    resumes from the latest ASYNC checkpoint
from kubedl_tpu.operator import Operator, OperatorOptions
from kubedl_tpu.runtime.executor import ThreadRuntime
from kubedl_tpu.training import entry as entry_mod
from tests.helpers import make_tpujob

opts = OperatorOptions(
    local_addresses=True,
    artifact_registry_root=os.path.join(tmp, "reg"),
    node_grace_seconds=3.0,              # heartbeat/beacon publish ~1s
    heartbeat_nodes=["hostX"],
    beacon_dir=os.path.join(tmp, "beacons"),
    watchdog_multiplier=3.0,
    watchdog_min_budget_seconds=1.0,
    watchdog_startup_grace_seconds=300.0,  # compile never trips it
)
cfg = {"model": "tiny", "steps": 6, "global_batch": 8, "seq_len": 32,
       "ckpt_every": 2}
# call 3 (step 3 of attempt 1, after the step-2 async save) wedges the
# loop without exiting; every other call pays 700ms so the watchdog
# observes real step spacing (the EWMA its hang budget derives from)
plan = FaultPlan(7, sites={"trainer.step_stall": [
    FaultSpec.nth(3), FaultSpec.latency(700.0, every=1),
]})
with plan, Operator(opts, runtime=ThreadRuntime()) as op:
    job = make_tpujob("hangjob", workers=1,
                      entrypoint="kubedl_tpu.training.entry:train_main")
    spec = job.spec.replica_specs[ReplicaType.WORKER]
    spec.template.spec.node_name = "hostX"
    main = spec.template.spec.containers[0]
    main.env.append(EnvVar("KUBEDL_TRAIN_CONFIG", json.dumps(cfg)))
    main.env.append(EnvVar(constants.ENV_CKPT_DIR, os.path.join(tmp, "ck")))
    op.submit(job)
    got = op.wait_for_phase(
        "TPUJob", "hangjob",
        [JobConditionType.SUCCEEDED, JobConditionType.FAILED], timeout=180)
    check("hung job recovers and SUCCEEDS",
          got.status.phase == JobConditionType.SUCCEEDED,
          f"phase={got.status.phase}")
    check("watchdog drove a gang restart",
          got.status.restart_count >= 1
          and op.metrics.watchdog_restarts.value(reason="hang") >= 1,
          f"restarts={got.status.restart_count}")
    check("HangDetected condition + event recorded",
          any(c.type == JobConditionType.HANG_DETECTED
              for c in got.status.conditions)
          and any(e.reason == "HangDetected"
                  for e in op.store.list("Event", None)))
    check("exactly the planned single wedge was injected",
          plan.faults("trainer.step_stall") == 1)
summary = entry_mod.LAST_SUMMARY or {}
check("retry resumed from the async checkpoint, not step 0",
      summary.get("start_step", 0) >= 2
      and summary.get("ckpt_async") is True,
      f"start_step={summary.get('start_step')}")

# 3. peer-replicated restore: local shard dir deleted, replica saves it
import jax

from kubedl_tpu.remote import RemoteStoreServer
from kubedl_tpu.training.checkpoint import (
    AsyncCheckpointer, restore_from_best)
from kubedl_tpu.api.topology import MeshSpec
from kubedl_tpu.models import llama
from kubedl_tpu.parallel.mesh import build_mesh
from kubedl_tpu.training.data import SyntheticTokens
from kubedl_tpu.training.trainer import TrainConfig, Trainer

mesh = build_mesh(MeshSpec({"data": 1}), jax.devices()[:1])
tcfg = TrainConfig(model=llama.TINY, global_batch=4, seq_len=16, steps=2)
trainer = Trainer(tcfg, mesh)
state, _ = trainer.fit(iter(SyntheticTokens(4, 16, llama.TINY.vocab_size)))
local = os.path.join(tmp, "peer-ck")
with RemoteStoreServer(os.path.join(tmp, "peer-root")) as srv:
    peer = f"{srv.base_url}/blobs/replicas/w0"
    with AsyncCheckpointer(local, peer_url=peer) as acp:
        acp.save(state, 2)
    check("completed save mirrored to the peer", acp.peer_pushes == 1)
    shutil.rmtree(local)  # the owning host's disk is gone
    restored = restore_from_best(local, trainer.init_state(), sources=[peer])
    check("restore succeeds from the peer replica after local loss",
          restored is not None
          and int(jax.device_get(restored["step"])) == 2)

shutil.rmtree(tmp, ignore_errors=True)
print(f"\n{sum(ok)}/{len(ok)} checks passed")
sys.exit(0 if all(ok) else 1)

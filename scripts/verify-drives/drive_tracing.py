"""Drive end-to-end distributed tracing through the REAL two-leg
disaggregated path: one prefill + one decode engine replica as
subprocesses (`python -m kubedl_tpu.serving.server`), the role-aware
router in front, one request with the flight recorder armed
(`"debug": {"trace": true}`). Acceptance (docs/observability.md): the
request dispatches as a genuine two-leg flow (no fallback), and the
returned span tree shows BOTH legs parented under the router's root span
— `engine.request(kind=prefill)` under `router.prefill_leg` and
`engine.request(kind=adopt)` under `router.adopt_leg` — i.e. parentage,
not span counts, proves the context crossed every hop. The per-process
`/v1/trace` dumps then fuse through `scripts/tracemerge.py` into one
Chrome trace whose events carry the same parent chain."""
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
os.environ["JAX_PLATFORMS"] = "cpu"
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

ok = []
def check(name, cond, detail=""):
    ok.append(bool(cond))
    print(("PASS" if cond else "FAIL"), name, detail)

from kubedl_tpu.observability.tracing import TRACER, span_to_dict
from kubedl_tpu.serving.router import ServingRouter

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def spawn_replica(port, role):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["KUBEDL_SERVE_CONFIG"] = json.dumps({
        "preset": "tiny", "port": port, "max_batch": 2, "role": role,
        "handoff_ttl_s": 20.0,
    })
    env.pop("KUBEDL_MODEL_PATH", None)
    return subprocess.Popen(
        [sys.executable, "-m", "kubedl_tpu.serving.server"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def wait_healthy(port, timeout=180.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=2
            ) as r:
                if r.status == 200:
                    return True
        except Exception:
            time.sleep(0.3)
    return False


def walk(nodes):
    """Flatten a flight-recorder tree, yielding every node."""
    for n in nodes:
        yield n
        yield from walk(n["children"])


def find(nodes, name, **attrs):
    for n in walk(nodes):
        if n["name"] == name and all(
            n["attrs"].get(k) == v for k, v in attrs.items()
        ):
            return n
    return None


ROLES = {"p0": "prefill", "d0": "decode"}
ports = {n: free_port() for n in ROLES}
procs = {n: spawn_replica(ports[n], ROLES[n]) for n in ROLES}
try:
    up = all(wait_healthy(p) for p in ports.values())
    check("prefill + decode replicas come up", up)
    if not up:
        raise SystemExit(1)

    router = ServingRouter(
        [{"name": n, "host": "127.0.0.1", "port": ports[n],
          "role": ROLES[n], "model": "tiny"} for n in sorted(ROLES)],
        probe_interval_s=0.2, probe_timeout_s=1.0,
        disagg_enabled=True,
    )
    router.start()
    router.probe_once()
    TRACER.clear()

    code, payload, _ = router.handle_generate(
        {"prompt_ids": [3, 1, 4, 1, 5, 9, 2, 6], "max_tokens": 6,
         "temperature": 0.0, "debug": {"trace": True}})
    m = router.metrics
    check("request rode the REAL two-leg path (no fallback)",
          code == 200 and m.disagg_requests.value() == 1
          and m.disagg_fallbacks.value() == 0,
          f"code={code} disagg={m.disagg_requests.value()} "
          f"fallbacks={m.disagg_fallbacks.value()}")

    rec = payload.get("trace") or {}
    tree = rec.get("spans") or []
    tid = rec.get("trace_id", "")
    root = tree[0] if tree else None
    check("flight recorder returned one tree rooted at router.request",
          len(tree) == 1 and root and root["name"] == "router.request",
          f"roots={[n['name'] for n in tree]}")

    # -- the tentpole assertion: PARENTAGE across every hop ---------------
    pleg = find(tree, "router.prefill_leg")
    aleg = find(tree, "router.adopt_leg")
    check("both disagg legs parent under the router root span",
          pleg is not None and aleg is not None
          and pleg["parent_id"] == root["span_id"]
          and aleg["parent_id"] == root["span_id"])

    er_pre = find(tree, "engine.request", kind="prefill")
    er_dec = find(tree, "engine.request", kind="adopt")
    check("prefill replica's engine.request parents under its leg",
          er_pre is not None and pleg is not None
          and er_pre["parent_id"] == pleg["span_id"])
    check("decode replica's engine.request parents under its leg",
          er_dec is not None and aleg is not None
          and er_dec["parent_id"] == aleg["span_id"])

    names_pre = {n["name"] for n in walk([er_pre])} if er_pre else set()
    names_dec = {n["name"] for n in walk([er_dec])} if er_dec else set()
    check("prefill-side spans (queue/admission/prefill/export) attached",
          {"engine.queue_wait", "engine.admission", "engine.prefill",
           "engine.handoff_export"} <= names_pre,
          f"prefill-side={sorted(names_pre)}")
    check("decode-side spans (adopt + decode segments) attached",
          {"engine.handoff_adopt", "engine.decode_segment"} <= names_dec,
          f"decode-side={sorted(names_dec)}")

    ids = {n["trace_id"] for n in walk(tree)}
    check("every span in the tree shares ONE trace id",
          ids == {tid} and len(tid) == 32, f"ids={ids}")

    # -- multi-process dump fusion through scripts/tracemerge.py ----------
    with tempfile.TemporaryDirectory() as tmp:
        dumps = [os.path.join(tmp, "router.json")]
        with open(dumps[0], "w") as f:
            json.dump({"spans": [span_to_dict(s)
                                 for s in TRACER.trace_spans(tid)]}, f)
        for n in sorted(ROLES):
            path = os.path.join(tmp, f"{n}.json")
            with urllib.request.urlopen(
                f"http://127.0.0.1:{ports[n]}/v1/trace?trace_id={tid}",
                timeout=5,
            ) as r:
                with open(path, "wb") as f:
                    f.write(r.read())
            dumps.append(path)
        merged_path = os.path.join(tmp, "merged.json")
        res = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "tracemerge.py"),
             *dumps, "-o", merged_path, "--trace-id", tid],
            cwd=REPO, capture_output=True, text=True,
        )
        check("tracemerge fuses the three per-process dumps",
              res.returncode == 0, res.stderr[-200:])
        merged = json.load(open(merged_path))
        events = merged["traceEvents"]
        spans = [e for e in events if e.get("ph") == "X"]
        pids = {e["pid"] for e in spans}
        procs_named = [e for e in events
                       if e.get("ph") == "M" and e["name"] == "process_name"]
        check("merged trace renders 3 process tracks with spans from each",
              len(procs_named) == 3 and pids == {1, 2, 3},
              f"pids={pids}")
        by_id = {e["args"].get("span_id"): e for e in spans
                 if e["args"].get("span_id")}

        def parent_name(ev):
            p = by_id.get(ev["args"].get("parent_id"))
            return p["name"] if p else None

        mroot = next(e for e in spans if e["name"] == "router.request")
        legs = {e["name"]: e for e in spans
                if e["name"].startswith("router.") and e is not mroot}
        ereqs = [e for e in spans if e["name"] == "engine.request"]
        check("merged events reproduce the cross-process parent chain",
              all(parent_name(l) == "router.request"
                  for l in legs.values())
              and sorted(parent_name(e) for e in ereqs)
              == ["router.adopt_leg", "router.prefill_leg"],
              f"engine.request parents="
              f"{[parent_name(e) for e in ereqs]}")

    router.stop()
finally:
    for p in procs.values():
        try:
            p.send_signal(signal.SIGKILL)
        except Exception:
            pass

print(f"\n{sum(ok)}/{len(ok)} checks passed")
sys.exit(0 if all(ok) else 1)

"""Drive control-plane crash recovery with a REAL SIGKILL across process
boundaries (docs/robustness.md "Crash recovery"):

1. a child process runs a WAL-backed Operator, brings two gang jobs to
   RUNNING (every pod appends its name to a shared launches.log), stages a
   third job mid-gang-create (PodGroup admitted, zero pods), then
   SIGKILLs ITSELF — no atexit, no cleanup, pods orphaned alive;
2. the parent restarts an Operator on the same WAL dir and asserts full
   convergence: every surviving pod adopted by (name, uid, pid) with ZERO
   duplicate launches (kubelet launch log), identical gang slice
   re-reservation, the mid-create job's pods created exactly once, and
   the whole recovery inside the time budget.

Run with `python scripts/verify-drives/drive_crash_recovery.py`
(CPU only; sets JAX_PLATFORMS=cpu itself).
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
os.environ["JAX_PLATFORMS"] = "cpu"
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

from kubedl_tpu.api.topology import get_slice
from kubedl_tpu.api.types import JobConditionType
from kubedl_tpu.core.objects import PodPhase
from kubedl_tpu.gang.slice_scheduler import SliceInventory
from kubedl_tpu.operator import Operator, OperatorOptions
from kubedl_tpu.runtime.executor import SubprocessRuntime

RECOVERY_BUDGET_S = 30.0


def inventory():
    inv = SliceInventory()
    for s in ("s1", "s2", "s3"):
        inv.add_slice(s, "v5e-8")
    return inv


def sleep_cmd(launch_log):
    # every launch leaves a fingerprint: duplicate creates are visible as
    # duplicate lines no matter which operator incarnation launched them
    body = (
        "import os,time;"
        f"open({launch_log!r},'a').write(os.environ['KUBEDL_POD_NAME']+'\\n');"
        "time.sleep(180)"
    )
    return [sys.executable, "-c", body]


def running_pods(store):
    return {
        f"{p.metadata.namespace}/{p.metadata.name}": p.metadata.uid
        for p in store.list("Pod")
        if p.status.phase == PodPhase.RUNNING
    }


def child_main(wal_dir, launch_log, log_dir):
    opts = OperatorOptions(
        local_addresses=True, wal_dir=wal_dir, pod_log_dir=log_dir,
        artifact_registry_root=os.path.join(wal_dir, "..", "reg"),
    )
    op = Operator(opts, runtime=SubprocessRuntime(log_dir),
                  inventory=inventory())
    op.start()
    from tests.helpers import make_tpujob

    topo = get_slice("v5e-8")
    for name in ("job1", "job2"):
        op.submit(make_tpujob(name, workers=2, command=sleep_cmd(launch_log),
                              topology=topo))
        op.wait_for_phase("TPUJob", name, JobConditionType.RUNNING, timeout=30)
    assert op.manager.wait(lambda: len(running_pods(op.store)) == 4,
                           timeout=20)
    # stage job3 mid-gang-create: admitted PodGroup in the WAL, no pods
    op.manager.stop()
    job3 = make_tpujob("job3", workers=2, command=sleep_cmd(launch_log),
                       topology=topo)
    op.submit(job3)
    gang3 = op.gang.create_gang(job3)
    assert op.gang.try_admit(gang3)
    state = {
        "pods": running_pods(op.store),
        "gangs": {g.metadata.name: sorted(g.assigned_slices)
                  for g in op.store.list("PodGroup")},
        "launch_count": op.kubelet.launch_count,
    }
    print("STATE " + json.dumps(state), flush=True)
    os.kill(os.getpid(), signal.SIGKILL)  # the real thing: no teardown


def parent_main():
    ok = []

    def check(name, cond, detail=""):
        ok.append(bool(cond))
        print(("PASS" if cond else "FAIL"), name, detail)

    tmp = tempfile.mkdtemp(prefix="kdl-crash-drive-")
    wal_dir = os.path.join(tmp, "wal")
    launch_log = os.path.join(tmp, "launches.log")
    log_dir = os.path.join(tmp, "logs")
    child = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", wal_dir,
         launch_log, log_dir],
        capture_output=True, text=True, timeout=120,
    )
    check("child died by SIGKILL", child.returncode == -signal.SIGKILL,
          f"rc={child.returncode} stderr={child.stderr[-400:]}")
    state_lines = [l for l in child.stdout.splitlines()
                   if l.startswith("STATE ")]
    check("child reported pre-kill state", len(state_lines) == 1)
    if not state_lines:
        return finish(ok, tmp)
    state = json.loads(state_lines[0][6:])
    check("child had 4 running pods, one gang staged mid-create",
          len(state["pods"]) == 4 and state["gangs"].get("job3-gang"))

    t0 = time.perf_counter()
    op = Operator(
        OperatorOptions(local_addresses=True, wal_dir=wal_dir,
                        pod_log_dir=log_dir,
                        artifact_registry_root=os.path.join(tmp, "reg2")),
        runtime=SubprocessRuntime(log_dir), inventory=inventory(),
    )
    check("store rehydrated from WAL",
          op.store.rehydrated and op.store.replayed_records > 0,
          f"{op.store.replayed_records} records")
    op.start()
    try:
        op.wait_for_phase("TPUJob", "job3", JobConditionType.RUNNING,
                          timeout=RECOVERY_BUDGET_S)
        converged = op.manager.wait(
            lambda: len(running_pods(op.store)) == 6,
            timeout=RECOVERY_BUDGET_S)
        elapsed = time.perf_counter() - t0
        check("reconverged to 6 running pods", converged)
        check(f"time-to-reconverge under {RECOVERY_BUDGET_S:.0f}s",
              elapsed < RECOVERY_BUDGET_S, f"{elapsed:.2f}s")
        after = running_pods(op.store)
        check("every surviving pod adopted with its original uid",
              all(after.get(k) == uid for k, uid in state["pods"].items()),
              str({k: (state["pods"][k], after.get(k))
                   for k in state["pods"] if after.get(k) != state["pods"][k]}))
        check("adopted_count == 4", op.kubelet.adopted_count == 4,
              str(op.kubelet.adopted_count))
        check("new incarnation launched ONLY job3's pods",
              op.kubelet.launch_count == 2, str(op.kubelet.launch_count))
        # a pod is RUNNING the moment its process spawns, but the
        # fingerprint line lands only once the subprocess executes its
        # first statement — poll for all 6 before judging uniqueness
        # (the invariant under test is ZERO DUPLICATES, not exec speed)
        deadline = time.perf_counter() + 15.0
        lines = []
        while time.perf_counter() < deadline:
            lines = open(launch_log).read().split()
            if len(lines) >= 6:
                break
            time.sleep(0.1)
        check("zero duplicate launches across both incarnations",
              len(lines) == 6 and len(set(lines)) == 6, str(sorted(lines)))
        gangs = {g.metadata.name: sorted(g.assigned_slices)
                 for g in op.store.list("PodGroup")}
        check("identical gang slice assignments", gangs == state["gangs"],
              f"{gangs} vs {state['gangs']}")
        repinned = all(
            sorted(op.inventory.owned_slices(
                f"{g.metadata.namespace}/{g.metadata.name}"))
            == sorted(g.assigned_slices)
            for g in op.store.list("PodGroup"))
        check("slices re-reserved in the fresh inventory", repinned)
        phases = {n: op.store.get("TPUJob", n).status.phase
                  for n in ("job1", "job2", "job3")}
        check("all jobs RUNNING after recovery",
              all(p == JobConditionType.RUNNING for p in phases.values()),
              str(phases))
        rendered = op.render_metrics()
        check("recovery metrics exported",
              "kubedl_tpu_pods_adopted 4.0" in rendered
              and "kubedl_tpu_wal_replayed_records" in rendered
              and "kubedl_tpu_recovery_duration_seconds" in rendered)
    finally:
        op.stop()  # kills the adopted orphans too
    return finish(ok, tmp)


def finish(ok, tmp):
    shutil.rmtree(tmp, ignore_errors=True)
    print(f"\n{sum(ok)}/{len(ok)} checks passed")
    return 0 if all(ok) and ok else 1


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child_main(*sys.argv[2:5])
    else:
        sys.exit(parent_main())

"""Drive the double-buffered pipeline end to end over real HTTP:
serve_main-equivalent engine + handler, /v1/stats pipeline block,
/metrics Prometheus text, and the autoscaler consuming the REAL
dict-shaped http probe (qps + queued)."""
import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
os.environ["JAX_PLATFORMS"] = "cpu"
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

ok = []
def check(name, cond, detail=""):
    ok.append(bool(cond))
    print(("PASS" if cond else "FAIL"), name, detail)

from http.server import ThreadingHTTPServer
from kubedl_tpu.serving.server import LlamaEngine, make_handler

eng = LlamaEngine(preset="tiny", max_batch=4, max_seq=64)
srv = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(eng, "tiny"))
port = srv.server_address[1]
threading.Thread(target=srv.serve_forever, daemon=True).start()

def post(payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())

# concurrent load so segments + deferred harvests actually happen
threads = []
results = []
def go(n):
    results.append(post({"prompt_ids": [1, 2, n], "max_tokens": 24}))
for n in range(6):
    t = threading.Thread(target=go, args=(n,))
    t.start(); threads.append(t)
for t in threads:
    t.join()
check("6 concurrent HTTP generates complete",
      len(results) == 6 and all(len(r.get("token_ids", [])) == 24 for r in results))

with urllib.request.urlopen(f"http://127.0.0.1:{port}/v1/stats", timeout=10) as r:
    st = json.loads(r.read())
p = st.get("pipeline", {})
check("/v1/stats has pipeline accounting",
      p.get("segments", 0) >= 1 and "overlap_ratio" in p and "tick_ms_p50" in p,
      json.dumps({k: p.get(k) for k in ("ticks","segments","deferred_harvests","overlap_ratio")}))
check("pipeline actually double-buffered", p.get("deferred_harvests", 0) >= 1,
      f"deferred={p.get('deferred_harvests')}")
check("queued surfaced in stats", st.get("queued") == 0)

with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
    text = r.read().decode()
check("/metrics exports serving family",
      "kubedl_tpu_serving_segments" in text
      and "kubedl_tpu_serving_harvest_ms_bucket" in text
      and "kubedl_tpu_serving_overlap_ratio" in text)

# autoscaler consumes the REAL http probe (dict: qps + queued)
from kubedl_tpu.serving.controller import http_qps_probe
probe = http_qps_probe(port=port)
class FakePod:
    class status:
        pod_ip = "127.0.0.1"
v = probe(FakePod())
check("http probe returns full stats dict",
      isinstance(v, dict) and "qps" in v and "queued" in v,
      f"qps={v.get('qps')} queued={v.get('queued')}")

# injected failure mid-service, then engine keeps serving over HTTP
orig = eng._segment_fn
state = {"armed": True}
def boom(k, greedy):
    fn = orig(k, greedy)
    def w(*a, **kw):
        if state["armed"]:
            state["armed"] = False
            raise RuntimeError("injected")
        return fn(*a, **kw)
    return w
eng._segment_fn = boom
r1 = post({"prompt_ids": [9], "max_tokens": 8})
r2 = post({"prompt_ids": [9], "max_tokens": 8})
check("failure fails one request, next serves",
      "error" in r1 and len(r2.get("token_ids", [])) == 8,
      f"r1={r1.get('error','?')[:30]}")
with urllib.request.urlopen(f"http://127.0.0.1:{port}/v1/stats", timeout=10) as r:
    st2 = json.loads(r.read())
check("error accounted + pipeline counters reset",
      st2["pipeline"]["errors"] == 1 and st2["pipeline"]["inflight"] == 0)

srv.shutdown(); srv.server_close(); eng.close()
print(f"\n{sum(ok)}/{len(ok)} checks passed")
raise SystemExit(0 if all(ok) else 1)

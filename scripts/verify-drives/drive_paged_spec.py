"""Verify drive: paged KV-block allocator + speculative decoding (PR 8).

Drives the paged serving subsystem through the PUBLIC surface — real
LlamaEngines behind the real HTTP handler — and checks the contracts
docs/serving.md "Paged KV" / "Speculative decoding" promise:

  1. paged greedy outputs over HTTP are bit-identical to the contiguous
     engine (the exactness gate, end to end);
  2. /v1/stats carries kv_blocks accounting and the pool drains back to
     empty once every request finishes (no block leaks);
  3. /metrics serves the kubedl_tpu_serving_kv_* gauge family;
  4. speculative decoding (ngram draft-k/verify-1) stays bit-identical
     over HTTP and reports acceptance in /v1/stats + /metrics;
  5. block exhaustion below the low watermark sheds with a REAL HTTP
     503 + Retry-After, and admission recovers once blocks free;
  6. the serving.kv_alloc chaos site forces the preempt-and-requeue
     path with outputs still exact and kv_preemptions counted;
  7. prefix-cache entries share row blocks by reference (shared>0 while
     cached, refs returned on reclaim);
  8. KUBEDL_SERVE_CONFIG plumbing (kv_layout/kv_blocks/spec_k reach
     engine_kwargs, paged is the serve default);
  9. block-table host overhead stays under the tier-1 budget.

Run: python scripts/verify-drives/drive_paged_spec.py  (CPU-forced, ~90s)
"""

import json
import os
import sys
import threading
import urllib.error
import urllib.request
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested  # noqa: E402

ensure_cpu_if_requested()

CHECKS = []


def check(name, ok, detail=""):
    CHECKS.append((name, bool(ok), detail))
    print(f"  [{'ok' if ok else 'FAIL'}] {name}" + (f" — {detail}" if detail else ""))


def post(port, payload, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/{path.lstrip('/')}", timeout=30
    ) as resp:
        return resp.read()


def serve(eng, name):
    import http.server

    from kubedl_tpu.serving.server import make_handler

    srv = http.server.ThreadingHTTPServer(
        ("127.0.0.1", 0), make_handler(eng, name)
    )
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]


def main():
    from kubedl_tpu.serving.server import LlamaEngine, engine_kwargs

    prompts = [[5, 9, 13], [1, 2, 3, 4, 5, 6, 7], [7, 7, 7], [42]]
    n_tok = 8

    print("== contiguous reference ==")
    ref = LlamaEngine(preset="tiny", max_seq=64, max_batch=2,
                      kv_layout="contiguous", prefix_cache_mb=0)
    try:
        want = [ref.generate(p, max_tokens=n_tok)["token_ids"]
                for p in prompts]
    finally:
        ref.close()

    print("== paged engine over HTTP ==")
    eng = LlamaEngine(preset="tiny", max_seq=64, max_batch=2,
                      kv_layout="paged", kv_block_size=8, prefix_cache_mb=0)
    srv, port = serve(eng, "tiny")
    try:
        got = [post(port, {"prompt_ids": p, "max_tokens": n_tok})
               for p in prompts]
        check("paged greedy outputs bit-identical to contiguous over HTTP",
              [r["token_ids"] for r in got] == want)
        stats = json.loads(get(port, "/v1/stats"))
        kv = stats.get("kv_blocks") or {}
        check("/v1/stats kv_blocks: pool drained, allocs counted",
              kv.get("used") == 0 and kv.get("allocs", 0) > 0
              and kv.get("free") == kv.get("total"),
              f"used={kv.get('used')} free={kv.get('free')}"
              f"/{kv.get('total')} allocs={kv.get('allocs')}")
        metrics = get(port, "/metrics").decode()
        check("/metrics serves kubedl_tpu_serving_kv_* family",
              all(f"kubedl_tpu_serving_kv_{m}" in metrics
                  for m in ("blocks_total", "blocks_free", "blocks_shared")))
    finally:
        srv.shutdown()
        eng.close()

    print("== speculative engine over HTTP ==")
    spec = LlamaEngine(preset="tiny", max_seq=64, max_batch=2,
                       kv_layout="paged", spec_k=4, spec_draft="ngram",
                       prefix_cache_mb=0)
    srv, port = serve(spec, "tiny")
    try:
        got = [post(port, {"prompt_ids": p, "max_tokens": n_tok})
               for p in prompts]
        check("speculative greedy outputs bit-identical over HTTP",
              [r["token_ids"] for r in got] == want)
        stats = json.loads(get(port, "/v1/stats"))
        sp = stats.get("speculative") or {}
        check("/v1/stats speculative: verifies>0, acceptance reported",
              sp.get("verifies", 0) > 0 and "acceptance_rate" in sp,
              f"verifies={sp.get('verifies')} "
              f"acc={sp.get('acceptance_rate')} "
              f"tok/verify={sp.get('tokens_per_verify')}")
        metrics = get(port, "/metrics").decode()
        check("/metrics serves kubedl_tpu_serving_spec_* family",
              all(f"kubedl_tpu_serving_spec_{m}" in metrics
                  for m in ("tokens_proposed", "tokens_accepted",
                            "acceptance_rate")))
    finally:
        srv.shutdown()
        spec.close()

    print("== block exhaustion: 503 + Retry-After, then recovery ==")
    # 11 usable blocks, watermarks 0.2/0.5: draining the pool closes
    # admission; freeing past the high watermark reopens it
    small = LlamaEngine(preset="tiny", max_seq=64, max_batch=2,
                        kv_layout="paged", kv_block_size=8, kv_blocks=12,
                        kv_low_watermark=0.2, kv_high_watermark=0.5,
                        prefix_cache_mb=0)
    srv, port = serve(small, "tiny")
    try:
        held = small._alloc.alloc(small._alloc.free_count)
        code, retry_after = 0, None
        try:
            post(port, {"prompt_ids": [5, 9], "max_tokens": 2}, timeout=30)
        except urllib.error.HTTPError as e:
            code = e.code
            retry_after = e.headers.get("Retry-After")
            e.read()
        check("pool below low watermark sheds with HTTP 503 + Retry-After",
              code == 503 and retry_after is not None,
              f"code={code} Retry-After={retry_after}")
        small._alloc.free(held)
        r = post(port, {"prompt_ids": [5, 9], "max_tokens": 2})
        check("admission recovers once blocks free past the high watermark",
              len(r.get("token_ids", [])) == 2
              and json.loads(get(port, "/v1/stats"))["kv_sheds"] >= 1)
    finally:
        srv.shutdown()
        small.close()

    print("== chaos serving.kv_alloc: preempt-and-requeue stays exact ==")
    from kubedl_tpu import chaos

    vict = LlamaEngine(preset="tiny", max_seq=64, max_batch=2,
                       kv_layout="paged", kv_block_size=8,
                       prefix_cache_mb=0)
    try:
        plan = chaos.FaultPlan(
            seed=3, sites={"serving.kv_alloc": [chaos.FaultSpec.nth(1)]}
        )
        outs = [None, None]

        def worker(i):
            outs[i] = vict.generate(prompts[i], max_tokens=n_tok)

        with plan:
            ts = [threading.Thread(target=worker, args=(i,))
                  for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=120)
        check("outputs exact through an injected reservation failure",
              [r["token_ids"] for r in outs] == want[:2]
              and plan.faults("serving.kv_alloc") == 1
              and vict.stats()["kv_blocks"]["used"] == 0,
              f"faults={plan.faults('serving.kv_alloc')}")
    finally:
        vict.close()

    # In the plain segment path the double-buffered pipeline keeps every
    # co-resident row at pending>0 when the reserve runs, so a failing
    # row finds no victim and DEFERS (the check above). The speculative
    # path harvests synchronously — co-resident rows sit at pending==0
    # and are eligible victims, so an injected reservation failure on
    # the first-processed row deterministically preempts the other.
    spec2 = LlamaEngine(preset="tiny", max_seq=64, max_batch=2,
                        kv_layout="paged", kv_block_size=8, spec_k=4,
                        spec_draft="ngram", prefix_cache_mb=0)
    try:
        sprompts = [[5, 9, 13], [1, 2, 3]]
        sw = [spec2.generate(p, max_tokens=24)["token_ids"]
              for p in sprompts]
        plan = chaos.FaultPlan(
            seed=5, sites={"serving.kv_alloc": [chaos.FaultSpec.nth(4)]}
        )
        outs = [None, None]

        def sworker(i):
            outs[i] = spec2.generate(sprompts[i], max_tokens=24,
                                     timeout_s=120)

        with plan:
            ts = [threading.Thread(target=sworker, args=(i,))
                  for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=180)
        st = spec2.stats()
        check("spec-path reserve failure preempts-and-requeues the "
              "youngest row with exact outputs",
              [r["token_ids"] for r in outs] == sw
              and plan.faults("serving.kv_alloc") == 1
              and st["kv_preemptions"] >= 1
              and st["kv_blocks"]["used"] == 0,
              f"preemptions={st['kv_preemptions']} "
              f"faults={plan.faults('serving.kv_alloc')}")
    finally:
        spec2.close()

    print("== prefix entries share blocks by reference ==")
    pfx = LlamaEngine(preset="tiny", max_seq=64, max_batch=2,
                      kv_layout="paged", kv_block_size=4,
                      prefix_cache_mb=8, prefix_min_len=4)
    try:
        head = [3, 4, 5, 6, 7, 8, 9, 10]
        pfx.generate(head + [99], max_tokens=2, cache_prefix=True)
        st = pfx.stats()["kv_blocks"]
        check("cached prefix holds block refs (used>0, shared after hit)",
              st["used"] > 0, f"used={st['used']} shared={st['shared']}")
        r = pfx.generate(head + [77], max_tokens=2)
        check("second request grafted the shared-prefix blocks",
              r.get("cached_prefix_len", 0) >= 4,
              f"cached_prefix_len={r.get('cached_prefix_len')}")
        pfx._pcache.reclaim(10 ** 9)
        check("reclaim returns entry refs to the allocator",
              pfx.stats()["kv_blocks"]["used"] == 0)
    finally:
        pfx.close()

    print("== config plumbing + host-overhead budget ==")
    kw = engine_kwargs({"kv_blocks": 40, "spec_k": 4}, "")
    check("KUBEDL_SERVE_CONFIG kv/spec knobs reach engine_kwargs "
          "(paged is the serve default)",
          kw.get("kv_layout") == "paged" and kw.get("kv_blocks") == 40
          and kw.get("spec_k") == 4
          and engine_kwargs({}, "").get("kv_block_size") == 16)
    from scripts.scheduler_microbench import run_paged_microbench

    mb = run_paged_microbench(requests=8, max_tokens=16)
    check("block-table host overhead within tier-1 budget, no leaks",
          mb["within_budget"] and mb["blocks_leaked"] == 0,
          f"tick_p50={mb['tick_ms_p50']}ms "
          f"mirror_upload={mb['mirror_upload_ms']}ms")

    failed = [c for c in CHECKS if not c[1]]
    print(f"\n{len(CHECKS) - len(failed)}/{len(CHECKS)} checks passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

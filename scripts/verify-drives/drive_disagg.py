"""Drive the disaggregated prefill/decode fleet end to end: four REAL
engine replicas as subprocesses (`python -m kubedl_tpu.serving.server`)
— one prefill, two decode, one colocated — with the role-aware router in
front, and a seeded FaultPlan choosing the moment a DECODE replica is
SIGKILLed under client load. Acceptance (docs/serving.md "Disaggregated
serving"): the router partitions the fleet into role pools, two-leg
disagg dispatch produces greedy output bit-identical to a direct
colocated call, zero requests are lost when a decode replica dies
mid-load (the survivor or the colocated fallback absorbs them), and a
full decode-pool outage degrades to colocated fallback — never a
fleet-wide 503."""
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
os.environ["JAX_PLATFORMS"] = "cpu"
from kubedl_tpu.utils.jaxenv import ensure_cpu_if_requested
ensure_cpu_if_requested()

ok = []
def check(name, cond, detail=""):
    ok.append(bool(cond))
    print(("PASS" if cond else "FAIL"), name, detail)

from kubedl_tpu import chaos
from kubedl_tpu.chaos import FaultPlan, FaultSpec
from kubedl_tpu.serving import router_policy as policy
from kubedl_tpu.serving.router import ServingRouter

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def spawn_replica(port, role):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["KUBEDL_SERVE_CONFIG"] = json.dumps({
        "preset": "tiny", "port": port, "max_batch": 2, "role": role,
        "handoff_ttl_s": 20.0,
    })
    env.pop("KUBEDL_MODEL_PATH", None)
    return subprocess.Popen(
        [sys.executable, "-m", "kubedl_tpu.serving.server"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def wait_healthy(port, timeout=180.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=2
            ) as r:
                if r.status == 200:
                    return True
        except Exception:
            time.sleep(0.3)
    return False


def post_generate(port, body, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


ROLES = {"p0": "prefill", "d0": "decode", "d1": "decode",
         "c0": "colocated"}
ports = {n: free_port() for n in ROLES}
procs = {n: spawn_replica(ports[n], ROLES[n]) for n in ROLES}
try:
    up = all(wait_healthy(p) for p in ports.values())
    check("4 engine replicas come up (1 prefill / 2 decode / 1 colocated)",
          up)
    if not up:
        raise SystemExit(1)

    router = ServingRouter(
        [{"name": n, "host": "127.0.0.1", "port": ports[n],
          "role": ROLES[n], "model": "tiny"} for n in sorted(ROLES)],
        probe_interval_s=0.2, probe_timeout_s=1.0,
        eject_threshold=3, readmit_cooldown_s=1.0,
        max_retries=1, default_deadline_ms=30_000.0,
        disagg_enabled=True,
    )
    router.start()
    router.probe_once()

    pools = router.stats()["pools"]
    check("router partitions the fleet into role pools",
          pools == {"prefill": 1, "decode": 2, "colocated": 1},
          f"pools={pools}")

    # -- two-leg dispatch must never change RESULTS -----------------------
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    direct = post_generate(ports["c0"], {"prompt_ids": prompt,
                                         "max_tokens": 8,
                                         "temperature": 0.0})
    code, via, _ = router.handle_generate(
        {"prompt_ids": prompt, "max_tokens": 8, "temperature": 0.0})
    m = router.metrics
    check("disagg greedy output bit-identical to direct colocated call",
          code == 200 and via["token_ids"] == direct["token_ids"]
          and m.disagg_requests.value() >= 1,
          f"direct={direct['token_ids']} routed={via.get('token_ids')} "
          f"disagg_requests={m.disagg_requests.value()}")

    # -- SIGKILL one decode replica under load, moment seeded -------------
    N = 32
    plan = FaultPlan(seed=12, sites={"replica.kill": [FaultSpec.nth(7)]})
    victim = "d0"
    results = [None] * N
    killed_at = {"i": None}

    def client(i):
        body = {"prompt_ids": [(i % 5) + 2] * 8 + [100 + i],
                "max_tokens": 4, "temperature": 0.0}
        code, payload, _ = router.handle_generate(body, deadline_ms=25_000)
        results[i] = (code, payload)

    threads = []
    with plan:
        for i in range(N):
            if chaos.should_fail("replica.kill"):
                killed_at["i"] = i
                procs[victim].send_signal(signal.SIGKILL)
            t = threading.Thread(target=client, args=(i,), daemon=True)
            t.start()
            threads.append(t)
            time.sleep(0.03)
        for t in threads:
            t.join(timeout=40)
    check("seeded plan injected exactly one decode kill",
          plan.faults("replica.kill") == 1 and killed_at["i"] == 6,
          f"killed before request #{killed_at['i']}")

    codes = [r[0] for r in results if r is not None]
    lost = N - len(codes)
    failures = [c for c in codes if c != 200]
    check("zero lost requests across the decode-replica kill",
          lost == 0 and not failures,
          f"lost={lost} non200={failures[:5]}")

    # -- full decode-pool outage: degrade to colocated, never 503 ---------
    procs["d1"].send_signal(signal.SIGKILL)
    deadline = time.time() + 15
    while time.time() < deadline:
        router.probe_once()
        st = router.stats()["replicas"]
        if (st["d0"]["state"] == policy.OPEN
                and st["d1"]["state"] == policy.OPEN):
            break
        time.sleep(0.2)
    check("mid-flight adopt-leg failure fell back within the request",
          m.disagg_fallbacks.value() >= 1,
          f"fallbacks={m.disagg_fallbacks.value()}")
    disagg_before = m.disagg_requests.value()
    okc = 0
    for i in range(8):
        code, _, _ = router.handle_generate(
            {"prompt_ids": [40 + i] * 8, "max_tokens": 2,
             "temperature": 0.0}, deadline_ms=25_000)
        okc += (code == 200)
    check("decode-pool outage degrades to colocated fallback, not 503",
          okc == 8 and m.disagg_requests.value() == disagg_before,
          f"ok={okc} disagg_delta="
          f"{m.disagg_requests.value() - disagg_before}")

    router.stop()
finally:
    for p in procs.values():
        try:
            p.send_signal(signal.SIGKILL)
        except Exception:
            pass

print(f"\n{sum(ok)}/{len(ok)} checks passed")
sys.exit(0 if all(ok) else 1)

"""Drive sharded-control-plane failover with a REAL SIGKILL across
process boundaries (docs/architecture.md "Sharded control plane"):

1. two child processes run the real multi-shard control plane —
   :class:`ShardedObjectStore` (2 shards, shared WAL root, ``fsync=
   "group"`` with a 5ms commit window — the PR 19 group-commit path,
   so the SIGKILL lands while a committer thread owns durability),
   flock-backed :class:`FileLeaseStore`, the real
   :class:`ControllerManager` with per-shard workqueues and a 20ms
   reconcile coalescing window — churning jobs through a create-pods/
   observe/tear-down reconcile loop. Owner A holds shard 0; owner B
   holds shard 1 AND stands by for shard 0. Every pod "launch" appends
   its name to a shared launches.log AFTER the create was acknowledged
   (group commit acks only after the batched fsync covering the
   record), so a duplicate create by any incarnation shows up as a
   duplicate line;
2. the driver SIGKILLs A mid-churn — no teardown, lease unreleased, WAL
   handle dead, staged-but-unacked records torn away with the process —
   and asserts: B's standby campaign wins shard 0 within ~the lease
   TTL, B drains every job A left behind (rehydrate-then-adopt over A's
   WAL segment), launches.log holds ZERO duplicates (an acked create
   that replayed twice, or a lost acked create re-launched by B, would
   both show), B's own shard 1 never stalls through the whole window,
   and the survivor's WAL really amortized — fewer fsyncs than appends
   and at least one reconcile coalesced under the churn bursts.

Run with `python scripts/verify-drives/drive_shards.py`
(CPU only; control plane only — no jax needed).
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

LEASE_TTL = 1.0
#: expiry (ttl) + standby campaign beat (ttl/3) + scheduling slop
TAKEOVER_BUDGET_S = LEASE_TTL * 4 + 2.0
PODS_PER_JOB = 3
MAX_INFLIGHT = 12


def _write_status(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(json.dumps(payload))
    os.replace(tmp, path)


def _read_status(path):
    try:
        with open(path) as fh:
            return json.loads(fh.read())
    except (OSError, ValueError):
        return None


class DriveReconciler:
    """Job -> pods churn: create missing pods (fingerprinting each
    launch AFTER its create is durable), then tear the job down."""

    def __init__(self, store, launch_log):
        import threading

        self.store = store
        self.launch_log = launch_log
        self.completed = {0: 0, 1: 0}
        self._done = set()
        self._lock = threading.Lock()

    def reconcile(self, namespace, name):
        from kubedl_tpu.core.objects import OwnerRef, Pod
        from kubedl_tpu.core.store import AlreadyExists

        job = self.store.try_get("TPUJob", name, namespace)
        if job is None:
            return
        missing = [
            k for k in range(PODS_PER_JOB)
            if self.store.try_get("Pod", f"{name}-p{k}", namespace) is None
        ]
        if missing:
            for k in missing:
                pod = Pod()
                pod.metadata.name = f"{name}-p{k}"
                pod.metadata.namespace = namespace
                pod.metadata.owner_refs.append(OwnerRef(
                    kind="TPUJob", name=name, uid=job.metadata.uid,
                    controller=True,
                ))
                try:
                    self.store.create(pod)
                except AlreadyExists:
                    continue
                # fingerprint AFTER the create is durable in the WAL: a
                # re-create by any incarnation duplicates the line
                with open(self.launch_log, "a") as fh:
                    fh.write(pod.metadata.name + "\n")
            return  # pod ADDED events re-queue this key
        # the JOB delete is the durable completion marker and goes first:
        # a crash after it leaves orphan pods for the GC, never a pod-less
        # job a successor would re-launch
        self.store.try_delete("TPUJob", name, namespace)
        for k in range(PODS_PER_JOB):
            self.store.try_delete("Pod", f"{name}-p{k}", namespace)
        uid = job.metadata.uid
        with self._lock:
            if uid not in self._done:
                self._done.add(uid)
                shard = self.store.shard_for_key(namespace, name)
                self.completed[shard] += 1


def child_main(role, wal_root, lease_dir, launch_log, status_path):
    from kubedl_tpu.core.manager import ControllerManager, owner_mapper
    from kubedl_tpu.shards import FileLeaseStore, ShardedObjectStore
    from kubedl_tpu.workloads.tpujob import TPUJob

    my_shard = 0 if role == "a" else 1
    store = ShardedObjectStore(
        shards=2, wal_dir=wal_root, wal_fsync="group",
        wal_group_window=0.005,
        wal_snapshot_every=1_000_000_000,
        lease_backend=FileLeaseStore(lease_dir),
        identity=f"owner-{role}", lease_ttl=LEASE_TTL,
        own=[my_shard], standby=[0] if role == "b" else [],
        fence_verify_interval=0.05,
    )
    reconciler = DriveReconciler(store, launch_log)
    manager = ControllerManager(store=store)
    manager.register(
        "drive", reconciler.reconcile, watch_kinds=["TPUJob", "Pod"],
        mapper=owner_mapper("TPUJob"), workers=2, coalesce_window=0.02,
    )
    manager.start()
    store.start_campaigns()

    submitted = 0
    i = 0
    while True:  # churn forever; the driver owns this process's death
        name = f"{role}-{i:05d}"
        i += 1
        if store.shard_for_key("default", name) != my_shard:
            continue
        job = TPUJob()
        job.metadata.name = name
        job.metadata.namespace = "default"
        store.create(job)
        submitted += 1
        while submitted - sum(reconciler.completed.values()) > MAX_INFLIGHT:
            time.sleep(0.005)
        remaining0 = 0
        if role == "b" and store.takeovers:
            remaining0 = sum(
                1 for j in store.list("TPUJob")
                if store.shard_for_key("default", j.metadata.name) == 0
            )
        _write_status(status_path, {
            "submitted": submitted,
            "completed0": reconciler.completed[0],
            "completed1": reconciler.completed[1],
            "takeovers": store.takeovers,
            "remaining0": remaining0,
            "wal_appends": store.wal_appends,
            "wal_fsyncs": store.wal_fsyncs,
            "coalesced": manager.coalesced_reconciles,
        })


def parent_main():
    ok = []

    def check(name, cond, detail=""):
        ok.append(bool(cond))
        print(("PASS" if cond else "FAIL"), name, detail)

    def poll(status_path, pred, timeout):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            st = _read_status(status_path)
            if st is not None and pred(st):
                return st
            time.sleep(0.05)
        return _read_status(status_path)

    tmp = tempfile.mkdtemp(prefix="kdl-shards-drive-")
    wal_root = os.path.join(tmp, "wal")
    lease_dir = os.path.join(tmp, "leases")
    launch_log = os.path.join(tmp, "launches.log")
    open(launch_log, "w").close()
    status = {r: os.path.join(tmp, f"status_{r}.json") for r in ("a", "b")}
    procs = {}
    try:
        for role in ("a", "b"):
            procs[role] = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--child", role,
                 wal_root, lease_dir, launch_log, status[role]],
                stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
            )
        st_a = poll(status["a"], lambda s: s["completed0"] >= 15, 60.0)
        st_b = poll(status["b"], lambda s: s["completed1"] >= 15, 60.0)
        check("both owners churning through their shards",
              st_a and st_b and st_a["completed0"] >= 15
              and st_b["completed1"] >= 15, f"a={st_a} b={st_b}")
        if not (st_a and st_b):
            return finish(ok, tmp, procs)
        check("owner A killed mid-churn (jobs in flight)",
              st_a["submitted"] > st_a["completed0"], str(st_a))
        b_before = st_b["completed1"]

        t_kill = time.perf_counter()
        procs["a"].send_signal(signal.SIGKILL)
        procs["a"].wait(timeout=10)
        check("A died by SIGKILL, lease unreleased",
              procs["a"].returncode == -signal.SIGKILL)

        st_b = poll(status["b"], lambda s: s["takeovers"] >= 1,
                    TAKEOVER_BUDGET_S + 5.0)
        elapsed = time.perf_counter() - t_kill
        check("standby B took over shard 0", st_b and st_b["takeovers"] == 1,
              str(st_b))
        check(f"takeover within ~lease TTL (<{TAKEOVER_BUDGET_S:.0f}s)",
              elapsed < TAKEOVER_BUDGET_S, f"{elapsed:.2f}s")

        # a third campaigner cannot steal the shard from live owner B
        from kubedl_tpu.shards import FileLeaseStore, acquire_shard_lease

        check("live takeover lease is not stealable",
              acquire_shard_lease(FileLeaseStore(lease_dir), 0, "driver",
                                  ttl=LEASE_TTL) is None)

        st_b = poll(
            status["b"],
            lambda s: s["takeovers"] >= 1 and s["remaining0"] == 0
            and s["completed0"] > 0,
            60.0,
        )
        check("B drained every job A left behind",
              st_b and st_b["remaining0"] == 0 and st_b["completed0"] > 0,
              str(st_b))
        check("surviving shard 1 never stalled",
              st_b and st_b["completed1"] > b_before,
              f"{b_before} -> {st_b and st_b['completed1']}")

        check("group commit amortized the survivor's WAL",
              st_b and st_b["wal_fsyncs"] < st_b["wal_appends"],
              f"{st_b and st_b['wal_fsyncs']} fsyncs for "
              f"{st_b and st_b['wal_appends']} appends")
        check("churn bursts coalesced at least one reconcile",
              st_b and st_b["coalesced"] >= 1,
              f"coalesced={st_b and st_b['coalesced']}")

        lines = [l for l in open(launch_log).read().splitlines() if l]
        check("zero duplicate launches across both owners",
              len(lines) == len(set(lines)),
              f"{len(lines)} launches, "
              f"{len(lines) - len(set(lines))} duplicates")
        check("launch volume sane for the churn",
              len(lines) >= (st_a["completed0"] + st_b["completed1"])
              * PODS_PER_JOB, str(len(lines)))
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    return finish(ok, tmp, procs)


def finish(ok, tmp, procs):
    for role, p in procs.items():
        if p.stderr is not None and p.returncode not in (None, -signal.SIGKILL):
            err = p.stderr.read()[-400:]
            if err:
                print(f"--- child {role} stderr ---\n{err}")
    shutil.rmtree(tmp, ignore_errors=True)
    print(f"\n{sum(ok)}/{len(ok)} checks passed")
    return 0 if all(ok) and ok else 1


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child_main(*sys.argv[2:7])
    else:
        sys.exit(parent_main())

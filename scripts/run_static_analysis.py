#!/usr/bin/env python3
"""CLI wrapper for the project static analyzer (same as
``python -m kubedl_tpu.analysis``; rule catalog: docs/static-analysis.md).

    python scripts/run_static_analysis.py [--no-baseline] [--write-baseline]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubedl_tpu.analysis.engine import run  # noqa: E402

if __name__ == "__main__":
    sys.exit(run())

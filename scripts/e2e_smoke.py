#!/usr/bin/env python3
"""Post-cluster half of the e2e recipe, shared between the kind lane
(scripts/kind-e2e.sh) and the always-on boot test
(tests/test_deploy_boot.py).

Mirrors the reference's CI job body (/root/reference/.github/workflows/
ci.yaml e2e-tests + scripts/run_tf_test_job.sh): against an ALREADY
RUNNING operator console, submit a small distributed TFJob and wait for
a terminal phase. The caller decides what the operator runs on — a kind
cluster behind a port-forward, or the subprocess operator booted from
the rendered Deployment's own argv.

Usage: python scripts/e2e_smoke.py [base_url] [timeout_s]
Exits 0 on Succeeded, 1 on Failed, 2 on timeout/transport errors.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request


SMOKE_JOB = {
    "kind": "TFJob",
    "metadata": {"name": "e2e-smoke", "namespace": "default"},
    "spec": {"replica_specs": {"Worker": {
        "replicas": 2,
        "template": {"spec": {"containers": [{
            "name": "main",
            # bare "python": must resolve inside the kind-deployed image
            # AND on the host subprocess runtime — the submitting host's
            # sys.executable would not exist in the container
            "command": ["python", "-c",
                        "import os, json; json.loads(os.environ['TF_CONFIG'])"],
        }]}},
    }}},
}


def run_smoke(base_url: str, timeout: float = 120.0) -> int:
    req = urllib.request.Request(
        f"{base_url}/api/v1/job/submit",
        data=json.dumps(SMOKE_JOB).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        if r.status != 200:
            print(f"submit: HTTP {r.status}", file=sys.stderr)
            return 2
    deadline = time.time() + timeout
    while time.time() < deadline:
        with urllib.request.urlopen(
            f"{base_url}/api/v1/job/list?kind=TFJob", timeout=10
        ) as r:
            jobs = json.loads(r.read())["data"]["jobInfos"]
        phase = next(
            (j["phase"] for j in jobs if j["name"] == "e2e-smoke"), ""
        )
        if phase in ("Succeeded", "Failed"):
            print("terminal phase:", phase)
            return 0 if phase == "Succeeded" else 1
        time.sleep(1)
    print("timeout waiting for e2e-smoke", file=sys.stderr)
    return 2


if __name__ == "__main__":
    base = sys.argv[1] if len(sys.argv) > 1 else "http://127.0.0.1:9090"
    t = float(sys.argv[2]) if len(sys.argv) > 2 else 120.0
    sys.exit(run_smoke(base, t))

#!/usr/bin/env python
"""Cross-check README headline performance numbers against the committed
bench artifact (VERDICT r5 #4: the table quoted driver-capture numbers no
artifact in the repo could reproduce).

Every number in the README performance table must be recomputable from
`BENCH_r05_builder.json` — the script derives the expected display strings
from the artifact's `summary{}` (and per-run `targets` medians for the
serving/long-context rows) and fails if the README does not contain them.
Run directly (`python scripts/check_readme_numbers.py`) or via tier-1
(`tests/test_chaos.py::TestReadmeNumbers`).
"""

from __future__ import annotations

import json
import statistics
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
ARTIFACT = "BENCH_r05_builder.json"
#: prefix-cache serving row (r6): separate artifact, same runs[] shape
PREFIX_ARTIFACT = "BENCH_r06_prefix.json"
#: router availability row (r7): separate artifact, same runs[] shape
ROUTER_ARTIFACT = "BENCH_r07_router.json"
#: paged-KV + speculative rows (r8): separate artifact, same runs[] shape
PAGED_ARTIFACT = "BENCH_r08.json"
#: auto-parallelism planner row (r9): separate artifact, same runs[] shape
PLANNER_ARTIFACT = "BENCH_r09_planner.json"
#: sharded weight update + overlap row (r10): separate artifact, same
#: runs[] shape (CPU proxy — see docs/performance.md)
TRAINING_ARTIFACT = "BENCH_r10_training.json"
#: blocked paged-attention decode + model-draft row (r11): separate
#: artifact, same runs[] shape (CPU proxy — see docs/serving.md).
#: r16 repointed this at BENCH_r16_decode.json without committing the
#: artifact (its chunked-TTFT gate does not pass on this box), which
#: broke the tier-1 README gate at HEAD — the pointer stays on the
#: last committed artifact until a passing r16 artifact lands.
DECODE_ARTIFACT = "BENCH_r11_decode.json"
#: disaggregated prefill/decode fleet row (r12): separate artifact, same
#: runs[] shape (CPU proxy — see docs/serving.md)
DISAGG_ARTIFACT = "BENCH_r12_disagg.json"
#: tracing-overhead row (r13): separate artifact, same runs[] shape
#: (CPU proxy — see docs/observability.md)
TRACING_ARTIFACT = "BENCH_r13_tracing.json"
#: parameter-service preemption-storm row (r15): separate artifact, same
#: runs[] shape (CPU proxy — see docs/elasticity.md)
PS_ARTIFACT = "BENCH_r15_ps.json"
#: model-lifecycle hot-swap/canary row (r17): separate artifact, same
#: runs[] shape (CPU proxy — see docs/serving.md)
ROLLOUT_ARTIFACT = "BENCH_r17_rollout.json"
#: sharded control-plane churn-replay row (r18): separate artifact, same
#: runs[] shape (CPU proxy — see docs/architecture.md)
SHARDS_ARTIFACT = "BENCH_r18_shards.json"
#: control-plane scaling-efficiency row (r19): separate artifact, same
#: runs[] shape (group commit + coalescing — see docs/architecture.md)
CP_SCALE_ARTIFACT = "BENCH_r19_cp_scale.json"
#: multi-operator federation row (r20): separate artifact, same runs[]
#: shape (cross-process failover — see docs/architecture.md)
FEDERATION_ARTIFACT = "BENCH_r20_federation.json"


def _runs_median(runs, *path) -> float:
    vals = []
    for r in runs:
        v = r.get("detail", {})
        for k in path:
            v = v.get(k) if isinstance(v, dict) else None
            if v is None:
                break
        if v is not None:
            vals.append(float(v))
    if not vals:
        raise KeyError(f"no run carries {'.'.join(path)}")
    return statistics.median(vals)


def expected_strings(artifact: dict) -> dict:
    """README display string -> how it was derived (for error messages)."""
    s = artifact["summary"]
    runs = artifact["runs"]
    tgt = ("targets",)
    out = {
        f"{round(s['tokens_per_sec_per_chip']['median']):,} tokens/s/chip":
            "summary.tokens_per_sec_per_chip.median",
        f"MFU median {s['mfu']['median'] * 100:.1f}%":
            "summary.mfu.median",
        f"{_runs_median(runs, 'step_time_ms'):.1f} ms/step":
            "median of runs[].detail.step_time_ms",
        f"{s['startup_cold_s']['median']:.1f} s / {s['startup_warm_s']['median']:.1f} s":
            "summary.startup_cold_s/startup_warm_s medians",
        f"MFU median {s['long_context_mfu']['median'] * 100:.1f}%":
            "summary.long_context_mfu.median",
        f"{_runs_median(runs, *tgt, 'long_context', 'tokens_per_sec_per_chip') / 1000:.1f}k tokens/s/chip":
            "median of runs[].targets.long_context.tokens_per_sec_per_chip",
        # serving decode medians (bf16 -> int8), tokens/s
        "{:.0f}/{:.0f} -> {:.0f}/{:.0f}".format(
            _runs_median(runs, *tgt, "serving", "decode_tokens_per_sec_b1"),
            _runs_median(runs, *tgt, "serving", "decode_tokens_per_sec_b8"),
            _runs_median(runs, *tgt, "serving", "decode_tokens_per_sec_b1_int8"),
            _runs_median(runs, *tgt, "serving", "decode_tokens_per_sec_b8_int8"),
        ): "medians of runs[].targets.serving.decode_tokens_per_sec_*",
        # serving-engine medians (only runs that carry the target)
        f"{_runs_median(runs, *tgt, 'serving_engine', 'engine_decode_ms_per_token_b1'):.2f} ms/token":
            "median of runs[].targets.serving_engine.engine_decode_ms_per_token_b1",
        f"+{_runs_median(runs, *tgt, 'serving_engine', 'engine_overhead_vs_raw_b1_pct'):.1f}% over raw":
            "median of runs[].targets.serving_engine.engine_overhead_vs_raw_b1_pct",
        f"TTFT median {_runs_median(runs, *tgt, 'serving_engine', 'engine_ttft_64_prompt_ms'):.1f} ms":
            "median of runs[].targets.serving_engine.engine_ttft_64_prompt_ms",
    }
    return out


def expected_prefix_strings(artifact: dict) -> dict:
    """README prefix-cache row strings derived from BENCH_r06_prefix.json."""
    runs = artifact["runs"]
    tgt = ("targets", "prefix_reuse")
    off = _runs_median(runs, *tgt, "ttft_ms_p50_cache_off")
    on = _runs_median(runs, *tgt, "ttft_ms_p50_cache_on")
    saved = _runs_median(runs, *tgt, "tokens_saved")
    return {
        f"TTFT p50 **{off:.2f} -> {on:.2f} ms**":
            "medians of runs[].targets.prefix_reuse.ttft_ms_p50_cache_*",
        f"{off / on:.2f}x":
            "ratio of the ttft_ms_p50_cache_off/_on medians",
        f"{saved:,.0f} prefill tokens saved":
            "median of runs[].targets.prefix_reuse.tokens_saved",
    }


def expected_router_strings(artifact: dict) -> dict:
    """README router row strings derived from BENCH_r07_router.json."""
    runs = artifact["runs"]
    tgt = ("targets", "router_availability")
    avail = _runs_median(runs, *tgt, "availability_pct")
    lost = _runs_median(runs, *tgt, "lost")
    burst = _runs_median(runs, *tgt, "error_burst")
    reqs = _runs_median(runs, *tgt, "requests")
    readmit = _runs_median(runs, *tgt, "readmit_after_restart_ms")
    return {
        f"**{avail:.0f}%** availability":
            "median of runs[].targets.router_availability.availability_pct",
        f"{lost:.0f} lost / {burst:.0f} errored of {reqs:.0f} requests":
            "medians of runs[].targets.router_availability.lost/error_burst/requests",
        f"breaker readmit **{readmit / 1000:.1f} s** after restart":
            "median of runs[].targets.router_availability.readmit_after_restart_ms",
    }


def expected_paged_strings(artifact: dict) -> dict:
    """README paged-KV + speculative row strings from BENCH_r08.json."""
    runs = artifact["runs"]
    pk = ("targets", "paged_kv")
    sp = ("targets", "speculative")
    gain = _runs_median(runs, *pk, "occupancy_gain")
    paged = _runs_median(runs, *pk, "peak_concurrent_paged")
    contig = _runs_median(runs, *pk, "peak_concurrent_contiguous")
    budget = _runs_median(runs, *pk, "kv_slot_budget")
    speedup = _runs_median(runs, *sp, "latency_speedup")
    acc = _runs_median(runs, *sp, "acceptance_rate")
    tpv = _runs_median(runs, *sp, "tokens_per_verify")
    return {
        f"**{gain:.1f}x** concurrent occupancy":
            "median of runs[].targets.paged_kv.occupancy_gain",
        f"{paged:.0f} vs {contig:.0f} in-flight at equal KV HBM "
        f"(same {budget:.0f} token-slot budget)":
            "medians of runs[].targets.paged_kv.peak_concurrent_*/"
            "kv_slot_budget",
        f"**{speedup:.2f}x** single-stream speedup":
            "median of runs[].targets.speculative.latency_speedup",
        f"acceptance {acc * 100:.0f}%, {tpv:.2f} tokens/verify":
            "medians of runs[].targets.speculative.acceptance_rate/"
            "tokens_per_verify",
    }


def expected_planner_strings(artifact: dict) -> dict:
    """README auto-planner row strings from BENCH_r09_planner.json."""
    runs = artifact["runs"]
    tgt = ("targets", "planner")
    p95 = _runs_median(runs, *tgt, "plan_ms_p95")
    plans = _runs_median(runs, *tgt, "plans")
    cand = _runs_median(runs, *tgt, "candidates_evaluated")
    pred = _runs_median(runs, *tgt, "predicted_step_ms")
    meas = _runs_median(runs, *tgt, "measured_step_ms")
    return {
        f"plan p95 **{p95:.1f} ms**":
            "median of runs[].targets.planner.plan_ms_p95",
        f"{plans:.0f} plans / {cand:,.0f} layouts priced":
            "medians of runs[].targets.planner.plans/candidates_evaluated",
        f"predicted {pred:.1f} vs measured {meas:.1f} ms/step":
            "medians of runs[].targets.planner.predicted_step_ms/"
            "measured_step_ms",
    }


def expected_training_strings(artifact: dict) -> dict:
    """README sharded-update row strings from BENCH_r10_training.json."""
    runs = artifact["runs"]
    tgt = ("targets", "training")
    rep_b = _runs_median(runs, *tgt, "opt_state_bytes_replicated")
    sh_b = _runs_median(runs, *tgt, "opt_state_bytes_sharded")
    nc_rep = _runs_median(runs, *tgt, "noncompute_ms_replicated")
    nc_best = _runs_median(runs, *tgt, "noncompute_ms_best")
    delta = _runs_median(runs, *tgt, "max_loss_delta")
    return {
        f"optimizer state **{rep_b / sh_b:.1f}x** smaller per replica":
            "ratio of runs[].targets.training.opt_state_bytes_"
            "replicated/_sharded medians",
        f"{sh_b / 2**20:.1f} vs {rep_b / 2**20:.1f} MiB/device":
            "medians of runs[].targets.training.opt_state_bytes_*",
        f"non-compute step time **{nc_rep:.0f} -> {nc_best:.0f} ms**":
            "medians of runs[].targets.training.noncompute_ms_"
            "replicated/_best",
        f"max loss delta {delta:.1e}":
            "median of runs[].targets.training.max_loss_delta",
    }


def expected_decode_strings(artifact: dict) -> dict:
    """README blocked-decode row strings from BENCH_r11_decode.json.

    The r11 artifact carries no ``openloop`` target — the chunked-
    admission p95-TTFT string returns with the r16 artifact (see the
    DECODE_ARTIFACT note above)."""
    runs = artifact["runs"]
    tgt = ("targets", "decode")
    g12 = _runs_median(runs, *tgt, "raw", "b12", "gather_tokens_per_sec")
    b12 = _runs_median(runs, *tgt, "raw", "b12", "blocked_tokens_per_sec")
    speedup = _runs_median(runs, *tgt, "raw", "b12", "blocked_speedup")
    macc = _runs_median(runs, *tgt, "spec", "model_acceptance")
    nacc = _runs_median(runs, *tgt, "spec", "ngram_acceptance")
    return {
        f"**{speedup:.2f}x** 12-way decode":
            "median of runs[].targets.decode.raw.b12.blocked_speedup",
        f"{g12:,.0f} -> {b12:,.0f} tokens/s":
            "medians of runs[].targets.decode.raw.b12."
            "gather/blocked_tokens_per_sec",
        f"model-draft acceptance {macc * 100:.0f}% vs ngram "
        f"{nacc * 100:.0f}%":
            "medians of runs[].targets.decode.spec."
            "model/ngram_acceptance",
    }


def expected_disagg_strings(artifact: dict) -> dict:
    """README disaggregated-fleet row strings from BENCH_r12_disagg.json."""
    runs = artifact["runs"]
    tgt = ("targets", "disagg")
    colo = _runs_median(runs, *tgt, "raw", "b12", "colocated",
                        "decode_tokens_per_sec")
    dis = _runs_median(runs, *tgt, "raw", "b12", "disagg",
                       "decode_tokens_per_sec")
    speedup = _runs_median(runs, *tgt, "raw", "b12", "disagg_speedup")
    t_off = _runs_median(runs, *tgt, "raw", "b12", "colocated",
                         "ttft_ms_p50")
    t_on = _runs_median(runs, *tgt, "raw", "b12", "disagg", "ttft_ms_p50")
    gold = _runs_median(runs, *tgt, "qos_burst", "sheds", "gold")
    bronze = _runs_median(runs, *tgt, "qos_burst", "sheds", "bronze")
    return {
        f"**{speedup:.2f}x** 12-way disagg decode":
            "median of runs[].targets.disagg.raw.b12.disagg_speedup",
        f"{colo:,.0f} -> {dis:,.0f} tokens/s":
            "medians of runs[].targets.disagg.raw.b12."
            "colocated/disagg.decode_tokens_per_sec",
        f"TTFT p50 {t_off:.0f} -> {t_on:.0f} ms":
            "medians of runs[].targets.disagg.raw.b12."
            "colocated/disagg.ttft_ms_p50",
        f"burst sheds gold {gold:.0f} / bronze {bronze:.0f}":
            "medians of runs[].targets.disagg.qos_burst.sheds.gold/bronze",
    }


def expected_tracing_strings(artifact: dict) -> dict:
    """README tracing-overhead row strings from BENCH_r13_tracing.json."""
    runs = artifact["runs"]
    tgt = ("targets", "tracing")
    disarmed = _runs_median(runs, *tgt, "disarmed_decode_tokens_per_sec")
    armed = _runs_median(runs, *tgt, "armed_decode_tokens_per_sec")
    ratio = _runs_median(runs, *tgt, "armed_over_disarmed")
    span_us = _runs_median(runs, *tgt, "disarmed_call", "span_us")
    return {
        f"armed tracing at **{ratio * 100:.0f}%** of disarmed throughput":
            "median of runs[].targets.tracing.armed_over_disarmed",
        f"{disarmed:,.0f} -> {armed:,.0f} tokens/s 12-way":
            "medians of runs[].targets.tracing."
            "disarmed/armed_decode_tokens_per_sec",
        f"disarmed span call {span_us:.2f} µs":
            "median of runs[].targets.tracing.disarmed_call.span_us",
    }


def expected_ps_strings(artifact: dict) -> dict:
    """README parameter-service row strings from BENCH_r15_ps.json."""
    runs = artifact["runs"]
    tgt = ("targets", "ps")
    restart = _runs_median(runs, *tgt, "restart_goodput")
    ps = _runs_median(runs, *tgt, "ps_goodput")
    gap = _runs_median(runs, *tgt, "loss_gap")
    tol = _runs_median(runs, *tgt, "loss_tol")
    return {
        f"goodput **{restart:.2f} -> {ps:.2f}**":
            "medians of runs[].targets.ps.restart_goodput/ps_goodput",
        f"{ps / restart:.1f}x":
            "ratio of the ps_goodput/restart_goodput medians",
        f"final-loss gap {gap:.3f} vs sync (tol {tol:g})":
            "medians of runs[].targets.ps.loss_gap/loss_tol",
    }


def expected_rollout_strings(artifact: dict) -> dict:
    """README model-lifecycle row strings from BENCH_r17_rollout.json."""
    runs = artifact["runs"]
    tgt = ("targets", "rollout")
    load_ms = _runs_median(runs, *tgt, "hot_swap_load_ms")
    single = _runs_median(runs, *tgt, "single_version_tokens_per_sec")
    mixed = _runs_median(runs, *tgt, "mixed_version_tokens_per_sec")
    ratio = _runs_median(runs, *tgt, "mixed_over_single")
    return {
        f"hot swap commits in **{load_ms:.0f} ms** off the dispatch path":
            "median of runs[].targets.rollout.hot_swap_load_ms",
        f"two-version mix holds **{ratio * 100:.0f}%** of single-version"
        " decode":
            "median of runs[].targets.rollout.mixed_over_single",
        f"{single:,.0f} -> {mixed:,.0f} tokens/s 8-way":
            "medians of runs[].targets.rollout."
            "single/mixed_version_tokens_per_sec",
    }


def expected_shards_strings(artifact: dict) -> dict:
    """README sharded control-plane row strings from BENCH_r18_shards.json."""
    runs = artifact["runs"]
    tgt = ("targets", "shards")
    one = _runs_median(runs, *tgt, "arms", "1_shard", "jobs_per_s")
    four = _runs_median(runs, *tgt, "arms", "4_shard", "jobs_per_s")
    thpt = _runs_median(runs, *tgt, "throughput_speedup")
    p99 = _runs_median(runs, *tgt, "reconcile_p99_speedup")
    launch = _runs_median(runs, *tgt, "median_launch_speedup")
    return {
        f"**{thpt:.2f}x** job throughput — {one:g} -> {four:g} jobs/s":
            "medians of runs[].targets.shards.throughput_speedup and "
            "arms.{1,4}_shard.jobs_per_s",
        f"reconcile p99 **{p99:.2f}x**":
            "median of runs[].targets.shards.reconcile_p99_speedup",
        f"median time-to-launch **{launch:.2f}x**":
            "median of runs[].targets.shards.median_launch_speedup",
    }


def expected_cp_scale_strings(artifact: dict) -> dict:
    """README control-plane scaling row strings from BENCH_r19_cp_scale.json."""
    runs = artifact["runs"]
    tgt = ("targets", "cp_scale")
    speedup = _runs_median(runs, *tgt, "throughput_speedup_4x1")
    one = _runs_median(runs, *tgt, "arms", "1_shard", "jobs_per_s")
    four = _runs_median(runs, *tgt, "arms", "4_shard", "jobs_per_s")
    r18_qw = _runs_median(runs, *tgt, "r18_queue_wait_p99_ms")
    qw = _runs_median(runs, *tgt, "arms", "4_shard", "queue_wait_p99_ms")
    amort = _runs_median(runs, *tgt, "fsync_amortization_4_shard")
    return {
        f"**{speedup:.2f}x** job throughput at 4 shards — "
        f"{one:g} -> {four:g} jobs/s":
            "medians of runs[].targets.cp_scale.throughput_speedup_4x1 and "
            "arms.{1,4}_shard.jobs_per_s",
        f"queue wait p99 **{r18_qw / qw:.1f}x** lower than r18 "
        f"({r18_qw:,.0f} -> {qw:,.0f} ms)":
            "medians of runs[].targets.cp_scale.r18_queue_wait_p99_ms and "
            "arms.4_shard.queue_wait_p99_ms",
        f"**{amort:.0f}** appends per fsync (r18: 1)":
            "median of runs[].targets.cp_scale.fsync_amortization_4_shard",
    }


def expected_federation_strings(artifact: dict) -> dict:
    """README federation row strings from BENCH_r20_federation.json."""
    runs = artifact["runs"]
    tgt = ("targets", "federation")
    speedup = _runs_median(runs, *tgt, "fed_speedup_vs_inprocess_8shard")
    r19 = _runs_median(runs, *tgt, "r19_8shard_jobs_per_s")
    fed = _runs_median(runs, *tgt, "fed_4proc", "jobs_per_s")
    reconverge = _runs_median(runs, *tgt, "member_kill", "reconverge_s")
    dups = _runs_median(runs, *tgt, "member_kill", "duplicate_launches")
    return {
        f"**{speedup:.2f}x** the in-process 8-shard arm — "
        f"{r19:g} -> {fed:g} jobs/s":
            "medians of runs[].targets.federation."
            "fed_speedup_vs_inprocess_8shard, r19_8shard_jobs_per_s and "
            "fed_4proc.jobs_per_s",
        f"member SIGKILL reconverges in **{reconverge:.2f} s**":
            "median of runs[].targets.federation.member_kill.reconverge_s",
        f"**{dups:.0f}** duplicate pod launches":
            "median of runs[].targets.federation.member_kill."
            "duplicate_launches",
    }


def check(repo: Path = REPO) -> list:
    """Returns a list of mismatch descriptions (empty = README is clean)."""
    artifact = json.loads((repo / ARTIFACT).read_text())
    readme = (repo / "README.md").read_text()
    expected = expected_strings(artifact)
    expected.update(
        expected_prefix_strings(
            json.loads((repo / PREFIX_ARTIFACT).read_text())
        )
    )
    expected.update(
        expected_router_strings(
            json.loads((repo / ROUTER_ARTIFACT).read_text())
        )
    )
    expected.update(
        expected_paged_strings(
            json.loads((repo / PAGED_ARTIFACT).read_text())
        )
    )
    expected.update(
        expected_planner_strings(
            json.loads((repo / PLANNER_ARTIFACT).read_text())
        )
    )
    expected.update(
        expected_training_strings(
            json.loads((repo / TRAINING_ARTIFACT).read_text())
        )
    )
    expected.update(
        expected_decode_strings(
            json.loads((repo / DECODE_ARTIFACT).read_text())
        )
    )
    expected.update(
        expected_disagg_strings(
            json.loads((repo / DISAGG_ARTIFACT).read_text())
        )
    )
    expected.update(
        expected_tracing_strings(
            json.loads((repo / TRACING_ARTIFACT).read_text())
        )
    )
    expected.update(
        expected_ps_strings(
            json.loads((repo / PS_ARTIFACT).read_text())
        )
    )
    expected.update(
        expected_rollout_strings(
            json.loads((repo / ROLLOUT_ARTIFACT).read_text())
        )
    )
    expected.update(
        expected_shards_strings(
            json.loads((repo / SHARDS_ARTIFACT).read_text())
        )
    )
    expected.update(
        expected_cp_scale_strings(
            json.loads((repo / CP_SCALE_ARTIFACT).read_text())
        )
    )
    expected.update(
        expected_federation_strings(
            json.loads((repo / FEDERATION_ARTIFACT).read_text())
        )
    )
    problems = []
    for text, derivation in expected.items():
        if text not in readme:
            problems.append(
                f"README.md is missing {text!r} (derived from {derivation})"
            )
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"MISMATCH: {p}", file=sys.stderr)
    if problems:
        print(
            f"{len(problems)} README number(s) not derivable from {ARTIFACT}; "
            "update the table or the derivation",
            file=sys.stderr,
        )
        return 1
    print(f"README headline numbers match {ARTIFACT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
